"""Versioned, endian-explicit tensor wire format.

Replaces the reference's cross-device serialization protocol
(``cpp/utils.cpp:124-368``): ``[numTensors: size_t][dtype, ndims, dims...,
raw]`` in *native* endianness with ``size_t`` fields — defect #9 in
SURVEY.md Appendix B (not portable across hosts).  This format fixes that:

- explicit little-endian for every header field and for raw data;
- fixed-width field types (no ``size_t``);
- a 4-byte magic + 1-byte version so the receiver can reject garbage and
  future revisions can evolve the layout;
- bfloat16 as a first-class dtype (the TPU-native activation dtype — the
  reference's ORT path had no bf16 at the wire, forcing f32 activations).

Layout (all little-endian):

    header:  magic "DWT1" | version:u8 | flags:u8 | checksum:u16 | ntensors:u32
    tensor:  dtype:u8 | ndims:u8 | reserved:u16 | nbytes:u64 | dims:u64*ndims
             | raw bytes (C-contiguous)

The message header's 16-bit field (reserved through PR 4) carries an
integrity checksum over everything after the header: CRC-32 of the
payload XOR-folded to 16 bits, with 0 remapped to 0xFFFF so the value 0
unambiguously means "no checksum" — frames from pre-checksum peers (and
``checksum=False`` senders) decode unchanged, while a corrupt frame
raises :class:`WireIntegrityError` instead of decoding garbage
activations into a wrong token.  The fold keeps CRC-32's guarantee for
single-bit flips and detects random corruption with 1 - 2^-16
probability; the native codec (``native_codec.py``) reads and writes the
same field, byte-identically.

Token ids travel as 4-byte little-endian ints (reference
``utils.cpp:11-25`` used native-endian).

A byte-identical C++ implementation lives in ``native/codec.cc`` (loaded via
``comm.native``); this module is the reference implementation and the
fallback when the native lib isn't built.
"""

from __future__ import annotations

import enum
import struct
import zlib
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

try:  # bfloat16 numpy dtype (always present in this env via jax)
    import ml_dtypes
    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None

MAGIC = b"DWT1"
VERSION = 1
_HEADER = struct.Struct("<4sBBHI")          # magic, version, flags, rsv, n
_TENSOR_HDR = struct.Struct("<BBHQ")        # dtype, ndims, rsv, nbytes

# flags bit 0: the message carries a trace-context trailer — the LAST
# tensor is a u64[2] of (trace_id, parent_span_id) appended by
# serialize_tensors_traced and stripped by split_trace_context.  The
# trailer is a perfectly ordinary tensor counted in ntensors, so decoders
# that predate the bit (including native/codec.cc, which returns flags
# verbatim and never interprets them) decode traced frames without
# change; frames without the bit are byte-identical to the pre-trace
# format.  Bits 1-7 stay reserved.
FLAG_TRACE_CONTEXT = 0x01


class DType(enum.IntEnum):
    """Wire dtype enum (stable across versions; extend, never renumber).

    Mirrors the 11-dtype table of the reference's ``CopyOrtValue``
    (``utils.cpp:49-113``) plus bfloat16.
    """

    F32 = 0
    F64 = 1
    F16 = 2
    BF16 = 3
    I8 = 4
    I16 = 5
    I32 = 6
    I64 = 7
    U8 = 8
    U16 = 9
    U32 = 10
    U64 = 11
    BOOL = 12


_TO_NP = {
    DType.F32: np.dtype("<f4"), DType.F64: np.dtype("<f8"),
    DType.F16: np.dtype("<f2"),
    DType.I8: np.dtype("i1"), DType.I16: np.dtype("<i2"),
    DType.I32: np.dtype("<i4"), DType.I64: np.dtype("<i8"),
    DType.U8: np.dtype("u1"), DType.U16: np.dtype("<u2"),
    DType.U32: np.dtype("<u4"), DType.U64: np.dtype("<u8"),
    DType.BOOL: np.dtype("bool"),
}
if _BFLOAT16 is not None:
    _TO_NP[DType.BF16] = _BFLOAT16

_FROM_NP = {v: k for k, v in _TO_NP.items()}


class WireError(ValueError):
    """Malformed or incompatible wire payload."""


class WireIntegrityError(WireError):
    """Checksum mismatch: the frame was corrupted in flight.  Receivers
    treat this as a droppable event (counted + flight-recorded) — the
    step-timeout/elastic-reshard path recovers, never a wrong token."""


def payload_checksum(payload) -> int:  # bytes or memoryview
    """CRC-32 of ``payload`` XOR-folded to 16 bits, never 0 (0 is the
    wire's "no checksum" sentinel).  One owner for the math — the native
    codec binding uses this exact function so both codecs stay
    byte-identical."""
    c = zlib.crc32(payload) & 0xFFFFFFFF
    folded = (c & 0xFFFF) ^ (c >> 16)
    return folded or 0xFFFF


def verify_checksum(data: bytes) -> None:
    """Raise :class:`WireIntegrityError` when ``data``'s header carries a
    nonzero checksum that does not match its payload.  Zero-checksum
    frames (pre-checksum peers) pass — version compat.  Shared by both
    codecs; structural validation stays the decoder's job."""
    if len(data) < _HEADER.size:
        return                     # the decoder's short-message error wins
    (claimed,) = struct.unpack_from("<H", data, 6)
    if claimed == 0:
        return
    # memoryview: CRC the payload in place — no full-frame copy on the
    # per-hop receive path
    actual = payload_checksum(memoryview(data)[_HEADER.size:])
    if actual != claimed:
        raise WireIntegrityError(
            f"wire checksum mismatch: header says 0x{claimed:04x}, "
            f"payload is 0x{actual:04x} ({len(data)} bytes) — frame "
            "corrupted in flight")


@dataclass
class TensorMessage:
    """A decoded wire payload: a list of ndarrays plus the header flags."""

    tensors: List[np.ndarray]
    flags: int = 0


def _np_dtype_to_wire(dt: np.dtype) -> DType:
    dt = np.dtype(dt)
    # normalize endianness: the wire is little-endian
    key = dt.newbyteorder("<") if dt.byteorder == ">" else dt
    try:
        return _FROM_NP[key]
    except KeyError:
        raise WireError(f"unsupported dtype for wire: {dt}") from None


def serialize_tensors(arrays: Sequence[np.ndarray], flags: int = 0,
                      checksum: bool = True) -> bytes:
    """Encode a sequence of arrays into one wire message.

    Counterpart of ``SerializeTensorVectorToBytes`` (``utils.cpp:124-264``),
    including its total-size self-check — here the check is structural
    (we build the buffer piecewise and verify the final length).

    ``checksum=False`` emits the pre-checksum frame (header field 0) —
    the knob exists for compat tests and for peers that must talk to
    pre-checksum decoders, not for the hot path (the CRC costs ~1 GB/s-
    class zlib time, negligible next to serialization itself).
    """
    parts = []
    expected = 0
    for a in arrays:
        a = np.asarray(a)
        if not a.flags["C_CONTIGUOUS"]:  # 0-d arrays are always contiguous,
            a = np.ascontiguousarray(a)  # so this never promotes 0-d to 1-d

        wdt = _np_dtype_to_wire(a.dtype)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        raw = a.tobytes()
        parts.append(_TENSOR_HDR.pack(int(wdt), a.ndim, 0, len(raw)))
        parts.append(struct.pack(f"<{a.ndim}Q", *a.shape))
        parts.append(raw)
        expected += _TENSOR_HDR.size + 8 * a.ndim + len(raw)
    payload = b"".join(parts)
    if len(payload) != expected:  # structural self-check (utils.cpp:250-261)
        raise WireError(
            f"serializer size mismatch: {len(payload)} != {expected}")
    csum = payload_checksum(payload) if checksum else 0
    return _HEADER.pack(MAGIC, VERSION, flags & 0xFF, csum,
                        len(arrays)) + payload


def deserialize_tensors(data: bytes) -> TensorMessage:
    """Decode one wire message.  Counterpart of
    ``DeserializeTensorVectorFromBytes`` (``utils.cpp:266-368``)."""
    if len(data) < _HEADER.size:
        raise WireError(f"short message: {len(data)} bytes")
    magic, version, flags, csum, n = _HEADER.unpack_from(data, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != VERSION:
        raise WireError(f"unsupported wire version {version}")
    if csum:
        # verified BEFORE any tensor parsing: a corrupt frame must raise
        # WireIntegrityError, never decode garbage (csum 0 = legacy peer)
        verify_checksum(data)
    off = _HEADER.size
    out: List[np.ndarray] = []
    for _ in range(n):
        if off + _TENSOR_HDR.size > len(data):
            raise WireError("truncated tensor header")
        dt_raw, ndims, _rsv, nbytes = _TENSOR_HDR.unpack_from(data, off)
        off += _TENSOR_HDR.size
        try:
            wdt = DType(dt_raw)
            np_dt = _TO_NP[wdt]
        except (ValueError, KeyError):
            raise WireError(f"unknown wire dtype {dt_raw}") from None
        if off + 8 * ndims > len(data):
            raise WireError("truncated dims")
        dims = struct.unpack_from(f"<{ndims}Q", data, off)
        off += 8 * ndims
        count = 1
        for d in dims:
            count *= d
        if count * np_dt.itemsize != nbytes:
            raise WireError(
                f"nbytes {nbytes} inconsistent with shape {dims} {np_dt}")
        if off + nbytes > len(data):
            raise WireError("truncated tensor data")
        arr = np.frombuffer(data, np_dt, count=count, offset=off)
        out.append(arr.reshape(dims).copy())  # own the memory
        off += nbytes
    if off != len(data):
        raise WireError(f"{len(data) - off} trailing bytes")
    return TensorMessage(tensors=out, flags=flags)


def serialize_tensors_traced(arrays: Sequence[np.ndarray],
                             trace_id: Optional[int],
                             parent_span_id: int = 0,
                             flags: int = 0) -> bytes:
    """Encode ``arrays`` with an optional trace-context trailer.

    ``trace_id=None`` is byte-identical to :func:`serialize_tensors`
    (tracing off costs nothing on the wire); otherwise a u64[2]
    ``[trace_id, parent_span_id]`` tensor is appended and
    :data:`FLAG_TRACE_CONTEXT` set so the receiver can strip it with
    :func:`split_trace_context`.
    """
    if trace_id is None:
        return serialize_tensors(arrays, flags)
    trailer = np.array([trace_id & _U64_MASK,
                        parent_span_id & _U64_MASK], dtype="<u8")
    return serialize_tensors(list(arrays) + [trailer],
                             flags | FLAG_TRACE_CONTEXT)


_U64_MASK = (1 << 64) - 1


def split_trace_context(msg: TensorMessage):
    """``(tensors, (trace_id, parent_span_id) | None)`` from a decoded
    message.  Messages without :data:`FLAG_TRACE_CONTEXT` pass through
    untouched; a set flag with a malformed trailer is a hard
    :class:`WireError` (a half-stripped payload would silently shift
    every tensor index downstream)."""
    if not (msg.flags & FLAG_TRACE_CONTEXT):
        return msg.tensors, None
    if not msg.tensors:
        raise WireError("trace-context flag set on an empty message")
    trailer = msg.tensors[-1]
    if trailer.dtype != np.dtype("<u8") or trailer.shape != (2,):
        raise WireError(
            f"malformed trace-context trailer: {trailer.dtype} "
            f"{trailer.shape}")
    return msg.tensors[:-1], (int(trailer[0]), int(trailer[1]))


def serialize_token(token_id: int) -> bytes:
    """4-byte little-endian token id (reference ``utils.cpp:11-17``)."""
    return struct.pack("<i", token_id)


def deserialize_token(data: bytes) -> int:
    """Counterpart of ``DeserializeInt`` (``utils.cpp:19-25``)."""
    if len(data) != 4:
        raise WireError(f"token message must be 4 bytes, got {len(data)}")
    return struct.unpack("<i", data)[0]
