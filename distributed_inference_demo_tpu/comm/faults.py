"""Deterministic fault injection: seeded fault plans + a transport wrapper.

Chaos-engineering substrate for the elastic pipeline (docs/DESIGN.md
§12).  The recovery path — CRC drop, send retry, step-timeout, elastic
reshard, drain/resume — is only trustworthy if it is *continuously
executed* under injected faults (the Chaos Monkey / Jepsen lesson), so
this module makes the messy failures real deployments see reproducible:

- :class:`FaultPlan` — a seeded RNG plus an ordered list of declarative
  :class:`FaultRule`\\ s (``drop``, ``delay``, ``duplicate``, ``reorder``,
  ``corrupt``, ``partition``, ``crash_after``), each scoped by peer,
  tag prefix, message count, and probability.  Same seed + same rules +
  same message sequence ⇒ byte-identical injected-fault event sequence
  (:attr:`FaultPlan.events`; asserted by ``tests/test_chaos.py``), so a
  failing soak run is replayable from its postmortem bundle by seed
  alone.
- :class:`FaultyTransport` — implements the full ``BaseTransport``
  surface and slots between any header/worker and the real
  ``LoopbackTransport``/``ZmqTransport`` unchanged.  Faults are injected
  on the SEND side (every ring edge has a sending wrapper, so every edge
  is coverable); ``crash_after`` fires on sends *and* receives so a
  mostly-receiving stage can die mid-reshard too.

Plans are built from a JSON spec (``DWT_FAULT_PLAN`` env var or
``--fault-plan`` on serve/worker), OFF by default, and **rejected unless
--chaos is set** — fault injection in a production process must be a
double-keyed decision.  Every injected fault is counted
(``dwt_fault_injected_faults_total{kind=...}``) and flight-recorded
(``fault_injected`` events), so a chaos run's postmortem bundle names
its own cause (``tools/postmortem.py`` surfaces them).

Spec shape::

    {"seed": 1234, "name": "soak-1", "rules": [
        {"kind": "delay", "peer": "s1", "tag_prefix": "h:",
         "prob": 0.2, "delay_ms": 15},
        {"kind": "corrupt", "peer": "s2", "after": 3, "max_count": 1},
        {"kind": "crash_after", "peer": null, "n_msgs": 40}]}
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .transport import BaseTransport, TransportError

log = logging.getLogger(__name__)

FAULT_KINDS = ("drop", "delay", "duplicate", "reorder", "corrupt",
               "partition", "crash_after")


class FaultConfigError(ValueError):
    """Malformed fault-plan spec, or a plan supplied without --chaos."""


class InjectedCrash(RuntimeError):
    """A ``crash_after`` rule fired: the wrapped process must die NOW.

    Deliberately NOT a TransportError — the elastic worker swallows
    TransportError on forward sends (a dead next hop is survivable); an
    injected crash must propagate out of the serve loop exactly like a
    real unhandled exception so the crash handler / supervisor sees it.
    """


@dataclass
class FaultRule:
    """One declarative fault.  ``peer``/``tag_prefix`` scope which
    messages match (None = any); ``after`` skips the first N matching
    messages; ``max_count`` bounds how many times the rule fires;
    ``prob`` gates each firing through the plan's seeded RNG."""

    kind: str
    peer: Optional[str] = None
    tag_prefix: Optional[str] = None
    prob: float = 1.0
    after: int = 0
    max_count: Optional[int] = None
    delay_ms: float = 0.0             # delay only
    n_msgs: Optional[int] = None      # crash_after only
    # runtime counters (not part of the spec)
    seen: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise FaultConfigError(
                f"unknown fault kind {self.kind!r} "
                f"(known: {list(FAULT_KINDS)})")
        if self.kind == "crash_after" and self.n_msgs is None:
            raise FaultConfigError("crash_after rule needs n_msgs")
        if not 0.0 <= self.prob <= 1.0:
            raise FaultConfigError(f"prob must be in [0,1], got {self.prob}")

    def to_spec(self) -> dict:
        out: dict = {"kind": self.kind}
        for key in ("peer", "tag_prefix", "max_count", "n_msgs"):
            v = getattr(self, key)
            if v is not None:
                out[key] = v
        if self.prob != 1.0:
            out["prob"] = self.prob
        if self.after:
            out["after"] = self.after
        if self.delay_ms:
            out["delay_ms"] = self.delay_ms
        return out

    def matches(self, peer: str, tag: str) -> bool:
        if self.peer is not None and peer != self.peer:
            return False
        if self.tag_prefix is not None and not tag.startswith(
                self.tag_prefix):
            return False
        return True


class FaultPlan:
    """Seeded, ordered fault rules + the injected-event record.

    Thread-safe: one plan may back every edge of a pipeline (header +
    workers share it in the loopback chaos tests).  Determinism holds
    per message *sequence* — identical send/recv sequences replay
    identical decisions because the RNG is consumed in message order
    under the lock."""

    def __init__(self, seed: int = 0,
                 rules: Sequence[FaultRule] = (), name: str = ""):
        self.seed = int(seed)
        self.name = name
        self.rules: List[FaultRule] = list(rules)
        self.rng = random.Random(self.seed)
        self.events: List[dict] = []     # every injected fault, in order
        self._seq = 0                    # messages consulted
        self._msgs = 0                   # messages seen by crash counters
        self._lock = threading.Lock()

    # -- construction ------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        if not isinstance(spec, dict):
            raise FaultConfigError(
                f"fault plan must be a JSON object, got {type(spec).__name__}")
        known = {"kind", "peer", "tag_prefix", "prob", "after", "max_count",
                 "delay_ms", "n_msgs"}
        rules = []
        for i, r in enumerate(spec.get("rules") or []):
            extra = set(r) - known
            if extra:
                raise FaultConfigError(
                    f"rule {i}: unknown fields {sorted(extra)}")
            rules.append(FaultRule(**r))
        return cls(seed=spec.get("seed", 0), rules=rules,
                   name=spec.get("name", ""))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        try:
            spec = json.loads(text)
        except json.JSONDecodeError as e:
            raise FaultConfigError(f"fault plan is not valid JSON: {e}")
        return cls.from_spec(spec)

    def to_spec(self) -> dict:
        return {"seed": self.seed, "name": self.name,
                "rules": [r.to_spec() for r in self.rules]}

    # -- decisions ---------------------------------------------------------

    def _record(self, kind: str, **fields) -> dict:
        ev = dict(seq=self._seq, kind=kind, **fields)
        self.events.append(ev)
        try:
            from ..telemetry import catalog
            catalog.FAULT_INJECTED.inc(kind=kind)
        except Exception:    # pragma: no cover - defensive
            pass
        try:
            from ..telemetry.flightrecorder import get_flight_recorder
            # the flight event's own kind is "fault_injected"; the rule
            # kind rides as fault_kind (record(kind, **fields) would see
            # ev's "kind" as a duplicate argument otherwise)
            get_flight_recorder().record(
                "fault_injected", fault_kind=kind,
                **{k: v for k, v in ev.items() if k != "kind"})
        except Exception:    # pragma: no cover - defensive
            pass
        return ev

    def on_send(self, device_id: str, peer: str, tag: str,
                nbytes: int) -> List[dict]:
        """Decide the faults for one outbound message.  Returns the fired
        actions in rule order; also advances the crash counter (a send is
        a message)."""
        with self._lock:
            self._seq += 1
            self._msgs += 1
            fired: List[dict] = []
            for rule in self.rules:
                if rule.kind == "crash_after":
                    if (rule.matches(peer, tag)
                            and self._msgs > rule.n_msgs
                            and (rule.max_count is None
                                 or rule.fired < rule.max_count)):
                        rule.fired += 1
                        fired.append(self._record(
                            "crash_after", device=device_id, peer=peer,
                            tag=tag, n_msgs=rule.n_msgs))
                    continue
                if not rule.matches(peer, tag):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if (rule.max_count is not None
                        and rule.fired >= rule.max_count):
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                ev = {"device": device_id, "peer": peer, "tag": tag,
                      "nbytes": nbytes}
                if rule.kind == "delay":
                    ev["delay_ms"] = rule.delay_ms
                elif rule.kind == "corrupt":
                    # deterministic byte flip: position + mask from the
                    # plan RNG (mask never 0 — the flip must change bits)
                    ev["pos"] = self.rng.randrange(max(1, nbytes))
                    ev["mask"] = self.rng.randrange(1, 256)
                fired.append(self._record(rule.kind, **ev))
            return fired

    def on_recv(self, device_id: str) -> Optional[dict]:
        """Advance the crash counter for one received message; returns a
        crash event if a matching ``crash_after`` rule fires (receive
        rules are unscoped by peer/tag — the receiver often can't know
        the sender before decoding)."""
        with self._lock:
            self._msgs += 1
            for rule in self.rules:
                if (rule.kind == "crash_after" and rule.peer is None
                        and rule.tag_prefix is None
                        and self._msgs > rule.n_msgs
                        and (rule.max_count is None
                             or rule.fired < rule.max_count)):
                    rule.fired += 1
                    return self._record("crash_after", device=device_id,
                                        peer=None, tag=None,
                                        n_msgs=rule.n_msgs)
            return None


class FaultyTransport(BaseTransport):
    """Fault-injecting wrapper with the full ``BaseTransport`` API.

    Wraps the SEND side of one endpoint; receive calls delegate to the
    inner transport's queues (the wrapper registered nothing of its own,
    so the inner endpoint keeps receiving).  ``close`` closes the inner
    transport."""

    def __init__(self, inner: BaseTransport, plan: FaultPlan):
        # deliberately NOT calling super().__init__: recv state lives in
        # the inner transport (its pump threads deliver into *its* inbox)
        self.inner = inner
        self.plan = plan
        self.device_id = inner.device_id
        self.address = getattr(inner, "address", f"faulty:{self.device_id}")
        self._held: List[Tuple[str, str, bytes]] = []   # reorder buffer
        self._held_lock = threading.Lock()
        self._partitioned: set = set()
        self._crashed = False

    # -- fault application -------------------------------------------------

    def _crash(self, ev: dict) -> None:
        """First crash event wins; the bundle names the injected fault so
        the chaos run's postmortem states its own cause."""
        if not self._crashed:
            self._crashed = True
            try:
                from ..telemetry import postmortem
                postmortem.trigger(
                    "injected_fault_crash",
                    detail={"fault": ev, "plan_seed": self.plan.seed,
                            "plan_name": self.plan.name,
                            "plan": self.plan.to_spec(),  # replayable
                            "device": self.device_id})    # by bundle alone
            except Exception:    # pragma: no cover - defensive
                pass
        raise InjectedCrash(
            f"{self.device_id}: injected crash_after fault (plan seed "
            f"{self.plan.seed}, event seq {ev.get('seq')})")

    def _deliver_later(self, peer_id: str, tag: str, payload: bytes,
                       delay_ms: float) -> None:
        def fire():
            try:
                self.inner.send(peer_id, tag, payload)
            except TransportError:
                pass     # the delayed world may have moved on; that's chaos
        t = threading.Timer(delay_ms / 1000.0, fire)
        t.daemon = True
        t.start()

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        if self._crashed:
            raise InjectedCrash(f"{self.device_id}: already crashed")
        actions = self.plan.on_send(self.device_id, peer_id, tag,
                                    len(payload))
        if peer_id in self._partitioned:
            # an active partition swallows everything to that peer
            with self.plan._lock:
                self.plan._record("partition_drop", device=self.device_id,
                                  peer=peer_id, tag=tag)
            return
        duplicate = False
        delay_ms = None
        reorder = False
        for ev in actions:
            kind = ev["kind"]
            if kind == "crash_after":
                self._crash(ev)
            elif kind == "partition":
                self._partitioned.add(peer_id)
                return                   # this message is the first casualty
            elif kind == "drop":
                return
            elif kind == "corrupt":
                buf = bytearray(payload)
                if buf:
                    buf[ev["pos"]] ^= ev["mask"]
                payload = bytes(buf)
            elif kind == "delay":
                delay_ms = ev["delay_ms"]
            elif kind == "duplicate":
                duplicate = True
            elif kind == "reorder":
                reorder = True
        if reorder:
            with self._held_lock:
                self._held.append((peer_id, tag, payload))
            return
        sends = [(peer_id, tag, payload)]
        if duplicate:
            sends.append((peer_id, tag, payload))
        if delay_ms is not None:
            for p, t, b in sends:
                self._deliver_later(p, t, b, delay_ms)
        else:
            for p, t, b in sends:
                self.inner.send(p, t, b)
        # a held (reordered) message goes out AFTER the message that
        # overtook it — the two swap places on the wire
        with self._held_lock:
            held, self._held = self._held, []
        for p, t, b in held:
            try:
                self.inner.send(p, t, b)
            except TransportError:
                pass

    # -- plumbing ----------------------------------------------------------

    def connect(self, peer_id: str, address: str) -> None:
        self.inner.connect(peer_id, address)

    def recv_any(self, timeout: Optional[float] = None):
        got = self.inner.recv_any(timeout=timeout)
        ev = self.plan.on_recv(self.device_id)
        if ev is not None:
            self._crash(ev)
        return got

    def recv(self, tag: str, timeout: Optional[float] = None) -> bytes:
        got = self.inner.recv(tag, timeout=timeout)
        ev = self.plan.on_recv(self.device_id)
        if ev is not None:
            self._crash(ev)
        return got

    def _deliver(self, tag: str, payload: bytes) -> None:
        self.inner._deliver(tag, payload)

    def close(self) -> None:
        self.inner.close()


# ---------------------------------------------------------------------------
# CLI/env plumbing (serve + worker)
# ---------------------------------------------------------------------------

ENV_FAULT_PLAN = "DWT_FAULT_PLAN"


def load_fault_plan(flag_value: Optional[str],
                    chaos: bool) -> Optional[FaultPlan]:
    """Resolve ``--fault-plan`` (a JSON file path or inline JSON) or the
    ``DWT_FAULT_PLAN`` env var into a plan.  None when neither is set —
    fault injection is strictly opt-in.  A plan WITHOUT ``--chaos`` is a
    hard :class:`FaultConfigError`: production serving must not silently
    run with injected faults because an env var leaked into the
    environment."""
    value = flag_value or os.environ.get(ENV_FAULT_PLAN, "")
    if not value:
        return None
    if not chaos:
        raise FaultConfigError(
            "a fault plan is configured (--fault-plan or "
            f"{ENV_FAULT_PLAN}) but --chaos is not set; refusing to "
            "inject faults into a production process")
    if os.path.exists(value):
        try:
            with open(value, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            raise FaultConfigError(f"cannot read fault plan {value!r}: {e}")
    else:
        text = value
    plan = FaultPlan.from_json(text)
    log.warning("CHAOS MODE: fault plan active (seed=%d, %d rules%s)",
                plan.seed, len(plan.rules),
                f", name={plan.name!r}" if plan.name else "")
    return plan


def maybe_wrap(transport: BaseTransport,
               plan: Optional[FaultPlan]) -> BaseTransport:
    """Wrap ``transport`` when a plan is active; identity otherwise."""
    return transport if plan is None else FaultyTransport(transport, plan)
