// Native BPE tokenizer with the Encode/Decode/TokenToId/IdToToken surface of
// the reference's tokenizers_cpp facade (cpp/tokenizers-cpp/include/
// tokenizers_cpp.h:25-48).  The reference backs that surface with a Rust HF
// tokenizer + vendored sentencepiece; Rust isn't in this image, so this is a
// from-scratch C++ BPE engine covering both schemes the model catalog needs:
//
//  - "bytelevel": GPT-2/BLOOM style byte-level BPE (byte<->unicode alphabet,
//    GPT-2-style pre-tokenization).
//  - "metaspace": sentencepiece-style BPE (llama/mistral): spaces become
//    U+2581, per-word BPE over codepoints, <0xXX> byte fallback.
//
// The model blob is NOT tokenizer.json — the Python facade
// (distributed_inference_demo_tpu/tokenizer.py) lowers tokenizer.json into a
// simple line-based exchange format so the C++ side has no JSON dependency.
// A byte-identical pure-Python implementation of the same spec lives next to
// the facade; tests assert equivalence of all three (C++, Python, HF).
//
// C ABI (ctypes), mirroring the reference's tokenizers_c.h.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// UTF-8 helpers
// ---------------------------------------------------------------------------

// Append codepoint as UTF-8.
void append_utf8(std::string& s, uint32_t cp) {
  if (cp < 0x80) {
    s.push_back((char)cp);
  } else if (cp < 0x800) {
    s.push_back((char)(0xC0 | (cp >> 6)));
    s.push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    s.push_back((char)(0xE0 | (cp >> 12)));
    s.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    s.push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    s.push_back((char)(0xF0 | (cp >> 18)));
    s.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    s.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    s.push_back((char)(0x80 | (cp & 0x3F)));
  }
}

// Decode the UTF-8 codepoint at s[i]; advances i. Invalid bytes yield the
// byte value itself (caller handles fallback).
uint32_t next_cp(const std::string& s, size_t& i) {
  unsigned char c = s[i];
  uint32_t cp;
  int extra;
  if (c < 0x80) { cp = c; extra = 0; }
  else if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
  else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
  else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
  else { ++i; return c; }
  if (i + extra >= s.size()) { ++i; return c; }
  for (int k = 1; k <= extra; ++k) {
    unsigned char cc = s[i + k];
    if ((cc >> 6) != 0x2) { ++i; return c; }
    cp = (cp << 6) | (cc & 0x3F);
  }
  i += extra + 1;
  return cp;
}

// Split a UTF-8 string into per-codepoint strings.
std::vector<std::string> split_cps(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    size_t start = i;
    next_cp(s, i);
    out.push_back(s.substr(start, i - start));
  }
  return out;
}

// ---------------------------------------------------------------------------
// GPT-2 byte <-> unicode alphabet (the byte-level scheme's symbol space).
// Matches huggingface/transformers bytes_to_unicode().
// ---------------------------------------------------------------------------

struct ByteAlphabet {
  std::string byte_to_sym[256];          // byte -> UTF-8 symbol
  std::unordered_map<uint32_t, int> sym_to_byte;  // codepoint -> byte

  ByteAlphabet() {
    std::vector<int> bs;
    for (int b = '!'; b <= '~'; ++b) bs.push_back(b);
    for (int b = 0xA1; b <= 0xAC; ++b) bs.push_back(b);
    for (int b = 0xAE; b <= 0xFF; ++b) bs.push_back(b);
    std::vector<uint32_t> cs(bs.begin(), bs.end());
    int n = 0;
    for (int b = 0; b < 256; ++b) {
      if (std::find(bs.begin(), bs.end(), b) == bs.end()) {
        bs.push_back(b);
        cs.push_back(256 + n);
        ++n;
      }
    }
    for (size_t i = 0; i < bs.size(); ++i) {
      std::string sym;
      append_utf8(sym, cs[i]);
      byte_to_sym[bs[i]] = sym;
      sym_to_byte[cs[i]] = bs[i];
    }
  }
};

const ByteAlphabet& byte_alphabet() {
  static ByteAlphabet a;
  return a;
}

// ---------------------------------------------------------------------------
// Tokenizer model
// ---------------------------------------------------------------------------

struct Tok {
  // config
  std::string scheme;  // "bytelevel" | "metaspace" | "none"
  bool byte_fallback = false;
  bool prepend = false;       // metaspace: prepend U+2581 at sequence start
  int unk_id = -1;
  // model
  std::unordered_map<std::string, int> vocab;
  std::vector<std::string> id_to_tok;
  std::unordered_map<std::string, int> merge_rank;  // "left\x01right" -> rank
  std::unordered_map<std::string, int> specials;    // token -> id
  std::vector<std::string> special_list;            // longest-first
  // result buffers (mirrors the reference Rust TokenizerWrapper's buffer
  // ownership, lib.rs:8-95)
  std::vector<int32_t> ids_buf;
  std::string str_buf;
};

std::string merge_key(const std::string& a, const std::string& b) {
  std::string k = a;
  k.push_back('\x01');
  k += b;
  return k;
}

// Apply BPE merges to a symbol sequence; returns token strings.
std::vector<std::string> bpe(const Tok& t, std::vector<std::string> syms) {
  if (syms.size() < 2) return syms;
  while (true) {
    int best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto it = t.merge_rank.find(merge_key(syms[i], syms[i + 1]));
      if (it != t.merge_rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    syms[best_i] += syms[best_i + 1];
    syms.erase(syms.begin() + best_i + 1);
  }
  return syms;
}

// Emit token ids for one BPE'd word, with unk/byte-fallback handling.
void emit(const Tok& t, const std::vector<std::string>& toks,
          std::vector<int32_t>& out) {
  for (const auto& tok : toks) {
    auto it = t.vocab.find(tok);
    if (it != t.vocab.end()) {
      out.push_back(it->second);
    } else if (t.byte_fallback) {
      static const char* hex = "0123456789ABCDEF";
      for (unsigned char b : tok) {
        std::string fb = "<0x";
        fb.push_back(hex[b >> 4]);
        fb.push_back(hex[b & 0xF]);
        fb += ">";
        auto fit = t.vocab.find(fb);
        if (fit != t.vocab.end()) out.push_back(fit->second);
        else if (t.unk_id >= 0) out.push_back(t.unk_id);
      }
    } else if (t.unk_id >= 0) {
      out.push_back(t.unk_id);
    }
  }
}

// ---------------------------------------------------------------------------
// Pre-tokenizers
// ---------------------------------------------------------------------------

bool is_ws(uint32_t cp) {
  return cp == ' ' || cp == '\t' || cp == '\n' || cp == '\r' || cp == 0x0B ||
         cp == 0x0C || cp == 0xA0 || cp == 0x2028 || cp == 0x2029 ||
         (cp >= 0x2000 && cp <= 0x200A);
}
bool is_digit(uint32_t cp) { return cp >= '0' && cp <= '9'; }
bool is_letter(uint32_t cp) {
  // ASCII letters exactly; non-ASCII non-whitespace approximated as letters
  // (full \p{L} tables are out of scope; identical rule in the Python twin).
  return (cp >= 'a' && cp <= 'z') || (cp >= 'A' && cp <= 'Z') ||
         (cp >= 0x80 && !is_ws(cp));
}

// GPT-2-style pre-tokenization over codepoints (simplified \p{L}/\p{N}):
//   's|'t|'re|'ve|'m|'ll|'d | ?L+ | ?N+ | ?[^ws L N]+ | ws+(?!\S) | ws+
std::vector<std::string> pretok_gpt2(const std::string& text) {
  std::vector<uint32_t> cps;
  std::vector<std::string> raw;  // utf-8 per cp
  size_t i = 0;
  while (i < text.size()) {
    size_t s = i;
    cps.push_back(next_cp(text, i));
    raw.push_back(text.substr(s, i - s));
  }
  std::vector<std::string> out;
  size_t n = cps.size(), p = 0;
  auto take = [&](size_t a, size_t b) {
    std::string w;
    for (size_t k = a; k < b; ++k) w += raw[k];
    out.push_back(w);
  };
  while (p < n) {
    // contractions
    if (cps[p] == '\'' && p + 1 < n) {
      uint32_t c1 = cps[p + 1] | 0x20;  // lowercase ASCII
      if (c1 == 's' || c1 == 't' || c1 == 'm' || c1 == 'd') {
        take(p, p + 2); p += 2; continue;
      }
      if (p + 2 < n) {
        uint32_t c2 = cps[p + 2] | 0x20;
        if ((c1 == 'r' && c2 == 'e') || (c1 == 'v' && c2 == 'e') ||
            (c1 == 'l' && c2 == 'l')) {
          take(p, p + 3); p += 3; continue;
        }
      }
    }
    size_t start = p;
    bool lead_space = (cps[p] == ' ' && p + 1 < n && !is_ws(cps[p + 1]));
    size_t q = p + (lead_space ? 1 : 0);
    if (q < n && is_letter(cps[q])) {
      while (q < n && is_letter(cps[q])) ++q;
      take(start, q); p = q; continue;
    }
    if (q < n && is_digit(cps[q])) {
      while (q < n && is_digit(cps[q])) ++q;
      take(start, q); p = q; continue;
    }
    if (q < n && !is_ws(cps[q])) {  // punctuation run (apostrophes included;
      // contractions were already matched above, so a remaining ' is punct)
      while (q < n && !is_ws(cps[q]) && !is_letter(cps[q]) && !is_digit(cps[q]))
        ++q;
      take(start, q); p = q; continue;
    }
    // whitespace run: \s+(?!\S) — leave the last ws char to the next token
    // when a non-ws follows the run (it then joins that token via " ?", or
    // stands alone if it isn't a plain space).
    size_t w = p;
    while (w < n && is_ws(cps[w])) ++w;
    if (w < n && w - p > 1) { take(p, w - 1); p = w - 1; }
    else { take(p, w); p = w; }
  }
  return out;
}

// Metaspace pre-tokenization: replace ' ' with U+2581, optionally prepend,
// split so each piece starts at a U+2581 boundary.
std::vector<std::string> pretok_metaspace(const std::string& text,
                                          bool prepend) {
  std::string meta = "\xE2\x96\x81";  // U+2581
  std::string s;
  if (prepend && !text.empty() && text.compare(0, 1, " ") != 0) s += meta;
  size_t i = 0;
  while (i < text.size()) {
    if (text[i] == ' ') { s += meta; ++i; }
    else { s.push_back(text[i]); ++i; }
  }
  std::vector<std::string> pieces;
  std::vector<std::string> cps = split_cps(s);
  std::string cur;
  for (auto& c : cps) {
    if (c == meta && !cur.empty()) { pieces.push_back(cur); cur.clear(); }
    cur += c;
  }
  if (!cur.empty()) pieces.push_back(cur);
  return pieces;
}

// ---------------------------------------------------------------------------
// Encode / Decode
// ---------------------------------------------------------------------------

void encode_plain(const Tok& t, const std::string& text,
                  std::vector<int32_t>& out) {
  if (t.scheme == "bytelevel") {
    const ByteAlphabet& alpha = byte_alphabet();
    for (const auto& word : pretok_gpt2(text)) {
      std::vector<std::string> syms;
      for (unsigned char b : word) syms.push_back(alpha.byte_to_sym[b]);
      emit(t, bpe(t, std::move(syms)), out);
    }
  } else if (t.scheme == "metaspace") {
    for (const auto& word : pretok_metaspace(text, t.prepend)) {
      emit(t, bpe(t, split_cps(word)), out);
    }
  } else {  // "none": whole text as one BPE word over codepoints
    emit(t, bpe(t, split_cps(text)), out);
  }
}

void encode(const Tok& t, const std::string& text, std::vector<int32_t>& out) {
  // split out special tokens first (longest match wins)
  size_t pos = 0;
  std::string pending;
  while (pos < text.size()) {
    bool matched = false;
    for (const auto& sp : t.special_list) {
      if (text.compare(pos, sp.size(), sp) == 0) {
        if (!pending.empty()) { encode_plain(t, pending, out); pending.clear(); }
        out.push_back(t.specials.at(sp));
        pos += sp.size();
        matched = true;
        break;
      }
    }
    if (!matched) { pending.push_back(text[pos]); ++pos; }
  }
  if (!pending.empty()) encode_plain(t, pending, out);
}

std::string decode(const Tok& t, const int32_t* ids, uint64_t n,
                   bool skip_special) {
  std::string joined;
  std::vector<uint8_t> bytes;
  auto flush_pending = [&]() {};
  (void)flush_pending;
  if (t.scheme == "bytelevel") {
    for (uint64_t i = 0; i < n; ++i) {
      if (ids[i] < 0 || (size_t)ids[i] >= t.id_to_tok.size()) continue;
      const std::string& tok = t.id_to_tok[ids[i]];
      bool special = t.specials.count(tok) > 0;
      if (special) {
        if (!skip_special) joined += tok;
        continue;
      }
      const ByteAlphabet& alpha = byte_alphabet();
      size_t j = 0;
      while (j < tok.size()) {
        uint32_t cp = next_cp(tok, j);
        auto it = alpha.sym_to_byte.find(cp);
        if (it != alpha.sym_to_byte.end()) joined.push_back((char)it->second);
        else append_utf8(joined, cp);
      }
    }
    return joined;
  }
  // metaspace / none: concat tokens, then <0xXX> fallback and U+2581 -> ' '
  for (uint64_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || (size_t)ids[i] >= t.id_to_tok.size()) continue;
    const std::string& tok = t.id_to_tok[ids[i]];
    bool special = t.specials.count(tok) > 0;
    if (special) {
      if (!skip_special) joined += tok;
      continue;
    }
    if (tok.size() == 6 && tok.compare(0, 3, "<0x") == 0 && tok[5] == '>') {
      auto hexval = [](char c) -> int {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        return -1;
      };
      int hi = hexval(tok[3]), lo = hexval(tok[4]);
      if (hi >= 0 && lo >= 0) { joined.push_back((char)(hi * 16 + lo)); continue; }
    }
    joined += tok;
  }
  if (t.scheme == "metaspace") {
    std::string meta = "\xE2\x96\x81";
    std::string out;
    size_t i = 0;
    while (i < joined.size()) {
      if (joined.compare(i, meta.size(), meta) == 0) {
        out.push_back(' ');
        i += meta.size();
      } else {
        out.push_back(joined[i]);
        ++i;
      }
    }
    if (t.prepend && !out.empty() && out[0] == ' ') out.erase(0, 1);
    return out;
  }
  return joined;
}

// ---------------------------------------------------------------------------
// Blob parsing (the exchange format written by the Python facade)
// ---------------------------------------------------------------------------

Tok* parse_blob(const std::string& blob) {
  auto* t = new Tok();
  std::istringstream in(blob);
  std::string line;
  auto fields = [](const std::string& l) {
    std::vector<std::string> f;
    size_t p = 0;
    while (true) {
      size_t q = l.find('\t', p);
      if (q == std::string::npos) { f.push_back(l.substr(p)); break; }
      f.push_back(l.substr(p, q - p));
      p = q + 1;
    }
    return f;
  };
  // unescape \n \t \\ in token strings
  auto unesc = [](const std::string& s) {
    std::string o;
    for (size_t i = 0; i < s.size(); ++i) {
      if (s[i] == '\\' && i + 1 < s.size()) {
        char c = s[++i];
        o.push_back(c == 'n' ? '\n' : c == 't' ? '\t' : c);
      } else o.push_back(s[i]);
    }
    return o;
  };
  int64_t ntok = -1, nmerge = -1, nspecial = -1;
  try {
    while (std::getline(in, line)) {
      auto f = fields(line);
      if (f.empty() || f[0].empty()) continue;
      if (f[0] == "scheme") t->scheme = f.at(1);
      else if (f[0] == "fallback") t->byte_fallback = f.at(1) == "1";
      else if (f[0] == "prepend") t->prepend = f.at(1) == "1";
      else if (f[0] == "unk") t->unk_id = std::stoi(f.at(1));
      else if (f[0] == "ntok") {
        ntok = std::stoll(f.at(1));
        for (int64_t i = 0; i < ntok; ++i) {
          if (!std::getline(in, line)) throw std::runtime_error("eof");
          auto vf = fields(line);
          int id = std::stoi(vf.at(0));
          std::string tok = unesc(vf.at(1));
          if ((int64_t)t->id_to_tok.size() <= id) t->id_to_tok.resize(id + 1);
          t->id_to_tok[id] = tok;
          t->vocab[tok] = id;
        }
      } else if (f[0] == "nmerge") {
        nmerge = std::stoll(f.at(1));
        for (int64_t i = 0; i < nmerge; ++i) {
          if (!std::getline(in, line)) throw std::runtime_error("eof");
          auto mf = fields(line);
          t->merge_rank[merge_key(unesc(mf.at(0)), unesc(mf.at(1)))] = (int)i;
        }
      } else if (f[0] == "nspecial") {
        nspecial = std::stoll(f.at(1));
        for (int64_t i = 0; i < nspecial; ++i) {
          if (!std::getline(in, line)) throw std::runtime_error("eof");
          auto sf = fields(line);
          int id = std::stoi(sf.at(0));
          std::string tok = unesc(sf.at(1));
          t->specials[tok] = id;
          if ((int64_t)t->id_to_tok.size() <= id) t->id_to_tok.resize(id + 1);
          t->id_to_tok[id] = tok;
          t->vocab[tok] = id;
        }
      }
    }
  } catch (...) {
    delete t;
    return nullptr;
  }
  if (ntok < 0) { delete t; return nullptr; }
  t->special_list.reserve(t->specials.size());
  for (auto& kv : t->specials) t->special_list.push_back(kv.first);
  std::sort(t->special_list.begin(), t->special_list.end(),
            [](const std::string& a, const std::string& b) {
              return a.size() > b.size();
            });
  return t;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (shape mirrors the reference's tokenizers_c.h)
// ---------------------------------------------------------------------------

extern "C" {

void* dwt_tok_new(const char* blob, uint64_t len) {
  return parse_blob(std::string(blob, len));
}

void dwt_tok_free(void* h) { delete static_cast<Tok*>(h); }

// Encode text; result stays in the handle's buffer until the next call.
void dwt_tok_encode(void* h, const char* text, uint64_t len) {
  auto* t = static_cast<Tok*>(h);
  t->ids_buf.clear();
  encode(*t, std::string(text, len), t->ids_buf);
}

uint64_t dwt_tok_ids_len(void* h) {
  return static_cast<Tok*>(h)->ids_buf.size();
}

const int32_t* dwt_tok_ids(void* h) {
  return static_cast<Tok*>(h)->ids_buf.data();
}

void dwt_tok_decode(void* h, const int32_t* ids, uint64_t n,
                    int skip_special) {
  auto* t = static_cast<Tok*>(h);
  t->str_buf = decode(*t, ids, n, skip_special != 0);
}

uint64_t dwt_tok_str_len(void* h) {
  return static_cast<Tok*>(h)->str_buf.size();
}

const char* dwt_tok_str(void* h) {
  return static_cast<Tok*>(h)->str_buf.data();
}

int32_t dwt_tok_token_to_id(void* h, const char* tok, uint64_t len) {
  auto* t = static_cast<Tok*>(h);
  auto it = t->vocab.find(std::string(tok, len));
  return it == t->vocab.end() ? -1 : it->second;
}

// Writes the token string into the handle's buffer; returns 0 on bad id.
int dwt_tok_id_to_token(void* h, int32_t id) {
  auto* t = static_cast<Tok*>(h);
  if (id < 0 || (size_t)id >= t->id_to_tok.size()) return 0;
  t->str_buf = t->id_to_tok[id];
  return 1;
}

uint64_t dwt_tok_vocab_size(void* h) {
  return static_cast<Tok*>(h)->id_to_tok.size();
}

}  // extern "C"
