// Native wire codec: byte-identical to the Python reference implementation
// in ../wire.py (format "DWT1").  This is the TPU-host-side equivalent of
// the reference's cpp/utils.cpp:124-368 (SerializeTensorVectorToBytes /
// DeserializeTensorVectorFromBytes), with the portability defects fixed:
// explicit little-endian, fixed-width fields, magic+version header
// (reference used native endianness + size_t — SURVEY.md Appendix B #9).
//
// C ABI only (consumed from Python via ctypes — no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr char kMagic[4] = {'D', 'W', 'T', '1'};
constexpr uint8_t kVersion = 1;
constexpr size_t kHeaderSize = 4 + 1 + 1 + 2 + 4;
constexpr size_t kTensorHdrSize = 1 + 1 + 2 + 8;

// dtype -> element size; indices match wire.py DType.
constexpr int kItemSize[] = {4, 8, 2, 2, 1, 2, 4, 8, 1, 2, 4, 8, 1};
constexpr int kNumDTypes = 13;

// The wire is little-endian; so is every platform we build for (x86-64,
// arm64, TPU hosts).  Guard anyway so a big-endian port fails loudly.
static_assert(__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__,
              "wire codec assumes a little-endian host");

inline void put_u16(uint8_t* p, uint16_t v) { std::memcpy(p, &v, 2); }
inline void put_u32(uint8_t* p, uint32_t v) { std::memcpy(p, &v, 4); }
inline void put_u64(uint8_t* p, uint64_t v) { std::memcpy(p, &v, 8); }
inline uint32_t get_u32(const uint8_t* p) { uint32_t v; std::memcpy(&v, p, 4); return v; }
inline uint64_t get_u64(const uint8_t* p) { uint64_t v; std::memcpy(&v, p, 8); return v; }

struct TensorView {
  uint8_t dtype;
  uint8_t ndims;
  uint64_t nbytes;
  const uint8_t* dims;  // ndims x u64, little-endian, within the message
  const uint8_t* data;  // raw bytes within the message
};

struct Message {
  std::vector<uint8_t> owned;  // copy of the wire buffer
  std::vector<TensorView> tensors;
  uint8_t flags = 0;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Serialization.  Caller passes parallel arrays describing n tensors.
// ---------------------------------------------------------------------------

// Total wire size for the given tensor set; 0 on invalid input.
uint64_t dwt_serialized_size(uint32_t n, const uint8_t* dtypes,
                             const uint8_t* ndims,
                             const uint64_t* const* dims) {
  uint64_t total = kHeaderSize;
  for (uint32_t i = 0; i < n; ++i) {
    if (dtypes[i] >= kNumDTypes) return 0;
    uint64_t count = 1;
    for (uint8_t d = 0; d < ndims[i]; ++d) count *= dims[i][d];
    total += kTensorHdrSize + 8ull * ndims[i] + count * kItemSize[dtypes[i]];
  }
  return total;
}

// Write the message into out (of capacity out_len).  Returns bytes written,
// or 0 on error (bad dtype / insufficient capacity) — mirroring the
// reference serializer's size self-check (utils.cpp:250-261).
uint64_t dwt_serialize(uint32_t n, const uint8_t* dtypes, const uint8_t* ndims,
                       const uint64_t* const* dims,
                       const uint8_t* const* data, uint8_t flags,
                       uint8_t* out, uint64_t out_len) {
  uint64_t need = dwt_serialized_size(n, dtypes, ndims, dims);
  if (need == 0 || need > out_len) return 0;
  uint8_t* p = out;
  std::memcpy(p, kMagic, 4); p += 4;
  *p++ = kVersion;
  *p++ = flags;
  put_u16(p, 0); p += 2;
  put_u32(p, n); p += 4;
  for (uint32_t i = 0; i < n; ++i) {
    uint64_t count = 1;
    for (uint8_t d = 0; d < ndims[i]; ++d) count *= dims[i][d];
    uint64_t nbytes = count * kItemSize[dtypes[i]];
    *p++ = dtypes[i];
    *p++ = ndims[i];
    put_u16(p, 0); p += 2;
    put_u64(p, nbytes); p += 8;
    for (uint8_t d = 0; d < ndims[i]; ++d) { put_u64(p, dims[i][d]); p += 8; }
    std::memcpy(p, data[i], nbytes); p += nbytes;
  }
  return (uint64_t)(p - out) == need ? need : 0;
}

// ---------------------------------------------------------------------------
// Deserialization: open a message handle, then query tensors by index.
// ---------------------------------------------------------------------------

// Returns an opaque handle, or nullptr on malformed input.
void* dwt_open(const uint8_t* buf, uint64_t len) {
  if (len < kHeaderSize || std::memcmp(buf, kMagic, 4) != 0 ||
      buf[4] != kVersion) {
    return nullptr;
  }
  auto* msg = new Message();
  msg->owned.assign(buf, buf + len);
  const uint8_t* base = msg->owned.data();
  msg->flags = base[5];
  uint32_t n = get_u32(base + 6 + 2);
  uint64_t off = kHeaderSize;
  for (uint32_t i = 0; i < n; ++i) {
    if (off + kTensorHdrSize > len) { delete msg; return nullptr; }
    TensorView tv;
    tv.dtype = base[off];
    tv.ndims = base[off + 1];
    tv.nbytes = get_u64(base + off + 4);
    off += kTensorHdrSize;
    if (tv.dtype >= kNumDTypes || off + 8ull * tv.ndims > len) {
      delete msg; return nullptr;
    }
    tv.dims = base + off;
    // Overflow-safe element count: dims are attacker-controlled, so the
    // product must be checked against wrap before the nbytes comparison
    // (count*itemsize could wrap to a small value and "match").
    uint64_t count = 1;
    bool overflow = false;
    for (uint8_t d = 0; d < tv.ndims; ++d) {
      uint64_t dim = get_u64(tv.dims + 8 * d);
      if (dim != 0 && count > UINT64_MAX / dim) { overflow = true; break; }
      count *= dim;
    }
    uint64_t item = (uint64_t)kItemSize[tv.dtype];
    if (overflow || count > UINT64_MAX / item) { delete msg; return nullptr; }
    off += 8ull * tv.ndims;
    // off <= len is guaranteed above; compare against the remainder so a
    // huge nbytes cannot wrap off + nbytes back into range.
    if (count * item != tv.nbytes || tv.nbytes > len - off) {
      delete msg; return nullptr;
    }
    tv.data = base + off;
    off += tv.nbytes;
    msg->tensors.push_back(tv);
  }
  if (off != len) { delete msg; return nullptr; }  // trailing bytes
  return msg;
}

uint32_t dwt_ntensors(void* h) {
  return (uint32_t)static_cast<Message*>(h)->tensors.size();
}

uint8_t dwt_flags(void* h) { return static_cast<Message*>(h)->flags; }

// Fills dtype/ndims/nbytes and up to max_dims dims. Returns 0 on bad index.
int dwt_tensor_info(void* h, uint32_t i, uint8_t* dtype, uint8_t* ndims,
                    uint64_t* nbytes, uint64_t* dims_out, uint8_t max_dims) {
  auto* msg = static_cast<Message*>(h);
  if (i >= msg->tensors.size()) return 0;
  const TensorView& tv = msg->tensors[i];
  *dtype = tv.dtype;
  *ndims = tv.ndims;
  *nbytes = tv.nbytes;
  for (uint8_t d = 0; d < tv.ndims && d < max_dims; ++d) {
    dims_out[d] = get_u64(tv.dims + 8 * d);
  }
  return 1;
}

const uint8_t* dwt_tensor_data(void* h, uint32_t i) {
  auto* msg = static_cast<Message*>(h);
  if (i >= msg->tensors.size()) return nullptr;
  return msg->tensors[i].data;
}

void dwt_close(void* h) { delete static_cast<Message*>(h); }

// Token framing (reference utils.cpp:11-25), little-endian fixed.
void dwt_serialize_token(int32_t token, uint8_t out[4]) {
  std::memcpy(out, &token, 4);
}
int32_t dwt_deserialize_token(const uint8_t in[4]) {
  int32_t v; std::memcpy(&v, in, 4); return v;
}

}  // extern "C"
