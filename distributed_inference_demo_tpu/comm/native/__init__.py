"""Native (C++) comm components, built on demand with g++ (see build.py)."""
