"""Build the native comm library (codec + tokenizer) with g++.

No pybind11 in the image, so everything is a plain C ABI shared object
loaded via ctypes.  Build is on-demand and cached next to the sources;
``python -m distributed_inference_demo_tpu.comm.native.build`` forces a
rebuild.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

_DIR = Path(__file__).resolve().parent
SOURCES = ["codec.cc", "tokenizer.cc"]
LIB_NAME = "libdwt_native.so"


def lib_path() -> Path:
    return _DIR / LIB_NAME


def _needs_build() -> bool:
    lib = lib_path()
    if not lib.exists():
        return True
    lib_mtime = lib.stat().st_mtime
    return any((_DIR / s).exists() and (_DIR / s).stat().st_mtime > lib_mtime
               for s in SOURCES)


def build(force: bool = False) -> Path:
    """Compile the shared library if sources changed.  Returns its path."""
    lib = lib_path()
    if not force and not _needs_build():
        return lib
    srcs = [str(_DIR / s) for s in SOURCES if (_DIR / s).exists()]
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-Wall",
           "-o", str(lib)] + srcs
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return lib


if __name__ == "__main__":
    path = build(force=True)
    print(f"built {path} ({os.path.getsize(path)} bytes)")
