"""ctypes bindings for the native wire codec (native/codec.cc).

Byte-compatible with the pure-Python codec in ``wire.py``; `available()`
gates use so every caller can fall back to Python transparently.  The
reference's equivalent layer is the JNI bridge over ``utils.cpp``
(``native-lib.cpp:662-694``); here the binding is ctypes because pybind11
isn't in the image.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence

import numpy as np

from .wire import (_HEADER, _TO_NP, DType, TensorMessage, WireError,
                   _np_dtype_to_wire, payload_checksum, verify_checksum)

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        from .native.build import build
        lib = ctypes.CDLL(str(build()))
    except Exception:
        _load_failed = True
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.dwt_serialized_size.restype = ctypes.c_uint64
    lib.dwt_serialized_size.argtypes = [
        ctypes.c_uint32, u8p, u8p, ctypes.POINTER(u64p)]
    lib.dwt_serialize.restype = ctypes.c_uint64
    lib.dwt_serialize.argtypes = [
        ctypes.c_uint32, u8p, u8p, ctypes.POINTER(u64p),
        ctypes.POINTER(u8p), ctypes.c_uint8, u8p, ctypes.c_uint64]
    lib.dwt_open.restype = ctypes.c_void_p
    lib.dwt_open.argtypes = [u8p, ctypes.c_uint64]
    lib.dwt_ntensors.restype = ctypes.c_uint32
    lib.dwt_ntensors.argtypes = [ctypes.c_void_p]
    lib.dwt_flags.restype = ctypes.c_uint8
    lib.dwt_flags.argtypes = [ctypes.c_void_p]
    lib.dwt_tensor_info.restype = ctypes.c_int
    lib.dwt_tensor_info.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, u8p, u8p, u64p, u64p,
        ctypes.c_uint8]
    lib.dwt_tensor_data.restype = u8p
    lib.dwt_tensor_data.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.dwt_close.restype = None
    lib.dwt_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def serialize_tensors(arrays: Sequence[np.ndarray], flags: int = 0,
                      checksum: bool = True) -> bytes:
    lib = _load()
    if lib is None:
        raise WireError("native codec not available")
    def _contig(x):
        x = np.asarray(x)
        # Wire format is little-endian (wire.py does the same normalization);
        # byteswap any big-endian input before handing raw bytes to C++.
        if x.dtype.byteorder == ">":
            x = x.astype(x.dtype.newbyteorder("<"))
        # ascontiguousarray would promote 0-d to 1-d; 0-d is always contiguous
        return x if x.flags["C_CONTIGUOUS"] else np.ascontiguousarray(x)

    arrays = [_contig(a) for a in arrays]
    n = len(arrays)
    dtypes = (ctypes.c_uint8 * n)(*[int(_np_dtype_to_wire(a.dtype))
                                    for a in arrays])
    ndims = (ctypes.c_uint8 * n)(*[a.ndim for a in arrays])
    dim_arrays = [(ctypes.c_uint64 * a.ndim)(*a.shape) for a in arrays]
    dims = (ctypes.POINTER(ctypes.c_uint64) * n)(
        *[ctypes.cast(d, ctypes.POINTER(ctypes.c_uint64))
          for d in dim_arrays])
    datas = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[ctypes.cast(a.ctypes.data, ctypes.POINTER(ctypes.c_uint8))
          for a in arrays])
    size = lib.dwt_serialized_size(n, dtypes, ndims, dims)
    if size == 0 and n > 0:
        raise WireError("native serializer rejected input")
    out = ctypes.create_string_buffer(size)
    written = lib.dwt_serialize(
        n, dtypes, ndims, dims, datas, flags & 0xFF,
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), size)
    if written != size:
        raise WireError(f"native serializer wrote {written}, expected {size}")
    if not checksum:
        return out.raw
    # The C codec writes 0 into the header's 16-bit field; the binding
    # stamps the payload checksum (wire.payload_checksum — the ONE owner
    # of the math) so native and Python frames stay byte-identical.
    # zlib.crc32 runs at C speed, so there is no native-side win to chase.
    import struct as _struct
    buf = bytearray(out.raw)
    _struct.pack_into("<H", buf, 6,
                      payload_checksum(memoryview(buf)[_HEADER.size:]))
    return bytes(buf)


def deserialize_tensors(data: bytes) -> TensorMessage:
    lib = _load()
    if lib is None:
        raise WireError("native codec not available")
    # Same integrity contract as wire.deserialize_tensors: a nonzero
    # header checksum is verified BEFORE the C decoder touches any tensor
    # (WireIntegrityError, never garbage); zero = pre-checksum peer.
    verify_checksum(data)
    # Zero-copy handoff: c_char_p keeps a reference to `data`; dwt_open makes
    # its own owned copy, so no Python-side staging copy is needed.
    buf = ctypes.cast(ctypes.c_char_p(data),
                      ctypes.POINTER(ctypes.c_uint8))
    h = lib.dwt_open(buf, len(data))
    if not h:
        raise WireError("native codec rejected message")
    try:
        n = lib.dwt_ntensors(h)
        flags = lib.dwt_flags(h)
        out: List[np.ndarray] = []
        for i in range(n):
            dt = ctypes.c_uint8()
            nd = ctypes.c_uint8()
            nbytes = ctypes.c_uint64()
            dims = (ctypes.c_uint64 * 16)()
            ok = lib.dwt_tensor_info(
                h, i, ctypes.byref(dt), ctypes.byref(nd),
                ctypes.byref(nbytes), dims, 16)
            if not ok or nd.value > 16:
                raise WireError("native codec: bad tensor info")
            np_dt = _TO_NP[DType(dt.value)]
            ptr = lib.dwt_tensor_data(h, i)
            shape = tuple(dims[d] for d in range(nd.value))
            # Single copy, straight from the C++ buffer into the final
            # writable array (no string_at staging + trailing .copy()).
            arr = np.empty(shape, np_dt)
            if nbytes.value:
                ctypes.memmove(arr.ctypes.data, ptr, nbytes.value)
            out.append(arr)
        return TensorMessage(tensors=out, flags=flags)
    finally:
        lib.dwt_close(h)
