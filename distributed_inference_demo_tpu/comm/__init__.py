"""Communication layer: wire codec, transports, message schema.

The heterogeneous boundary of the framework: TPU mesh ranks talk to each
other via XLA collectives over ICI (parallel/), but CPU/edge workers and the
control plane talk over sockets.  This package owns that socket side —
replacing the reference's ZeroMQ + hand-rolled binary framing
(``utils.cpp:124-368``, ``Communication.java``).
"""

from .wire import (DType, FLAG_TRACE_CONTEXT, TensorMessage,
                   WireError, WireIntegrityError,
                   deserialize_tensors, payload_checksum, serialize_tensors,
                   serialize_tensors_traced, split_trace_context,
                   deserialize_token, serialize_token)

__all__ = ["DType", "FLAG_TRACE_CONTEXT", "TensorMessage",
           "WireError", "WireIntegrityError", "payload_checksum",
           "serialize_tensors", "serialize_tensors_traced",
           "split_trace_context", "deserialize_tensors",
           "serialize_token", "deserialize_token"]
