"""Inter-stage data-plane transports: ZMQ sockets + in-process loopback.

Replaces the reference's per-edge DEALER/ROUTER socket mesh with its
pull-based "Request Data" handshake and deterministic port arithmetic
(``Communication.java:712-744, 937-961``).  Design differences:

- **One inbound ROUTER per worker** instead of a socket set per concurrency
  slot; concurrent in-flight samples are demultiplexed by message *tag*
  (``kind:request_id:step``), not by socket identity.
- **Push with bounded queues** instead of request/reply pull: ZMQ high-water
  marks give the same backpressure property as the reference's handshake
  without paying an extra round-trip per tensor per hop.
- **Loopback transport** with the identical API for in-process multi-stage
  tests (SURVEY.md §4 calls out the reference's total lack of fake
  transports).
- **Bounded send retry** with exponential backoff + jitter and
  reconnect-on-hard-error (docs/DESIGN.md §12).  Safe end to end: ring
  receivers dedup by (rid, step), so a retried frame that duplicates is
  dropped above, never run into a KV cache twice.

Payloads are opaque bytes — tensor framing is wire.py's job; fault
injection wraps this layer (comm/faults.py) rather than living in it.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from typing import Dict, Optional, Tuple

import zmq

from ..telemetry._env import env_float, env_int

log = logging.getLogger(__name__)

DEFAULT_HWM = 64          # messages buffered per edge before backpressure

# send-retry knobs (docs/DESIGN.md §12 table).  Defaults keep the worst
# case bounded: 2 retries x (SNDTIMEO + backoff) on a dead peer, then the
# caller's TransportTimeout -> elastic reshard path takes over.
DEFAULT_SEND_RETRIES = env_int("DWT_TRANSPORT_SEND_RETRIES", 2)
DEFAULT_RETRY_BACKOFF_S = env_float("DWT_TRANSPORT_RETRY_BACKOFF_S", 0.05)


class TransportError(RuntimeError):
    pass


class TransportTimeout(TransportError):
    """recv deadline expired (replaces the reference's indefinite blocking
    ``recv(0)`` hangs, defect #7)."""


def _transport_metrics():
    """The dwt_transport_* counters, resolved lazily (telemetry.catalog
    pulls monitor probes at scrape time; the transport must stay cheap to
    import) and never fatally — a metrics regression must not take down
    the data plane."""
    try:
        from ..telemetry import catalog
        return catalog
    except Exception:       # pragma: no cover - defensive
        return None


def record_corrupt_frame(device_id: str, tag: str, nbytes: int,
                         err: Exception) -> None:
    """ONE owner for the corrupt-frame drop bookkeeping (worker + header
    + elastic receive paths): count ``dwt_transport_corrupt_frames_total``
    and flight-record the drop so a postmortem bundle shows which frame
    died.  The caller then DROPS the frame — the step-timeout/reshard
    path recovers; a wrong token never does."""
    cat = _transport_metrics()
    if cat is not None:
        try:
            cat.TRANSPORT_CORRUPT_FRAMES.inc()
        except Exception:   # pragma: no cover - defensive
            pass
    try:
        from ..telemetry.flightrecorder import get_flight_recorder
        get_flight_recorder().record(
            "corrupt_frame", stage=device_id, tag=tag, nbytes=nbytes,
            error=str(err))
    except Exception:       # pragma: no cover - defensive
        pass
    log.warning("%s: dropping corrupt frame tag=%r (%d bytes): %s",
                device_id, tag, nbytes, err)


class BaseTransport:
    """Tagged message transport between named peers.

    ``recv(tag)`` returns the payload for that tag, stashing any other
    messages that arrive meanwhile; ``recv_any()`` returns the next message
    of any tag — the worker-loop entry point.
    """

    def __init__(self, device_id: str):
        self.device_id = device_id
        self._inbox: "queue.Queue[Tuple[str, bytes]]" = queue.Queue()
        self._stash: Dict[str, list] = {}
        self._stash_lock = threading.Lock()

    # -- to be provided by implementations ---------------------------------

    def connect(self, peer_id: str, address: str) -> None:
        raise NotImplementedError

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    # -- shared receive logic ----------------------------------------------

    def _deliver(self, tag: str, payload: bytes) -> None:
        self._inbox.put((tag, payload))

    def recv_any(self, timeout: Optional[float] = None
                 ) -> Tuple[str, bytes]:
        """Next message of any tag (stashed messages first)."""
        with self._stash_lock:
            for tag, items in self._stash.items():
                if items:
                    payload = items.pop(0)
                    if not items:
                        del self._stash[tag]
                    return tag, payload
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout(
                f"{self.device_id}: no message within {timeout}s") from None

    def recv(self, tag: str, timeout: Optional[float] = None) -> bytes:
        """Payload for ``tag``; other arrivals are stashed, not dropped."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._stash_lock:
            items = self._stash.get(tag)
            if items:
                payload = items.pop(0)
                if not items:
                    del self._stash[tag]
                return payload
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                got_tag, payload = self._inbox.get(timeout=remaining)
            except queue.Empty:
                raise TransportTimeout(
                    f"{self.device_id}: no {tag!r} within {timeout}s"
                ) from None
            if got_tag == tag:
                return payload
            with self._stash_lock:
                self._stash.setdefault(got_tag, []).append(payload)


class ZmqTransport(BaseTransport):
    """Socket transport: inbound ROUTER (bound), one outbound DEALER per
    peer (connected lazily via ``connect``)."""

    def __init__(self, device_id: str, bind_host: str = "127.0.0.1",
                 port: int = 0, hwm: int = DEFAULT_HWM,
                 send_timeout: float = 60.0,
                 ctx: Optional[zmq.Context] = None,
                 send_retries: Optional[int] = None,
                 retry_backoff: Optional[float] = None):
        """``send_retries``/``retry_backoff``: bounded send retry with
        exponential backoff + jitter (None = the DWT_TRANSPORT_* env
        knobs, then the defaults).  A retry re-sends the SAME payload; a
        duplicate at the receiver is dropped by the (rid, step) dedup in
        the ring loops, so retrying is safe end to end."""
        super().__init__(device_id)
        self._ctx = ctx or zmq.Context.instance()
        self._hwm = hwm
        self._send_timeout_ms = int(send_timeout * 1000)
        self._send_retries = (DEFAULT_SEND_RETRIES if send_retries is None
                              else max(0, int(send_retries)))
        # per-ATTEMPT bound: send_timeout divides across the attempts so
        # retrying never stretches the total block past ~send_timeout —
        # the elastic header's step_timeout math (and its failure-signal
        # polling) assumes a send returns in bounded time
        self._attempt_timeout_ms = max(
            1, self._send_timeout_ms // (self._send_retries + 1))
        self._retry_backoff = (DEFAULT_RETRY_BACKOFF_S
                               if retry_backoff is None
                               else max(0.0, float(retry_backoff)))
        self._jitter = random.Random()   # non-crypto; spreads herd retries
        self._addrs: Dict[str, str] = {}
        self._in = self._ctx.socket(zmq.ROUTER)
        self._in.setsockopt(zmq.LINGER, 0)
        self._in.setsockopt(zmq.RCVHWM, hwm)
        # a reconnecting peer re-dials with the SAME identity; without
        # handover the ROUTER keeps routing to the half-dead old
        # connection until its teardown completes and silently drops the
        # new one's frames — the fresh connection must win immediately
        self._in.setsockopt(zmq.ROUTER_HANDOVER, 1)
        if port == 0:
            self.port = self._in.bind_to_random_port(f"tcp://{bind_host}")
        else:
            self._in.bind(f"tcp://{bind_host}:{port}")
            self.port = port
        self.address = f"{bind_host}:{self.port}"
        self._out: Dict[str, zmq.Socket] = {}
        self._out_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"transport-{device_id}")
        self._thread.start()

    def _pump(self) -> None:
        poller = zmq.Poller()
        poller.register(self._in, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=100)):
                continue
            frames = self._in.recv_multipart()
            # [sender identity, tag, payload]
            if len(frames) != 3:
                continue
            self._deliver(frames[1].decode(), frames[2])

    def _new_out_socket(self, address: str) -> zmq.Socket:
        sock = self._ctx.socket(zmq.DEALER)
        sock.setsockopt(zmq.IDENTITY, self.device_id.encode())
        sock.setsockopt(zmq.LINGER, 0)
        sock.setsockopt(zmq.SNDHWM, self._hwm)
        # A dead peer fills the HWM queue; a bounded send turns that
        # into TransportTimeout instead of an indefinite hang (the
        # send-side counterpart of reference defect #7).
        sock.setsockopt(zmq.SNDTIMEO, self._attempt_timeout_ms)
        sock.connect(f"tcp://{address}")
        return sock

    def connect(self, peer_id: str, address: str) -> None:
        with self._out_lock:
            if peer_id in self._out:
                return
            self._out[peer_id] = self._new_out_socket(address)
            self._addrs[peer_id] = address

    def _reconnect(self, peer_id: str) -> None:
        """Drop the peer's DEALER socket and dial a fresh one (ZMQ hides
        TCP reconnects for transient breaks; this handles the cases it
        can't — a socket broken by a hard error).  Caller holds no lock."""
        with self._out_lock:
            addr = self._addrs.get(peer_id)
            if addr is None:
                return
            old = self._out.pop(peer_id, None)
            if old is not None:
                try:
                    old.close(linger=0)
                except zmq.ZMQError:
                    pass
            try:
                self._out[peer_id] = self._new_out_socket(addr)
            except zmq.ZMQError as e:    # keep the peer absent; the next
                log.warning("%s: reconnect to %r failed: %s",  # retry or
                            self.device_id, peer_id, e)  # send() reports
                return
        cat = _transport_metrics()
        if cat is not None:
            try:
                cat.TRANSPORT_RECONNECTS.inc()
            except Exception:   # pragma: no cover - defensive
                pass

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        """Send with bounded retry: exponential backoff + jitter between
        attempts, and a reconnect after a hard socket error.  An
        unconnected peer fails immediately (config error, not flakiness);
        exhausted retries raise the LAST error — TransportTimeout for a
        blocked HWM (dead/slow peer), TransportError otherwise."""
        cat = _transport_metrics()
        delay = self._retry_backoff
        last_exc: Optional[TransportError] = None
        for attempt in range(self._send_retries + 1):
            if attempt:
                if cat is not None:
                    try:
                        cat.TRANSPORT_SEND_RETRIES.inc()
                    except Exception:   # pragma: no cover - defensive
                        pass
                time.sleep(delay + self._jitter.uniform(0, delay))
                delay *= 2
            # one lock hold for lookup + send: a concurrent close() cannot
            # invalidate the socket between the two
            with self._out_lock:
                sock = self._out.get(peer_id)
                if sock is None:
                    if attempt == 0:
                        raise TransportError(
                            f"{self.device_id}: peer {peer_id!r} not "
                            "connected")
                    # socket lost mid-retry (failed reconnect): fall
                    # through and retry the reconnect below
                    last_exc = last_exc or TransportError(
                        f"{self.device_id}: peer {peer_id!r} vanished")
                    err = "reconnect"
                else:
                    try:
                        sock.send_multipart([tag.encode(), payload])
                        return
                    except zmq.Again:
                        last_exc = TransportTimeout(
                            f"{self.device_id}: send to {peer_id!r} "
                            f"blocked > {self._attempt_timeout_ms} ms "
                            f"x {attempt + 1} attempts (peer dead?)")
                        err = "hwm"      # queue full: the socket is fine,
                    except zmq.ZMQError as e:     # reconnecting would drop
                        last_exc = TransportError(  # the queued messages
                            f"{self.device_id}: send to {peer_id!r} "
                            f"failed: {e}")
                        err = "socket"
            if err in ("socket", "reconnect"):
                self._reconnect(peer_id)
        raise last_exc from None

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        with self._out_lock:
            for sock in self._out.values():
                sock.close(linger=0)
            self._out.clear()
            self._addrs.clear()   # a racing _reconnect finds no address
        self._in.close(linger=0)


class LoopbackNetwork:
    """Shared in-process fabric for LoopbackTransport endpoints."""

    def __init__(self):
        self._endpoints: Dict[str, "LoopbackTransport"] = {}
        self._lock = threading.Lock()

    def register(self, t: "LoopbackTransport") -> None:
        with self._lock:
            self._endpoints[t.device_id] = t

    def deliver(self, peer_id: str, tag: str, payload: bytes) -> None:
        with self._lock:
            target = self._endpoints.get(peer_id)
        if target is None:
            raise TransportError(f"unknown loopback peer {peer_id!r}")
        target._deliver(tag, payload)


class LoopbackTransport(BaseTransport):
    """In-process fake with the ZmqTransport API (tests, single-host runs)."""

    def __init__(self, device_id: str, network: LoopbackNetwork):
        super().__init__(device_id)
        self._net = network
        self.address = f"loopback:{device_id}"
        network.register(self)

    def connect(self, peer_id: str, address: str) -> None:
        pass  # loopback needs no connection setup

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        self._net.deliver(peer_id, tag, payload)

    def close(self) -> None:
        pass
