"""Inter-stage data-plane transports: ZMQ sockets + in-process loopback.

Replaces the reference's per-edge DEALER/ROUTER socket mesh with its
pull-based "Request Data" handshake and deterministic port arithmetic
(``Communication.java:712-744, 937-961``).  Design differences:

- **One inbound ROUTER per worker** instead of a socket set per concurrency
  slot; concurrent in-flight samples are demultiplexed by message *tag*
  (``kind:request_id:step``), not by socket identity.
- **Push with bounded queues** instead of request/reply pull: ZMQ high-water
  marks give the same backpressure property as the reference's handshake
  without paying an extra round-trip per tensor per hop.
- **Loopback transport** with the identical API for in-process multi-stage
  tests (SURVEY.md §4 calls out the reference's total lack of fake
  transports).

Payloads are opaque bytes — tensor framing is wire.py's job.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional, Tuple

import zmq

DEFAULT_HWM = 64          # messages buffered per edge before backpressure


class TransportError(RuntimeError):
    pass


class TransportTimeout(TransportError):
    """recv deadline expired (replaces the reference's indefinite blocking
    ``recv(0)`` hangs, defect #7)."""


class BaseTransport:
    """Tagged message transport between named peers.

    ``recv(tag)`` returns the payload for that tag, stashing any other
    messages that arrive meanwhile; ``recv_any()`` returns the next message
    of any tag — the worker-loop entry point.
    """

    def __init__(self, device_id: str):
        self.device_id = device_id
        self._inbox: "queue.Queue[Tuple[str, bytes]]" = queue.Queue()
        self._stash: Dict[str, list] = {}
        self._stash_lock = threading.Lock()

    # -- to be provided by implementations ---------------------------------

    def connect(self, peer_id: str, address: str) -> None:
        raise NotImplementedError

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    # -- shared receive logic ----------------------------------------------

    def _deliver(self, tag: str, payload: bytes) -> None:
        self._inbox.put((tag, payload))

    def recv_any(self, timeout: Optional[float] = None
                 ) -> Tuple[str, bytes]:
        """Next message of any tag (stashed messages first)."""
        with self._stash_lock:
            for tag, items in self._stash.items():
                if items:
                    payload = items.pop(0)
                    if not items:
                        del self._stash[tag]
                    return tag, payload
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout(
                f"{self.device_id}: no message within {timeout}s") from None

    def recv(self, tag: str, timeout: Optional[float] = None) -> bytes:
        """Payload for ``tag``; other arrivals are stashed, not dropped."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._stash_lock:
            items = self._stash.get(tag)
            if items:
                payload = items.pop(0)
                if not items:
                    del self._stash[tag]
                return payload
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                got_tag, payload = self._inbox.get(timeout=remaining)
            except queue.Empty:
                raise TransportTimeout(
                    f"{self.device_id}: no {tag!r} within {timeout}s"
                ) from None
            if got_tag == tag:
                return payload
            with self._stash_lock:
                self._stash.setdefault(got_tag, []).append(payload)


class ZmqTransport(BaseTransport):
    """Socket transport: inbound ROUTER (bound), one outbound DEALER per
    peer (connected lazily via ``connect``)."""

    def __init__(self, device_id: str, bind_host: str = "127.0.0.1",
                 port: int = 0, hwm: int = DEFAULT_HWM,
                 send_timeout: float = 60.0,
                 ctx: Optional[zmq.Context] = None):
        super().__init__(device_id)
        self._ctx = ctx or zmq.Context.instance()
        self._hwm = hwm
        self._send_timeout_ms = int(send_timeout * 1000)
        self._in = self._ctx.socket(zmq.ROUTER)
        self._in.setsockopt(zmq.LINGER, 0)
        self._in.setsockopt(zmq.RCVHWM, hwm)
        if port == 0:
            self.port = self._in.bind_to_random_port(f"tcp://{bind_host}")
        else:
            self._in.bind(f"tcp://{bind_host}:{port}")
            self.port = port
        self.address = f"{bind_host}:{self.port}"
        self._out: Dict[str, zmq.Socket] = {}
        self._out_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"transport-{device_id}")
        self._thread.start()

    def _pump(self) -> None:
        poller = zmq.Poller()
        poller.register(self._in, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=100)):
                continue
            frames = self._in.recv_multipart()
            # [sender identity, tag, payload]
            if len(frames) != 3:
                continue
            self._deliver(frames[1].decode(), frames[2])

    def connect(self, peer_id: str, address: str) -> None:
        with self._out_lock:
            if peer_id in self._out:
                return
            sock = self._ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.IDENTITY, self.device_id.encode())
            sock.setsockopt(zmq.LINGER, 0)
            sock.setsockopt(zmq.SNDHWM, self._hwm)
            # A dead peer fills the HWM queue; a bounded send turns that
            # into TransportTimeout instead of an indefinite hang (the
            # send-side counterpart of reference defect #7).
            sock.setsockopt(zmq.SNDTIMEO, self._send_timeout_ms)
            sock.connect(f"tcp://{address}")
            self._out[peer_id] = sock

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        # one lock hold for lookup + send: a concurrent close() cannot
        # invalidate the socket between the two
        with self._out_lock:
            sock = self._out.get(peer_id)
            if sock is None:
                raise TransportError(
                    f"{self.device_id}: peer {peer_id!r} not connected")
            try:
                sock.send_multipart([tag.encode(), payload])
            except zmq.Again:
                raise TransportTimeout(
                    f"{self.device_id}: send to {peer_id!r} blocked "
                    f"> {self._send_timeout_ms} ms (peer dead?)") from None
            except zmq.ZMQError as e:
                raise TransportError(
                    f"{self.device_id}: send to {peer_id!r} failed: {e}"
                ) from None

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        with self._out_lock:
            for sock in self._out.values():
                sock.close(linger=0)
            self._out.clear()
        self._in.close(linger=0)


class LoopbackNetwork:
    """Shared in-process fabric for LoopbackTransport endpoints."""

    def __init__(self):
        self._endpoints: Dict[str, "LoopbackTransport"] = {}
        self._lock = threading.Lock()

    def register(self, t: "LoopbackTransport") -> None:
        with self._lock:
            self._endpoints[t.device_id] = t

    def deliver(self, peer_id: str, tag: str, payload: bytes) -> None:
        with self._lock:
            target = self._endpoints.get(peer_id)
        if target is None:
            raise TransportError(f"unknown loopback peer {peer_id!r}")
        target._deliver(tag, payload)


class LoopbackTransport(BaseTransport):
    """In-process fake with the ZmqTransport API (tests, single-host runs)."""

    def __init__(self, device_id: str, network: LoopbackNetwork):
        super().__init__(device_id)
        self._net = network
        self.address = f"loopback:{device_id}"
        network.register(self)

    def connect(self, peer_id: str, address: str) -> None:
        pass  # loopback needs no connection setup

    def send(self, peer_id: str, tag: str, payload: bytes) -> None:
        self._net.deliver(peer_id, tag, payload)

    def close(self) -> None:
        pass
