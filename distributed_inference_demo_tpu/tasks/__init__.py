from .classification import (ClassificationDataset, evaluate_classifier,
                             load_csv_dataset)

__all__ = ["ClassificationDataset", "evaluate_classifier",
           "load_csv_dataset"]
