"""Classification task: dataset loading + accuracy evaluation.

The reference's classification path — CSV ``text,label`` dataset loader
(``Dataset.java:20-44``), binary-classification inference variant
(``cpp/inference.cpp:220-270``, JNI ``native-lib.cpp:1305-1366``) and the
accuracy loop in ``BackgroundService.java:233-245`` — re-designed for the
TPU engine: classification is a single KV-less prefill whose last-position
logits are restricted to one verbalizer token id per class and argmaxed
(``InferenceEngine.classify`` single-chip,
``PipelineHeader.classify_many`` over a pipeline).
"""

from __future__ import annotations

import csv
from dataclasses import dataclass, field
from typing import Callable, List, Sequence

import numpy as np


@dataclass
class ClassificationDataset:
    """Parallel lists of texts and integer labels, plus the label names in
    index order (``label_names[labels[i]]`` is row i's original label)."""

    texts: List[str]
    labels: List[int]
    label_names: List[str] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.texts)


def load_csv_dataset(path: str, text_col: int = 0,
                     label_col: int = 1, skip_header: bool = False
                     ) -> ClassificationDataset:
    """Load a ``text,label`` CSV (the reference's eval format,
    ``Dataset.java:20-44``).  Labels may be ints or names; names are mapped
    to indices in first-seen order."""
    texts: List[str] = []
    raw_labels: List[str] = []
    with open(path, newline="") as f:
        for i, row in enumerate(csv.reader(f)):
            if not row or (skip_header and i == 0):
                continue
            texts.append(row[text_col])
            raw_labels.append(row[label_col].strip())

    names: List[str] = []
    index = {}
    labels = []
    for lab in raw_labels:
        if lab not in index:
            index[lab] = len(names)
            names.append(lab)
        labels.append(index[lab])
    return ClassificationDataset(texts=texts, labels=labels,
                                 label_names=names)


def evaluate_classifier(
    classify_fn: Callable[[np.ndarray], np.ndarray],
    prompts: Sequence[np.ndarray],
    labels: Sequence[int],
    batch_size: int = 8,
) -> dict:
    """Accuracy loop (reference ``BackgroundService.java:233-245``).

    ``classify_fn`` maps a [b, s] int32 prompt batch to [b] predicted label
    indices (``InferenceEngine.classify`` / ``PipelineHeader.classify_many``
    partials).  ``prompts`` is one [1, s] array per example (ragged lengths
    allowed — batches group equal-length prompts to keep shapes static for
    jit).  Returns {"accuracy", "correct", "total", "predictions"}.
    """
    if len(prompts) != len(labels):
        raise ValueError("prompts and labels must align")
    by_len: dict = {}
    for i, p in enumerate(prompts):
        p = np.asarray(p)
        if p.ndim == 1:
            p = p[None, :]
        by_len.setdefault(p.shape[1], []).append((i, p))

    preds = np.full(len(prompts), -1, np.int32)
    for _, group in sorted(by_len.items()):
        for start in range(0, len(group), batch_size):
            chunk = group[start:start + batch_size]
            batch = np.concatenate([p for _, p in chunk], axis=0)
            out = np.asarray(classify_fn(batch)).reshape(-1)
            for (i, _), pred in zip(chunk, out):
                preds[i] = pred

    labels_arr = np.asarray(labels, np.int32)
    correct = int((preds == labels_arr).sum())
    return {"accuracy": correct / max(1, len(labels)),
            "correct": correct, "total": len(labels),
            "predictions": preds.tolist()}
