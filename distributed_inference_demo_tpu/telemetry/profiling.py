"""The cost observatory: per-dispatch profiling, compile accounting,
HBM watermarks, and workload sketches (docs/DESIGN.md §20).

The auto-planner (ROADMAP item 3) needs *measured* artifacts at
dispatch granularity — what each jitted program class actually costs,
how often XLA recompiles, how big the pools really got, and what the
live workload looks like.  This module is the measurement half: four
stitched parts sharing one module-level observatory so every engine,
worker and HTTP surface in the process reports into the same ledger.

1. :class:`DispatchProfiler` — a sampled ``block_until_ready`` timer
   around each jitted program class, keyed by a stable *dispatch
   signature* (``program|b<batch-bucket>|c<chunk-or-K>|<kv_dtype>``).
   Sampling (``DWT_PROFILE_SAMPLE_N``, default every 64th dispatch per
   signature; ``0`` disables) keeps the off-path free: an unsampled
   dispatch is one dict increment and one modulo — ZERO added device
   syncs, no rng spend, no numeric change.  A sampled dispatch blocks
   on the outputs (a sync the fused paths already pay via their
   ``int(steps)`` readback) and records wall time plus an achieved-
   bytes/s attribution computed from the one-owner KV byte math in
   ``ops/quant.py``, reconciled against ``ROOFLINE_LEDGER.json``.

2. :class:`CompileTracker` — wraps jitted callables at their creation
   site and counts cache-entry growth per program variant (compiles,
   compile-seconds, live cache entries, documented variant budget).
   The ``stats()["compile"]`` fragment feeds ``anomaly.py``'s
   ``recompile_storm`` detector: a program compiling past its budget
   (e.g. ``_mixed_step``'s two-variant invariant, §19) becomes a named
   anomaly + postmortem bundle instead of a silent latency cliff.

3. :class:`HbmWatermarks` — high-water-mark ledger per pool owner
   (``kv_page_pool``, ``kv_host_pool``, ``draft_scratch``,
   ``stage_pool``, ``migration_staged``), sampled at scheduler
   iterations.  "How big could the pool have been" is answered from
   ``dwt_hbm_*`` telemetry instead of OOM bisection.  Watermarks are
   monotone until :meth:`HbmWatermarks.reset` (engine close resets its
   own owners).

4. :class:`WorkloadSketchRecorder` — streaming fixed-bucket histogram
   sketches of the live workload (prompt length, interarrival,
   prefix-hit share, tenant mix, decode lengths).  No RNG reservoir:
   every sketch is a pure fold over the request trace, so the JSON
   artifact (``GET /sketch``, ``tools/sketch.py``) is byte-identical
   for identical traces.  The schema (``SKETCH_SCHEMA_VERSION``) is
   the planner's workload-input contract — ``planner/planner.py`` pins
   the same version and ``tools/check_sketch_schema.py`` lints the
   agreement.

Metric emission is lazy (``catalog`` imported inside the slow paths)
so this module stays importable without pulling the full telemetry
surface, and pure-Python snapshots stay testable without a registry.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ._env import env_float, env_int

# -- knobs ------------------------------------------------------------------

#: default: time every 64th dispatch per signature (0 disables).
DEFAULT_SAMPLE_N = 64

#: pinned with ``planner.SKETCH_SCHEMA_VERSION`` by
#: ``tools/check_sketch_schema.py`` — bump BOTH together.
SKETCH_SCHEMA_VERSION = 1

#: top-level keys every sketch artifact carries (the planner's parse
#: contract; pinned with ``planner.SKETCH_REQUIRED_KEYS`` by the lint).
SKETCH_REQUIRED_KEYS = ("schema_version", "window_s", "requests",
                        "tenants", "prompt_tokens", "decode_tokens",
                        "interarrival_s", "prefix_hit")


def profile_sample_n() -> int:
    """``DWT_PROFILE_SAMPLE_N`` (>=0; 0 = profiling off-path entirely)."""
    return max(0, env_int("DWT_PROFILE_SAMPLE_N", DEFAULT_SAMPLE_N))


# -- dispatch signatures ----------------------------------------------------

def batch_bucket(n: int) -> int:
    """Next power of two ≥ n — signatures must not fork per exact batch
    size (slots vary by ±1 constantly; the cost regime doesn't)."""
    n = max(1, int(n))
    b = 1
    while b < n:
        b <<= 1
    return b


def dispatch_signature(program: str, batch: int = 1, chunk: int = 0,
                       kv_dtype: str = "bf16") -> str:
    """The stable key every observatory artifact joins on:
    ``program|b<batch-bucket>|c<chunk-or-K>|<kv_dtype>``.

    ``chunk`` is the program's second shape knob — prefill chunk
    length, fused rounds K, or draft length — whatever forks a compiled
    variant.  Identical call shapes MUST map to identical signatures
    (pinned by ``tests/test_profiling.py``)."""
    return (f"{program}|b{batch_bucket(batch)}|c{max(0, int(chunk))}"
            f"|{kv_dtype}")


def parse_signature(sig: str) -> dict:
    """Inverse of :func:`dispatch_signature` (tools-side: merge keys)."""
    parts = sig.split("|")
    if len(parts) != 4 or not parts[1].startswith("b") \
            or not parts[2].startswith("c"):
        raise ValueError(f"not a dispatch signature: {sig!r}")
    return {"program": parts[0], "batch_bucket": int(parts[1][1:]),
            "chunk": int(parts[2][1:]), "kv_dtype": parts[3]}


# -- roofline reconciliation ------------------------------------------------

_ROOFLINE_CACHE: List[Optional[float]] = []


def roofline_ceiling_gbs() -> Optional[float]:
    """The HBM GB/s ceiling achieved-bandwidth attributions reconcile
    against: ``DWT_ROOFLINE_GBS`` env override, else the max entry in
    the repo's ``ROOFLINE_LEDGER.json``, else None (no frac emitted).
    Cached after first read (the ledger is a committed artifact)."""
    env = env_float("DWT_ROOFLINE_GBS", 0.0)
    if env > 0:
        return env
    if _ROOFLINE_CACHE:
        return _ROOFLINE_CACHE[0]
    ceiling: Optional[float] = None
    try:
        import pathlib
        path = (pathlib.Path(__file__).resolve().parents[2]
                / "ROOFLINE_LEDGER.json")
        ledger = json.loads(path.read_text())
        vals = [float(v["hbm_gbs"]) for v in ledger.values()
                if isinstance(v, dict) and "hbm_gbs" in v]
        ceiling = max(vals) if vals else None
    except Exception:
        ceiling = None
    _ROOFLINE_CACHE.append(ceiling)
    return ceiling


def kv_dispatch_bytes(tokens: int, layers: int, kv_heads: int,
                      head_dim: int, kv_dtype: Optional[str],
                      base_dtype) -> int:
    """HBM bytes the KV pages contribute to one dispatch touching
    ``tokens`` (written or read), through the one-owner per-(token,
    head) byte math in ``ops/quant.py`` — K and V both counted.  An
    *attribution*, not a meter: weights and activations ride on top,
    so per-signature achieved-bytes/s is a lower bound."""
    from ..ops.quant import kv_token_head_bytes
    return (max(0, int(tokens)) * max(1, int(layers))
            * max(1, int(kv_heads)) * 2
            * kv_token_head_bytes(head_dim, kv_dtype, base_dtype))


# -- 1. dispatch profiler ---------------------------------------------------

class _SigStats:
    """Per-signature accumulator: exact dispatch count, sampled-timing
    sums, and a last-256 duration window for deterministic percentiles
    (no RNG reservoir)."""

    __slots__ = ("dispatches", "samples", "total_s", "durations",
                 "bytes_total", "last_gbs")

    def __init__(self) -> None:
        self.dispatches = 0
        self.samples = 0
        self.total_s = 0.0
        self.durations: deque = deque(maxlen=256)
        self.bytes_total = 0
        self.last_gbs = 0.0


def _percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


class DispatchProfiler:
    """Sampled ``block_until_ready`` timer keyed by dispatch signature.

    Hot-path contract: :meth:`begin` on an UNSAMPLED dispatch is one
    dict increment + one modulo and returns ``None``; :meth:`end` with
    ``t0 is None`` returns immediately.  No sync, no allocation, no
    metric-registry lock ever touches the unsampled path.  With
    ``sample_n == 0`` even the dispatch counting is skipped — the
    observatory is then bit-for-bit absent from the engine's behavior.
    """

    def __init__(self, sample_n: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.sample_n = (profile_sample_n() if sample_n is None
                         else max(0, int(sample_n)))
        self._clock = clock
        self._lock = threading.Lock()
        self._stats: Dict[str, _SigStats] = {}
        self._counts: Dict[str, int] = {}

    # hot path ---------------------------------------------------------
    def begin(self, sig: str) -> Optional[float]:
        """Start-of-dispatch: returns a t0 only when THIS dispatch is
        sampled (every ``sample_n``-th per signature), else None."""
        n = self.sample_n
        if n <= 0:
            return None
        c = self._counts.get(sig, 0) + 1
        self._counts[sig] = c
        if c % n:
            return None
        return self._clock()

    def end(self, sig: str, t0: Optional[float], out=None,
            hbm_bytes: int = 0) -> Optional[float]:
        """End-of-dispatch: no-op unless :meth:`begin` sampled it.
        Blocks on ``out`` (any jax pytree) so the timer measures device
        completion, records the duration, and attributes achieved
        bytes/s when the call site passed an ``hbm_bytes`` estimate."""
        if t0 is None:
            return None
        if out is not None:
            try:
                import jax
                jax.block_until_ready(out)
            except Exception:
                pass
        dt = max(1e-9, self._clock() - t0)
        with self._lock:
            s = self._stats.setdefault(sig, _SigStats())
            s.samples += 1
            s.total_s += dt
            s.durations.append(dt)
            if hbm_bytes > 0:
                s.bytes_total += int(hbm_bytes)
                s.last_gbs = hbm_bytes / dt / 1e9
        self._observe_metric(sig, dt, hbm_bytes)
        return dt

    # slow path --------------------------------------------------------
    def _observe_metric(self, sig: str, dt: float,
                        hbm_bytes: int) -> None:
        try:
            from . import catalog
            catalog.PROFILE_DISPATCH_SECONDS.observe(dt, signature=sig)
            catalog.PROFILE_SAMPLES.inc(signature=sig)
            if hbm_bytes > 0:
                bps = hbm_bytes / dt
                catalog.PROFILE_ACHIEVED_BPS.set(round(bps, 1),
                                                 signature=sig)
                ceil = roofline_ceiling_gbs()
                if ceil:
                    catalog.PROFILE_ROOFLINE_FRAC.set(
                        round(bps / (ceil * 1e9), 4), signature=sig)
        except Exception:
            pass

    def snapshot(self) -> dict:
        """Deterministic per-signature summary (sorted keys, rounded
        floats) — what ``/debugz``, bench extras and the probe tools
        all export."""
        ceil = roofline_ceiling_gbs()
        out: Dict[str, dict] = {}
        with self._lock:
            for sig in sorted(self._stats):
                s = self._stats[sig]
                durs = sorted(s.durations)
                entry = {
                    "dispatches": self._counts.get(sig, 0),
                    "samples": s.samples,
                    "p50_ms": round(_percentile(durs, 0.50) * 1e3, 4),
                    "p95_ms": round(_percentile(durs, 0.95) * 1e3, 4),
                    "mean_ms": round(s.total_s / s.samples * 1e3, 4)
                    if s.samples else 0.0,
                }
                if s.bytes_total:
                    entry["achieved_gbs"] = round(
                        s.bytes_total / s.total_s / 1e9, 3)
                    if ceil:
                        entry["roofline_frac"] = round(
                            entry["achieved_gbs"] / ceil, 4)
                out[sig] = entry
        return out

    def dispatch_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._counts.clear()


# -- 2. compile observability -----------------------------------------------

class _TrackedJit:
    """A jitted callable wrapped for cache-entry accounting.  Calls
    pass straight through (donation, statics and AOT attributes all
    reach the inner jit via ``__getattr__``); when the inner call grew
    the jit cache, the call's wall time is booked as compile-seconds
    (trace+lower+compile dominate a first call)."""

    __slots__ = ("inner", "_tracker", "_program", "_countable")

    def __init__(self, fn, tracker: "CompileTracker", program: str):
        self.inner = fn
        self._tracker = tracker
        self._program = program
        self._countable = hasattr(fn, "_cache_size")

    def _entries(self) -> Optional[int]:
        if not self._countable:
            return None
        try:
            return int(self.inner._cache_size())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._entries()
        if before is None:
            return self.inner(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.inner(*args, **kwargs)
        after = self._entries()
        if after is not None and after > before:
            self._tracker.note_compile(
                self._program, n=after - before,
                seconds=time.perf_counter() - t0, cache_entries=after)
        return out

    def __getattr__(self, name):
        return getattr(self.inner, name)


class CompileTracker:
    """Per-program compile ledger.  ``variant_budget`` documents how
    many compiled variants a program is ALLOWED (``mixed_step``: two —
    the §19 invariant); the anomaly layer turns budget overruns into
    ``recompile_storm``.  Wrapping the same program name again (a
    second engine in-process) accumulates into the same entry."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: Dict[str, dict] = {}

    def wrap(self, program: str, fn, variant_budget: Optional[int] = None):
        with self._lock:
            e = self._programs.setdefault(program, {
                "compiles": 0, "compile_seconds": 0.0,
                "variant_budget": None, "cache_entries": 0})
            if variant_budget is not None:
                # a fresh engine resets the budget meaning: its warmup
                # variants are new cache entries on a new jit object
                e["variant_budget"] = int(variant_budget)
        return _TrackedJit(fn, self, program)

    def note_compile(self, program: str, n: int = 1,
                     seconds: float = 0.0,
                     cache_entries: Optional[int] = None) -> None:
        with self._lock:
            e = self._programs.setdefault(program, {
                "compiles": 0, "compile_seconds": 0.0,
                "variant_budget": None, "cache_entries": 0})
            e["compiles"] += max(1, int(n))
            e["compile_seconds"] += max(0.0, float(seconds))
            if cache_entries is not None:
                e["cache_entries"] = int(cache_entries)

    def snapshot(self) -> dict:
        """Deterministic ``{program: {compiles, compile_seconds,
        variant_budget, cache_entries}}`` — the ``stats()["compile"]``
        fragment the anomaly detector reads."""
        with self._lock:
            return {p: {"compiles": e["compiles"],
                        "compile_seconds": round(e["compile_seconds"], 4),
                        "variant_budget": e["variant_budget"],
                        "cache_entries": e["cache_entries"]}
                    for p, e in sorted(self._programs.items())}

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()


# -- 3. HBM watermark ledger ------------------------------------------------

class HbmWatermarks:
    """High-water-mark bytes per pool owner.  ``sample`` is called at
    scheduler iterations with the owner's CURRENT resident bytes; the
    watermark only ever grows until :meth:`reset` (monotone — pinned by
    tests), so a pool's worst case survives the quiet period after the
    burst that caused it.

    Owners: ``kv_page_pool``, ``kv_host_pool``, ``draft_scratch``,
    ``stage_pool``, ``migration_staged``, and (despite the ledger's
    name) ``host_tier`` — the §21 demoted-prefix ring's host-RAM bytes
    ride the same postmortem surface and the same reset-on-close."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._current: Dict[str, int] = {}
        self._hwm: Dict[str, int] = {}

    def sample(self, owner: str, nbytes: int) -> None:
        cur = max(0, int(nbytes))
        with self._lock:
            self._current[owner] = cur
            if cur > self._hwm.get(owner, 0):
                self._hwm[owner] = cur

    def watermarks(self) -> dict:
        with self._lock:
            return {o: {"bytes": self._current.get(o, 0),
                        "watermark_bytes": self._hwm[o]}
                    for o in sorted(self._hwm)}

    def reset(self, owner: Optional[str] = None) -> None:
        """Drop one owner's ledger (engine close resets the owners it
        fed) or, with no argument, everything."""
        with self._lock:
            if owner is None:
                self._current.clear()
                self._hwm.clear()
            else:
                self._current.pop(owner, None)
                self._hwm.pop(owner, None)


# -- 4. workload sketch recorder --------------------------------------------

PROMPT_TOKEN_EDGES = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)
DECODE_TOKEN_EDGES = (4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048)
INTERARRIVAL_EDGES_S = (0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 15.0)


class _Hist:
    """Fixed-edge streaming histogram: deterministic, mergeable.
    ``counts[i]`` = values ≤ ``edges[i]``; the last bin is overflow."""

    __slots__ = ("edges", "counts", "total", "count", "max")

    def __init__(self, edges: Tuple[float, ...]):
        self.edges = tuple(edges)
        self.counts = [0] * (len(edges) + 1)
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, v: float) -> None:
        v = max(0.0, float(v))
        self.counts[bisect.bisect_left(self.edges, v)] += 1
        self.total += v
        self.count += 1
        if v > self.max:
            self.max = v

    def percentile(self, p: float) -> float:
        """Upper edge of the bucket holding the p-quantile (the
        planner's conservative read; overflow reports the max seen)."""
        if not self.count:
            return 0.0
        target = p * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return (float(self.edges[i]) if i < len(self.edges)
                        else self.max)
        return self.max

    def to_dict(self) -> dict:
        return {"edges": list(self.edges), "counts": list(self.counts),
                "sum": round(self.total, 6), "count": self.count,
                "max": round(self.max, 6)}

    def merge_dict(self, d: dict) -> None:
        if tuple(d.get("edges", ())) != self.edges:
            raise ValueError("sketch histogram edges disagree")
        for i, c in enumerate(d.get("counts", ())):
            self.counts[i] += int(c)
        self.total += float(d.get("sum", 0.0))
        self.count += int(d.get("count", 0))
        self.max = max(self.max, float(d.get("max", 0.0)))


class WorkloadSketchRecorder:
    """Streaming workload sketch.  Every record method takes explicit
    values (and an explicit ``now`` for interarrival) — no internal
    clock, no RNG — so an identical request trace folds to a
    byte-identical artifact (pinned by tests)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.requests = 0
        self.tenants: Dict[str, int] = {}
        self.prompt_tokens = _Hist(PROMPT_TOKEN_EDGES)
        self.decode_tokens = _Hist(DECODE_TOKEN_EDGES)
        self.interarrival_s = _Hist(INTERARRIVAL_EDGES_S)
        self.prefix_matched = 0
        self.prefix_prompt = 0
        self._last_arrival: Optional[float] = None
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    def record_request(self, prompt_tokens: int,
                       tenant: str = "default",
                       now: Optional[float] = None) -> None:
        with self._lock:
            self.requests += 1
            self.tenants[tenant] = self.tenants.get(tenant, 0) + 1
            self.prompt_tokens.add(prompt_tokens)
            if now is not None:
                if self._last_arrival is not None:
                    self.interarrival_s.add(now - self._last_arrival)
                self._last_arrival = now
                self._t_first = (now if self._t_first is None
                                 else self._t_first)
                self._t_last = now

    def record_prefix(self, matched_tokens: int,
                      prompt_tokens: int) -> None:
        with self._lock:
            self.prefix_matched += max(0, int(matched_tokens))
            self.prefix_prompt += max(0, int(prompt_tokens))

    def record_decode(self, decode_tokens: int) -> None:
        with self._lock:
            self.decode_tokens.add(decode_tokens)

    def snapshot(self) -> dict:
        """The sketch artifact, schema ``SKETCH_SCHEMA_VERSION`` — the
        planner's workload input."""
        with self._lock:
            share = (round(self.prefix_matched / self.prefix_prompt, 6)
                     if self.prefix_prompt else 0.0)
            window = (round(self._t_last - self._t_first, 6)
                      if self._t_first is not None else 0.0)
            return {
                "schema_version": SKETCH_SCHEMA_VERSION,
                "window_s": window,
                "requests": self.requests,
                "tenants": dict(sorted(self.tenants.items())),
                "prompt_tokens": self.prompt_tokens.to_dict(),
                "decode_tokens": self.decode_tokens.to_dict(),
                "interarrival_s": self.interarrival_s.to_dict(),
                "prefix_hit": {"matched_tokens": self.prefix_matched,
                               "prompt_tokens": self.prefix_prompt,
                               "share": share},
            }

    def to_json(self) -> str:
        """Canonical bytes: sorted keys, minimal separators, rounded
        floats — the determinism contract ``GET /sketch`` serves."""
        return render_sketch(self.snapshot())

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()


def render_sketch(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def merge_sketches(sections: List[Tuple[str, dict]]) -> dict:
    """Merge per-replica sketch artifacts into one fleet sketch —
    deterministic (sections sorted by replica id; histograms summed
    bin-wise; the fleet interarrival histogram is the per-replica SUM,
    an approximation the artifact labels honestly).  Skips sections
    whose schema version disagrees (counted in ``dropped``)."""
    acc = WorkloadSketchRecorder()
    replicas: List[str] = []
    dropped: List[str] = []
    for rid, obj in sorted(sections, key=lambda kv: kv[0]):
        if not isinstance(obj, dict) or \
                obj.get("schema_version") != SKETCH_SCHEMA_VERSION:
            dropped.append(rid)
            continue
        replicas.append(rid)
        acc.requests += int(obj.get("requests", 0))
        for t, n in (obj.get("tenants") or {}).items():
            acc.tenants[t] = acc.tenants.get(t, 0) + int(n)
        for name in ("prompt_tokens", "decode_tokens", "interarrival_s"):
            frag = obj.get(name)
            if isinstance(frag, dict):
                getattr(acc, name).merge_dict(frag)
        ph = obj.get("prefix_hit") or {}
        acc.prefix_matched += int(ph.get("matched_tokens", 0))
        acc.prefix_prompt += int(ph.get("prompt_tokens", 0))
    out = acc.snapshot()
    out["window_s"] = max((float(o.get("window_s", 0.0))
                           for _, o in sections
                           if isinstance(o, dict)), default=0.0)
    out["replicas"] = replicas
    if dropped:
        out["dropped_replicas"] = sorted(dropped)
    return out


# -- the process-wide observatory -------------------------------------------

_LOCK = threading.Lock()
_PROFILER: Optional[DispatchProfiler] = None
_COMPILES: Optional[CompileTracker] = None
_HBM: Optional[HbmWatermarks] = None
_SKETCH: Optional[WorkloadSketchRecorder] = None


def get_profiler() -> DispatchProfiler:
    global _PROFILER
    if _PROFILER is None:
        with _LOCK:
            if _PROFILER is None:
                _PROFILER = DispatchProfiler()
    return _PROFILER


def get_compile_tracker() -> CompileTracker:
    global _COMPILES
    if _COMPILES is None:
        with _LOCK:
            if _COMPILES is None:
                _COMPILES = CompileTracker()
    return _COMPILES


def get_hbm_watermarks() -> HbmWatermarks:
    global _HBM
    if _HBM is None:
        with _LOCK:
            if _HBM is None:
                _HBM = HbmWatermarks()
    return _HBM


def get_sketch() -> WorkloadSketchRecorder:
    global _SKETCH
    if _SKETCH is None:
        with _LOCK:
            if _SKETCH is None:
                _SKETCH = WorkloadSketchRecorder()
    return _SKETCH


def reset_observatory() -> None:
    """Rebuild every singleton from the current env (tests; also the
    hook a long-lived process can use to re-arm after a config flip)."""
    global _PROFILER, _COMPILES, _HBM, _SKETCH
    with _LOCK:
        _PROFILER = DispatchProfiler()
        _COMPILES = CompileTracker()
        _HBM = HbmWatermarks()
        _SKETCH = WorkloadSketchRecorder()
    _ROOFLINE_CACHE.clear()


def observatory_state() -> dict:
    """The ``/debugz`` section: every ledger's deterministic snapshot."""
    return {
        "sample_n": get_profiler().sample_n,
        "profile": get_profiler().snapshot(),
        "compile": get_compile_tracker().snapshot(),
        "hbm": get_hbm_watermarks().watermarks(),
        "sketch_requests": get_sketch().requests,
    }
