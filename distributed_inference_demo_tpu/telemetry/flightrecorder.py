"""Flight recorder: a bounded, always-on ring of recent runtime events.

The aircraft-black-box layer: every process keeps the last N interesting
moments — engine generate calls, ring hop send/recv, batching admissions
and completions, lifecycle transitions — in a fixed-size in-memory ring.
Nothing is written anywhere until something goes wrong; then the
postmortem writer (``telemetry/postmortem.py``) dumps the ring next to a
metrics snapshot and the run-log tail, so the moments *before* a stall or
crash are diagnosable after the fact without re-running.

Events are plain dicts ``{"ts": <epoch s>, "kind": "<what>", ...fields}``
— the same shape as run-log lines, so a bundle's ``flight.jsonl`` and
``runlog_tail.jsonl`` read with the same tools.  Recording is one dict
build + a locked deque append (~µs), cheap enough to leave on in the ring
hot loop; memory is O(``max_events``) forever.

Like ``runlog``, a process-default recorder is available via
:func:`get_flight_recorder` so instrumentation points don't thread a
recorder handle through every constructor.  Unlike runlog there is no
null variant: the ring is always on (that is the point of a black box),
and ``DWT_FLIGHT_EVENTS=0`` shrinks it to a single slot rather than
adding an enabled-check branch to every call site.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from ._env import env_int

_MAX_EVENTS = 4096


class FlightRecorder:
    """Bounded per-process event ring.  Thread-safe; ``total`` counts
    every event ever recorded (overwritten ones included) so the
    ``dwt_flight_events_total`` counter stays monotone while the ring
    wraps."""

    def __init__(self, proc: str = "", max_events: Optional[int] = None,
                 clock=time.time):
        if max_events is None:
            max_events = env_int("DWT_FLIGHT_EVENTS", _MAX_EVENTS)
        self.proc = proc
        self.capacity = max(1, int(max_events))
        self._clock = clock
        self._events: "deque[dict]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total = 0

    def record(self, kind: str, **fields) -> None:
        ev = {"ts": round(self._clock(), 6), "kind": kind}
        if self.proc:
            ev["proc"] = self.proc
        if fields:
            ev.update(fields)
        with self._lock:
            self._events.append(ev)
            self.total += 1

    def snapshot(self) -> List[dict]:
        """Every buffered event, oldest first (does not drain — the ring
        keeps recording; a postmortem capture must not blind the next
        one)."""
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> List[dict]:
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            return list(self._events)[-n:]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Install the process-default recorder (``None`` resets so the next
    :func:`get_flight_recorder` builds a fresh one — test isolation)."""
    global _default
    with _default_lock:
        _default = recorder


def get_flight_recorder() -> FlightRecorder:
    """The process-default flight recorder, created on first use."""
    global _default
    if _default is not None:
        return _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
    return _default


def debug_state(tail: int = 128) -> dict:
    """The flight fragment of a ``GET /debugz`` payload — ONE owner for
    the shape, shared by the header HTTP server and the worker metrics
    server so the two endpoints cannot drift."""
    fr = get_flight_recorder()
    return {"total": fr.total, "buffered": len(fr),
            "capacity": fr.capacity, "tail": fr.tail(tail)}
