"""Distributed request tracing: spans across the ring, Chrome trace export.

Each generate/classify request is assigned a 64-bit trace id at the header.
The id (plus the sender's span id as parent) rides every data-plane hop as
a wire trailer (``comm/wire.py`` ``FLAG_TRACE_CONTEXT``), so every stage
tags its ``recv_wait`` / ``compute`` / ``send`` spans — and the header its
``ring_rtt`` span — to the request that caused them.  Worker spans flow
back to the header on the existing ``statsreq`` control path
(``runtime/distributed.py``), and the merged set exports as Chrome
trace-event JSON (``to_chrome_trace``) loadable in Perfetto /
``chrome://tracing``.

Timestamps are epoch microseconds (``time.time()``); durations come from
``perf_counter`` deltas.  Within one host the span chain for a token step
nests exactly; across hosts it is as aligned as the hosts' clocks — good
enough for "which hop ate the time", which is the question this exists to
answer.

The two clocks are never mixed: :class:`SpanClock` captures the
wall-clock start ONCE at span open and measures the duration on
``perf_counter``, so a span's start cannot drift when NTP steps the wall
clock mid-span (reconstructing start as ``time.time() - dur`` at close
would move it by exactly the step).
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

_MAX_SPANS = 8192          # bounded: long runs keep O(1) memory

# Trace/span ids must stay unique across processes that FORKED from one
# parent: the module-level ``random`` generator's state is copied by
# fork, so two replicas forked after import would mint the *same* id
# sequence and their traces would merge into one request at the gateway.
# ``SystemRandom`` reads the kernel CSPRNG per call — no Python-level
# state to inherit.
_SYS_RANDOM = random.SystemRandom()


def new_trace_id() -> int:
    """Random nonzero 64-bit trace id (collision odds are irrelevant at
    any realistic request volume).  Drawn from ``os.urandom`` via
    ``SystemRandom`` so ids stay distinct across forked replicas."""
    return _SYS_RANDOM.getrandbits(64) | 1


class SpanClock:
    """Span timing with the clocks kept apart: ``ts`` is the wall-clock
    start captured once at construction (span open); ``seconds`` is the
    elapsed ``perf_counter`` duration, frozen on first read or on context
    exit.  The one timing helper for instrumented spans
    (``with SpanClock() as t: ...`` then ``t.ts`` / ``t.seconds``)."""

    __slots__ = ("ts", "_t0", "_dur")

    def __init__(self):
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self._dur: Optional[float] = None

    def stop(self) -> float:
        if self._dur is None:
            self._dur = time.perf_counter() - self._t0
        return self._dur

    @property
    def seconds(self) -> float:
        return self.stop()

    def __enter__(self) -> "SpanClock":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


class TraceRecorder:
    """Bounded per-process span sink.

    ``record()`` returns the new span's id so the caller can thread it as
    the parent of downstream spans (the wire trailer's second field).
    ``drain()`` pops everything recorded so far — the statsrep /
    export path — so each span is exported exactly once.
    """

    def __init__(self, proc: str, max_spans: int = _MAX_SPANS):
        self.proc = proc
        self._spans: "deque[dict]" = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        # span ids: process-unique base + counter, so two stages' ids
        # cannot collide when merged at the header.  SystemRandom for the
        # same reason as new_trace_id(): a fork must not clone the base.
        self._base = (_SYS_RANDOM.getrandbits(32) << 24) ^ (os.getpid() << 8)
        self._seq = itertools.count(1)

    def next_span_id(self) -> int:
        return (self._base + next(self._seq)) & ((1 << 63) - 1)

    def record(self, name: str, trace_id: int, parent_id: int = 0,
               ts: Optional[float] = None, dur: float = 0.0,
               span_id: Optional[int] = None,
               clock: Optional[SpanClock] = None, **args) -> int:
        """Record a completed span.  Preferred timing source is a
        :class:`SpanClock` opened at span start (``clock=``); explicit
        ``ts`` (epoch-seconds start) + ``dur`` (seconds) also work.  With
        neither, ``ts`` defaults to the call time — NOT ``now - dur``,
        which would reconstruct the start by mixing the wall clock with a
        perf_counter duration and drift whenever NTP steps the clock."""
        sid = span_id if span_id is not None else self.next_span_id()
        if clock is not None:
            ts, dur = clock.ts, clock.seconds
        if ts is None:
            ts = time.time()
        span = {"name": name, "proc": self.proc,
                "trace_id": int(trace_id), "span_id": int(sid),
                "parent_id": int(parent_id),
                "ts_us": int(ts * 1e6),
                "dur_us": max(0, int(dur * 1e6))}
        if args:
            span["args"] = {k: v for k, v in args.items() if v is not None}
        with self._lock:
            self._spans.append(span)
        return sid

    def drain(self) -> List[dict]:
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def to_chrome_trace(spans: Iterable[dict]) -> dict:
    """Merge span dicts (from any number of TraceRecorders / statsrep
    payloads) into a Chrome trace-event JSON object.

    Layout choices for Perfetto readability: one "process" row per stage
    (``proc``), one "thread" lane per trace id within it — so a request's
    hops line up vertically and concurrent requests stack as lanes.
    """
    spans = list(spans)
    pids: Dict[str, int] = {}
    tids: Dict[int, int] = {}
    events: List[dict] = []
    for s in spans:
        proc = s.get("proc", "?")
        if proc not in pids:
            pids[proc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[proc], "tid": 0,
                           "args": {"name": proc}})
        trace_id = int(s.get("trace_id", 0))
        if trace_id not in tids:
            tids[trace_id] = len(tids) + 1
        args = dict(s.get("args") or {})
        args["trace_id"] = f"{trace_id:016x}"
        if s.get("parent_id"):
            args["parent_span_id"] = f"{int(s['parent_id']):016x}"
        args["span_id"] = f"{int(s.get('span_id', 0)):016x}"
        events.append({
            "ph": "X", "name": s.get("name", "?"),
            "cat": "ring", "pid": pids[proc], "tid": tids[trace_id],
            "ts": int(s.get("ts_us", 0)), "dur": int(s.get("dur_us", 0)),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_chrome_traces(traces: Iterable[dict]) -> dict:
    """Merge already-exported Chrome trace objects (``{"traceEvents":
    [...]}``) into one.

    Each input was built by :func:`to_chrome_trace` in a *different*
    process (replica ``/trace`` exports plus the gateway's own), so their
    small-integer pids collide.  Pids are renumbered per input object;
    ``process_name`` metadata rows are deduplicated by name so the merged
    view shows one row per distinct proc, and duration events whose proc
    already has a row reuse it — a request's gateway-proxy, engine, and
    migration spans land in one file, joined by the ``trace_id`` arg the
    per-span export already carries.
    """
    name_pids: Dict[str, int] = {}
    events: List[dict] = []
    next_pid = 1
    for trace in traces:
        remap: Dict[int, int] = {}
        pending: List[dict] = []   # events seen before their meta row
        for ev in (trace or {}).get("traceEvents", []):
            pid = int(ev.get("pid", 0))
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                proc = str((ev.get("args") or {}).get("name", "?"))
                if proc in name_pids:
                    remap[pid] = name_pids[proc]
                else:
                    name_pids[proc] = remap[pid] = next_pid
                    next_pid += 1
                    events.append(dict(ev, pid=remap[pid]))
                continue
            pending.append(ev)
        for ev in pending:
            pid = int(ev.get("pid", 0))
            if pid not in remap:
                remap[pid] = next_pid
                next_pid += 1
            events.append(dict(ev, pid=remap[pid]))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable[dict]) -> None:
    """Export spans to ``path`` as Chrome trace JSON (open in Perfetto:
    ui.perfetto.dev → "Open trace file")."""
    import json
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
