"""Structured JSONL run logs: one event per line, one file per run.

Replaces scattered prints as the machine-readable record of a run: the
bench harness, the engines, and the control-plane lifecycle all emit
through one surface.  Every line is a self-contained JSON object::

    {"ts": <epoch seconds>, "run_id": "...", "event": "<kind>", ...fields}

Enabling: pass a path explicitly (``RunLog(path)`` + ``set_run_log``), use
``serve --run-log`` / ``bench --run-log``, or set ``DWT_RUN_LOG=<path>``
in the environment — any process in the deployment then appends to its
own file (the path gets a ``.<pid>`` suffix when it would be shared, so
workers never interleave partial lines with the header).  When nothing is
configured, ``get_run_log()`` returns a no-op sink: instrumented hot paths
cost one attribute check.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import IO, Optional


def new_run_id() -> str:
    return uuid.uuid4().hex[:16]


class RunLog:
    """Append-only JSONL event sink.  Thread-safe; every event is one
    ``write`` + ``flush`` so a crash loses at most the in-flight line.

    ``max_bytes`` (or ``DWT_RUN_LOG_MAX_BYTES``) bounds the file for
    long serving runs: when appending a line would push the file past
    the limit, the current file rolls to ``<path>.1`` (replacing any
    previous rollover) and a fresh file starts — at most two
    generations, so disk stays O(2 x max_bytes) forever.  0 disables
    rollover; fileobj-backed logs never roll (no path to rename)."""

    enabled = True

    def __init__(self, path: Optional[str] = None,
                 fileobj: Optional[IO[str]] = None,
                 run_id: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        if (path is None) == (fileobj is None):
            raise ValueError("RunLog needs exactly one of path/fileobj")
        if max_bytes is None:
            from ._env import env_int
            max_bytes = env_int("DWT_RUN_LOG_MAX_BYTES", 0)
        self.max_bytes = max(0, max_bytes)
        self.run_id = run_id or new_run_id()
        self.path = path
        # opened EAGERLY: a bad --run-log path must fail loudly at
        # startup, not silently drop every event of the run
        self._f = fileobj if fileobj is not None else open(
            path, "a", encoding="utf-8")
        self._nbytes = 0
        if path is not None:
            try:
                self._nbytes = os.path.getsize(path)
            except OSError:
                pass
        self._lock = threading.Lock()

    def _maybe_roll(self, incoming: int) -> None:
        """Roll the file when the next line would cross ``max_bytes``.
        Caller holds the lock.  ``_nbytes > 0`` guards a line larger
        than the whole budget: it lands in a fresh file instead of
        rolling forever."""
        if (self.path is None or not self.max_bytes
                or self._nbytes + incoming <= self.max_bytes
                or self._nbytes == 0):
            return
        # each step is isolated: a failed rename must not leave a CLOSED
        # handle installed (every later event would silently die on it) —
        # the reopen below runs regardless, so appending continues into
        # whichever file the filesystem let us keep
        try:
            self._f.close()
        except (OSError, ValueError):
            pass
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass    # rename refused: reopen the (unrotated) file below
        try:
            self._f = open(self.path, "a", encoding="utf-8")
            self._nbytes = os.path.getsize(self.path)
        except OSError:
            self._f = None    # event() treats None as closed

    def event(self, kind: str, **fields) -> None:
        rec = {"ts": round(time.time(), 6), "run_id": self.run_id,
               "event": kind}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=str) + "\n"
        except (TypeError, ValueError):
            line = json.dumps({"ts": rec["ts"], "run_id": self.run_id,
                               "event": kind,
                               "error": "unserializable fields"}) + "\n"
        nbytes = len(line.encode("utf-8"))
        with self._lock:
            if self._f is None:
                return          # closed
            self._maybe_roll(nbytes)
            if self._f is None:
                return          # rollover reopen failed (disk/perm)
            try:
                self._f.write(line)
                self._f.flush()
                self._nbytes += nbytes
            except (OSError, ValueError):
                pass    # a full disk must never take down the serving loop

    def close(self) -> None:
        with self._lock:
            if self._f is not None and self.path is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


class _NullRunLog:
    """No-op sink returned when no run log is configured."""

    enabled = False
    run_id = ""

    def event(self, kind: str, **fields) -> None:
        pass

    def close(self) -> None:
        pass


NULL = _NullRunLog()
_default: object = None
_default_lock = threading.Lock()


def set_run_log(runlog) -> None:
    """Install the process-default run log (``None`` restores the no-op)."""
    global _default
    with _default_lock:
        _default = runlog


def get_run_log():
    """The process-default run log.  Lazily honors ``DWT_RUN_LOG``: the
    first call in a process with the env var set opens
    ``$DWT_RUN_LOG.<pid>`` (per-process files — concurrent workers must
    not interleave lines in one file).  An unopenable env path degrades
    to the no-op sink with one stderr warning — the env var is ambient
    configuration and must not crash a serving hot path."""
    global _default
    if _default is not None:
        return _default
    with _default_lock:
        if _default is None:
            path = os.environ.get("DWT_RUN_LOG", "")
            if path:
                try:
                    _default = RunLog(f"{path}.{os.getpid()}")
                except OSError as e:
                    import sys
                    print(f"runlog: cannot open {path!r}: {e}; run-log "
                          "events disabled", file=sys.stderr)
                    _default = NULL
            else:
                _default = NULL
    return _default
