"""Online anomaly detection over the existing stats surfaces.

PR 1 made the raw signals pollable (``StageStats`` snapshots, the
batching scheduler's counters, the ``dwt_*`` series); this module watches
them *continuously* and names the moment something leaves its envelope:

- **straggler_hop** — one pipeline stage's compute p95 sits far above
  the ring median (a slow host / thermal-throttled chip / dying link);
- **slo_ttft / slo_tpot** — the batching engine's time-to-first-token or
  per-output-token p95 breaches a configured SLO;
- **slo_burn** — a tenant's error-budget burn rate (from the SLO
  ledger, ``telemetry/slo.py``) exceeds ``DWT_ANOMALY_BURN_RATE`` on
  every window at once (fast 5m AND slow 1h — multiwindow alerting);
- **queue_saturation** — admitted-but-unslotted requests pile up past a
  threshold (the system is falling behind offered load);
- **accept_collapse** — the speculative accept rate collapses (the draft
  stopped predicting the target; every round is wasted work);
- **recompile_storm** — a tracked jitted program compiled past its
  documented variant budget (``stats()["compile"]`` fragment from
  ``telemetry/profiling.py``; e.g. ``_mixed_step``'s two-variant
  invariant) — a silent recompile latency cliff becomes a named event;
- **pipeline_stall** — work is in flight but the step counter has not
  advanced for longer than the watchdog window (the explicit
  TransportTimeout path in ``runtime/distributed.py`` covers the ring;
  this covers the single-process slot scheduler).

Detection is intentionally boring: fixed thresholds from env knobs, a
``sustain`` count so one noisy sample can't fire, and a per-kind
``cooldown`` so a persistent condition produces ONE postmortem bundle,
not a bundle storm.  Every threshold is overridable per deployment
(``DWT_ANOMALY_*`` / ``DWT_SLO_*``, docs/DESIGN.md §8); every detector
takes its clock from the constructor so tests drive scenarios with a
fake clock deterministically.

:class:`AnomalyMonitor` couples a detector to the flight recorder, the
``dwt_anomaly_*`` series, and the postmortem writer — the piece the
serving loops actually call.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ._env import env_float as _env_float, env_int as _env_int


@dataclass(frozen=True)
class Thresholds:
    """Detector knobs; ``from_env`` reads the ``DWT_*`` overrides once at
    construction so a long-lived detector is immune to env churn."""

    straggler_factor: float = 3.0     # stage p95 vs ring median multiple
    straggler_min_ms: float = 1.0     # ignore sub-ms absolute noise
    ttft_slo_ms: float = 0.0          # 0 = SLO disabled
    tpot_slo_ms: float = 0.0          # 0 = SLO disabled
    queue_depth: int = 64             # waiting requests = saturation
    accept_floor: float = 0.1         # speculative acceptance collapse
    accept_min_drafted: int = 256     # ... after this many drafted tokens
    stall_s: float = 30.0             # watchdog: no progress with work
    burn_rate: float = 0.0            # 0 = SLO burn detector disabled
    recompile_slack: int = 0          # extra compiles tolerated past a
    # program's variant budget before recompile_storm (-1 disables)
    sustain: int = 3                  # consecutive breaches before firing
    cooldown_s: float = 300.0         # per-kind re-fire suppression

    @staticmethod
    def from_env() -> "Thresholds":
        return Thresholds(
            straggler_factor=_env_float("DWT_ANOMALY_STRAGGLER_FACTOR",
                                        3.0),
            straggler_min_ms=_env_float("DWT_ANOMALY_STRAGGLER_MIN_MS",
                                        1.0),
            ttft_slo_ms=_env_float("DWT_SLO_TTFT_MS", 0.0),
            tpot_slo_ms=_env_float("DWT_SLO_TPOT_MS", 0.0),
            queue_depth=_env_int("DWT_ANOMALY_QUEUE_DEPTH", 64),
            accept_floor=_env_float("DWT_ANOMALY_ACCEPT_FLOOR", 0.1),
            accept_min_drafted=_env_int(
                "DWT_ANOMALY_ACCEPT_MIN_DRAFTED", 256),
            stall_s=_env_float("DWT_ANOMALY_STALL_S", 30.0),
            burn_rate=_env_float("DWT_ANOMALY_BURN_RATE", 0.0),
            recompile_slack=_env_int("DWT_ANOMALY_RECOMPILE_SLACK", 0),
            sustain=_env_int("DWT_ANOMALY_SUSTAIN", 3),
            cooldown_s=_env_float("DWT_ANOMALY_COOLDOWN_S", 300.0),
        )


@dataclass
class Anomaly:
    kind: str
    severity: str                     # "warn" | "critical"
    ts: float
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "severity": self.severity,
                "ts": round(self.ts, 6), "detail": self.detail}


class AnomalyDetector:
    """Sliding-window detectors over stats dicts.

    ``observe(stats)`` accepts either shape the repo produces — a
    pipeline snapshot ``{"stages": [...]}`` (``HeaderBackend.stats``) or
    a batching-engine ``stats()`` dict — and returns the anomalies that
    *fired this observation* (sustain + cooldown already applied).
    """

    def __init__(self, thresholds: Optional[Thresholds] = None,
                 clock=time.time):
        self.thresholds = thresholds or Thresholds.from_env()
        self._clock = clock
        self._streak: Dict[str, int] = {}
        self._last_fire: Dict[str, float] = {}
        self._recent: "deque[Anomaly]" = deque(maxlen=64)
        # stall watchdog state: (last steps value, ts it last changed)
        self._steps_seen: Optional[int] = None
        self._steps_ts: float = 0.0

    # -- breach bookkeeping ------------------------------------------------

    def _breach(self, kind: str, severity: str, detail: dict,
                key: Optional[str] = None) -> Optional[Anomaly]:
        """One breached observation; fires after ``sustain`` consecutive
        breaches, then goes quiet for ``cooldown_s``.  ``key`` names the
        SUSTAIN identity when one kind has several independent sources
        (per-stage straggler streaks must not alias into one counter —
        two stages' single noisy samples would add up to a firing);
        cooldown stays per ``kind`` so simultaneous sources still
        produce one bundle, not one per source."""
        t = self.thresholds
        key = key or kind
        streak = self._streak.get(key, 0) + 1
        self._streak[key] = streak
        if streak < t.sustain:
            return None
        now = self._clock()
        if now - self._last_fire.get(kind, -1e18) < t.cooldown_s:
            return None
        self._last_fire[kind] = now
        a = Anomaly(kind=kind, severity=severity, ts=now, detail=detail)
        self._recent.append(a)
        return a

    def _clear(self, key: str) -> None:
        self._streak.pop(key, None)

    # -- detectors ---------------------------------------------------------

    def observe(self, stats: dict) -> List[Anomaly]:
        if not isinstance(stats, dict):
            return []
        stages = stats.get("stages")
        if isinstance(stages, list):
            return self.observe_stages(stages)
        return self.observe_batching(stats)

    def observe_stages(self, snapshots: List[dict]) -> List[Anomaly]:
        """Straggler detection over per-stage snapshots (one poll of
        ``collect_stats``): a stage whose compute p95 exceeds
        ``straggler_factor`` x the median of the OTHER stages is the slow
        hop.  Self-excluded baseline on purpose: with the ring median
        over ALL stages, a 2-stage ring's straggler IS the median and
        could never fire (xs[n//2] picks the larger of two)."""
        t = self.thresholds
        out: List[Anomaly] = []
        p95s = []
        for s in snapshots:
            v = s.get("compute_p95_ms")
            if isinstance(v, (int, float)):
                p95s.append((v, s))
        if len(p95s) < 2:
            # an observation GAP (timed-out poll, fresh stats) restarts
            # every straggler streak: sustain means consecutive, and a
            # stale streak surviving the gap could fire off one later
            # noisy sample (same rule as the SLO loop's missing-metric
            # clear in observe_batching)
            for key in [k for k in self._streak
                        if k.startswith("straggler_hop:")]:
                self._clear(key)
            return out
        vals = [v for v, _ in p95s]
        breached_keys = set()
        for i, (v, s) in enumerate(p95s):
            others = sorted(vals[:i] + vals[i + 1:])
            baseline = others[(len(others) - 1) // 2]   # lower median
            if (v > t.straggler_min_ms
                    and baseline > 0
                    and v > t.straggler_factor * baseline):
                # per-stage sustain identity (see _breach)
                key = f"straggler_hop:{s.get('device_id', '')}" \
                      f":{s.get('role', '')}"
                breached_keys.add(key)
                a = self._breach(
                    "straggler_hop", "warn",
                    {"role": s.get("role"),
                     "device": s.get("device_id", ""),
                     "compute_p95_ms": v,
                     "ring_median_ms": round(baseline, 3),
                     "factor": round(v / baseline, 2)}, key=key)
                if a:
                    out.append(a)
        for key in [k for k in self._streak
                    if k.startswith("straggler_hop:")
                    and k not in breached_keys]:
            self._clear(key)            # recovered stages restart at 0
        return out

    def observe_batching(self, stats: dict) -> List[Anomaly]:
        t = self.thresholds
        out: List[Anomaly] = []
        lat = stats.get("latency") or {}

        # a missing/ineligible metric clears its streak too: "sustain"
        # means CONSECUTIVE breaches, so a stats-reset gap (the value
        # vanishes, e.g. POST /stats/reset clearing the reservoirs) must
        # not let two old breaches + one later noisy sample fire
        for kind, slo, key in (("slo_ttft", t.ttft_slo_ms, "ttft_p95_ms"),
                               ("slo_tpot", t.tpot_slo_ms,
                                "per_token_p95_ms")):
            v = lat.get(key)
            if slo <= 0:
                continue
            if isinstance(v, (int, float)) and v > slo:
                a = self._breach(kind, "critical",
                                 {key: v, "slo_ms": slo})
                if a:
                    out.append(a)
            else:
                self._clear(kind)

        # multiwindow burn-rate: a tenant is burning error budget only
        # when EVERY window (fast 5m AND slow 1h) sits over the
        # threshold — the classic guard against paging on a short blip
        # (5m alone) or on a long-recovered incident (1h alone).  Keyed
        # per tenant so one noisy tenant can't mask another's streak.
        burning = set()
        slo_block = stats.get("slo")
        if t.burn_rate > 0 and isinstance(slo_block, dict):
            from .slo import isfinite
            tenants = slo_block.get("tenants")
            for tenant, ts_ in (tenants or {}).items():
                burn = ts_.get("burn") if isinstance(ts_, dict) else None
                if not isinstance(burn, dict) or not burn:
                    continue
                vals = list(burn.values())
                if not all(isfinite(v) for v in vals):
                    # NaN/inf: unusable sample — it can't fire, and the
                    # streak restarts (sustain means CONSECUTIVE, the
                    # same gap rule as the SLO p95 loop above)
                    continue
                key = f"slo_burn:{tenant}"
                if all(v > t.burn_rate for v in vals):
                    burning.add(key)
                    a = self._breach(
                        "slo_burn", "critical",
                        {"tenant": tenant, "burn": burn,
                         "threshold": t.burn_rate}, key=key)
                    if a:
                        out.append(a)
        for key in [k for k in self._streak
                    if k.startswith("slo_burn:") and k not in burning]:
            self._clear(key)

        # recompile storm: a tracked program's compile count exceeds
        # its documented variant budget (telemetry/profiling.py feeds
        # the stats()["compile"] fragment; e.g. _mixed_step may compile
        # exactly two variants, docs/DESIGN.md §19).  Keyed per program
        # so one storming program can't mask another's streak; only
        # budgeted programs are eligible (budget None = unbounded by
        # design, e.g. per-chunk-length prefill variants).
        storming = set()
        compile_block = stats.get("compile")
        if t.recompile_slack >= 0 and isinstance(compile_block, dict):
            for prog, e in compile_block.items():
                if not isinstance(e, dict):
                    continue
                budget = e.get("variant_budget")
                compiles = e.get("compiles")
                if not isinstance(budget, int) or \
                        not isinstance(compiles, (int, float)):
                    continue
                key = f"recompile:{prog}"
                if compiles > budget + t.recompile_slack:
                    storming.add(key)
                    a = self._breach(
                        "recompile_storm", "critical",
                        {"program": prog, "compiles": int(compiles),
                         "variant_budget": budget,
                         "slack": t.recompile_slack,
                         "compile_seconds":
                             e.get("compile_seconds", 0.0)}, key=key)
                    if a:
                        out.append(a)
        for key in [k for k in self._streak
                    if k.startswith("recompile:") and k not in storming]:
            self._clear(key)

        depth = stats.get("queue_depth")
        if isinstance(depth, int) and depth >= t.queue_depth:
            a = self._breach(
                "queue_saturation", "warn",
                {"queue_depth": depth, "threshold": t.queue_depth,
                 "active_slots": stats.get("active_slots"),
                 "slots": stats.get("slots")})
            if a:
                out.append(a)
        else:
            self._clear("queue_saturation")

        sp = stats.get("speculative") or {}
        rate = sp.get("acceptance_rate")
        drafted = sp.get("rounds", 0) * sp.get("num_draft", 0)
        if (rate is not None and drafted >= t.accept_min_drafted
                and rate < t.accept_floor):
            a = self._breach(
                "accept_collapse", "warn",
                {"acceptance_rate": rate, "floor": t.accept_floor,
                 "drafted": drafted})
            if a:
                out.append(a)
        else:
            self._clear("accept_collapse")

        a = self._watchdog(stats)
        if a:
            out.append(a)
        return out

    def _watchdog(self, stats: dict) -> Optional[Anomaly]:
        """Stalled-pipeline watchdog: work in flight but the step counter
        frozen for longer than ``stall_s``.  Sustain does not apply (the
        window IS the debounce); cooldown still does."""
        t = self.thresholds
        steps = stats.get("steps")
        if not isinstance(steps, int):
            return None
        now = self._clock()
        if self._steps_seen is None or steps != self._steps_seen:
            self._steps_seen, self._steps_ts = steps, now
            return None
        busy = (stats.get("active_slots") or 0) + (
            stats.get("queue_depth") or 0)
        if busy == 0:
            # idle is not stalling: keep the window anchored at NOW so
            # an idle-then-resume cycle doesn't instantly fire a stale
            # 10-minute "stall" on the first busy observation
            self._steps_ts = now
            return None
        stalled_for = now - self._steps_ts
        if stalled_for > t.stall_s:
            if now - self._last_fire.get("pipeline_stall",
                                         -1e18) < t.cooldown_s:
                return None
            self._last_fire["pipeline_stall"] = now
            a = Anomaly("pipeline_stall", "critical", now,
                        {"stalled_for_s": round(stalled_for, 3),
                         "steps": steps, "busy": busy})
            self._recent.append(a)
            return a
        return None

    # -- introspection (``/debugz``) ---------------------------------------

    def recent(self) -> List[dict]:
        return [a.to_dict() for a in self._recent]

    def state(self) -> dict:
        from dataclasses import asdict
        return {"thresholds": asdict(self.thresholds),
                "streaks": dict(self._streak),
                "last_fire": {k: round(v, 3)
                              for k, v in self._last_fire.items()},
                "recent": self.recent()}


class AnomalyMonitor:
    """Detector + consequences: feed a stats dict in, and every anomaly
    that fires is recorded into the flight ring, counted on the
    ``dwt_anomaly_*`` series, and (when a postmortem writer is
    configured) dumped as a bundle.  ``observe`` is throttled to
    ``min_interval_s`` so a tight scheduler loop can call it every
    iteration for free."""

    def __init__(self, detector: Optional[AnomalyDetector] = None,
                 min_interval_s: float = 1.0, clock=time.time,
                 config: Optional[dict] = None):
        self.detector = detector or AnomalyDetector(clock=clock)
        self.min_interval_s = min_interval_s
        self._clock = clock
        self._last_obs = -1e18
        self._config = config
        self._lock = threading.Lock()
        # bounded to the writer's prune depth: a long-serving monitor
        # must not grow this forever nor advertise pruned paths
        self.bundles: "deque[str]" = deque(maxlen=16)

    def observe(self, stats) -> List[Anomaly]:
        now = self._clock()
        with self._lock:
            if now - self._last_obs < self.min_interval_s:
                return []
            self._last_obs = now
        if callable(stats):
            # lazily built: don't pay a stats() snapshot on throttled calls
            try:
                stats = stats()
            except Exception:
                return []
        anomalies = self.detector.observe(stats)
        for a in anomalies:
            self._react(a)
        return anomalies

    def _react(self, a: Anomaly) -> None:
        from . import postmortem
        from .catalog import ANOMALY_EVENTS, ANOMALY_LAST
        from .flightrecorder import get_flight_recorder
        ANOMALY_EVENTS.inc(kind=a.kind)
        ANOMALY_LAST.set(a.ts, kind=a.kind)
        get_flight_recorder().record("anomaly", anomaly=a.kind,
                                     severity=a.severity, **a.detail)
        path = postmortem.trigger(a.kind, detail=a.to_dict(),
                                  config=self._config)
        if path:
            self.bundles.append(path)

    def state(self) -> dict:
        """``/debugz`` payload fragment.  Bundles are filtered to the
        paths still on disk — the writer prunes old ones, and a
        mid-incident operator following a reported path must find it."""
        import os
        return dict(self.detector.state(),
                    bundles=[p for p in self.bundles
                             if os.path.isdir(p)])
