"""Per-request timeline ledger + per-tenant SLO/goodput accounting.

The batching scheduler (docs/DESIGN.md §10) already measures TTFT /
per-token / e2e latency as anonymous reservoirs; this module is the
*attributed* layer on top: every request carries a ``tenant`` identity
(``/generate`` body field or ``X-DWT-Tenant`` header, forwarded by the
gateway and preserved across the §18 migration export/import seam) and
closes into one **timeline record** decomposing where its milliseconds
went:

    queue_wait  — admission to first scheduler pickup
    prefill     — pickup to first emitted token (chunked prefill time)
    ttft        — admission to first token (= queue_wait + prefill)
    per_token   — steady-state decode seconds/token, pauses excluded
    migration_pause — freeze→first-relayed-token gap, live migrations
    resume_pause — replay window on a survivor after gateway failover
                   (docs/DESIGN.md §23), recorded like migration_pause
    e2e         — admission to final token

By construction ``ttft + per_token*(tokens-1) + migration_pause +
resume_pause == e2e`` for every closed record, so the decomposition
always sums — a timeline that doesn't add up is a measurement bug, not
a rounding artifact.

Each close rolls into per-tenant labeled Prometheus series
(``dwt_slo_*``): latency histograms, goodput counters (tokens served
within the configured TTFT/TPOT SLO vs total — a request's first token
is judged against ``DWT_SLO_TTFT_MS``, its decode tokens against
``DWT_SLO_TPOT_MS``; with a threshold unset/0 that phase always counts
as good), and multi-window **burn-rate** gauges: the fraction of
SLO-violating tokens over a trailing window divided by the error budget
``1 - DWT_SLO_TARGET``.  Burn rate 1.0 means the tenant is consuming
its budget exactly at the sustainable pace; the classic multiwindow
alert (short AND long window both high) is what the anomaly layer's
``slo_burn`` detector consumes via the scheduler ``stats()`` surface.

Process-default accessor mirrors the flight recorder: one ledger per
process (``get_slo_ledger()``), recent timelines queryable at
``GET /timeline`` and dumped into postmortem bundles as
``timelines.jsonl``.  Recording is a dict build + locked deque append;
memory is O(recent + windows) forever.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .metrics import counter, gauge, histogram

# ---------------------------------------------------------------------------
# series (registered once at import; the catalog imports this module so
# the standard-set lint sees them)

_TTFT_BUCKETS_S = (0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 15.0, 60.0)
_TOKEN_BUCKETS_S = (0.0005, 0.001, 0.002, 0.004, 0.008, 0.016, 0.032,
                    0.064, 0.25, 1.0)

SLO_TTFT = histogram(
    "dwt_slo_ttft_seconds",
    "Per-tenant time to first token (admission to first emitted token)",
    labels=("tenant",), buckets=_TTFT_BUCKETS_S)
SLO_QUEUE_WAIT = histogram(
    "dwt_slo_queue_wait_seconds",
    "Per-tenant admission-to-scheduler-pickup wait",
    labels=("tenant",), buckets=_TTFT_BUCKETS_S)
SLO_PER_TOKEN = histogram(
    "dwt_slo_per_token_seconds",
    "Per-tenant steady-state decode seconds per token "
    "(migration pause excluded)",
    labels=("tenant",), buckets=_TOKEN_BUCKETS_S)
SLO_E2E = histogram(
    "dwt_slo_e2e_seconds",
    "Per-tenant end-to-end request latency (admission to final token)",
    labels=("tenant",), buckets=_TTFT_BUCKETS_S)
SLO_MIGRATION_PAUSE = histogram(
    "dwt_slo_migration_pause_seconds",
    "Per-tenant live-migration pause (freeze to first relayed token), "
    "observed only for migrated requests",
    labels=("tenant",), buckets=_TTFT_BUCKETS_S)
SLO_REQUESTS = counter(
    "dwt_slo_requests_total",
    "Per-tenant closed request timelines", labels=("tenant",))
SLO_FAILED_REQUESTS = counter(
    "dwt_slo_failed_requests_total",
    "Per-tenant requests closed with an error (their tokens all count "
    "against the SLO budget)", labels=("tenant",))
SLO_TOKENS = counter(
    "dwt_slo_tokens_total",
    "Per-tenant tokens emitted by closed requests", labels=("tenant",))
SLO_GOOD_TOKENS = counter(
    "dwt_slo_good_tokens_total",
    "Per-tenant tokens served within the configured TTFT/TPOT SLO "
    "(goodput numerator; equals dwt_slo_tokens_total when no SLO is set)",
    labels=("tenant",))
SLO_GOOD_TTFT_REQUESTS = counter(
    "dwt_slo_good_ttft_requests_total",
    "Per-tenant requests whose first token met the TTFT SLO",
    labels=("tenant",))
SLO_MIGRATED_REQUESTS = counter(
    "dwt_slo_migrated_requests_total",
    "Per-tenant closed requests that were live-migrated at least once",
    labels=("tenant",))
SLO_RESUME_PAUSE = histogram(
    "dwt_slo_resume_pause_seconds",
    "Per-tenant gateway-failover resume pause (replay window on the "
    "survivor: first replayed token to first visible token, docs/"
    "DESIGN.md §23), observed only for resumed requests",
    labels=("tenant",), buckets=_TTFT_BUCKETS_S)
SLO_RESUMED_REQUESTS = counter(
    "dwt_slo_resumed_requests_total",
    "Per-tenant closed requests admitted through the gateway-failover "
    "resume path (delivered prefix re-derived on a survivor replica)",
    labels=("tenant",))
SLO_BURN_RATE = gauge(
    "dwt_slo_burn_rate_ratio",
    "Per-tenant SLO burn rate over a trailing window: fraction of "
    "SLO-violating tokens divided by the error budget (1 - target); "
    "1.0 = burning exactly at the sustainable pace",
    labels=("tenant", "window"))

# ---------------------------------------------------------------------------

DEFAULT_TENANT = "default"
_TENANT_RE = re.compile(r"[^A-Za-z0-9._:@/-]")
_MAX_TENANT_LEN = 64

#: trailing windows for burn-rate gauges: (seconds, label)
BURN_WINDOWS = ((300.0, "5m"), (3600.0, "1h"))


def sanitize_tenant(raw) -> str:
    """Clamp an untrusted tenant identity (HTTP header / JSON body) to a
    safe metric label value: bounded length, conservative charset,
    never empty.  Unknown/absent identities collapse to ``default`` so
    the per-tenant series always partition the full traffic."""
    if raw is None:
        return DEFAULT_TENANT
    s = _TENANT_RE.sub("_", str(raw).strip())[:_MAX_TENANT_LEN]
    return s or DEFAULT_TENANT


def _env_ms(name: str) -> float:
    try:
        return float(os.environ.get(name, "0") or 0)
    except ValueError:
        return 0.0


class SloLedger:
    """Bounded per-process ledger of closed request timelines with
    per-tenant SLO accounting.

    ``close_request()`` is the single write path — the scheduler calls
    it when a request completes locally, and the migration relay calls
    it on the *source* replica for migrated-out requests (the source
    keeps the client connection, so its view is the user-visible one;
    the adopting replica deliberately does not double-close).
    """

    def __init__(self, *, ttft_slo_ms: Optional[float] = None,
                 tpot_slo_ms: Optional[float] = None,
                 target: Optional[float] = None,
                 max_recent: int = 256,
                 clock=time.time):
        self.ttft_slo_ms = (_env_ms("DWT_SLO_TTFT_MS")
                            if ttft_slo_ms is None else float(ttft_slo_ms))
        self.tpot_slo_ms = (_env_ms("DWT_SLO_TPOT_MS")
                            if tpot_slo_ms is None else float(tpot_slo_ms))
        if target is None:
            try:
                target = float(os.environ.get("DWT_SLO_TARGET", "0.99"))
            except ValueError:
                target = 0.99
        # clamp: target outside (0, 1) would make the error budget
        # non-positive and every burn rate infinite/negative
        self.target = min(max(float(target), 0.0), 0.9999)
        self._budget = max(1.0 - self.target, 1e-4)
        self._clock = clock
        self._lock = threading.Lock()
        self._recent: "deque[dict]" = deque(maxlen=max_recent)
        # per-tenant trailing (ts, tokens, bad_tokens) events for the
        # burn windows; pruned past the longest window on every touch
        self._events: Dict[str, "deque"] = {}
        self._totals: Dict[str, Dict[str, float]] = {}

    # -- write path --------------------------------------------------------

    def close_request(self, *, rid: str, tenant: str = DEFAULT_TENANT,
                      trace_id: int = 0, t_submit_wall: float = 0.0,
                      queue_wait_s: float = 0.0, ttft_s: float = 0.0,
                      e2e_s: float = 0.0, tokens: int = 0,
                      migration_pause_s: float = 0.0,
                      migrated: bool = False,
                      resume_pause_s: float = 0.0,
                      resumed: bool = False, replica: str = "",
                      error: Optional[str] = None) -> dict:
        """Close one request into a timeline record and roll it into the
        per-tenant series.  Returns the record (also kept in the recent
        ring for ``/timeline`` and postmortem bundles)."""
        tenant = sanitize_tenant(tenant)
        tokens = max(0, int(tokens))
        queue_wait_s = max(0.0, float(queue_wait_s))
        ttft_s = max(queue_wait_s, float(ttft_s))
        migration_pause_s = max(0.0, float(migration_pause_s))
        resume_pause_s = max(0.0, float(resume_pause_s))
        pause_s = migration_pause_s + resume_pause_s
        e2e_s = max(ttft_s + pause_s, float(e2e_s))
        decode_s = e2e_s - ttft_s
        # max(0): float dust when decode == pause exactly must not
        # produce a negative per-token latency
        per_token_s = (max(0.0, decode_s - pause_s)
                       / (tokens - 1) if tokens > 1 else 0.0)
        prefill_s = ttft_s - queue_wait_s

        ttft_ok = (error is None and tokens > 0
                   and (self.ttft_slo_ms <= 0
                        or ttft_s * 1e3 <= self.ttft_slo_ms))
        tpot_ok = (error is None
                   and (self.tpot_slo_ms <= 0
                        or per_token_s * 1e3 <= self.tpot_slo_ms))
        good = ((1 if ttft_ok else 0)
                + (tokens - 1 if tokens > 1 and tpot_ok else 0))
        bad = tokens - good

        rec = {
            "ts": self._clock(), "rid": str(rid), "tenant": tenant,
            "trace_id": f"{int(trace_id):016x}" if trace_id else "",
            "t_submit_wall": float(t_submit_wall),
            "queue_wait_s": queue_wait_s, "prefill_s": prefill_s,
            "ttft_s": ttft_s, "per_token_s": per_token_s,
            "decode_s": decode_s,
            "migration_pause_s": migration_pause_s,
            "resume_pause_s": resume_pause_s,
            "e2e_s": e2e_s, "tokens": tokens,
            "good_tokens": good, "migrated": bool(migrated),
            "resumed": bool(resumed),
            "replica": str(replica),
        }
        if error is not None:
            rec["error"] = str(error)

        SLO_REQUESTS.inc(tenant=tenant)
        if error is not None:
            SLO_FAILED_REQUESTS.inc(tenant=tenant)
        if migrated:
            SLO_MIGRATED_REQUESTS.inc(tenant=tenant)
        if tokens > 0:
            SLO_TOKENS.inc(tokens, tenant=tenant)
            if good:
                SLO_GOOD_TOKENS.inc(good, tenant=tenant)
            SLO_QUEUE_WAIT.observe(queue_wait_s, tenant=tenant)
            SLO_TTFT.observe(ttft_s, tenant=tenant)
            SLO_E2E.observe(e2e_s, tenant=tenant)
            if tokens > 1:
                SLO_PER_TOKEN.observe(per_token_s, tenant=tenant)
        if ttft_ok:
            SLO_GOOD_TTFT_REQUESTS.inc(tenant=tenant)
        if migrated:
            SLO_MIGRATION_PAUSE.observe(migration_pause_s, tenant=tenant)
        if resumed:
            SLO_RESUMED_REQUESTS.inc(tenant=tenant)
            SLO_RESUME_PAUSE.observe(resume_pause_s, tenant=tenant)

        with self._lock:
            self._recent.append(rec)
            ev = self._events.setdefault(tenant, deque())
            ev.append((rec["ts"], tokens, bad))
            tot = self._totals.setdefault(
                tenant, {"requests": 0, "tokens": 0, "good_tokens": 0,
                         "failed": 0, "migrated": 0, "resumed": 0})
            tot["requests"] += 1
            tot["tokens"] += tokens
            tot["good_tokens"] += good
            tot["failed"] += 1 if error is not None else 0
            tot["migrated"] += 1 if migrated else 0
            tot["resumed"] += 1 if resumed else 0
            burn = self._burn_locked(tenant)
        for label, rate in burn.items():
            SLO_BURN_RATE.set(rate, tenant=tenant, window=label)
        return rec

    # -- burn windows ------------------------------------------------------

    def _burn_locked(self, tenant: str) -> Dict[str, float]:
        now = self._clock()
        ev = self._events.get(tenant)
        if ev is None:
            return {label: 0.0 for _, label in BURN_WINDOWS}
        horizon = now - max(w for w, _ in BURN_WINDOWS)
        while ev and ev[0][0] < horizon:
            ev.popleft()
        out = {}
        for win_s, label in BURN_WINDOWS:
            cut = now - win_s
            total = bad = 0
            for ts, tok, b in ev:
                if ts >= cut:
                    total += tok
                    bad += b
            frac = (bad / total) if total else 0.0
            out[label] = frac / self._budget
        return out

    def burn_rates(self, tenant: str) -> Dict[str, float]:
        with self._lock:
            return self._burn_locked(sanitize_tenant(tenant))

    # -- read paths --------------------------------------------------------

    def recent(self, n: int = 64) -> List[dict]:
        """Most recent ``n`` closed timelines, oldest first."""
        with self._lock:
            items = list(self._recent)
        return items[-max(0, int(n)):]

    def summary(self) -> dict:
        """Per-tenant rollup for ``/stats``, ``/debugz``, and the
        anomaly layer: lifetime counts, goodput ratio, burn rates."""
        with self._lock:
            tenants = {}
            for tenant, tot in self._totals.items():
                toks = tot["tokens"]
                tenants[tenant] = {
                    "requests": tot["requests"],
                    "failed": tot["failed"],
                    "migrated": tot["migrated"],
                    "resumed": tot.get("resumed", 0),
                    "tokens": toks,
                    "good_tokens": tot["good_tokens"],
                    "goodput_ratio": (tot["good_tokens"] / toks
                                      if toks else 1.0),
                    "burn": self._burn_locked(tenant),
                }
        return {
            "slo": {"ttft_ms": self.ttft_slo_ms,
                    "tpot_ms": self.tpot_slo_ms,
                    "target": self.target},
            "tenants": tenants,
        }

    def refresh_series(self) -> None:
        """Re-set the burn-rate gauges from the current clock (a scrape
        between closes must see windows decay, not the last close's
        value frozen)."""
        with self._lock:
            burns = {t: self._burn_locked(t) for t in self._events}
        for tenant, by_win in burns.items():
            for label, rate in by_win.items():
                SLO_BURN_RATE.set(rate, tenant=tenant, window=label)

    def debug_state(self, tail: int = 32) -> dict:
        out = self.summary()
        out["recent"] = self.recent(tail)
        return out


# ---------------------------------------------------------------------------
# process-default ledger (flight-recorder pattern)

_DEFAULT: Optional[SloLedger] = None
_DEFAULT_LOCK = threading.Lock()


def get_slo_ledger() -> SloLedger:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = SloLedger()
    return _DEFAULT


def set_slo_ledger(ledger: Optional[SloLedger]) -> None:
    """Install (or with ``None``, reset) the process-default ledger —
    tests use this to control thresholds and clocks."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = ledger


def update_slo_series() -> None:
    """Scrape-time bridge (called from ``catalog.scrape``): refresh the
    burn-rate gauges so windows decay between request closes."""
    if _DEFAULT is not None:
        _DEFAULT.refresh_series()


def debug_state(tail: int = 32) -> dict:
    return get_slo_ledger().debug_state(tail)


def timelines_jsonl(tail: int = 256) -> List[str]:
    """Recent timelines as JSONL lines (postmortem ``timelines.jsonl``)."""
    import json
    out = []
    for rec in get_slo_ledger().recent(tail):
        try:
            out.append(json.dumps(rec, default=str))
        except (TypeError, ValueError):
            continue
    return out


def isfinite(v) -> bool:
    """Shared ``is this metric sample usable`` predicate: real number,
    not NaN/inf — the anomaly layer uses it so a NaN reservoir (empty
    stats window) can neither fire nor mask a breach."""
    return isinstance(v, (int, float)) and math.isfinite(v)
