"""Tolerant env-knob parsing, shared by the telemetry modules.

Every ``DWT_*`` knob is ambient configuration read on a hot or
startup-critical path; a typo'd value must degrade to the default, never
raise into the serving loop (the always-on black box especially).  One
owner for that rule — ``runlog``, ``flightrecorder``, and ``anomaly``
all parse through here.
"""

from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default
