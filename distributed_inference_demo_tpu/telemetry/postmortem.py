"""Postmortem bundles: dump everything diagnosable the moment it breaks.

A bundle is one directory capturing the state around a trigger — an
anomaly, a ring stall, an unhandled crash — written by
:class:`PostmortemWriter`:

- ``manifest.json``   — reason, trigger detail, timestamps, pid/proc;
- ``flight.jsonl``    — the flight-recorder ring (the moments *before*);
- ``metrics.prom``    — a full ``REGISTRY.render()`` snapshot;
- ``trace.json``      — Chrome trace-event JSON of the offending window
  (caller-provided spans as duration events + flight events as instant
  events — loadable in Perfetto next to a ``/trace`` export);
- ``config.json``     — whatever run configuration the caller holds;
- ``runlog_tail.jsonl`` — the tail of the active structured run log;
- ``timelines.jsonl`` — recent per-request SLO timeline records from
  the SLO ledger (``telemetry/slo.py``): queue wait / TTFT / per-token
  / migration-pause decomposition for the requests leading up to the
  trigger.

Writing is best-effort everywhere: a postmortem must never add a second
failure to the one being recorded (a full disk degrades to a partial
bundle, not an exception in the serving loop).  Bundles are pruned to
``max_bundles`` newest so a flapping detector cannot fill the disk, and
the module-level :func:`trigger` is the one call sites use — it is a
no-op until a writer is configured (``DWT_POSTMORTEM_DIR`` or
:func:`set_postmortem_writer`), so the hot paths stay free when the
operator hasn't asked for black-box capture.

``tools/postmortem.py`` is the offline half: it reads a bundle back and
summarizes it down to the offending hop/window.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import List, Optional

_RUNLOG_TAIL_BYTES = 64 * 1024


def _json_default(o):
    return str(o)


class PostmortemWriter:
    """Writes bundles under ``out_dir``; thread-safe; prunes old ones."""

    def __init__(self, out_dir: str, max_bundles: int = 16,
                 clock=time.time, proc: str = ""):
        self.out_dir = out_dir
        self.max_bundles = max(1, max_bundles)
        self.proc = proc
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        os.makedirs(out_dir, exist_ok=True)   # fail loudly at CONFIG time

    # -- capture -----------------------------------------------------------

    def write_bundle(self, reason: str, detail: Optional[dict] = None,
                     config: Optional[dict] = None,
                     spans: Optional[List[dict]] = None) -> Optional[str]:
        """Capture one bundle; returns its directory path (None if even
        the directory could not be created)."""
        from .flightrecorder import get_flight_recorder
        ts = self._clock()
        with self._lock:
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(ts))
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        # pid in the name: processes routinely SHARE an out_dir (a ring's
        # workers + header), and two same-second crashes with the same
        # per-process seq must not overwrite each other's black box
        path = os.path.join(
            self.out_dir,
            f"pm-{stamp}-p{os.getpid()}-{seq:03d}-{safe}")
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:
            return None

        events = get_flight_recorder().snapshot()
        manifest = {
            "reason": reason,
            "ts": round(ts, 6),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts)),
            "pid": os.getpid(),
            "proc": self.proc,
            "detail": detail or {},
            "flight_events": len(events),
        }
        self._write_json(path, "manifest.json", manifest)
        self._write_lines(path, "flight.jsonl",
                          (json.dumps(e, default=_json_default)
                           for e in events))
        self._write_text(path, "metrics.prom", self._render_metrics())
        self._write_json(path, "trace.json",
                         self._chrome_trace(spans or [], events))
        if config is not None:
            self._write_json(path, "config.json", config)
        tail = self._runlog_tail()
        if tail:
            self._write_text(path, "runlog_tail.jsonl", tail)
        timelines = self._timelines()
        if timelines:
            self._write_text(path, "timelines.jsonl", timelines)
        self._count_bundle()
        self._prune()
        return path

    # -- pieces (each isolated: one failing source loses one file) ---------

    @staticmethod
    def _render_metrics() -> str:
        try:
            from .catalog import REGISTRY, update_flight_series
            update_flight_series()
            return REGISTRY.render()
        except Exception as e:
            return f"# metrics snapshot failed: {e}\n"

    @staticmethod
    def _chrome_trace(spans: List[dict], events: List[dict]) -> dict:
        from .tracing import to_chrome_trace
        try:
            trace = to_chrome_trace(spans)
        except Exception:
            trace = {"traceEvents": [], "displayTimeUnit": "ms"}
        for e in events:
            # flight events as instant markers on one shared lane, so
            # Perfetto shows admissions/hops/stalls against the spans
            trace["traceEvents"].append({
                "ph": "i", "s": "g", "name": e.get("kind", "?"),
                "pid": 0, "tid": 0,
                "ts": int(float(e.get("ts", 0)) * 1e6),
                "args": {k: v for k, v in e.items()
                         if k not in ("ts", "kind")},
            })
        return trace

    @staticmethod
    def _timelines() -> str:
        try:
            from .slo import timelines_jsonl
            lines = timelines_jsonl()
            return "\n".join(lines) + "\n" if lines else ""
        except Exception:
            return ""

    @staticmethod
    def _runlog_tail() -> str:
        from .runlog import get_run_log
        rl = get_run_log()
        path = getattr(rl, "path", None)
        if not path or not os.path.exists(path):
            return ""
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - _RUNLOG_TAIL_BYTES))
                data = f.read()
            # drop a partial first line after the seek
            if size > _RUNLOG_TAIL_BYTES and b"\n" in data:
                data = data.split(b"\n", 1)[1]
            return data.decode("utf-8", "replace")
        except OSError:
            return ""

    def _write_json(self, path: str, name: str, obj) -> None:
        try:
            with open(os.path.join(path, name), "w",
                      encoding="utf-8") as f:
                json.dump(obj, f, indent=1, default=_json_default)
        except OSError:
            pass

    def _write_text(self, path: str, name: str, text: str) -> None:
        try:
            with open(os.path.join(path, name), "w",
                      encoding="utf-8") as f:
                f.write(text)
        except OSError:
            pass

    def _write_lines(self, path: str, name: str, lines) -> None:
        try:
            with open(os.path.join(path, name), "w",
                      encoding="utf-8") as f:
                for line in lines:
                    f.write(line + "\n")
        except OSError:
            pass

    @staticmethod
    def _count_bundle() -> None:
        try:
            from .catalog import ANOMALY_POSTMORTEMS
            ANOMALY_POSTMORTEMS.inc()
        except Exception:
            pass

    def _bundle_names(self) -> List[str]:
        """Bundle directory names, oldest first — ordered by mtime, not
        name (the unpadded pid in the name makes lexicographic order
        non-chronological across processes sharing the directory)."""

        def mtime(d: str) -> float:
            try:
                return os.path.getmtime(os.path.join(self.out_dir, d))
            except OSError:
                return 0.0

        try:
            dirs = [d for d in os.listdir(self.out_dir)
                    if d.startswith("pm-")]
        except OSError:
            return []
        return sorted(dirs, key=lambda d: (mtime(d), d))

    def _prune(self) -> None:
        for d in self._bundle_names()[:-self.max_bundles]:
            full = os.path.join(self.out_dir, d)
            try:
                for name in os.listdir(full):
                    os.unlink(os.path.join(full, name))
                os.rmdir(full)
            except OSError:
                pass

    def bundle_dirs(self) -> List[str]:
        """Bundle paths, oldest first (same mtime order as the pruner)."""
        return [os.path.join(self.out_dir, d)
                for d in self._bundle_names()]


# -- process-default writer + trigger (the call-site surface) --------------

_default: Optional[object] = None      # None-not-yet / _DISABLED / writer
_DISABLED = object()
_default_lock = threading.Lock()


def set_postmortem_writer(writer: Optional[PostmortemWriter]) -> None:
    """Install the process-default writer (``None`` resets to the lazy
    ``DWT_POSTMORTEM_DIR`` resolution)."""
    global _default
    with _default_lock:
        _default = writer


def get_postmortem_writer() -> Optional[PostmortemWriter]:
    """The process-default writer, or None when postmortem capture is
    not configured.  Lazily honors ``DWT_POSTMORTEM_DIR``; an unusable
    path degrades to disabled with one stderr warning (ambient config
    must not crash a serving path)."""
    global _default
    if _default is _DISABLED:
        return None
    if _default is not None:
        return _default
    with _default_lock:
        if _default is None:
            out = os.environ.get("DWT_POSTMORTEM_DIR", "")
            if not out:
                _default = _DISABLED
            else:
                try:
                    _default = PostmortemWriter(out)
                except OSError as e:
                    print(f"postmortem: cannot use {out!r}: {e}; "
                          "bundles disabled", file=sys.stderr)
                    _default = _DISABLED
    return None if _default is _DISABLED else _default


def trigger(reason: str, detail: Optional[dict] = None,
            config: Optional[dict] = None,
            spans: Optional[List[dict]] = None) -> Optional[str]:
    """Write a bundle through the process-default writer.  No-op (None)
    when capture is unconfigured; never raises into the caller — the
    trigger sits on failure paths that must stay failure paths."""
    w = get_postmortem_writer()
    if w is None:
        return None
    try:
        return w.write_bundle(reason, detail=detail, config=config,
                              spans=spans)
    except Exception as e:
        print(f"postmortem: bundle write failed: {e}", file=sys.stderr)
        return None


def debug_state() -> dict:
    """The postmortem fragment of a ``GET /debugz`` payload — ONE owner
    for the shape (see ``flightrecorder.debug_state``)."""
    w = get_postmortem_writer()
    return ({"dir": w.out_dir, "bundles": w.bundle_dirs()}
            if w is not None else {"dir": None, "bundles": []})


_crash_installed = False


def install_crash_handler(config: Optional[dict] = None) -> None:
    """Chain sys/threading excepthooks so an unhandled crash dumps a
    ``crash`` bundle before the process dies (the black box's raison
    d'être).  Idempotent; the previous hooks still run afterwards."""
    global _crash_installed
    if _crash_installed:
        return
    _crash_installed = True
    prev_sys = sys.excepthook

    def _detail(exc_type, exc, tb) -> dict:
        return {"exc_type": getattr(exc_type, "__name__", str(exc_type)),
                "exc": str(exc),
                "traceback": traceback.format_exception(exc_type, exc,
                                                        tb)}

    def hook(exc_type, exc, tb):
        # deliberate shutdowns are not crashes: a Ctrl-C'd rolling
        # restart must not write bundles that prune real incidents
        if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
            trigger("crash", detail=_detail(exc_type, exc, tb),
                    config=config)
        prev_sys(exc_type, exc, tb)

    sys.excepthook = hook

    prev_thread = threading.excepthook

    def thread_hook(args):
        if not issubclass(args.exc_type,
                          (KeyboardInterrupt, SystemExit)):
            trigger("crash", detail=dict(
                _detail(args.exc_type, args.exc_value, args.exc_traceback),
                thread=getattr(args.thread, "name", "?")), config=config)
        prev_thread(args)

    threading.excepthook = thread_hook
