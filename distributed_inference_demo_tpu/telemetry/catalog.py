"""The repo's standard metric set, registered at import time.

One place owns every Prometheus series name, its help text, and the
mapping from the existing stats surfaces (``runtime/stats.StageStats``
snapshots, ``runtime/batching`` scheduler counters, ``monitor/probes``
measurements, HTTP handler events) onto those series.  Naming convention
``dwt_<subsystem>_<name>_<unit>`` (+ ``_total`` on counters) is enforced
by ``tools/check_metrics_names.py``, which walks :data:`metrics.REGISTRY`
after importing this module.

``scrape(backend)`` is the one entry point the HTTP handlers call: it
refreshes snapshot-bridged series from the backend and renders the
registry.
"""

from __future__ import annotations

from .metrics import (LATENCY_BUCKETS_S, REGISTRY, counter, gauge,
                      histogram)
# the per-tenant SLO/goodput series (dwt_slo_*) register on slo's import
# — pulled in here so "import catalog" keeps meaning "the full standard
# set is registered" (the metric-name lint and /metrics both rely on it)
from . import slo  # noqa: E402  (registers dwt_slo_* series)

# -- stage (pipeline role) series, bridged from StageStats snapshots -------

_STAGE_LABELS = ("role", "device")

STAGE_STEPS = counter(
    "dwt_stage_steps_total",
    "Pipeline compute steps executed by this stage (prefill or decode "
    "chunk)", _STAGE_LABELS)
STAGE_RECV_WAIT = counter(
    "dwt_stage_recv_wait_seconds_total",
    "Seconds this stage spent blocked waiting for inbound ring messages",
    _STAGE_LABELS)
STAGE_COMPUTE = counter(
    "dwt_stage_compute_seconds_total",
    "Seconds of stage compute (deserialize + forward + serialize)",
    _STAGE_LABELS)
STAGE_SEND = counter(
    "dwt_stage_send_seconds_total",
    "Seconds spent in transport send calls", _STAGE_LABELS)
STAGE_RECV_BYTES = counter(
    "dwt_stage_recv_bytes_total",
    "Bytes received from the ring by this stage", _STAGE_LABELS)
STAGE_SENT_BYTES = counter(
    "dwt_stage_sent_bytes_total",
    "Bytes sent to the ring by this stage", _STAGE_LABELS)
STAGE_RECV_MSGS = counter(
    "dwt_stage_recv_messages_total",
    "Ring messages received by this stage", _STAGE_LABELS)
STAGE_SENT_MSGS = counter(
    "dwt_stage_sent_messages_total",
    "Ring messages sent by this stage", _STAGE_LABELS)
STAGE_UPTIME = gauge(
    "dwt_stage_uptime_seconds",
    "Seconds since this stage's stats were created or reset",
    _STAGE_LABELS)

_STAGE_PCT = {}
for _phase, _help in (("compute", "per-step stage compute latency"),
                      ("ring_rtt", "header hidden-out to token-back ring "
                                   "round trip")):
    for _q in (50, 95, 99):
        _STAGE_PCT[(_phase, _q)] = gauge(
            f"dwt_stage_{_phase}_p{_q}_seconds",
            f"p{_q} of {_help} (bounded reservoir)", _STAGE_LABELS)


def update_stage_series(snapshots) -> None:
    """Bridge StageStats ``snapshot()`` dicts (one per pipeline stage,
    as returned by ``PipelineHeader.collect_stats`` / ``/stats``) onto
    the ``dwt_stage_*`` series."""
    for s in snapshots:
        if not isinstance(s, dict) or "role" not in s:
            continue
        lab = {"role": s["role"], "device": s.get("device_id", "")}
        STAGE_STEPS.set_cumulative(s.get("steps", 0), **lab)
        STAGE_RECV_WAIT.set_cumulative(s.get("recv_wait_s", 0.0), **lab)
        STAGE_COMPUTE.set_cumulative(s.get("compute_s", 0.0), **lab)
        STAGE_SEND.set_cumulative(s.get("send_s", 0.0), **lab)
        STAGE_RECV_BYTES.set_cumulative(s.get("bytes_in", 0), **lab)
        STAGE_SENT_BYTES.set_cumulative(s.get("bytes_out", 0), **lab)
        STAGE_RECV_MSGS.set_cumulative(s.get("messages_in", 0), **lab)
        STAGE_SENT_MSGS.set_cumulative(s.get("messages_out", 0), **lab)
        STAGE_UPTIME.set(s.get("uptime_s", 0.0), **lab)
        for (phase, q), g in _STAGE_PCT.items():
            v = s.get(f"{phase}_p{q}_ms")
            # absent key = empty reservoir (fresh or just reset): the
            # gauge must say "no data" (NaN), not keep reporting the
            # pre-reset (e.g. compile-warmup) latency forever
            g.set(v / 1e3 if v is not None else float("nan"), **lab)


# -- batching / speculative series, bridged from scheduler counters --------

BATCH_QUEUE_DEPTH = gauge(
    "dwt_batching_queue_depth_requests",
    "Requests admitted to the scheduler but not yet holding a slot "
    "(submit queue + pending)")
BATCH_ACTIVE = gauge(
    "dwt_batching_active_slots",
    "Slots currently decoding a request")
BATCH_CAPACITY = gauge(
    "dwt_batching_capacity_slots",
    "Total decode slots in the continuous-batching pool")
BATCH_STEPS = counter(
    "dwt_batching_steps_total",
    "Lockstep decode steps (or speculative rounds) executed by the slot "
    "scheduler")
BATCH_COMPLETED = counter(
    "dwt_batching_completed_requests_total",
    "Requests fully served by the slot scheduler")
# (the deprecated dwt_batching_prefix_* aliases of the dwt_kvcache_*
# series — kept "one release" by PR 3 — are REMOVED: three releases
# shipped; dashboards migrate by recording rule, docs/DESIGN.md §10)
_BATCH_PCT = {
    (name, q): gauge(
        f"dwt_batching_{name}_p{q}_seconds",
        f"p{q} {desc} over the last completed requests")
    for name, desc in (("ttft", "time to first token"),
                       ("e2e", "request end-to-end latency"),
                       ("per_token", "per-output-token latency"))
    for q in (50, 95)}
BATCH_MIXED_DISPATCHES = counter(
    "dwt_batching_mixed_dispatches_total",
    "Mixed prefill+decode dispatches executed under the token budget "
    "(docs/DESIGN.md §19; each packs the fused decode block plus zero "
    "or more prefill chunk segments into one program)")
BATCH_MIXED_PREFILL_TOKENS = counter(
    "dwt_batching_mixed_prefill_tokens_total",
    "Prompt tokens prefilled inside mixed dispatches (piggybacked on "
    "the decode step instead of a serialized admission dispatch)")
BATCH_TOKEN_BUDGET_UTILIZATION = gauge(
    "dwt_batching_token_budget_utilization",
    "Packed tokens (prefill segments + decode-loop steps x active "
    "rows) over budgeted tokens across mixed dispatches; NaN until "
    "the first mixed dispatch")
# spec-in-the-batch series (docs/DESIGN.md §22): the scheduler-side view
# of speculation — drafted/accepted feed the acceptance ratio, and the
# per-bucket K_row occupancy gauge is the observable adaptive-K signal
# (a low-acceptance workload walks active rows toward bucket "1")
BATCH_DRAFT_TOKENS = counter(
    "dwt_batching_draft_tokens_total",
    "Draft tokens the slot scheduler offered to the verifier "
    "(speculative rows, serialized or mixed dispatch; adaptive K "
    "prices each row by what it actually offered)")
BATCH_ACCEPTED_TOKENS = counter(
    "dwt_batching_accepted_tokens_total",
    "Draft tokens the verifier accepted on scheduler rows (excl. the "
    "bonus/resample token)")
BATCH_DRAFT_LEN = gauge(
    "dwt_batching_draft_len",
    "Active decode rows currently assigned this adaptive draft-length "
    "bucket (K_row; docs/DESIGN.md §22)", ("bucket",))
BATCH_SPEC_ACCEPT_RATIO = gauge(
    "dwt_batching_spec_acceptance_ratio",
    "accepted/drafted over the scheduler's speculative rows (NaN until "
    "the first draft)")
BATCH_RESUMED = counter(
    "dwt_batching_resumed_requests_total",
    "Requests admitted through the gateway-failover resume path "
    "(docs/DESIGN.md §23): the delivered prefix re-derived through "
    "normal paged admission on a survivor replica, verified "
    "token-by-token, then streamed from the cut point")
BATCH_RESUME_REPLAYED = counter(
    "dwt_batching_resume_replayed_tokens_total",
    "Delivered tokens re-derived and verify-swallowed (never "
    "re-streamed) during resume replays")
BATCH_RESUME_DIVERGED = counter(
    "dwt_batching_resume_diverged_requests_total",
    "Resume replays that regenerated a token differing from the "
    "journal (foreign engine config/seed, or concurrent streams "
    "reordering the rng spend) — failed loudly instead of streaming "
    "a wrong suffix")

# -- block KV cache (runtime/kvcache), bridged from manager snapshots ------

KVCACHE_HITS = counter(
    "dwt_kvcache_hits_total",
    "Prompt lookups that matched at least one whole cached KV block")
KVCACHE_MISSES = counter(
    "dwt_kvcache_misses_total",
    "Prompt lookups (>= one block long) that matched nothing")
KVCACHE_PARTIAL_HIT_TOKENS = counter(
    "dwt_kvcache_partial_hit_tokens_total",
    "Prompt tokens whose prefill was skipped via matched KV blocks "
    "(every hit is a partial-prefix hit: reuse is capped below the "
    "prompt length so the suffix forward is never empty)")
KVCACHE_STORED_BLOCKS = counter(
    "dwt_kvcache_stored_blocks_total",
    "KV blocks admitted into the block pool at prefill time")
KVCACHE_EVICTED_BLOCKS = counter(
    "dwt_kvcache_evicted_blocks_total",
    "KV blocks reclaimed by LRU leaf eviction under pool pressure")
KVCACHE_RESIDENT_BYTES = gauge(
    "dwt_kvcache_resident_bytes",
    "Host bytes held by in-use KV blocks (K + V)")
KVCACHE_CAPACITY_BYTES = gauge(
    "dwt_kvcache_capacity_bytes",
    "Preallocated byte budget of the KV block pool")
KVCACHE_USED_BLOCKS = gauge(
    "dwt_kvcache_used_blocks",
    "KV blocks currently referenced by the radix tree (the prefix "
    "cache's share; compare dwt_kvcache_blocks_in_use for all owners)")
KVCACHE_NODES = gauge(
    "dwt_kvcache_tree_nodes",
    "Radix-tree nodes (excluding the root): distinct shared-prefix "
    "branch points plus leaves")
KVCACHE_DEVICE_RESIDENT_BYTES = gauge(
    "dwt_kvcache_device_resident_bytes",
    "Device HBM held by in-use KV blocks (paged layout: pages allocated "
    "to block tables or the radix tree; 0 on the host-pool dense "
    "layout)")
KVCACHE_BLOCKS_IN_USE = gauge(
    "dwt_kvcache_blocks_in_use",
    "KV blocks currently allocated, all owners: radix-tree cache plus "
    "(paged layout) in-flight requests' private blocks")
KVCACHE_H2D_BYTES = counter(
    "dwt_kvcache_h2d_bytes_total",
    "Bytes copied host-to-device to seed caches from prefix hits "
    "(dense layout's per-hit gather; stays 0 on the paged path, where "
    "hits are device block-table references)")
KVCACHE_PAGE_DTYPE = gauge(
    "dwt_kvcache_page_dtype_info",
    "Page width of the paged KV pool as an info gauge: the series with "
    "the active --kv-dtype label (bf16 / int8 / int4) reads 1, the "
    "others 0 (docs/DESIGN.md §17)", ("dtype",))
KVCACHE_QUANT_SCALE_BYTES = gauge(
    "dwt_kvcache_quant_scale_bytes",
    "Device bytes held by quantization scale (and int4 zero-point) "
    "sidecars of in-use pages — the accounting overhead the narrow "
    "page width pays; 0 on the bf16 layout")

# -- capacity tier below the device pool (docs/DESIGN.md §21) --------------
# demotions gather evicted radix leaves to a host-RAM ring (optionally
# spilling to an mmap'd disk segment); a radix miss whose prefix sits
# demoted promotes back through the staged-adopt seam.  Gauges carry a
# tier label (host / disk); promote H2D bytes ALSO count into
# dwt_kvcache_h2d_bytes_total — the honest-bytes invariant.

KVCACHE_TIER_RESIDENT_BYTES = gauge(
    "dwt_kvcache_tier_resident_bytes",
    "Bytes of demoted KV blocks resident per capacity tier (host ring "
    "/ disk segment); 0 when tiering is off (--kv-host-tier-bytes "
    "unset)", ("tier",))
KVCACHE_TIER_RESIDENT_BLOCKS = gauge(
    "dwt_kvcache_tier_resident_blocks",
    "Demoted KV blocks resident per capacity tier", ("tier",))
KVCACHE_TIER_CAPACITY_BYTES = gauge(
    "dwt_kvcache_tier_capacity_bytes",
    "Configured byte budget per capacity tier (--kv-host-tier-bytes / "
    "--kv-disk-tier-bytes)", ("tier",))
KVCACHE_TIER_DEMOTED_BLOCKS = counter(
    "dwt_kvcache_tier_demoted_blocks_total",
    "KV blocks demoted out of the device pool into the host ring by "
    "LRU leaf eviction (admitted after in-tier dedup)")
KVCACHE_TIER_DEMOTED_BYTES = counter(
    "dwt_kvcache_tier_demoted_bytes_total",
    "Bytes demoted into the host ring (quantized payload + sidecars, "
    "at page width — NOT dequantized)")
KVCACHE_TIER_PROMOTED_BLOCKS = counter(
    "dwt_kvcache_tier_promoted_blocks_total",
    "Demoted KV blocks promoted back into device pages on a tier hit "
    "(move semantics: the tier copy is consumed)")
KVCACHE_TIER_PROMOTED_BYTES = counter(
    "dwt_kvcache_tier_promoted_bytes_total",
    "Bytes promoted back to the device (also counted into "
    "dwt_kvcache_h2d_bytes_total: promotion is the one H2D path the "
    "paged layout has)")
KVCACHE_TIER_DROPPED_BLOCKS = counter(
    "dwt_kvcache_tier_dropped_blocks_total",
    "Demoted blocks dropped at the bottom of the hierarchy (host "
    "overflow with no disk tier, or disk overflow) — the tier is a "
    "cache, dropping is correct, but a high rate means the budgets "
    "are undersized for the prefix working set")
KVCACHE_TIER_SPILLED_BLOCKS = counter(
    "dwt_kvcache_tier_spilled_blocks_total",
    "Blocks spilled host ring -> disk segment under host-budget "
    "pressure (LRU position preserved; payload leaves RAM)")
KVCACHE_TIER_HITS = counter(
    "dwt_kvcache_tier_hits_total",
    "Tier lookups that promoted at least one block, per tier the "
    "payload was read from", ("tier",))

# demote is a device gather + host copy (sub-ms to ms); promote adds
# the staged-adopt scatter dispatch.  Both sit well below the request
# buckets, so they share the dispatch-scale profile buckets.
_TIER_BUCKETS_S = (0.0002, 0.0005, 0.001, 0.002, 0.004, 0.008,
                   0.016, 0.032, 0.064, 0.125, 0.25, 0.5, 1.0, 4.0)
KVCACHE_TIER_DEMOTE_SECONDS = histogram(
    "dwt_kvcache_tier_demote_seconds",
    "Wall time of one demotion (device gather of the evicted leaf + "
    "host-ring insert + budget eviction)", buckets=_TIER_BUCKETS_S)
KVCACHE_TIER_PROMOTE_SECONDS = histogram(
    "dwt_kvcache_tier_promote_seconds",
    "Wall time of one promotion (tier read + staged adopt scatter + "
    "radix re-insert)", buckets=_TIER_BUCKETS_S)


def update_kvcache_tier_series(tier: dict) -> None:
    """Bridge a ``TieredKVStore.snapshot()`` fragment (attached under
    ``snapshot()["tier"]`` by the pool owner) onto the
    ``dwt_kvcache_tier_*`` series."""
    for t in ("host", "disk"):
        KVCACHE_TIER_RESIDENT_BYTES.set(
            tier.get(f"{t}_resident_bytes", 0), tier=t)
        KVCACHE_TIER_RESIDENT_BLOCKS.set(
            tier.get(f"{t}_blocks", 0), tier=t)
        KVCACHE_TIER_CAPACITY_BYTES.set(
            tier.get(f"{t}_capacity_bytes", 0), tier=t)
        KVCACHE_TIER_HITS.set_cumulative(
            tier.get(f"{t}_hits", 0), tier=t)
    KVCACHE_TIER_DEMOTED_BLOCKS.set_cumulative(
        tier.get("demoted_blocks", 0))
    KVCACHE_TIER_DEMOTED_BYTES.set_cumulative(
        tier.get("demoted_bytes", 0))
    KVCACHE_TIER_PROMOTED_BLOCKS.set_cumulative(
        tier.get("promoted_blocks", 0))
    KVCACHE_TIER_PROMOTED_BYTES.set_cumulative(
        tier.get("promoted_bytes", 0))
    KVCACHE_TIER_DROPPED_BLOCKS.set_cumulative(
        tier.get("dropped_blocks", 0))
    KVCACHE_TIER_SPILLED_BLOCKS.set_cumulative(
        tier.get("spilled_blocks", 0))


def update_kvcache_series(kv: dict) -> None:
    """Bridge a ``KVCacheManager.snapshot()`` dict onto the
    ``dwt_kvcache_*`` series."""
    KVCACHE_HITS.set_cumulative(kv.get("hits", 0))
    KVCACHE_MISSES.set_cumulative(kv.get("misses", 0))
    KVCACHE_PARTIAL_HIT_TOKENS.set_cumulative(
        kv.get("partial_hit_tokens", 0))
    KVCACHE_STORED_BLOCKS.set_cumulative(kv.get("stored_blocks", 0))
    KVCACHE_EVICTED_BLOCKS.set_cumulative(kv.get("evicted_blocks", 0))
    KVCACHE_RESIDENT_BYTES.set(kv.get("resident_bytes", 0))
    KVCACHE_CAPACITY_BYTES.set(kv.get("capacity_bytes", 0))
    # used_blocks = the TREE's share (dense snapshots lack tree_blocks
    # because there blocks_used IS tree-owned); blocks_in_use = all
    # owners.  The gap between the two gauges is in-flight requests'
    # private pages — the §11 runbook's leak alert (blocks_in_use >
    # used_blocks while idle) depends on them being bridged from
    # DIFFERENT snapshot keys on the paged layout.
    KVCACHE_USED_BLOCKS.set(kv.get("tree_blocks",
                                   kv.get("blocks_used", 0)))
    KVCACHE_NODES.set(kv.get("nodes", 0))
    KVCACHE_DEVICE_RESIDENT_BYTES.set(kv.get("device_resident_bytes", 0))
    KVCACHE_BLOCKS_IN_USE.set(kv.get("blocks_used", 0))
    KVCACHE_H2D_BYTES.set_cumulative(kv.get("h2d_bytes", 0))
    page_dtype = kv.get("page_dtype")
    if page_dtype is not None:
        from ..ops.quant import KV_DTYPES
        for d in KV_DTYPES:
            KVCACHE_PAGE_DTYPE.set(1 if d == page_dtype else 0, dtype=d)
        KVCACHE_QUANT_SCALE_BYTES.set(kv.get("quant_scale_bytes", 0))
    tier = kv.get("tier")
    if tier:
        update_kvcache_tier_series(tier)


SPEC_ROUNDS = counter(
    "dwt_speculative_rounds_total",
    "Draft/verify rounds executed (speculative or prompt-lookup)")
SPEC_DRAFTED = counter(
    "dwt_speculative_drafted_tokens_total",
    "Draft tokens proposed to the verifier")
SPEC_ACCEPTED = counter(
    "dwt_speculative_accepted_tokens_total",
    "Draft tokens accepted by the verifier (excl. bonus/resample)")
SPEC_ACCEPT_RATIO = gauge(
    "dwt_speculative_accept_ratio",
    "accepted/drafted over the counters' lifetime (NaN until the first "
    "draft)")


def update_batching_series(stats: dict) -> None:
    """Bridge ``ContinuousBatchingEngine.stats()`` (or any dict with the
    same keys) onto the ``dwt_batching_*`` / ``dwt_speculative_*`` /
    ``dwt_kvcache_*`` series (a bare ``{"kvcache": ...}`` fragment — the
    plain engines' ``scrape_stats`` — bridges the kvcache section
    alone)."""
    if "slots" in stats:
        BATCH_CAPACITY.set(stats["slots"])
    if "queue_depth" in stats:
        BATCH_QUEUE_DEPTH.set(stats["queue_depth"])
    if "active_slots" in stats:
        BATCH_ACTIVE.set(stats["active_slots"])
    if "steps" in stats:
        BATCH_STEPS.set_cumulative(stats["steps"])
    lat = stats.get("latency") or {}
    if "completed" in lat:
        BATCH_COMPLETED.set_cumulative(lat["completed"])
    for (name, q), g in _BATCH_PCT.items():
        v = lat.get(f"{name}_p{q}_ms")
        # NaN on empty/reset reservoirs, as in update_stage_series
        g.set(v / 1e3 if v is not None else float("nan"))
    mx = stats.get("mixed") or {}
    if mx:
        BATCH_MIXED_DISPATCHES.set_cumulative(mx.get("dispatches", 0))
        BATCH_MIXED_PREFILL_TOKENS.set_cumulative(
            mx.get("prefill_tokens", 0))
        u = mx.get("budget_utilization")
        BATCH_TOKEN_BUDGET_UTILIZATION.set(
            u if u is not None else float("nan"))
    rs = stats.get("resumed") or {}
    if rs:
        BATCH_RESUMED.set_cumulative(rs.get("requests", 0))
        BATCH_RESUME_REPLAYED.set_cumulative(
            rs.get("replayed_tokens", 0))
        BATCH_RESUME_DIVERGED.set_cumulative(rs.get("diverged", 0))
    kv = stats.get("kvcache") or {}
    if kv:
        update_kvcache_series(kv)
    sp = stats.get("speculative") or {}
    if sp:
        SPEC_ROUNDS.set_cumulative(sp.get("rounds", 0))
        if "drafted" in sp:
            SPEC_DRAFTED.set_cumulative(sp["drafted"])
            BATCH_DRAFT_TOKENS.set_cumulative(sp["drafted"])
        if "accepted" in sp:
            SPEC_ACCEPTED.set_cumulative(sp["accepted"])
            BATCH_ACCEPTED_TOKENS.set_cumulative(sp["accepted"])
        ar = sp.get("acceptance_rate")
        if ar is not None:
            SPEC_ACCEPT_RATIO.set(ar)
        BATCH_SPEC_ACCEPT_RATIO.set(
            ar if ar is not None else float("nan"))
        for b, nrows in (sp.get("k_row_buckets") or {}).items():
            BATCH_DRAFT_LEN.set(nrows, bucket=str(b))


# -- engine device-loop series (event-driven, docs/DESIGN.md §13) ----------
# dispatches/token ≈ 1/K is the headline invariant: the device-resident
# decode loop touches the host once per K-token block (or earlier on an
# all-rows-done exit), so a ratio drifting toward 1 means the fused loop
# stopped engaging (stream_block/decode_block misconfigured, or a code
# path fell back to per-token dispatch)

ENGINE_HOST_DISPATCHES = counter(
    "dwt_engine_host_dispatches_total",
    "Decode-loop programs dispatched from the host, by engine "
    "(one per K-token device-loop block on the fused paths; one per "
    "token on the per-token reference path)", ("engine",))
ENGINE_DEVICE_LOOP_STEPS = counter(
    "dwt_engine_device_loop_steps_total",
    "Decode steps actually executed inside device-resident loops, by "
    "engine (early exit means steps < K for a block whose rows all "
    "finished; divide dwt_engine_host_dispatches_total by this for "
    "dispatches per token)", ("engine",))


# -- HTTP serving series (event-driven, not snapshot-bridged) --------------

HTTP_REQUESTS = counter(
    "dwt_http_requests_total",
    "HTTP requests answered, by route and status code",
    ("route", "code"))
HTTP_REQUEST_SECONDS = histogram(
    "dwt_http_request_seconds",
    "Wall-clock latency of successful blocking inference requests",
    ("route",), buckets=LATENCY_BUCKETS_S)
HTTP_GENERATED_TOKENS = counter(
    "dwt_http_generated_tokens_total",
    "Tokens returned by successful /generate requests")


# -- transport reliability / fault injection series ------------------------
# event-driven from comm/transport.py + comm/faults.py (docs/DESIGN.md §12
# runbook: which counter spiking means what)

TRANSPORT_SEND_RETRIES = counter(
    "dwt_transport_send_retries_total",
    "Transport send attempts beyond the first (bounded retry with "
    "exponential backoff + jitter; a sustained rate means a slow or "
    "flapping peer)")
TRANSPORT_RECONNECTS = counter(
    "dwt_transport_reconnects_total",
    "Outbound sockets torn down and re-dialed after a hard send error")
TRANSPORT_CORRUPT_FRAMES = counter(
    "dwt_transport_corrupt_frames_total",
    "Inbound frames dropped on wire-checksum mismatch (each is a frame "
    "that would otherwise have decoded garbage into the pipeline)")
FAULT_INJECTED = counter(
    "dwt_fault_injected_faults_total",
    "Faults injected by an active chaos fault plan, by kind (drop, "
    "delay, duplicate, reorder, corrupt, partition, partition_drop, "
    "crash_after).  Nonzero outside a chaos run is an incident",
    ("kind",))


# -- disaggregated prefill/decode series (docs/DESIGN.md §15) --------------
# event-driven from runtime/disagg.py: the prefill worker counts what it
# migrates, the decode worker what it adopts, the coordinator what it
# reschedules.  migrated vs adopted pages diverging means migrations are
# completing on the wire but failing to join (staging drops, manifest
# mismatches); rescheduled > 0 names prefill-worker deaths.

DISAGG_MIGRATED_PAGES = counter(
    "dwt_disagg_migrated_pages_total",
    "KV pages a prefill worker streamed to a decode worker (whole "
    "prompt blocks; counted once per completed, acknowledged "
    "migration)")
DISAGG_MIGRATED_BYTES = counter(
    "dwt_disagg_migrated_bytes_total",
    "Wire bytes of page-payload frames in completed migrations "
    "(CRC-framed K/V block runs + metadata)")
DISAGG_ADOPTED_PAGES = counter(
    "dwt_disagg_adopted_pages_total",
    "Migrated pages the decode worker landed in its pool and the radix "
    "tree adopted (device scatter + ownership transfer; the join side "
    "of dwt_disagg_migrated_pages_total)")
DISAGG_JOINED = counter(
    "dwt_disagg_joined_requests_total",
    "Disaggregated requests joined into the decode worker's "
    "continuous-batching drain after a complete migration")
DISAGG_RESCHEDULED = counter(
    "dwt_disagg_rescheduled_requests_total",
    "Handoffs resent to a different prefill worker after the original "
    "died or failed mid-migration (each bumps the request's attempt; "
    "stale-attempt frames are discarded by the decode worker)")
DISAGG_RETRANSMITTED = counter(
    "dwt_disagg_retransmitted_frames_total",
    "Page frames retransmitted after a receiver nack (go-back-n over "
    "dropped or CRC-rejected frames; a sustained rate means a lossy "
    "migration path)")
DISAGG_DROPPED_FRAMES = counter(
    "dwt_disagg_dropped_frames_total",
    "Migration frames the decode worker discarded: duplicates and "
    "reorder holes ((rid, attempt, seq) dedup), stale attempts, and "
    "frames for already-joined requests — each a retry made idempotent")
DISAGG_MIGRATION_SECONDS = histogram(
    "dwt_disagg_migration_seconds",
    "Prefill-worker wall time from handoff start to migration "
    "acknowledged (chunked prefill + page streaming + ack)",
    buckets=LATENCY_BUCKETS_S)
DISAGG_HANDOFF_QUEUE = gauge(
    "dwt_disagg_handoff_queue_depth_requests",
    "Requests submitted to the coordinator that have not yet produced "
    "their first decode-side token (prefilling, migrating, or waiting "
    "for a prefill worker)")
DISAGG_INFLIGHT = gauge(
    "dwt_disagg_inflight_requests",
    "Disaggregated requests submitted and not yet finished, all "
    "phases (handoff + migration + decode)")


# -- replicated serving gateway series (docs/DESIGN.md §16) ----------------
# event-driven from runtime/gateway/: the gateway process holds no
# engine backend, so nothing here is snapshot-bridged — every series is
# incremented at the moment the routing/proxy decision happens.

GATEWAY_PREFIX_ROUTED = counter(
    "dwt_gateway_prefix_routed_requests_total",
    "Requests routed by the prefix-aware policy: the chosen replica's "
    "routing-history index held the longest matching token prefix at "
    "or above the min-length threshold")
GATEWAY_HASHED = counter(
    "dwt_gateway_hashed_requests_total",
    "Requests routed by the consistent-hash-with-bounded-load "
    "fallback (no replica's index matched enough prefix, or routing "
    "keys were unavailable)")
GATEWAY_TIER_ROUTED = counter(
    "dwt_gateway_tier_routed_requests_total",
    "Requests routed by the host-tier second chance: no replica's "
    "device-tier index matched enough prefix, but a replica's "
    "reported demoted-prefix digest (docs/DESIGN.md §21) did — the "
    "replica promotes from its host ring instead of re-prefilling")
GATEWAY_RETRIED = counter(
    "dwt_gateway_retried_requests_total",
    "Requests re-proxied to an alternate replica after the first "
    "choice failed BEFORE its first streamed token (past first token "
    "the gateway never retries: the client already saw output)")
GATEWAY_SHED = counter(
    "dwt_gateway_shed_requests_total",
    "Requests the gateway answered 503/429: every replica down, every "
    "candidate overloaded, or a replica's Retry-After propagated "
    "through federated admission")
# §23 zero-loss streams: a replica dying MID-stream no longer ends the
# request — the gateway journals delivered lines and re-POSTs the
# stream to a survivor with a resume payload (attempts bounded by
# --resume-limit; exhaustion falls back to the error-line contract)
GATEWAY_RESUME_ATTEMPTS = counter(
    "dwt_gateway_resume_attempts_total",
    "Mid-stream failover resume attempts: a journaled stream's replica "
    "died after first token and the gateway re-POSTed the request to "
    "a survivor with the delivered-token journal (docs/DESIGN.md §23)")
GATEWAY_RESUME_SUCCEEDED = counter(
    "dwt_gateway_resume_succeeded_total",
    "Resume attempts that streamed the remainder to completion on a "
    "survivor (the client saw delivered prefix + resumed suffix with "
    "no repeats, gaps, or torn lines)")
GATEWAY_RESUME_EXHAUSTED = counter(
    "dwt_gateway_resume_exhausted_requests_total",
    "Mid-stream deaths whose resume attempts were exhausted (or no "
    "eligible survivor existed): degraded to the documented error-line "
    "fallback")
GATEWAY_RESUME_TTF_SECONDS = histogram(
    "dwt_gateway_resume_ttf_seconds",
    "Time from detecting a mid-stream replica death to the first "
    "resumed token forwarded from the survivor (routing + re-POST + "
    "replay window)",
    buckets=LATENCY_BUCKETS_S)
GATEWAY_REPLICA_FAILURES = counter(
    "dwt_gateway_replica_failures_total",
    "Replica failures recorded by the registry, by bounded failure "
    "reason: probe (health prober), proxy (pre-first-token proxy "
    "death), mid-stream (died after first streamed token), resume "
    "(failed while serving a failover resume), other",
    ("reason",))
GATEWAY_REPLICA_DOWN = counter(
    "dwt_gateway_replica_down_total",
    "Replica up->down transitions: health probes (or proxy failures) "
    "breached the sustain threshold and the registry evicted the "
    "replica from routing")
GATEWAY_REPLICA_UP = counter(
    "dwt_gateway_replica_up_total",
    "Replica down->up transitions: a probe succeeded after the "
    "readmission cooldown and the registry restored the replica")
GATEWAY_UP_REPLICAS = gauge(
    "dwt_gateway_up_replicas",
    "Replicas currently admitted to routing (registered minus "
    "evicted)")
GATEWAY_DRAINING = gauge(
    "dwt_gateway_draining_replicas",
    "Replicas marked draining by an operator or the migration "
    "controller: excluded from NEW routing decisions (no eviction "
    "strike — health is orthogonal) while in-flight proxies keep "
    "streaming.  Stuck nonzero means a drain is not converging")
GATEWAY_PREFIX_HIT_RATIO = gauge(
    "dwt_gateway_prefix_hit_ratio",
    "Per-replica estimate of the fraction of routed requests whose "
    "prefix the replica's cache already held (gateway-side estimate "
    "from its routing-history index; reconcile against the replica's "
    "own dwt_kvcache_hits_total)", ("replica",))
GATEWAY_INDEX_ENTRIES = gauge(
    "dwt_gateway_index_entries",
    "Token-prefix routing-history index entries per replica (bounded; "
    "reconciled against replica-reported dwt_kvcache_* stats)",
    ("replica",))
GATEWAY_QUEUE_DEPTH = gauge(
    "dwt_gateway_queue_depth_requests",
    "Last replica-reported admission queue depth (from /stats), per "
    "replica — the bounded-load signal for the hash fallback",
    ("replica",))
GATEWAY_PROXY_TTFT_SECONDS = histogram(
    "dwt_gateway_proxy_ttft_seconds",
    "Gateway-observed time from accepting /generate to the first "
    "byte proxied back from the replica (includes routing, replica "
    "queueing, and prefill)",
    buckets=LATENCY_BUCKETS_S)
GATEWAY_FLEET_SCRAPES = counter(
    "dwt_gateway_fleet_scrapes_total",
    "Successful per-replica /metrics pulls performed by the "
    "GET /metrics/fleet federation endpoint (cache refreshes, not "
    "client requests — a debounced request serves the cached text "
    "without counting here)", ("replica",))
GATEWAY_FLEET_SCRAPE_FAILURES = counter(
    "dwt_gateway_fleet_failed_scrapes_total",
    "Failed per-replica /metrics pulls during fleet federation; the "
    "endpoint serves that replica's last good text until the bounded "
    "staleness window expires, then drops its section with an "
    "explanatory comment", ("replica",))
GATEWAY_FLEET_SCRAPE_AGE = gauge(
    "dwt_gateway_fleet_scrape_age_seconds",
    "Age of each replica's federated /metrics section at the last "
    "GET /metrics/fleet render — bounded by the staleness window; a "
    "replica pinned at the bound is scraping dead", ("replica",))


# -- live decode-to-decode migration series (docs/DESIGN.md §18) -----------
# event-driven from runtime/migration.py: the source counts what it
# exports and replays, the target what it imports and aborts.  exported
# vs imported diverging means handoffs complete on the wire but fail to
# admit (capacity, dtype mismatch) — pair with failed_migrations in
# /debugz.  replayed_steps > 1 per migration means the freeze window is
# too wide (raise DWT_MIGRATION_FRAME_BLOCKS or check target load).

MIGRATION_EXPORTED = counter(
    "dwt_migration_exported_requests_total",
    "Mid-flight requests a source replica froze, shipped, and handed "
    "off to a target replica (counted once per acknowledged handoff; "
    "the source keeps relaying the stream to its client)")
MIGRATION_IMPORTED = counter(
    "dwt_migration_imported_requests_total",
    "Mid-flight requests a target replica admitted from staged pages "
    "+ state and resumed decoding (the import side of "
    "dwt_migration_exported_requests_total)")
MIGRATION_ABORTED = counter(
    "dwt_migration_aborted_requests_total",
    "Staged migrations the target discarded on a source abort (pgx "
    "frame), staging-cap eviction, or supersession by a newer attempt "
    "— staging bytes are freed and late frames of the attempt drop")
MIGRATION_REPLAYED = counter(
    "dwt_migration_replayed_steps_total",
    "Decode steps the target re-emitted that the source had already "
    "streamed (the at-most-one-step overlap of the atomic handoff; "
    "deduped by absolute step index, never forwarded twice)")
MIGRATION_MOVED_PAGES = counter(
    "dwt_migration_moved_pages_total",
    "KV pages shipped in acknowledged live migrations (phase-1 "
    "snapshot plus phase-2 delta blocks)")
MIGRATION_MOVED_BYTES = counter(
    "dwt_migration_moved_bytes_total",
    "Wire bytes of page-payload frames in acknowledged live "
    "migrations (CRC-framed K/V block runs + metadata)")
MIGRATION_HANDOFF_SECONDS = histogram(
    "dwt_migration_handoff_seconds",
    "Target-side wall time from first staged frame to the request "
    "resuming decode (staging + adopt scatter + admission)",
    buckets=LATENCY_BUCKETS_S)
MIGRATION_INFLIGHT = gauge(
    "dwt_migration_inflight_requests",
    "Live migrations currently between phase-1 start and handoff "
    "ack on the source replica (stuck nonzero means a wedged "
    "target or a partitioned migration path)")


# -- flight recorder / anomaly series --------------------------------------

FLIGHT_EVENTS = counter(
    "dwt_flight_events_total",
    "Events recorded into the process flight-recorder ring "
    "(monotone: overwritten ring entries stay counted)")
FLIGHT_BUFFER = gauge(
    "dwt_flight_buffer_events",
    "Events currently held in the flight-recorder ring")
ANOMALY_EVENTS = counter(
    "dwt_anomaly_events_total",
    "Anomalies flagged by the online detectors, by kind "
    "(straggler_hop, slo_ttft, slo_tpot, queue_saturation, "
    "accept_collapse, pipeline_stall, recompile_storm)", ("kind",))
ANOMALY_LAST = gauge(
    "dwt_anomaly_last_seconds",
    "Epoch seconds of the most recent anomaly of each kind", ("kind",))
ANOMALY_POSTMORTEMS = counter(
    "dwt_anomaly_postmortem_bundles_total",
    "Postmortem bundles written (anomaly triggers, ring stalls, and the "
    "crash handler)")


def update_flight_series() -> None:
    """Bridge the process flight recorder's occupancy onto the
    ``dwt_flight_*`` series (cheap: two locked reads)."""
    from .flightrecorder import get_flight_recorder
    fr = get_flight_recorder()
    FLIGHT_EVENTS.set_cumulative(fr.total)
    FLIGHT_BUFFER.set(len(fr))


# -- cost observatory series (docs/DESIGN.md §20) --------------------------
# fed by telemetry/profiling.py: the sampled dispatch timer observes
# dwt_profile_dispatch_seconds directly at sample time (the slow path —
# it just blocked on the device anyway); everything snapshot-shaped
# (dispatch counts, compile ledger, HBM watermarks) bridges at scrape
# via update_profiling_series so the hot path never touches the
# registry.

# dispatch wall times run far below the request-latency buckets: a
# fused decode step is ~100 µs–10 ms, a prefill chunk tens of ms.
PROFILE_BUCKETS_S = (0.0002, 0.0005, 0.001, 0.002, 0.004, 0.008,
                     0.016, 0.032, 0.064, 0.125, 0.25, 0.5, 1.0, 4.0)

PROFILE_DISPATCH_SECONDS = histogram(
    "dwt_profile_dispatch_seconds",
    "Sampled per-dispatch wall time (block_until_ready) of each jitted "
    "program class, keyed by dispatch signature "
    "program|b<batch-bucket>|c<chunk-or-K>|<kv_dtype> — every "
    "DWT_PROFILE_SAMPLE_N-th dispatch per signature is timed",
    ("signature",), buckets=PROFILE_BUCKETS_S)
PROFILE_SAMPLES = counter(
    "dwt_profile_samples_total",
    "Dispatches the sampled profiler actually timed, per signature "
    "(≈ dispatches / DWT_PROFILE_SAMPLE_N)", ("signature",))
PROFILE_DISPATCHES = counter(
    "dwt_profile_dispatches_total",
    "Total dispatches seen per dispatch signature (counted whenever "
    "sampling is enabled; exactly 0 with DWT_PROFILE_SAMPLE_N=0 — the "
    "off-path touches nothing)", ("signature",))
PROFILE_ACHIEVED_BPS = gauge(
    "dwt_profile_achieved_bytes_per_second",
    "Achieved HBM bandwidth attribution of the last sampled dispatch "
    "per signature, from the KV byte math in ops/quant.py (a lower "
    "bound: weights and activations ride on top)", ("signature",))
PROFILE_ROOFLINE_FRAC = gauge(
    "dwt_profile_roofline_ratio",
    "Achieved-bandwidth attribution over the ROOFLINE_LEDGER.json "
    "ceiling (DWT_ROOFLINE_GBS overrides), per signature",
    ("signature",))

COMPILE_EVENTS = counter(
    "dwt_compile_events_total",
    "XLA compiles observed per jitted program (jit-cache growth across "
    "a tracked call); a program compiling past its variant budget is "
    "the recompile_storm anomaly", ("program",))
COMPILE_SECONDS = counter(
    "dwt_compile_seconds_total",
    "Wall seconds spent in calls that grew a program's jit cache "
    "(trace + lower + compile dominate such calls)", ("program",))
COMPILE_CACHE_ENTRIES = gauge(
    "dwt_compile_cache_entries",
    "Live jit-cache entries per tracked program at last compile",
    ("program",))
COMPILE_VARIANT_BUDGET = gauge(
    "dwt_compile_variant_budget_entries",
    "Documented compiled-variant budget per tracked program (e.g. "
    "mixed_step's two-variant invariant, docs/DESIGN.md §19); only "
    "budgeted programs feed the recompile_storm detector", ("program",))

HBM_OWNER_BYTES = gauge(
    "dwt_hbm_owner_bytes",
    "Current resident bytes per pool owner (kv_page_pool, "
    "kv_host_pool, draft_scratch, stage_pool, migration_staged, "
    "host_tier — the §21 demoted-prefix ring rides the same ledger "
    "even though its bytes live in host RAM), sampled at scheduler "
    "iterations", ("owner",))
HBM_WATERMARK_BYTES = gauge(
    "dwt_hbm_watermark_bytes",
    "High-water-mark resident bytes per pool owner since process start "
    "or the owner's engine close — how big the pool could have been",
    ("owner",))


def update_profiling_series() -> None:
    """Bridge the cost observatory's snapshot-shaped ledgers onto the
    ``dwt_profile_*`` / ``dwt_compile_*`` / ``dwt_hbm_*`` series (cheap:
    three locked dict copies; runs at scrape time only)."""
    from . import profiling
    for sig, n in profiling.get_profiler().dispatch_counts().items():
        PROFILE_DISPATCHES.set_cumulative(n, signature=sig)
    for prog, e in profiling.get_compile_tracker().snapshot().items():
        COMPILE_EVENTS.set_cumulative(e["compiles"], program=prog)
        COMPILE_SECONDS.set_cumulative(e["compile_seconds"],
                                       program=prog)
        COMPILE_CACHE_ENTRIES.set(e["cache_entries"], program=prog)
        if e["variant_budget"] is not None:
            COMPILE_VARIANT_BUDGET.set(e["variant_budget"],
                                       program=prog)
    for owner, w in profiling.get_hbm_watermarks().watermarks().items():
        HBM_OWNER_BYTES.set(w["bytes"], owner=owner)
        HBM_WATERMARK_BYTES.set(w["watermark_bytes"], owner=owner)


# -- monitor series (probes.py measurements) -------------------------------

MONITOR_MEMORY = gauge(
    "dwt_monitor_host_memory_bytes",
    "Host memory from /proc/meminfo, by kind (total/available)",
    ("kind",))
MONITOR_BANDWIDTH = gauge(
    "dwt_monitor_peer_bandwidth_bytes_per_second",
    "Last measured p2p flood bandwidth to a peer (monitor round)",
    ("peer",))
MONITOR_LATENCY = gauge(
    "dwt_monitor_peer_latency_seconds",
    "Last measured TCP connect RTT to a peer (monitor round)",
    ("peer",))
MONITOR_FLOPS = gauge(
    "dwt_monitor_compute_flops_per_second",
    "Measured matmul throughput of the local accelerator (flops probe)")


def update_monitor_series() -> None:
    """Refresh the host-memory gauges (cheap: one /proc read).  Peer
    bandwidth/latency/flops update when the monitor agent measures
    (:func:`record_monitor_round`)."""
    from ..monitor.probes import memory_info
    mem = memory_info()
    MONITOR_MEMORY.set(mem.get("total", 0), kind="total")
    MONITOR_MEMORY.set(mem.get("available", 0), kind="available")


def record_monitor_round(report: dict) -> None:
    """Feed one MonitorAgent ``measure_round`` report into the gauges."""
    for peer, v in (report.get("bandwidth") or {}).items():
        MONITOR_BANDWIDTH.set(v, peer=peer)
    for peer, v in (report.get("latency") or {}).items():
        MONITOR_LATENCY.set(v, peer=peer)
    if report.get("flops"):
        MONITOR_FLOPS.set(report["flops"])


# -- the scrape entry point ------------------------------------------------

def scrape(backend=None) -> str:
    """Refresh snapshot-bridged series from ``backend`` (anything with a
    ``stats()`` dict — a HeaderBackend, a ContinuousBatchingEngine, a
    PipelineWorker's StageStats via ``render_worker``) and render the
    registry.  A failing backend degrades to whatever already rendered —
    a scrape must never 500 because the pipeline is mid-request.

    Backends that poll remote stages prefer ``scrape_stats()`` (bounded
    timeout) over ``stats()`` so a scheduled Prometheus scrape cannot
    stall on a dead stage."""
    update_monitor_series()
    update_flight_series()
    update_profiling_series()
    slo.update_slo_series()
    fn = getattr(backend, "scrape_stats", None) or getattr(
        backend, "stats", None)
    if fn is not None:
        try:
            snap = fn()
        except Exception:
            snap = None
        if isinstance(snap, dict):
            stages = snap.get("stages")
            if isinstance(stages, list):
                update_stage_series(stages)
            else:
                update_batching_series(snap)
    return REGISTRY.render()


def render_worker(stage_stats, device_id: str = "") -> str:
    """Scrape provider for a standalone stage-worker process: bridge its
    StageStats and render (``worker_main --metrics-port``)."""
    update_monitor_series()
    update_flight_series()
    update_profiling_series()
    snap = dict(stage_stats.snapshot(), device_id=device_id)
    update_stage_series([snap])
    return REGISTRY.render()
