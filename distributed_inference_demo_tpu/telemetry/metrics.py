"""Hand-rolled Prometheus metrics: registry, counter/gauge/histogram, text
exposition.

The reference system's only metrics surface was a stdout dump of
``commutimeArraySum``/``infertimeArraySum`` at run end
(``Communication.java:650-661``); our port grew an ad-hoc ``/stats`` JSON
blob.  This module is the standard surface both converge on: a small
registry (NO new dependency — the container has no prometheus_client)
rendering Prometheus text exposition format 0.0.4, scraped at
``GET /metrics`` on the header HTTP server and on every worker
(``MetricsHTTPServer``).

Conventions (enforced by ``tools/check_metrics_names.py``):

- names are ``dwt_<subsystem>_<name>_<unit>`` with counters additionally
  suffixed ``_total`` (Prometheus convention);
- every metric carries non-empty help text;
- histograms use FIXED buckets chosen at registration (cumulative,
  ``+Inf`` always present, ``_count``/``_sum`` consistent) so scrapes are
  O(buckets) regardless of traffic.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# default latency buckets: 1 ms .. 60 s, roughly x4 steps — wide enough for
# both a local chip (sub-ms decode steps) and the tunneled bench device
# (~10 ms dispatch floor) without per-deployment tuning
LATENCY_BUCKETS_S = (0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 15.0, 60.0)


class MetricError(ValueError):
    """Bad metric name / labels / usage."""


def _fmt(v: float) -> str:
    """Prometheus sample value formatting: integers without the trailing
    .0, +Inf/NaN spelled the Prometheus way."""
    if v == float("inf"):
        return "+Inf"
    if v != v:  # NaN
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class Metric:
    """Base: a named family with optional label dimensions.  Concrete
    classes own per-labelset children; ``samples()`` yields
    ``(suffix, label_pairs, value)`` rows for the renderer."""

    type: str = ""

    def __init__(self, name: str, help: str,
                 labels: Sequence[str] = ()) -> None:
        if not _NAME_RE.match(name):
            raise MetricError(f"bad metric name {name!r}")
        if not help or not help.strip():
            raise MetricError(f"metric {name!r} needs help text")
        for l in labels:
            if not _LABEL_RE.match(l):
                raise MetricError(f"bad label name {l!r} on {name!r}")
        self.name = name
        self.help = help.strip()
        self.label_names = tuple(labels)
        self._lock = threading.Lock()

    def _key(self, label_values: Dict[str, str]) -> Tuple[Tuple[str, str],
                                                          ...]:
        if set(label_values) != set(self.label_names):
            raise MetricError(
                f"{self.name}: labels {sorted(label_values)} != declared "
                f"{sorted(self.label_names)}")
        return tuple((k, str(label_values[k])) for k in self.label_names)

    def samples(self) -> Iterable[Tuple[str, Tuple[Tuple[str, str], ...],
                                        float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotone counter.  ``inc`` rejects negative deltas; ``set_cumulative``
    bridges an external cumulative value (e.g. a StageStats snapshot) and
    tolerates resets the way Prometheus counters do (value drops are kept,
    rate() handles them)."""

    type = "counter"

    def __init__(self, name, help, labels=()):
        super().__init__(name, help, labels)
        self._values: Dict[tuple, float] = {}

    def labels(self, **kv) -> "_CounterChild":
        return _CounterChild(self, self._key(kv))

    def inc(self, amount: float = 1.0, **kv) -> None:
        if amount < 0:
            raise MetricError(f"{self.name}: counter inc must be >= 0")
        key = self._key(kv)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_cumulative(self, value: float, **kv) -> None:
        key = self._key(kv)
        with self._lock:
            self._values[key] = float(value)

    def samples(self):
        with self._lock:
            items = sorted(self._values.items())
        if not self.label_names and not items:
            items = [((), 0.0)]      # unlabeled counters always render
        for key, v in items:
            yield "", key, v


class _CounterChild:
    __slots__ = ("_m", "_key")

    def __init__(self, m: Counter, key):
        self._m, self._key = m, key

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError(f"{self._m.name}: counter inc must be >= 0")
        with self._m._lock:
            self._m._values[self._key] = \
                self._m._values.get(self._key, 0.0) + amount


class Gauge(Metric):
    """Settable value; optionally backed by a callback sampled at render
    time (``set_function`` — e.g. live queue depth)."""

    type = "gauge"

    def __init__(self, name, help, labels=()):
        super().__init__(name, help, labels)
        self._values: Dict[tuple, float] = {}
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float, **kv) -> None:
        key = self._key(kv)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **kv) -> None:
        key = self._key(kv)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_function(self, fn: Callable[[], float]) -> None:
        if self.label_names:
            raise MetricError(
                f"{self.name}: callback gauges cannot be labeled")
        self._fn = fn

    def samples(self):
        if self._fn is not None:
            try:
                yield "", (), float(self._fn())
            except Exception:
                yield "", (), float("nan")
            return
        with self._lock:
            items = sorted(self._values.items())
        if not self.label_names and not items:
            items = [((), 0.0)]      # unlabeled gauges always render
        for key, v in items:
            yield "", key, v


class Histogram(Metric):
    """Fixed-bucket histogram.  Buckets are upper bounds (le); the
    renderer emits cumulative counts, a ``+Inf`` bucket, ``_count`` and
    ``_sum`` — the shape PromQL's ``histogram_quantile`` expects."""

    type = "histogram"

    def __init__(self, name, help, labels=(),
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        super().__init__(name, help, labels)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise MetricError(f"{self.name}: needs at least one bucket")
        if len(set(bs)) != len(bs):
            raise MetricError(f"{self.name}: duplicate buckets")
        self.buckets = tuple(bs)
        # per-labelset: ([per-bucket counts] + [inf count], sum)
        self._data: Dict[tuple, list] = {}

    def observe(self, value: float, **kv) -> None:
        key = self._key(kv)
        v = float(value)
        with self._lock:
            st = self._data.get(key)
            if st is None:
                st = self._data[key] = [[0] * (len(self.buckets) + 1), 0.0]
            counts, _ = st
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            st[1] += v

    def labels(self, **kv) -> "_HistChild":
        key = self._key(kv)          # validate eagerly
        return _HistChild(self, kv)

    def samples(self):
        with self._lock:
            items = sorted((k, ([*c], s)) for k, (c, s)
                           in self._data.items())
        if not self.label_names and not items:
            items = [((), ([0] * (len(self.buckets) + 1), 0.0))]
        for key, (counts, total) in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                yield "_bucket", key + (("le", _fmt(b)),), float(cum)
            cum += counts[-1]
            yield "_bucket", key + (("le", "+Inf"),), float(cum)
            yield "_count", key, float(cum)
            yield "_sum", key, total


class _HistChild:
    __slots__ = ("_m", "_kv")

    def __init__(self, m: Histogram, kv):
        self._m, self._kv = m, kv

    def observe(self, value: float) -> None:
        self._m.observe(value, **self._kv)


class Registry:
    """Metric families in registration order; ``render()`` is the text
    exposition payload for ``GET /metrics``."""

    def __init__(self):
        self._metrics: "Dict[str, Metric]" = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise MetricError(f"duplicate metric {metric.name!r}")
            self._metrics[metric.name] = metric
        return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def collect(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        lines: List[str] = []
        for m in self.collect():
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.type}")
            for suffix, label_pairs, value in m.samples():
                lines.append(f"{m.name}{suffix}"
                             f"{_render_labels(tuple(label_pairs))} "
                             f"{_fmt(value)}")
        return "\n".join(lines) + "\n"


# the process-default registry every subsystem registers into (see
# telemetry/catalog.py for the standard metric set)
REGISTRY = Registry()


def counter(name, help, labels=(), registry: Optional[Registry] = None):
    return (registry or REGISTRY).register(Counter(name, help, labels))


def gauge(name, help, labels=(), registry: Optional[Registry] = None):
    return (registry or REGISTRY).register(Gauge(name, help, labels))


def histogram(name, help, labels=(), buckets=LATENCY_BUCKETS_S,
              registry: Optional[Registry] = None):
    return (registry or REGISTRY).register(
        Histogram(name, help, labels, buckets))


class MetricsHTTPServer:
    """Minimal threaded ``GET /metrics`` endpoint for processes that have
    no other HTTP surface (pipeline stage workers — the header's main
    server exposes /metrics itself).  ``provider()`` returns the rendered
    text at scrape time.  ``debug_provider()`` (optional) returns a dict
    served as JSON at ``GET /debugz`` — live flight-recorder/anomaly
    state for operators poking a single worker."""

    def __init__(self, provider: Callable[[], str],
                 host: str = "127.0.0.1", port: int = 0,
                 debug_provider: Optional[Callable[[], dict]] = None):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # quiet
                pass

            def do_GET(self):
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                # query strings are ignored, matching the header HTTP
                # server's routing (a cache-busting ?x=1 must not 404)
                path = self.path.split("?")[0]
                if (debug_provider is not None
                        and path in ("/debugz", "/debugz/")):
                    ctype = "application/json"
                    try:
                        body = _json.dumps(debug_provider(),
                                           default=str).encode("utf-8")
                        self.send_response(200)
                    except Exception as e:
                        body = _json.dumps({"error": str(e)}).encode()
                        self.send_response(500)
                elif path not in ("/metrics", "/metrics/"):
                    body = b"see /metrics\n"
                    self.send_response(404)
                else:
                    try:
                        body = provider().encode("utf-8")
                        self.send_response(200)
                    except Exception as e:      # scrape must never 500 the
                        body = f"# scrape error: {e}\n".encode()
                        self.send_response(500)  # worker loop
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
