"""Unified telemetry layer: request tracing, Prometheus metrics, run logs.

Three integrated pieces (docs/DESIGN.md §7):

- ``tracing``: per-request trace ids propagated across the ring via a
  wire flags bit (``comm/wire.py``), per-stage spans, Chrome trace-event
  export for Perfetto;
- ``metrics``: a hand-rolled Prometheus registry (no new dependency) +
  ``catalog``, the standard ``dwt_*`` series bridging StageStats,
  batching/speculative counters, and monitor probes to ``GET /metrics``;
- ``runlog``: structured JSONL run logs shared by bench, the engines,
  and the control-plane lifecycle.

``catalog`` is imported lazily by its consumers (it pulls in
monitor.probes); importing this package stays dependency-light so the
engine hot path can use ``runlog`` without dragging the control plane in.
"""

from .metrics import (Counter, Gauge, Histogram, MetricError,
                      MetricsHTTPServer, REGISTRY, Registry)
from .runlog import RunLog, get_run_log, new_run_id, set_run_log
from .tracing import (TraceRecorder, new_trace_id, to_chrome_trace,
                      write_chrome_trace)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricError", "MetricsHTTPServer",
    "REGISTRY", "Registry",
    "RunLog", "get_run_log", "new_run_id", "set_run_log",
    "TraceRecorder", "new_trace_id", "to_chrome_trace",
    "write_chrome_trace",
]
