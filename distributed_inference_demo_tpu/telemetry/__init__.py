"""Unified telemetry layer: tracing, metrics, run logs, and the black box.

The integrated pieces (docs/DESIGN.md §7-§8):

- ``tracing``: per-request trace ids propagated across the ring via a
  wire flags bit (``comm/wire.py``), per-stage spans, Chrome trace-event
  export for Perfetto;
- ``metrics``: a hand-rolled Prometheus registry (no new dependency) +
  ``catalog``, the standard ``dwt_*`` series bridging StageStats,
  batching/speculative counters, and monitor probes to ``GET /metrics``;
- ``runlog``: structured JSONL run logs shared by bench, the engines,
  and the control-plane lifecycle;
- ``flightrecorder``: a bounded always-on ring of recent runtime events
  (the aircraft black box);
- ``anomaly``: online detectors over the existing stats surfaces
  (straggler hop, SLO breach, queue saturation, accept-rate collapse,
  stalled-pipeline watchdog);
- ``postmortem``: on trigger or crash, dump a bundle (flight ring,
  metrics snapshot, Chrome trace, config, run-log tail) for the offline
  analyzer ``tools/postmortem.py``.

``catalog`` is imported lazily by its consumers (it pulls in
monitor.probes); importing this package stays dependency-light so the
engine hot path can use ``runlog`` without dragging the control plane in.
"""

from .flightrecorder import (FlightRecorder, get_flight_recorder,
                             set_flight_recorder)
from .metrics import (Counter, Gauge, Histogram, MetricError,
                      MetricsHTTPServer, REGISTRY, Registry)
from .runlog import RunLog, get_run_log, new_run_id, set_run_log
from .tracing import (SpanClock, TraceRecorder, new_trace_id,
                      to_chrome_trace, write_chrome_trace)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricError", "MetricsHTTPServer",
    "REGISTRY", "Registry",
    "RunLog", "get_run_log", "new_run_id", "set_run_log",
    "FlightRecorder", "get_flight_recorder", "set_flight_recorder",
    "SpanClock", "TraceRecorder", "new_trace_id", "to_chrome_trace",
    "write_chrome_trace",
]
