"""Command-line apps: ``serve`` / ``worker`` / ``plan`` / ``generate`` /
``bench``.

Replaces the reference's entry points (SURVEY.md §7.9): ``server.py``'s
``__main__`` block + HTTP stub (``server.py:583-1052``), ``client.py``'s
argparse worker (``client.py:179-190``), and the Android
``BackgroundService`` driver — as one console tool:

    python -m distributed_inference_demo_tpu serve --model tinyllama-1.1b
    python -m distributed_inference_demo_tpu serve --model llama-test \\
        --chain w1@127.0.0.1:7001,w2@127.0.0.1:7002 --elastic
    python -m distributed_inference_demo_tpu worker --model llama-test ...
    python -m distributed_inference_demo_tpu plan --model llama-3-8b \\
        --devices devices.json --save plan.json
    python -m distributed_inference_demo_tpu generate --model llama-test \\
        --prompt-ids 1,2,3 --max-new-tokens 8 --greedy
    python -m distributed_inference_demo_tpu bench --model tinyllama-1.1b
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _load_tokenizer(path: Optional[str]):
    if not path:
        return None
    from .tokenizer import Tokenizer
    # auto-detects sentencepiece .model protobufs vs HF tokenizer.json
    return Tokenizer.from_file(path)


def _load_full_params(args, cfg):
    """Resolve the full parameter tree for a CLI invocation: checkpoint if
    ``--checkpoint`` was given, else seed-init (int8-quantized during init
    for ``-int8`` configs).  Shared by the single-node and ``--chain``
    serve paths so a checkpoint can never be silently ignored on one of
    them."""
    from .models.loader import load_or_init

    return load_or_init(args.model, cfg, getattr(args, "checkpoint", None),
                        seed=args.weights_seed)


def _sampling_from_args(args):
    """The one mapping from CLI flags to SamplingParams — shared by every
    serve mode so a new sampling flag cannot silently diverge between
    single-node, --chain, and --batch-slots."""
    from .ops.sampling import SamplingParams
    if args.greedy:
        return SamplingParams(greedy=True)
    return SamplingParams(temperature=args.temperature, top_k=args.top_k,
                          min_p=getattr(args, "min_p", 0.0))


def _tp_mesh_from_args(args):
    """tp mesh from the --tp flag (parallel.mesh owns the rule)."""
    from .parallel.mesh import local_tp_mesh
    return local_tp_mesh(getattr(args, "tp", 1))


def _load_params_for_mesh(args, cfg):
    """(params, mesh): checkpoint-or-seed params, sharded onto the --tp
    mesh when one is requested — the one load+shard sequence shared by
    every engine builder."""
    params = _load_full_params(args, cfg)
    mesh = _tp_mesh_from_args(args)
    if mesh is not None:
        from .runtime.engine import shard_engine_params
        params = shard_engine_params(params, cfg, mesh)
    return params, mesh


def _load_draft_for_mesh(args, mesh):
    """(draft_cfg, draft_params) from the --draft-model/--draft-checkpoint
    flags, sharded onto ``mesh`` when serving tensor-parallel — shared by
    the standalone speculative engine and the batching composition."""
    from .models.registry import get_model_config

    draft_cfg = get_model_config(args.draft_model)
    draft_params = _load_full_params(
        argparse.Namespace(**{**vars(args),
                              "model": args.draft_model,
                              "checkpoint": args.draft_checkpoint}),
        draft_cfg)
    if mesh is not None:
        from .runtime.engine import shard_engine_params
        draft_params = shard_engine_params(draft_params, draft_cfg, mesh)
    return draft_cfg, draft_params


def _kvcache_from_args(args):
    """``(kv_cache_blocks, kv_block_tokens)`` engine kwargs from the CLI
    flags (None = not given: the engine falls back to DWT_KVCACHE_* env
    knobs, then its own default) — one mapping shared by every engine
    builder so the block-cache flags cannot silently diverge between
    serve modes."""
    return {"kv_cache_blocks": getattr(args, "kv_cache_blocks", None),
            "kv_block_tokens": getattr(args, "kv_block_tokens", None)}


def _kv_tier_from_args(args):
    """The §21 tiered-KV kwargs for the one engine that plumbs them
    explicitly (ContinuousBatchingEngine).  Every OTHER engine reaches
    the tier through ``make_kv_backend``'s env fallback, which is why
    :func:`_export_kv_tier_env` pushes the flags into the ``DWT_KV_*``
    knobs instead of threading three kwargs through every ctor."""
    return {"kv_host_tier_bytes": getattr(args, "kv_host_tier_bytes",
                                          None),
            "kv_disk_tier_path": getattr(args, "kv_disk_tier_path",
                                         None) or None,
            "kv_disk_tier_bytes": getattr(args, "kv_disk_tier_bytes",
                                          None)}


def _export_kv_tier_env(args) -> None:
    """Arg-over-env, via env: the tier flags overwrite their own env
    knobs so ``resolve_tier_config`` (called inside ``make_kv_backend``
    at every pool-creation site) sees the CLI's values — the §17
    kv_dtype funnel pattern, flag wins, zero per-engine plumbing."""
    if getattr(args, "kv_host_tier_bytes", None) is not None:
        os.environ["DWT_KV_HOST_TIER_BYTES"] = str(
            args.kv_host_tier_bytes)
    if getattr(args, "kv_disk_tier_path", None):
        os.environ["DWT_KV_DISK_TIER_PATH"] = args.kv_disk_tier_path
    if getattr(args, "kv_disk_tier_bytes", None) is not None:
        os.environ["DWT_KV_DISK_TIER_BYTES"] = str(
            args.kv_disk_tier_bytes)


def _kvcache_flags_set(args) -> bool:
    """Did the user EXPLICITLY ask for the block cache?  Unset/0 is not
    a request (0 is 'off' everywhere) — the one condition every
    unsupported-mode rejection keys on, so no mode can accept one of
    the pair and silently drop the other."""
    return bool(getattr(args, "kv_cache_blocks", None)
                or getattr(args, "kv_block_tokens", None))


def _reject_kvcache_flags(args, mode: str) -> bool:
    """True (after printing) when the kv-cache flags were explicitly set
    for a mode with no block-cache plumbing — honor-or-reject, never
    silently ignore."""
    if _kvcache_flags_set(args):
        print("--kv-cache-blocks/--kv-block-tokens are not supported "
              f"with {mode}", file=sys.stderr)
        return True
    return False


def _build_spec_engine(args):
    """Construct the draft/verify SpeculativeEngine from CLI flags — the
    one site shared by ``generate --draft-model`` and
    ``serve --draft-model``.  Every engine flag composes here
    (--kv-cache-dtype, --prefill-chunk, --tp, --eos-id,
    --kv-cache-blocks)."""
    from .models.registry import get_model_config
    from .runtime import SpeculativeEngine

    if getattr(args, "stream_block", None) is not None:
        raise ValueError(
            "--stream-block is not supported with --draft-model "
            "(the draft/verify round is already the fused dispatch "
            "unit)")
    cfg = get_model_config(args.model)
    params, mesh = _load_params_for_mesh(args, cfg)
    draft_cfg, draft_params = _load_draft_for_mesh(args, mesh)
    return SpeculativeEngine(
        cfg, params, draft_cfg, draft_params,
        max_seq=args.max_seq, sampling=_sampling_from_args(args),
        num_draft=args.num_draft, attn_backend=args.attn_backend,
        mesh=mesh, eos_id=getattr(args, "eos_id", None),
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None) or None,
        prefill_chunk=getattr(args, "prefill_chunk", 0) or None,
        kv_layout=getattr(args, "kv_layout", None),
        kv_dtype=getattr(args, "kv_dtype", None),
        **_kvcache_from_args(args))


def _build_prompt_lookup_engine(args):
    """Construct the draft-free PromptLookupEngine from CLI flags — the one
    site shared by ``generate --prompt-lookup`` and
    ``serve --prompt-lookup``.  Every engine flag composes here
    (--kv-cache-dtype, --prefill-chunk, --tp, --eos-id)."""
    from .models.registry import get_model_config
    from .runtime.prompt_lookup import PromptLookupEngine

    if getattr(args, "stream_block", None) is not None:
        raise ValueError(
            "--stream-block is not supported with --prompt-lookup "
            "(the n-gram draft/verify round is already the fused "
            "dispatch unit)")
    cfg = get_model_config(args.model)
    params, mesh = _load_params_for_mesh(args, cfg)
    return PromptLookupEngine(
        cfg, params, max_seq=args.max_seq,
        sampling=_sampling_from_args(args), num_draft=args.num_draft,
        attn_backend=args.attn_backend, mesh=mesh,
        eos_id=getattr(args, "eos_id", None),
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None) or None,
        prefill_chunk=getattr(args, "prefill_chunk", 0) or None,
        kv_layout=getattr(args, "kv_layout", None),
        kv_dtype=getattr(args, "kv_dtype", None),
        **_kvcache_from_args(args))


def _build_engine(args):
    from .models.registry import get_model_config
    from .runtime import InferenceEngine

    cfg = get_model_config(args.model)
    sampling = _sampling_from_args(args)
    # tensor-parallel serving (BASELINE config #3): Megatron-sliced
    # weights + kv-head-sharded cache over the first tp local devices
    params, mesh = _load_params_for_mesh(args, cfg)
    return cfg, InferenceEngine(
        cfg, params, max_seq=args.max_seq, sampling=sampling,
        attn_backend=args.attn_backend,
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None) or None,
        prefill_chunk=getattr(args, "prefill_chunk", 0) or None,
        stream_block=getattr(args, "stream_block", None),
        mesh=mesh, eos_id=getattr(args, "eos_id", None),
        kv_layout=getattr(args, "kv_layout", None),
        kv_dtype=getattr(args, "kv_dtype", None),
        **_kvcache_from_args(args))


# ---------------------------------------------------------------------------
# serve
# ---------------------------------------------------------------------------

def cmd_serve(args) -> int:
    """Single-node engine serving, or pipeline-header serving over a worker
    chain (start the workers first with the ``worker`` subcommand)."""
    from .runtime.http_server import HeaderBackend, InferenceHTTPServer

    _export_kv_tier_env(args)
    if getattr(args, "run_log", ""):
        from .telemetry.runlog import RunLog, set_run_log
        rl = RunLog(args.run_log)
        set_run_log(rl)
        rl.event("serve_start", model=args.model,
                 max_seq=args.max_seq,
                 chain=bool(args.chain),
                 batch_slots=getattr(args, "batch_slots", 0))
    # black-box capture for this serving process: anomaly/stall triggers
    # and unhandled crashes dump bundles.  --postmortem-dir installs the
    # writer explicitly; DWT_POSTMORTEM_DIR alone is honored too (the
    # lazy get below resolves it), and EITHER configuration gets the
    # crash handler — env-only capture must not silently lose crashes
    from .telemetry import postmortem
    if getattr(args, "postmortem_dir", ""):
        postmortem.set_postmortem_writer(
            postmortem.PostmortemWriter(args.postmortem_dir))
    if postmortem.get_postmortem_writer() is not None:
        postmortem.install_crash_handler(config={
            k: v for k, v in vars(args).items() if k != "fn"})

    modes = [name for name, on in [("--chain", args.chain),
                                   ("--draft-model",
                                    getattr(args, "draft_model", "")),
                                   ("--prompt-lookup",
                                    getattr(args, "prompt_lookup", False)),
                                   ("--batch-slots",
                                    getattr(args, "batch_slots", 0)),
                                   ("--sp",
                                    getattr(args, "sp", 1) > 1),
                                   ("--vision",
                                    getattr(args, "vision", False))] if on]
    # --batch-slots composes with --draft-model OR --prompt-lookup
    # (speculative decoding inside the slot loop — the production serving
    # shape); every other pairing stays an explicit error
    if len(modes) > 1 and set(modes) not in (
            {"--batch-slots", "--draft-model"},
            {"--batch-slots", "--prompt-lookup"}):
        print(f"choose one serve mode, got {' + '.join(modes)}",
              file=sys.stderr)
        return 1
    if getattr(args, "no_spec_adaptive", False) and not (
            getattr(args, "batch_slots", 0)
            and ("--draft-model" in modes or "--prompt-lookup" in modes)):
        # adaptive K_row lives in the mixed slot loop; anywhere else the
        # flag would silently do nothing
        print("--no-spec-adaptive requires --batch-slots with "
              "--draft-model or --prompt-lookup", file=sys.stderr)
        return 1
    if getattr(args, "tp", 1) > 1 and "--chain" in modes:
        print("--tp is not supported with --chain (stages are whole-model "
              "slices per worker)", file=sys.stderr)
        return 1
    if getattr(args, "pool_size", 1) > 1 and not args.chain:
        # reject loudly rather than silently serializing requests
        print("--pool-size requires --chain (pipeline dynamic batching); "
              "--batch-slots is the single-node batching mode",
              file=sys.stderr)
        return 1

    # chaos fault plan: resolved EARLY so a leaked DWT_FAULT_PLAN env var
    # kills the process at startup instead of silently injecting faults
    from .comm.faults import FaultConfigError, load_fault_plan, maybe_wrap
    try:
        fault_plan = load_fault_plan(getattr(args, "fault_plan", ""),
                                     getattr(args, "chaos", False))
    except FaultConfigError as e:
        print(str(e), file=sys.stderr)
        return 1
    if fault_plan is not None and not args.chain:
        print("--fault-plan applies to the data-plane transport and "
              "requires --chain (single-process engine modes have no "
              "transport to fault)", file=sys.stderr)
        return 1

    tokenizer = _load_tokenizer(args.tokenizer)

    if args.chain:
        import jax

        from .comm.transport import ZmqTransport
        from .models.base import split_layer_ranges
        from .models.registry import get_model_config
        from .runtime.elastic import ElasticHeader, ElasticStageRuntime

        cfg = get_model_config(args.model)
        if getattr(args, "prefill_chunk", 0):
            print("--prefill-chunk is not supported with --chain",
                  file=sys.stderr)
            return 1
        if getattr(args, "stream_block", None) is not None:
            # the ring's topology caps a circuit at one token (DESIGN
            # §13: the tail fuses forward+sample instead); honor-or-
            # reject, never silently ignore
            print("--stream-block is not supported with --chain",
                  file=sys.stderr)
            return 1
        if _reject_kvcache_flags(args, "--chain (pipeline stages see "
                                 "activations, not tokens — there is "
                                 "no prompt key to match blocks by)"):
            return 1
        full = _load_full_params(args, cfg)
        sampling = _sampling_from_args(args)

        peers = [p.split("@", 1) for p in args.chain.split(",")]
        chain = [args.device_id] + [pid for pid, _ in peers]
        specs = split_layer_ranges(cfg.num_layers, len(chain))
        transport = maybe_wrap(
            ZmqTransport(args.device_id, bind_host=args.bind_host,
                         port=args.port), fault_plan)
        for pid, addr in peers:
            transport.connect(pid, addr)
        # the header's own stage honors --kv-cache-dtype; chain workers
        # take their own --kv-cache-dtype flag (each stage's cache is its
        # own business — the wire carries activations, not cache state)
        rt = ElasticStageRuntime(
            cfg, specs[0], full, args.max_seq, sampling,
            kv_cache_dtype=getattr(args, "kv_cache_dtype", "") or None,
            kv_layout=getattr(args, "kv_layout", None))
        header = ElasticHeader(rt, transport, chain,
                               eos_id=getattr(args, "eos_id", None),
                               step_timeout=args.step_timeout)
        # initial reshard pushes the authoritative layer plan to the chain —
        # workers may start with any placeholder range (cli worker --elastic
        # defaults to the full model) and are aligned here.
        header.reshard(chain)
        pool = getattr(args, "pool_size", 1)
        if pool > 1:
            # dynamic batching: concurrent HTTP requests group into
            # generate_many windows with pool_size rids interleaving
            # through the stages (runtime/dynamic_batch.py)
            from .runtime.dynamic_batch import DynamicBatchingHeaderBackend
            backend = DynamicBatchingHeaderBackend(
                header, max_seq=args.max_seq, num_stages=len(chain),
                pool_size=pool)
        else:
            backend = HeaderBackend(header, max_seq=args.max_seq,
                                    num_stages=len(chain))
        kv_dtype = getattr(args, "kv_cache_dtype", "") or None
        if kv_dtype:
            # each stage owns its cache dtype; this flag reaches only the
            # header's stage — say so loudly, or a chain whose workers
            # weren't launched with their own --kv-cache-dtype silently
            # keeps full-precision caches on every other host
            print(f"note: --kv-cache-dtype={kv_dtype} applies to the "
                  "header stage only; start each worker with its own "
                  "--kv-cache-dtype to reduce its cache too",
                  file=sys.stderr)
        print(f"SERVE_PIPELINE {chain} ranges="
              f"{[(s.layer_start, s.layer_end) for s in specs]}"
              + (f" header_kv_cache_dtype={kv_dtype}" if kv_dtype else ""),
              flush=True)
    elif getattr(args, "sp", 1) > 1:
        # long-context serving: ring/Ulysses sequence parallelism behind
        # the same HTTP surface (runtime/sp_backend.py); --tp is covered
        # by the mode exclusivity above only for other MODES, so guard
        # the mesh conflict explicitly
        from .models.registry import get_model_config
        from .parallel.mesh import local_sp_mesh
        from .runtime.sp_backend import SequenceParallelBackend

        if getattr(args, "tp", 1) > 1:
            print("--sp is exclusive with --tp", file=sys.stderr)
            return 1
        unsupported = _sp_unsupported_flags(args, allow_eos=True)
        if unsupported:
            print(f"{'/'.join(unsupported)} not supported with --sp",
                  file=sys.stderr)
            return 1
        cfg = get_model_config(args.model)
        mesh = local_sp_mesh(args.sp)
        params = _load_full_params(args, cfg)
        backend = SequenceParallelBackend(
            cfg, params, mesh, max_seq=args.max_seq,
            strategy=args.sp_strategy, sampling=_sampling_from_args(args),
            kv_cache_dtype=getattr(args, "kv_cache_dtype", None) or None,
            eos_id=getattr(args, "eos_id", None),
            max_queue_depth=getattr(args, "sp_queue_depth", None),
            kv_layout=getattr(args, "kv_layout", None))
        print(f"SERVE_SP {args.model} sp={args.sp} "
              f"strategy={args.sp_strategy} max_seq={args.max_seq}",
              flush=True)
    elif getattr(args, "vision", False):
        # LLaVA-style multimodal serving: ViT tower + projector in front
        # of the decoder; /generate takes an optional "image" field and
        # text-only requests run the plain engine path unchanged
        import jax as _jax

        from .models.registry import get_model_config
        from .models.vision import VisionConfig, init_vision_params
        from .runtime.multimodal import MultimodalBackend, MultimodalEngine

        unsupported = [flag for flag, on in [
            ("--kv-cache-dtype", bool(getattr(args, "kv_cache_dtype", ""))),
            ("--prefill-chunk", bool(getattr(args, "prefill_chunk", 0))),
            ("--stream-block",
             getattr(args, "stream_block", None) is not None),
            ("--kv-cache-blocks", _kvcache_flags_set(args)),
            ("--tp", getattr(args, "tp", 1) > 1)] if on]
        if unsupported:
            print(f"{'/'.join(unsupported)} not supported with --vision",
                  file=sys.stderr)
            return 1
        cfg = get_model_config(args.model)
        if args.vision_preset == "llava15":
            # the CLIP-ViT-L/14-336 geometry LLaVA-1.5 ships, faithful:
            # class token, pre-layernorm, projection biases, quick_gelu,
            # penultimate-layer feature select — HF CLIP/LLaVA vision
            # checkpoints load via --vision-checkpoint without
            # reinterpretation
            vcfg = VisionConfig(image_size=336, patch_size=14,
                                hidden_size=1024, num_layers=24,
                                num_heads=16, intermediate_size=4096,
                                dtype_name="bfloat16", clip_arch=True,
                                feature_layer=-2, hidden_act="quick_gelu")
        elif args.vision_preset == "clip-test":
            # tiny faithful tower for tests/drives (same arch flags as
            # llava15, checkpoint-loadable at toy scale)
            vcfg = VisionConfig(image_size=28, patch_size=14,
                                hidden_size=32, num_layers=3,
                                num_heads=4, intermediate_size=64,
                                dtype_name="float32", clip_arch=True,
                                feature_layer=-2, hidden_act="quick_gelu")
        else:     # "small": a CLIP-base-like tower for modest decoders
            vcfg = VisionConfig(image_size=224, patch_size=14,
                                hidden_size=256, num_layers=6,
                                num_heads=8, intermediate_size=1024,
                                dtype_name="bfloat16")
        params = _load_full_params(args, cfg)
        if getattr(args, "vision_checkpoint", ""):
            from .models.loader import load_vision_params
            vparams = load_vision_params(args.vision_checkpoint, vcfg,
                                         cfg.hidden_size,
                                         seed=args.weights_seed)
        else:
            # without a checkpoint the tower is seeded random init; the
            # geometry and serving surface are real.  Seeded from
            # --weights-seed like every other weight init, so the same
            # seed reproduces the model regardless of the sampling --seed
            vparams = init_vision_params(
                _jax.random.PRNGKey(args.weights_seed), vcfg,
                cfg.hidden_size)
        backend = MultimodalBackend(MultimodalEngine(
            cfg, params, vcfg, vparams, max_seq=args.max_seq,
            sampling=_sampling_from_args(args),
            eos_id=getattr(args, "eos_id", None),
            attn_backend=args.attn_backend,
            kv_layout=getattr(args, "kv_layout", None),
            kv_dtype=getattr(args, "kv_dtype", None)))
        print(f"SERVE_VISION {args.model} tower={args.vision_preset} "
              f"image={vcfg.image_size} patches={vcfg.num_patches}",
              flush=True)
    elif getattr(args, "batch_slots", 0):
        from .models.registry import get_model_config
        from .runtime.batching import ContinuousBatchingEngine

        if getattr(args, "stream_block", None) is not None:
            # the scheduler's fused block is --decode-block; a second K
            # knob must be rejected, never silently ignored
            print("--stream-block is not supported with --batch-slots "
                  "(use --decode-block)", file=sys.stderr)
            return 1
        cfg = get_model_config(args.model)
        sampling = _sampling_from_args(args)
        params, mesh = _load_params_for_mesh(args, cfg)
        draft_cfg = draft_params = None
        if getattr(args, "draft_model", ""):
            # speculative decoding inside the slot loop
            draft_cfg, draft_params = _load_draft_for_mesh(args, mesh)
        pld = bool(getattr(args, "prompt_lookup", False))
        backend = ContinuousBatchingEngine(
            cfg, params, max_seq=args.max_seq,
            max_batch=args.batch_slots, sampling=sampling, seed=args.seed,
            mesh=mesh,
            kv_cache_dtype=getattr(args, "kv_cache_dtype", None) or None,
            eos_id=getattr(args, "eos_id", None),
            draft_cfg=draft_cfg, draft_params=draft_params,
            num_draft=args.num_draft, prompt_lookup=pld,
            spec_adaptive=not getattr(args, "no_spec_adaptive", False),
            decode_block=args.decode_block,
            prefill_chunk=getattr(args, "prefill_chunk", 0) or None,
            mixed_token_budget=getattr(args, "mixed_token_budget", 0)
            or None,
            kv_layout=getattr(args, "kv_layout", None),
            kv_dtype=getattr(args, "kv_dtype", None),
            max_queue_depth=getattr(args, "admission_queue_depth", 0),
            **_kvcache_from_args(args), **_kv_tier_from_args(args))
        kvc = backend.kv_cache
        kv_desc = "off" if kvc is None else (
            f"{getattr(kvc, 'num_blocks', None) or kvc.pool.num_blocks}"
            f"x{kvc.block_tokens}tok {backend.kv_layout}")
        print(f"SERVE_BATCHING {args.model} slots={args.batch_slots} "
              f"kv_cache={kv_desc} "
              f"tp={getattr(args, 'tp', 1)}"
              + (f" draft={args.draft_model} k={args.num_draft}"
                 if draft_cfg is not None else "")
              + (f" prompt_lookup k={args.num_draft}" if pld else "")
              + (" k_adaptive" if (draft_cfg is not None or pld)
                 and not getattr(args, "no_spec_adaptive", False) else ""),
              flush=True)
    elif getattr(args, "draft_model", ""):
        from .runtime.speculative import SpeculativeBackend

        backend = SpeculativeBackend(_build_spec_engine(args))
        print(f"SERVE_SPECULATIVE {args.model} draft={args.draft_model} "
              f"k={args.num_draft}", flush=True)
    elif getattr(args, "prompt_lookup", False):
        from .runtime.speculative import SpeculativeBackend

        backend = SpeculativeBackend(_build_prompt_lookup_engine(args))
        print(f"SERVE_PROMPT_LOOKUP {args.model} k={args.num_draft}",
              flush=True)
    else:
        cfg, engine = _build_engine(args)
        backend = engine
        print(f"SERVE_ENGINE {args.model} attn={engine.attn_backend}",
              flush=True)

    server = InferenceHTTPServer(backend, host=args.http_host,
                                 port=args.http_port, tokenizer=tokenizer,
                                 model_name=args.model,
                                 default_max_new=args.max_new_tokens,
                                 request_timeout=getattr(
                                     args, "request_timeout", 0.0) or None)
    print(f"HTTP_READY http://{server.host}:{server.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        if hasattr(backend, "close"):
            # the dynamic-batching/continuous-batching backends run a
            # scheduler thread that must drain its waiters on the way out
            backend.close()
    return 0


# ---------------------------------------------------------------------------
# gateway (replicated serving: cache-aware routing over N replicas)
# ---------------------------------------------------------------------------

def _parse_replicas(spec: str):
    """``host:port,host:port,...`` → ``[(host, port), ...]``."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad replica {part!r}: expected host:port")
        out.append((host, int(port)))
    if not out:
        raise ValueError("--replicas needs at least one host:port")
    return out


def cmd_gateway(args) -> int:
    """The prefix-aware replicated serving gateway (docs/DESIGN.md §16):
    spread /generate traffic across N independent ``serve`` replicas,
    routing each request to the replica most likely to hold its prompt
    prefix in its radix cache.  Holds no engine — start the replicas
    first (``cli serve --batch-slots N ...``), then point the gateway
    at them."""
    from .runtime.gateway import (GatewayHTTPServer, PrefixAwareRouter,
                                  ReplicaRegistry)

    if args.drain or args.undrain:
        # client mode: flip the drain flag on an ALREADY-RUNNING
        # gateway at --http-host/--http-port, print its answer, exit
        import json as _json
        from http.client import HTTPConnection
        rid = args.drain or args.undrain
        body = _json.dumps({"replica": rid,
                            "draining": bool(args.drain)}).encode()
        conn = HTTPConnection(args.http_host, args.http_port, timeout=5.0)
        try:
            conn.request("POST", "/drain", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            print(resp.read().decode("utf-8", "replace"))
            return 0 if resp.status == 200 else 1
        except OSError as e:
            print(f"gateway at {args.http_host}:{args.http_port} "
                  f"unreachable: {e}", file=sys.stderr)
            return 1
        finally:
            conn.close()

    if not args.replicas:
        print("--replicas is required (except with --drain/--undrain)",
              file=sys.stderr)
        return 1
    try:
        replicas = _parse_replicas(args.replicas)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    registry = ReplicaRegistry(
        replicas, sustain=args.evict_sustain,
        readmit_cooldown_s=args.readmit_cooldown,
        probe_interval_s=args.health_interval,
        probe_timeout_s=args.probe_timeout)
    router = PrefixAwareRouter(
        registry, min_prefix_tokens=args.min_prefix_tokens,
        block_tokens=args.route_block_tokens,
        load_factor=args.load_factor)
    server = GatewayHTTPServer(
        registry, router, host=args.http_host, port=args.http_port,
        retry_limit=args.retry_limit,
        resume_limit=args.resume_limit,
        proxy_timeout_s=args.proxy_timeout or None)
    print(f"GATEWAY_READY http://{server.host}:{server.port} "
          f"replicas={','.join(registry.replica_ids())}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


# ---------------------------------------------------------------------------
# server (integrated root-server app)
# ---------------------------------------------------------------------------

def cmd_server(args) -> int:
    """The full root-server composition (reference ``server.py:583-1052``):
    collection window → monitor round → cost-model plan → lifecycle
    broadcast with weight-artifact distribution → pipeline header + HTTP."""
    import logging
    logging.basicConfig(level=logging.INFO)
    from .server_app import ServerApp

    if getattr(args, "tp", 1) > 1:
        print("--tp is not supported by the server app (the planner "
              "assigns whole layer ranges per worker)", file=sys.stderr)
        return 1

    app = ServerApp(
        model=args.model, num_workers=args.num_workers,
        checkpoint=args.checkpoint, weights_seed=args.weights_seed,
        max_seq=args.max_seq, max_new_tokens=args.max_new_tokens,
        greedy=args.greedy, temperature=args.temperature, top_k=args.top_k,
        min_p=getattr(args, "min_p", 0.0),
        bind_host=args.bind_host, http_host=args.http_host,
        http_port=args.http_port, collect_window=args.collect_window,
        collect_timeout=args.collect_timeout,
        monitor_timeout=args.monitor_timeout,
        step_timeout=args.step_timeout,
        # broadcast in the OPEN RunConfig, so every auto worker's stage
        # cache uses it too — no mixed-precision pipeline
        kv_cache_dtype=getattr(args, "kv_cache_dtype", "") or None,
        pool_size=args.pool_size)
    return app.run()


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def cmd_worker(args) -> int:
    """One pipeline stage process (see runtime/worker_main.py); ``--elastic``
    makes it reshard-capable (holds full weights, accepts live migration);
    ``--auto`` connects to a ``server`` app and receives its role, layer
    range, and weights from the control plane."""
    from .runtime import worker_main

    if args.auto:
        ap = argparse.ArgumentParser(prog="worker --auto")
        ap.add_argument("--registry", required=True,
                        help="server registration address host:port (the "
                             "only address a bare worker needs)")
        ap.add_argument("--device-id", required=True)
        ap.add_argument("--bind-host", default="127.0.0.1")
        ap.add_argument("--port", type=int, default=0)
        ap.add_argument("--step-timeout", type=float, default=120.0)
        a = ap.parse_args(args.rest)
        from .server_app import run_auto_worker
        return run_auto_worker(a.registry, a.device_id,
                               bind_host=a.bind_host,
                               port=a.port, step_timeout=a.step_timeout)

    if not args.elastic:
        return worker_main.main(args.rest)

    import jax

    from .comm.transport import ZmqTransport
    from .models.base import StageSpec
    from .models.decoder import init_full_params
    from .models.registry import get_model_config
    from .ops.sampling import SamplingParams
    from .runtime.elastic import ElasticStageRuntime, ElasticWorker

    ap = argparse.ArgumentParser(prog="worker --elastic")
    for a in ("--model", "--device-id", "--header"):
        ap.add_argument(a, required=True)
    # stage placement is optional: the serving header pushes the real plan
    # via an initial reshard, so these are placeholders for standalone use.
    ap.add_argument("--stage-id", type=int, default=1)
    ap.add_argument("--num-stages", type=int, default=2)
    ap.add_argument("--layer-start", type=int, default=0)
    ap.add_argument("--layer-end", type=int, default=-1,
                    help="-1 = whole model (placeholder until reshard)")
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--next", default="")
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--weights-seed", type=int, default=0)
    ap.add_argument("--greedy", action="store_true")
    ap.add_argument("--temperature", type=float, default=0.7)
    ap.add_argument("--top-k", type=int, default=7)
    ap.add_argument("--min-p", type=float, default=0.0)
    ap.add_argument("--step-timeout", type=float, default=120.0)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor parallelism over this host's first N "
                         "local devices (elastic pipeline x tp)")
    ap.add_argument("--kv-cache-dtype", default="",
                    help="reduced-precision KV cache storage for this "
                         "stage, e.g. float8_e4m3fn")
    ap.add_argument("--kv-layout", default=None,
                    choices=["paged"],
                    help="this stage's request-cache layout (paged is "
                         "the only layout: per-stage page pool, blocks "
                         "reserved per chunk actually run; 'dense' was "
                         "removed — docs/DESIGN.md §14)")
    ap.add_argument("--fault-plan", default="",
                    help="CHAOS TESTING ONLY: JSON fault-plan spec "
                         "(path or inline); requires --chaos")
    ap.add_argument("--chaos", action="store_true")
    a = ap.parse_args(args.rest)

    from .comm.faults import FaultConfigError, load_fault_plan, maybe_wrap
    try:
        fault_plan = load_fault_plan(a.fault_plan, a.chaos)
    except FaultConfigError as e:
        print(str(e), file=sys.stderr)
        return 1

    cfg = get_model_config(a.model)
    full = init_full_params(jax.random.PRNGKey(a.weights_seed), cfg)
    sampling = SamplingParams(greedy=True) if a.greedy else \
        SamplingParams(temperature=a.temperature, top_k=a.top_k,
                       min_p=a.min_p)
    layer_end = a.layer_end if a.layer_end >= 0 else cfg.num_layers
    spec = StageSpec(a.stage_id, a.num_stages, a.layer_start, layer_end)
    from .parallel.mesh import local_tp_mesh
    rt = ElasticStageRuntime(cfg, spec, full, a.max_seq, sampling,
                             mesh=local_tp_mesh(a.tp),
                             kv_cache_dtype=a.kv_cache_dtype or None,
                             kv_layout=a.kv_layout)
    transport = maybe_wrap(
        ZmqTransport(a.device_id, bind_host=a.bind_host, port=a.port),
        fault_plan)
    next_id = None
    if a.next:
        next_id, next_addr = a.next.split("@", 1)
        transport.connect(next_id, next_addr)
    header_id, header_addr = a.header.split("@", 1)
    transport.connect(header_id, header_addr)
    worker = ElasticWorker(rt, transport, next_id=next_id,
                           header_id=header_id, step_timeout=a.step_timeout)
    print(f"WORKER_READY {a.device_id} {transport.address}", flush=True)
    try:
        worker.serve_forever()
    finally:
        transport.close()
    return 0


# ---------------------------------------------------------------------------
# chat (streaming REPL client)
# ---------------------------------------------------------------------------

def _parse_url(url: str):
    from urllib.parse import urlparse
    u = urlparse(url if "//" in url else f"http://{url}")
    return u.hostname or "127.0.0.1", u.port or 5000


def stream_generate(host: str, port: int, payload: dict, timeout: float = 600):
    """POST /generate with stream=true; yield each JSONL line as a dict the
    moment its chunk arrives (http.client decodes chunked transfer encoding
    incrementally, so this generator runs concurrently with decoding)."""
    import http.client

    payload = dict(payload, stream=True)
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/generate", body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"HTTP {resp.status}: {resp.read().decode(errors='replace')}")
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if line:
                yield json.loads(line)
    finally:
        conn.close()


def cmd_chat(args) -> int:
    """Terminal chat REPL over the streaming HTTP endpoint — the reference's
    ChatScreen/DataRepository loop (``ChatScreen.kt:1-353``,
    ``DataRepository.kt:5-27``: partial decodes pushed to the UI as they
    stream, ``Communication.java:629-638``) as a console app.

    Reads one message per line, POSTs ``stream: true``, and renders tokens
    as each chunk arrives.  With ``--ids`` the input line is comma-separated
    token ids (drives tokenizer-less servers, e.g. in tests); otherwise the
    message is wrapped in the reference's prompt template
    (``BackgroundService.java:211``) and tokenized locally (``--tokenizer``)
    or server-side.
    """
    import http.client

    tokenizer = _load_tokenizer(args.tokenizer)
    host, port = _parse_url(args.url)

    print(f"chat -> http://{host}:{port}  (/quit to exit)", flush=True)
    while True:
        sys.stdout.write("> ")
        sys.stdout.flush()
        line = sys.stdin.readline()
        if not line:
            break                       # EOF
        line = line.strip()
        if not line:
            continue
        if line in ("/quit", "/exit"):
            break

        payload = {"max_new_tokens": args.max_new_tokens, "seed": args.seed}
        if getattr(args, "stop", None):
            payload["stop"] = args.stop
        if args.ids:
            try:
                payload["prompt_ids"] = [[int(t) for t in line.split(",")]]
            except ValueError:
                print("[error] --ids mode expects comma-separated ints",
                      file=sys.stderr)
                continue
        else:
            prompt = args.template.format(msg=line)
            if tokenizer is not None:
                payload["prompt_ids"] = [tokenizer.encode(prompt)]
            else:
                payload["prompt"] = prompt   # server-side tokenizer

        try:
            # incremental detokenization (tokenizer.StreamDetokenizer —
            # one owner of the boundary/holdback rules, shared with the
            # server's streaming "text" field)
            from .tokenizer import StreamDetokenizer
            detok = (StreamDetokenizer(tokenizer)
                     if tokenizer is not None else None)
            for item in stream_generate(host, port, payload):
                if "error" in item:
                    # a mid-stream server failure arrives as an error
                    # line; RuntimeError routes it to the REPL's
                    # report-and-continue handler below
                    raise RuntimeError(item["error"])
                if item.get("done"):
                    break              # stop-mode summary line
                if "text" in item:
                    piece = item["text"][0]
                elif detok is not None:
                    piece = detok.push(int(item["tokens"][0]))
                else:
                    piece = ("" if item["step"] == 0 else " ") + \
                        str(item["tokens"][0])
                sys.stdout.write(piece)
                sys.stdout.flush()
            if detok is not None:
                sys.stdout.write(detok.flush())
                sys.stdout.flush()
        except (ConnectionError, OSError, RuntimeError,
                http.client.HTTPException, json.JSONDecodeError) as e:
            # a server dying mid-stream (IncompleteRead, truncated JSONL)
            # must not kill the REPL — report and take the next prompt
            print(f"\n[error] {e}", file=sys.stderr)
            continue
        sys.stdout.write("\n")
        sys.stdout.flush()
    return 0


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

def cmd_plan(args) -> int:
    """Offline partition planning from device profiles (the planner the
    reference commented out, ``server.py:879-891``, made a first-class
    tool)."""
    from .models.registry import get_model_config
    from .planner.cost_model import model_cost_profile
    from .planner.planner import (DeviceProfile, PartitionPlan,
                                  plan_partition, round_robin_plan,
                                  save_plan_cache)

    cfg = get_model_config(args.model)
    if args.load:
        with open(args.load) as f:
            plan = PartitionPlan.from_json(json.load(f))   # validates shape
        if plan.model != args.model:
            print(f"cached plan is for {plan.model!r}, not {args.model!r}",
                  file=sys.stderr)
            return 1
        print(json.dumps(plan.to_json(), indent=2))
        return 0

    with open(args.devices) as f:
        dev_json = json.load(f)
    devs = [DeviceProfile(**d) for d in dev_json]
    if args.round_robin:
        plan = round_robin_plan(cfg, args.model, devs)
    else:
        plan = plan_partition(cfg, args.model, devs, ctx=args.ctx,
                              profile=model_cost_profile(cfg, ctx=args.ctx))
    if args.save:
        save_plan_cache(args.save, plan)
    print(json.dumps(plan.to_json(), indent=2))
    return 0


# ---------------------------------------------------------------------------
# generate / bench
# ---------------------------------------------------------------------------

def cmd_generate(args) -> int:
    """One-shot local generation (ids in, ids/text out)."""
    import numpy as np

    _export_kv_tier_env(args)
    if getattr(args, "no_spec_adaptive", False):
        print("--no-spec-adaptive requires serve --batch-slots with "
              "--draft-model or --prompt-lookup", file=sys.stderr)
        return 1
    tokenizer = _load_tokenizer(args.tokenizer)
    if args.prompt_ids:
        ids = np.asarray([[int(t) for t in args.prompt_ids.split(",")]],
                         dtype=np.int32)
    elif args.prompt is not None:
        if tokenizer is None:
            print("--prompt requires --tokenizer", file=sys.stderr)
            return 1
        ids = np.asarray([tokenizer.encode(args.prompt)], dtype=np.int32)
    else:
        print("need --prompt-ids or --prompt", file=sys.stderr)
        return 1

    stats = None
    if getattr(args, "draft_model", "") and getattr(args, "prompt_lookup",
                                                    False):
        print("choose one of --draft-model / --prompt-lookup",
              file=sys.stderr)
        return 1
    if getattr(args, "sp", 1) > 1:
        # long-context sequence parallelism: the prompt is sharded over
        # the sp mesh axis, prefill runs ring attention (or Ulysses
        # all-to-all), and the KV cache stays sequence-sharded for the
        # whole generation (parallel/sequence.py, parallel/ulysses.py)
        if (getattr(args, "draft_model", "")
                or getattr(args, "prompt_lookup", False)
                or getattr(args, "tp", 1) > 1):
            print("--sp is exclusive with --draft-model/--prompt-lookup/"
                  "--tp", file=sys.stderr)
            return 1
        return _generate_sp(args, ids, tokenizer)
    if getattr(args, "prompt_lookup", False):
        # draft-free speculation: n-gram lookup over the context proposes,
        # the target verifies (runtime/prompt_lookup.py)
        pld = _build_prompt_lookup_engine(args)
        res, stats = pld.generate(ids, args.max_new_tokens, seed=args.seed)
    elif getattr(args, "draft_model", ""):
        # speculative decoding: the draft model proposes, the target
        # verifies (runtime/speculative.py); shares every engine flag
        spec = _build_spec_engine(args)
        res, stats = spec.generate(ids, args.max_new_tokens, seed=args.seed)
    else:
        _, engine = _build_engine(args)
        res = engine.generate(ids, args.max_new_tokens, seed=args.seed)
    out = {"tokens": res.tokens.tolist(),
           "tokens_per_second": res.tokens_per_second}
    if stats is not None:
        from .runtime.speculative import stats_json
        out["speculative"] = stats_json(stats, args.num_draft)
    if tokenizer is not None:
        out["text"] = [tokenizer.decode(r) for r in res.tokens.tolist()]
    print(json.dumps(out))
    return 0


def _generate_sp(args, ids, tokenizer) -> int:
    """``generate --sp N``: one-shot long-context generation over a local
    sequence-parallel mesh.  ``--sp-strategy ring`` shards the KV cache by
    sequence (ring-attention prefill, log-sum-exp decode reduction);
    ``ulysses`` re-shards by head via all_to_all.  The prompt length must
    be a multiple of N (sharding is by contiguous chunk; pad or trim
    client-side — silent padding would change what the model attends)."""
    import time as _time

    import jax
    import numpy as np

    from .models.registry import get_model_config
    from .parallel.mesh import local_sp_mesh

    unsupported = _sp_unsupported_flags(args)
    if unsupported:
        # the sp generate fns own their attention/cache strategy and have
        # no eos/chunk plumbing — reject loudly rather than silently
        # ignoring the flags
        print(f"{'/'.join(unsupported)} not supported with --sp",
              file=sys.stderr)
        return 1
    from .parallel.sequence import validate_sp_prompt

    cfg = get_model_config(args.model)
    mesh = local_sp_mesh(args.sp)   # call site guards args.sp > 1
    # the generate fns re-validate at call time; running the shared rule
    # HERE fails fast before a multi-GB checkpoint load (its ValueError
    # renders as the CLI's one-line error like every other config error)
    validate_sp_prompt(ids.shape[1], args.sp, args.max_seq,
                       args.max_new_tokens)
    sampling = _sampling_from_args(args)
    kv_dtype = getattr(args, "kv_cache_dtype", None) or None
    if args.sp_strategy == "ring":
        from .parallel.sequence import make_sp_generate_fn
        gen = make_sp_generate_fn(cfg, mesh, max_seq=args.max_seq,
                                  num_new_tokens=args.max_new_tokens,
                                  sampling=sampling,
                                  kv_cache_dtype=kv_dtype)
    else:
        from .parallel.ulysses import make_ulysses_generate_fn
        gen = make_ulysses_generate_fn(cfg, mesh, max_seq=args.max_seq,
                                       num_new_tokens=args.max_new_tokens,
                                       sampling=sampling,
                                       kv_cache_dtype=kv_dtype)
    params = _load_full_params(args, cfg)
    t0 = _time.perf_counter()
    with mesh:
        toks = np.asarray(gen(params, np.asarray(ids),
                              jax.random.PRNGKey(args.seed)))
    dt = _time.perf_counter() - t0
    # like the plain generate path, the one-shot timing includes compile
    out = {"tokens": toks.tolist(),
           "tokens_per_second": toks.size / dt,
           "sp": args.sp, "sp_strategy": args.sp_strategy}
    if tokenizer is not None:
        out["text"] = [tokenizer.decode(r) for r in toks.tolist()]
    print(json.dumps(out))
    return 0


def cmd_classify(args) -> int:
    """Dataset classification accuracy run (the reference's classification
    task: ``Dataset.java:20-44`` CSV in, accuracy out,
    ``BackgroundService.java:233-245``).  Rows are ``text,label``; with
    ``--tokenizer`` the text is encoded, otherwise it must be
    space-separated token ids."""
    import numpy as np

    from .tasks import evaluate_classifier, load_csv_dataset

    ds = load_csv_dataset(args.dataset)
    tokenizer = _load_tokenizer(args.tokenizer)
    prompts = []
    for text in ds.texts:
        if tokenizer is not None:
            ids = tokenizer.encode(text)
        else:
            try:
                ids = [int(t) for t in text.split()]
            except ValueError:
                print("without --tokenizer, dataset text must be "
                      "space-separated token ids", file=sys.stderr)
                return 1
        prompts.append(np.asarray([ids], dtype=np.int32))

    label_ids = [int(t) for t in args.label_token_ids.split(",")]
    if len(label_ids) != len(ds.label_names):
        print(f"--label-token-ids has {len(label_ids)} entries but the "
              f"dataset has {len(ds.label_names)} classes "
              f"({ds.label_names})", file=sys.stderr)
        return 1

    _, engine = _build_engine(args)
    result = evaluate_classifier(
        lambda batch: engine.classify(batch, label_ids),
        prompts, ds.labels, batch_size=args.batch)
    result["label_names"] = ds.label_names
    print(json.dumps(result))
    return 0


def cmd_bench(args) -> int:
    """Engine decode benchmark (same shape as the repo-root bench.py).

    With ``--prompt-lookup`` or ``--draft-model``, ALSO times the
    speculative engine on the same workload and reports the speedup with
    acceptance stats — how speculation is evaluated on real weights."""
    import numpy as np

    want_pld = bool(getattr(args, "prompt_lookup", False))
    want_draft = bool(getattr(args, "draft_model", ""))
    if want_pld and want_draft:
        print("choose one of --draft-model / --prompt-lookup",
              file=sys.stderr)
        return 1

    spec = None
    if want_pld or want_draft:
        # build the speculative engine FIRST and reuse its target weights
        # for the baseline — loading a large checkpoint twice would hold
        # two copies in device memory (and can OOM exactly the models
        # this comparison is for)
        spec = (_build_prompt_lookup_engine(args) if want_pld
                else _build_spec_engine(args))
        from .runtime import InferenceEngine
        engine = InferenceEngine(
            spec.cfg, spec.params, max_seq=args.max_seq,
            sampling=_sampling_from_args(args),
            attn_backend=args.attn_backend, mesh=spec.mesh)
    else:
        _, engine = _build_engine(args)

    prompt = np.arange(args.batch * args.prompt_len).reshape(
        args.batch, args.prompt_len) % 1000
    engine.generate(prompt, args.max_new_tokens, seed=0)       # compile
    res = engine.generate(prompt, args.max_new_tokens, seed=0)
    out = {
        "metric": f"decode tokens/sec ({args.model}, batch={args.batch}, "
                  f"prompt={args.prompt_len}, new={args.max_new_tokens})",
        "value": round(res.tokens_per_second, 2),
        "unit": "tokens/sec",
    }
    if spec is not None:
        from .runtime.speculative import stats_json
        spec.generate(prompt, args.max_new_tokens, seed=0)     # compile
        sres, stats = spec.generate(prompt, args.max_new_tokens, seed=0)
        out["speculative"] = dict(
            stats_json(stats, args.num_draft),
            tokens_per_sec=round(sres.tokens_per_second, 2),
            speedup=round(sres.tokens_per_second
                          / res.tokens_per_second, 3))
    print(json.dumps(out))
    return 0


# ---------------------------------------------------------------------------

def _add_engine_args(ap):
    ap.add_argument("--model", required=True)
    ap.add_argument("--max-seq", type=int, default=1024)
    ap.add_argument("--max-new-tokens", type=int, default=128)
    ap.add_argument("--weights-seed", type=int, default=0)
    ap.add_argument("--checkpoint", default="",
                    help="local safetensors dir (else random init)")
    ap.add_argument("--tokenizer", default="",
                    help="tokenizer.json path for text in/out")
    ap.add_argument("--greedy", action="store_true")
    ap.add_argument("--temperature", type=float, default=0.7)
    ap.add_argument("--top-k", type=int, default=7)
    ap.add_argument("--min-p", type=float, default=0.0,
                    help="min-p filter: keep tokens with probability >= "
                         "min_p * max_prob on the temperature-scaled "
                         "distribution (0 disables; composes with top-k "
                         "and top-p)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--attn-backend", default="auto",
                    choices=["auto", "flash", "flash-interpret", "jnp"])
    ap.add_argument("--eos-id", type=int, default=None,
                    help="end-of-sequence token id: finished rows pad "
                         "with it and generation stops early once every "
                         "row emitted it")
    ap.add_argument("--kv-cache-dtype", default="",
                    help="reduced-precision KV cache storage, e.g. "
                         "float8_e4m3fn (half the cache bytes; small "
                         "accuracy cost)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="process prompts in fixed chunks of N tokens "
                         "(bounds prefill activation memory on long "
                         "prompts; with --batch-slots it also bounds the "
                         "decode stall a long admission imposes on "
                         "in-flight rows; 0 = whole-prompt prefill)")
    ap.add_argument("--stream-block", type=int, default=None,
                    help="fuse N decode steps per streaming dispatch "
                         "(docs/DESIGN.md §13): one host dispatch "
                         "per N tokens with on-device eos/stop matching "
                         "and early exit; output is bit-identical to "
                         "the per-token path; default DWT_STREAM_BLOCK "
                         "or 1")
    ap.add_argument("--kv-cache-blocks", type=int, default=None,
                    help="block-level KV prefix cache (runtime/kvcache): "
                         "host block-pool size in blocks; prompts sharing "
                         "whole leading blocks with earlier prefills skip "
                         "that prefill (radix-tree partial matches, exact "
                         "reuse).  Default: DWT_KVCACHE_BLOCKS, else on "
                         "(64) for --batch-slots and off (0) for the "
                         "single-request engines; 0 disables")
    ap.add_argument("--kv-block-tokens", type=int, default=None,
                    help="tokens per KV cache block (match granularity "
                         "AND minimum reusable prefix; default "
                         "DWT_KVCACHE_BLOCK_TOKENS, else 16)")
    ap.add_argument("--kv-host-tier-bytes", type=int, default=None,
                    help="tiered KV (docs/DESIGN.md §21): byte budget "
                         "of the host-RAM ring that catches KV blocks "
                         "LRU-evicted from the device page pool; a "
                         "radix miss whose prefix sits demoted promotes "
                         "it back for one h2d adopt instead of "
                         "re-prefilling.  Default DWT_KV_HOST_TIER_"
                         "BYTES, else 0 (off)")
    ap.add_argument("--kv-disk-tier-path", default=None,
                    help="optional mmap'd disk segment BELOW the host "
                         "ring: host-budget overflow spills here "
                         "(oldest first) instead of dropping; requires "
                         "--kv-host-tier-bytes > 0 and "
                         "--kv-disk-tier-bytes.  Default "
                         "DWT_KV_DISK_TIER_PATH")
    ap.add_argument("--kv-disk-tier-bytes", type=int, default=None,
                    help="byte budget of the disk segment (0 = no disk "
                         "tier; default DWT_KV_DISK_TIER_BYTES)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=["bf16", "int8", "int4"],
                    help="KV page WIDTH for the paged pool "
                         "(docs/DESIGN.md §17): bf16 stores full-width "
                         "pages (the default); int8 / packed int4 "
                         "quantize each page at write time with a "
                         "per-token scale sidecar riding the block "
                         "table — 2x / 4x the admissible batch at a "
                         "fixed HBM budget, small pinned accuracy "
                         "cost.  Default DWT_KV_DTYPE, else bf16; "
                         "mutually exclusive with --kv-cache-dtype")
    ap.add_argument("--kv-layout", default=None,
                    choices=["paged"],
                    help="KV cache memory layout (docs/DESIGN.md §14). "
                         "paged is the ONLY layout: device-resident "
                         "block pool + block tables (vLLM-style "
                         "PagedAttention) — HBM reserved per block "
                         "actually allocated instead of B x max_seq "
                         "rows, radix prefix hits shared by reference "
                         "with zero H2D.  'dense' (the host-pool "
                         "escape hatch) was removed after its "
                         "one-release deprecation; resolving it fails "
                         "loudly naming this removal")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor parallelism over the first N local "
                         "devices (Megatron-sliced weights, kv-head-"
                         "sharded cache; single-node serve/generate only)")


def _add_sp_args(p) -> None:
    """Sequence/context-parallelism flags, shared by generate and serve."""
    p.add_argument("--sp", type=int, default=1,
                   help="sequence/context parallelism over the first N "
                        "local devices for LONG prompts: the prompt "
                        "shards by contiguous chunk, prefill runs ring "
                        "attention (or Ulysses), the KV cache stays "
                        "sharded for the whole generation; prompt length "
                        "must divide by N")
    p.add_argument("--sp-strategy", default="ring",
                   choices=["ring", "ulysses"],
                   help="ring = sequence-sharded cache + ring-attention "
                        "prefill; ulysses = all_to_all to head-sharded "
                        "attention (needs heads divisible by N)")
    p.add_argument("--sp-queue-depth", type=int, default=None,
                   help="serve --sp: max requests allowed to WAIT behind "
                        "the one running before arrivals get 429 + "
                        "Retry-After (the sp mesh serializes requests); "
                        "default DWT_SP_QUEUE_DEPTH or 8, 0 = unbounded")


def _sp_unsupported_flags(args, allow_eos: bool = False) -> list:
    """Engine flags the sp paths have no plumbing for — one rule shared
    by ``generate --sp`` and ``serve --sp`` so the two surfaces cannot
    drift.  Rejected loudly rather than silently ignored.  ``serve``
    passes ``allow_eos=True``: its backend honors eos via the step-split
    stream programs; the one-shot generate fns are fused with a baked
    trip count and cannot."""
    return [flag for flag, on in [
        ("--eos-id", not allow_eos
         and getattr(args, "eos_id", None) is not None),
        ("--prefill-chunk", bool(getattr(args, "prefill_chunk", 0))),
        ("--stream-block",
         getattr(args, "stream_block", None) is not None),
        ("--kv-cache-blocks", _kvcache_flags_set(args)),
        ("--attn-backend", args.attn_backend != "auto")] if on]


def _add_draft_args(p) -> None:
    """Speculative-decoding flags, shared by generate and serve."""
    p.add_argument("--draft-model", default="",
                   help="speculative decoding: draft model name (must "
                        "share the target's vocab)")
    p.add_argument("--draft-checkpoint", default="",
                   help="checkpoint for the draft model weights")
    p.add_argument("--num-draft", type=int, default=4,
                   help="draft tokens proposed per verify round")
    p.add_argument("--prompt-lookup", action="store_true",
                   help="draft-FREE speculation: n-gram lookup over the "
                        "context proposes, the target verifies")
    p.add_argument("--no-spec-adaptive", action="store_true",
                   help="pin K_row = --num-draft in the mixed dispatch "
                        "instead of adapting per-row draft length to "
                        "measured acceptance (serve --batch-slots only)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distributed_inference_demo_tpu",
        description="TPU-native distributed LLM inference framework")
    # multi-host SPMD: join JAX's distributed runtime before any command
    # touches a backend; afterwards jax.devices() spans every host and the
    # parallel/ meshes run cross-host with collectives on ICI/DCN
    ap.add_argument("--jax-coordinator", default="",
                    help="host:port of process 0, enables multi-host JAX")
    ap.add_argument("--jax-num-processes", type=int, default=1)
    ap.add_argument("--jax-process-id", type=int, default=0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="HTTP inference server")
    _add_engine_args(s)
    s.add_argument("--http-host", default="127.0.0.1")
    s.add_argument("--http-port", type=int, default=5000)
    s.add_argument("--chain", default="",
                   help="pipeline mode: comma list of workerid@host:port")
    s.add_argument("--device-id", default="header")
    s.add_argument("--bind-host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0,
                   help="data-plane port (pipeline mode)")
    s.add_argument("--step-timeout", type=float, default=120.0)
    s.add_argument("--pool-size", type=int, default=1,
                   help="with --chain: dynamic batching — concurrent HTTP "
                        "requests group into windows of up to N in-flight "
                        "rids interleaving through the pipeline stages "
                        "(1 = serialized requests)")
    s.add_argument("--batch-slots", type=int, default=0,
                   help="continuous batching with N slots: concurrent "
                        "requests join the running decode batch between "
                        "steps (single-node mode only)")
    s.add_argument("--decode-block", type=int, default=1,
                   help="with --batch-slots: fuse N decode steps (or N "
                        "draft/verify rounds under --draft-model/"
                        "--prompt-lookup) per dispatch when no admission "
                        "could land anyway (one host sync per block; "
                        "admission latency <= N steps)")
    s.add_argument("--mixed-token-budget", type=int, default=0,
                   help="with --batch-slots and --prefill-chunk: pack "
                        "prefill chunk tokens from admitting prompts "
                        "into the SAME dispatch as the fused decode "
                        "block, up to N tokens total per step "
                        "(docs/DESIGN.md §19; decode fusion survives "
                        "admission and output stays bit-identical to "
                        "the serialized interleave; default "
                        "DWT_MIXED_TOKEN_BUDGET or 0 = serialized)")
    s.add_argument("--vision", action="store_true",
                   help="LLaVA-style multimodal serving: /generate takes "
                        "an optional 'image' field ([H][W][C] floats); "
                        "text-only requests serve unchanged")
    s.add_argument("--vision-preset", default="small",
                   choices=["small", "llava15", "clip-test"],
                   help="ViT tower geometry: small = 224px/6 layers, "
                        "llava15 = CLIP-ViT-L/14-336 faithful (class "
                        "token, pre-layernorm, quick_gelu, penultimate "
                        "feature select), clip-test = tiny faithful "
                        "tower for tests")
    s.add_argument("--vision-checkpoint", default="",
                   help="safetensors dir with HF CLIP/LLaVA vision tower "
                        "weights (vision_model.* names; LLaVA's "
                        "multi_modal_projector loads too when present); "
                        "empty = seeded random init")
    s.add_argument("--run-log", default="",
                   help="append structured JSONL run-log events "
                        "(serve start + per-request engine summaries) "
                        "to this path (telemetry/runlog)")
    s.add_argument("--postmortem-dir", default="",
                   help="write postmortem bundles (flight-recorder ring "
                        "+ metrics + trace + run-log tail) here on "
                        "anomaly/stall/crash; equivalent to "
                        "DWT_POSTMORTEM_DIR (docs/DESIGN.md §8)")
    s.add_argument("--admission-queue-depth", type=int, default=0,
                   help="with --batch-slots: shed load — when this many "
                        "requests are already waiting for a slot, "
                        "/generate answers 503 + Retry-After instead of "
                        "queueing unboundedly (0 = unbounded; env "
                        "DWT_MAX_QUEUE_DEPTH)")
    s.add_argument("--request-timeout", type=float, default=0.0,
                   help="per-request deadline in seconds for blocking "
                        "/generate: on expiry the request is CANCELLED "
                        "(its slot freed) and the client gets 504 "
                        "instead of a hang (0 = no deadline)")
    s.add_argument("--fault-plan", default="",
                   help="CHAOS TESTING ONLY: JSON fault-plan spec (path "
                        "or inline) injected into the data-plane "
                        "transport; requires --chaos and --chain "
                        "(docs/DESIGN.md §12; env DWT_FAULT_PLAN)")
    s.add_argument("--chaos", action="store_true",
                   help="explicitly acknowledge fault injection; "
                        "--fault-plan/DWT_FAULT_PLAN are rejected "
                        "without it")
    _add_sp_args(s)
    _add_draft_args(s)
    s.set_defaults(fn=cmd_serve)

    gw = sub.add_parser("gateway", help="replicated serving gateway: "
                        "prefix-aware routing over N serve replicas")
    gw.add_argument("--replicas", default="",
                    help="comma list of replica host:port (each a running "
                         "'serve' process); required except with "
                         "--drain/--undrain")
    gw.add_argument("--drain", default="",
                    help="client mode: mark REPLICA (host:port) draining "
                         "on the running gateway at --http-host/--http-"
                         "port — new requests stop routing to it while "
                         "in-flight streams finish (docs/DESIGN.md §18)")
    gw.add_argument("--undrain", default="",
                    help="client mode: clear REPLICA's draining flag")
    gw.add_argument("--http-host", default="127.0.0.1")
    gw.add_argument("--http-port", type=int, default=5080)
    gw.add_argument("--health-interval", type=float, default=1.0,
                    help="seconds between /stats health probes")
    gw.add_argument("--probe-timeout", type=float, default=2.0)
    gw.add_argument("--evict-sustain", type=int, default=3,
                    help="consecutive failures before a replica is "
                         "evicted from routing")
    gw.add_argument("--readmit-cooldown", type=float, default=5.0,
                    help="seconds a recovered replica must wait before "
                         "readmission")
    gw.add_argument("--min-prefix-tokens", type=int, default=16,
                    help="shortest prefix match that beats the hash "
                         "fallback")
    gw.add_argument("--route-block-tokens", type=int, default=16,
                    help="prefix-index granularity in tokens (match the "
                         "replicas' --kv-block-tokens)")
    gw.add_argument("--load-factor", type=float, default=2.0,
                    help="hashed picks above load_factor x (1 + fleet "
                         "mean load) are skipped down the rendezvous "
                         "order")
    gw.add_argument("--retry-limit", type=int, default=1,
                    help="alternate replicas tried when the routed one "
                         "dies before first token")
    gw.add_argument("--resume-limit", type=int, default=1,
                    help="mid-stream failover attempts: a replica dying "
                         "AFTER first token is resumed bit-identically "
                         "on a survivor this many times before the "
                         "error-line fallback (0 = disable)")
    gw.add_argument("--proxy-timeout", type=float, default=0.0,
                    help="per-socket replica timeout in seconds "
                         "(0 = none)")
    gw.set_defaults(fn=cmd_gateway)

    sv = sub.add_parser("server", help="integrated root server: collect, "
                        "profile, plan, distribute, serve")
    _add_engine_args(sv)
    sv.add_argument("--num-workers", type=int, default=1)
    sv.add_argument("--pool-size", type=int, default=1,
                    help="dynamic batching at the composed server's HTTP "
                         "surface: concurrent requests group into windows "
                         "of up to N in-flight pipeline requests")
    sv.add_argument("--bind-host", default="127.0.0.1")
    sv.add_argument("--http-host", default="127.0.0.1")
    sv.add_argument("--http-port", type=int, default=0)
    sv.add_argument("--collect-window", type=float, default=10.0,
                    help="quiet window closing device collection (ref 10s)")
    sv.add_argument("--collect-timeout", type=float, default=120.0)
    sv.add_argument("--monitor-timeout", type=float, default=60.0)
    sv.add_argument("--step-timeout", type=float, default=120.0)
    sv.set_defaults(fn=cmd_server)

    w = sub.add_parser("worker", help="pipeline stage worker",
                       add_help=False)
    w.add_argument("--elastic", action="store_true")
    w.add_argument("--auto", action="store_true",
                   help="receive role/range/weights from a `server` app")
    w.set_defaults(fn=cmd_worker)

    p = sub.add_parser("plan", help="partition planning")
    p.add_argument("--model", required=True)
    p.add_argument("--devices", help="JSON file: list of DeviceProfile")
    p.add_argument("--ctx", type=int, default=1024)
    p.add_argument("--round-robin", action="store_true",
                   help="reference-parity round robin instead of the "
                        "cost-model DP")
    p.add_argument("--save", default="")
    p.add_argument("--load", default="")
    p.set_defaults(fn=cmd_plan)

    c = sub.add_parser("chat", help="streaming chat REPL against a "
                       "serve/server HTTP endpoint")
    c.add_argument("--url", default="http://127.0.0.1:5000")
    c.add_argument("--max-new-tokens", type=int, default=128)
    c.add_argument("--stop", action="append", default=None,
                   help="stop sequence (repeatable); needs a server-side "
                        "tokenizer — generation ends at the earliest "
                        "match, which is not rendered")
    c.add_argument("--tokenizer", default="",
                   help="local tokenizer.json for encode/decode (else the "
                        "server's tokenizer handles text)")
    c.add_argument("--ids", action="store_true",
                   help="input lines are comma-separated token ids")
    c.add_argument("--template", default="User: {msg}. Response:",
                   help="prompt template (reference "
                        "BackgroundService.java:211)")
    c.add_argument("--seed", type=int, default=0)
    c.set_defaults(fn=cmd_chat)

    g = sub.add_parser("generate", help="one-shot local generation")
    _add_engine_args(g)
    g.add_argument("--prompt-ids", default="")
    g.add_argument("--prompt", default=None)
    _add_sp_args(g)
    _add_draft_args(g)
    g.set_defaults(fn=cmd_generate)

    b = sub.add_parser("bench", help="decode throughput benchmark")
    _add_engine_args(b)
    b.add_argument("--batch", type=int, default=8)
    b.add_argument("--prompt-len", type=int, default=64)
    _add_draft_args(b)
    b.set_defaults(fn=cmd_bench)

    cl = sub.add_parser("classify", help="CSV dataset classification "
                        "accuracy run")
    _add_engine_args(cl)
    cl.add_argument("--dataset", required=True,
                    help="CSV file of text,label rows (Dataset.java:20-44)")
    cl.add_argument("--label-token-ids", required=True,
                    help="comma list: one verbalizer token id per class, "
                         "in dataset label-name order")
    cl.add_argument("--batch", type=int, default=8)
    cl.set_defaults(fn=cmd_classify)

    args, rest = ap.parse_known_args(argv)
    args.rest = rest
    if args.cmd == "plan" and not (args.devices or args.load):
        ap.error("plan needs --devices or --load")
    if args.jax_coordinator:
        from .parallel.mesh import init_multihost
        init_multihost(args.jax_coordinator, args.jax_num_processes,
                       args.jax_process_id)
    elif args.jax_num_processes != 1 or args.jax_process_id != 0:
        # a forgotten coordinator must not silently run single-host
        ap.error("--jax-num-processes/--jax-process-id require "
                 "--jax-coordinator")
    try:
        return args.fn(args)
    except ValueError as e:
        # configuration errors raised below the flag layer (e.g. a tp
        # mesh rejecting kv_cache_dtype, or tp > local devices) render as
        # one stderr line, matching the CLI's explicit flag guards.
        # DIDEMO_DEBUG=1 re-raises with the full traceback so a genuine
        # bug surfacing as ValueError isn't flattened to one line.
        import os
        if os.environ.get("DIDEMO_DEBUG") == "1":
            raise
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
