"""Registration service: the dynamic-registration ROUTER endpoint.

TPU-native replacement for the reference's ``device_registration_thread``
(``server.py:310-473``, ZMQ ROUTER on :23457 handling ``RegisterIP`` /
``HEARTBEAT`` / ``GET_STATUS`` action strings) and its client counterpart
(``client.py:84-176``).  Differences:

- messages are schema'd msgpack envelopes (control/messages.py), not
  positional frames;
- binds an ephemeral port by default so tests and multi-server hosts never
  collide (the reference hardcodes ports — SURVEY.md §5.6);
- clean shutdown via a poller instead of blocking recv (reference defect #7).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

import zmq

from .messages import Envelope, MsgType, decode, make
from .pool import DeviceInfo, DevicePoolManager, DeviceRole
from .router import RouterService

log = logging.getLogger(__name__)


class RegistrationService(RouterService):
    """ROUTER service feeding a DevicePoolManager."""

    name = "registration"

    def __init__(self, pool: DevicePoolManager,
                 bind_host: str = "127.0.0.1", port: int = 0,
                 ctx: Optional[zmq.Context] = None):
        super().__init__(bind_host=bind_host, port=port, ctx=ctx)
        self.pool = pool
        self._endpoints: dict = {}
        self._ep_lock = threading.Lock()

    def publish_endpoint(self, name: str, address: str) -> None:
        """Advertise another control-plane service (monitor/lifecycle) so
        devices can bootstrap from this one address (the reference instead
        hardcodes its whole port map, SURVEY.md Appendix A)."""
        with self._ep_lock:
            self._endpoints[name] = address

    def handle(self, dev_id: str, msg: Envelope) -> List[bytes]:
        if msg.type == MsgType.GET_ENDPOINTS:
            with self._ep_lock:
                eps = dict(self._endpoints)
            return [make(MsgType.ENDPOINTS, endpoints=eps)]
        if msg.type == MsgType.REGISTER:
            # reference RegisterIP action, server.py:323-383
            info = DeviceInfo(
                device_id=msg.get("device_id") or dev_id,
                address=msg.get("address", ""),
                role=DeviceRole(msg.get("role", "worker")),
                model=msg.get("model"),
                capabilities=msg.get("capabilities", {}) or {},
            )
            ok = self.pool.register_device(info)
            return [make(MsgType.REGISTER_ACK, ok=ok,
                         reason=None if ok else "duplicate address")]
        if msg.type == MsgType.HEARTBEAT:
            ok = self.pool.heartbeat(msg.get("device_id", dev_id))
            return [make(MsgType.HEARTBEAT_ACK, ok=ok)]
        if msg.type == MsgType.GET_STATUS:
            return [make(MsgType.STATUS, **self.pool.status_snapshot())]
        return [make(MsgType.ERROR, reason=f"unexpected {msg.type.value}")]


class RegistrationClient:
    """Device-side client: register + heartbeat + status query.

    Mirrors ``client.py:51-176`` (DEALER with device_id identity, 5 s recv
    timeout, heartbeat thread with 3-strike reconnect)."""

    def __init__(self, server_address: str, device_id: str, address: str,
                 role: DeviceRole = DeviceRole.WORKER,
                 model: Optional[str] = None,
                 capabilities: Optional[dict] = None,
                 timeout_ms: int = 5000,
                 ctx: Optional[zmq.Context] = None):
        self._ctx = ctx or zmq.Context.instance()
        self.server_address = server_address
        self.device_id = device_id
        self.address = address
        self.role = role
        self.model = model
        self.capabilities = capabilities or {}
        self.timeout_ms = timeout_ms
        self._sock = self._connect()
        # One DEALER socket shared by the caller and the heartbeat thread:
        # ZMQ sockets are not thread-safe, so every request/reply pair holds
        # this lock for its full duration.
        self._sock_lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()

    def _connect(self) -> zmq.Socket:
        sock = self._ctx.socket(zmq.DEALER)
        sock.setsockopt(zmq.IDENTITY, self.device_id.encode())
        sock.setsockopt(zmq.RCVTIMEO, self.timeout_ms)
        sock.setsockopt(zmq.SNDTIMEO, self.timeout_ms)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(f"tcp://{self.server_address}")
        return sock

    def _rpc(self, raw: bytes) -> Envelope:
        with self._sock_lock:
            try:
                self._sock.send(raw)
                return decode(self._sock.recv())
            except zmq.ZMQError:
                # A timed-out recv leaves the late reply queued, which would
                # desync every later request/reply pair — drop the socket so
                # the stale reply dies with it.
                self._sock.close(linger=0)
                self._sock = self._connect()
                raise

    def register(self) -> bool:
        reply = self._rpc(make(
            MsgType.REGISTER, device_id=self.device_id, address=self.address,
            role=self.role.value, model=self.model,
            capabilities=self.capabilities))
        return bool(reply.get("ok"))

    def get_endpoints(self) -> dict:
        """Discover the other control-plane services' addresses."""
        reply = self._rpc(make(MsgType.GET_ENDPOINTS))
        return dict(reply.get("endpoints", {}) or {})

    def wait_for_endpoints(self, names, timeout: float = 120.0,
                           poll: float = 0.25) -> dict:
        """Poll until every name in ``names`` is advertised (they come up
        as the server progresses through its bootstrap phases)."""
        import time
        deadline = time.monotonic() + timeout
        while True:
            try:
                eps = self.get_endpoints()
            except zmq.ZMQError:
                eps = {}
            if all(n in eps for n in names):
                return eps
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"endpoints {names} not advertised within {timeout}s "
                    f"(have {sorted(eps)})")
            time.sleep(poll)

    def heartbeat_once(self) -> bool:
        try:
            reply = self._rpc(make(MsgType.HEARTBEAT,
                                   device_id=self.device_id))
            return bool(reply.get("ok"))
        except zmq.ZMQError:
            return False

    def get_status(self) -> dict:
        return self._rpc(make(MsgType.GET_STATUS)).payload

    def start_heartbeats(self, interval: float = 5.0,
                         max_strikes: int = 3) -> None:
        """Heartbeat loop with reconnect after ``max_strikes`` consecutive
        failures (reference ``client.py:51-82``)."""
        if self._hb_thread is not None:
            return

        def loop():
            strikes = 0
            while not self._hb_stop.wait(interval):
                if self.heartbeat_once():
                    strikes = 0
                    continue
                strikes += 1
                if strikes >= max_strikes:
                    log.warning("heartbeat: %d strikes, re-registering",
                                strikes)
                    try:
                        self.register()   # _rpc already rebuilt the socket
                    except zmq.ZMQError:
                        continue          # server still down; keep striking
                    strikes = 0

        self._hb_thread = threading.Thread(target=loop, daemon=True,
                                           name=f"hb-{self.device_id}")
        self._hb_thread.start()

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        self._sock.close(linger=0)
