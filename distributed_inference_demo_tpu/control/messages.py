"""Versioned msgpack message schema for the control plane.

The reference ships config as a fixed sequence of raw ZMQ frames whose
meaning is purely positional (``Client.java:69-82`` receives ipGraph,
sessionIndex, taskType, threadPoolSize, batch, seqLen, dependencyMap,
numDevice in exactly that order, no tags, no version) — SURVEY.md Appendix B
defect #4.  Here every control message is one msgpack map with:

- ``v``: protocol version int (bumped on breaking change; receivers reject
  unknown majors instead of silently misparsing),
- ``t``: message type tag (MsgType),
- the payload fields by name.

Registration / heartbeat / status mirror the reference's action strings
``RegisterIP`` / ``HEARTBEAT`` / ``GET_STATUS`` (``server.py:323-465``,
``client.py:84-176``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict

import msgpack

PROTOCOL_VERSION = 1


class MsgType(str, enum.Enum):
    # registration plane (reference server.py:310-473, client.py:84-176)
    REGISTER = "register"              # RegisterIP
    REGISTER_ACK = "register_ack"      # REGISTRATION_SUCCESSFUL / FAILED
    HEARTBEAT = "heartbeat"
    HEARTBEAT_ACK = "heartbeat_ack"
    GET_STATUS = "get_status"
    STATUS = "status"
    # service discovery: where are the monitor / lifecycle planes?  (the
    # reference hardcodes its port map — SURVEY.md Appendix A; here one
    # bootstrap address is enough)
    GET_ENDPOINTS = "get_endpoints"
    ENDPOINTS = "endpoints"
    # monitor plane (reference MonitorService.kt:149-225)
    MONITOR_HELLO = "monitor_hello"    # MonitorIP handshake
    MONITOR_GRAPH = "monitor_graph"    # ip graph reply
    MONITOR_REPORT = "monitor_report"  # {latency, bandwidth, memory, flops}
    MONITOR_STOP = "monitor_stop"
    # lifecycle FSM (reference RootServer.java:2-17 states)
    READY = "ready"
    OPEN = "open"                      # carries the full RunConfig
    PREPARE = "prepare"
    ARTIFACT_REQUEST = "artifact_request"
    ARTIFACT_CHUNK = "artifact_chunk"
    INITIALIZED = "initialized"
    START = "start"
    RUNNING = "running"
    FINISH = "finish"
    CLOSE = "close"
    # elasticity (reference Client.java:124-153 scaffold, completed here)
    REPLAN = "replan"                  # new plan broadcast mid-run
    REPLAN_ACK = "replan_ack"
    PAUSE = "pause"
    RESUME = "resume"
    ERROR = "error"


@dataclass
class Envelope:
    """One control-plane message: type tag + payload dict."""

    type: MsgType
    payload: Dict[str, Any] = field(default_factory=dict)
    version: int = PROTOCOL_VERSION

    def get(self, key: str, default=None):
        return self.payload.get(key, default)


def encode(msg: Envelope) -> bytes:
    body = {"v": msg.version, "t": msg.type.value}
    body.update(msg.payload)
    return msgpack.packb(body, use_bin_type=True)


def decode(raw: bytes) -> Envelope:
    body = msgpack.unpackb(raw, raw=False)
    if not isinstance(body, dict) or "t" not in body or "v" not in body:
        raise ValueError("malformed control message: missing v/t tags")
    v = body.pop("v")
    if v != PROTOCOL_VERSION:
        raise ValueError(
            f"unsupported control protocol version {v} "
            f"(this build speaks {PROTOCOL_VERSION})")
    t = MsgType(body.pop("t"))
    return Envelope(type=t, payload=body, version=v)


def make(type_: MsgType, **payload) -> bytes:
    return encode(Envelope(type_, payload))
