"""Control plane: device pool, registration, heartbeats, lifecycle FSM.

Typed, schema'd re-design of the reference's Python root server
(``server.py:38-473``) and its order-coupled ZMQ lifecycle protocol
(``RootServer.java:2-17`` / ``Client.java:50-173``).  Every message on the
wire is a versioned msgpack map (control/messages.py) instead of raw frames
whose meaning depends on position (reference ``Client.java:69-82`` — defect
#4 in SURVEY.md Appendix B).
"""

from .messages import (Envelope, MsgType, decode, encode)
from .pool import DeviceInfo, DevicePoolManager, DeviceRole
from .service import RegistrationClient, RegistrationService
from .lifecycle import (LifecycleClient, LifecycleServer, RunConfig,
                        LifecycleState)

__all__ = [
    "Envelope", "MsgType", "encode", "decode",
    "DeviceInfo", "DevicePoolManager", "DeviceRole",
    "RegistrationClient", "RegistrationService",
    "LifecycleClient", "LifecycleServer", "RunConfig", "LifecycleState",
]
