"""Lifecycle FSM: Ready → Open → Prepare → Initialized → Start → Running →
Finish → Close.

Re-design of the reference protocol documented at ``RootServer.java:2-17``
and implemented client-side in ``Client.communicationOpenClose``
(``Client.java:50-173``) with the server side missing from the snapshot
(``SecureConnection.root_server.communication_open_close``, inferred —
SURVEY.md §2.2).  Differences from the reference:

- **One schema'd OPEN message** (RunConfig as msgpack) instead of eight
  order-coupled raw frames (``Client.java:69-82``; defect #4).
- **Event-driven single ROUTER loop** on the server handling all devices by
  identity, with a barrier when every device reports INITIALIZED — the
  reference spawns one Python thread per device (``server.py:1032-1040``).
- **Chunked, checksummed artifact streaming** replacing the model-zip
  download (``Client.java:174-256``): artifacts are named blobs (weight
  shard manifests, tokenizer files) with sha256 verification; devices that
  already hold an artifact skip the transfer (``skip_model_transmission``,
  ``server.py:1009``; ``MODEL_EXIST_ON_DEVICE``, ``init_server.py:19``).
- All receives are polled with timeouts — no blocking ``recv(0)`` hangs
  (defect #7).
"""

from __future__ import annotations

import enum
import hashlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import zmq

from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.runlog import get_run_log
from .messages import Envelope, MsgType, decode, make
from .router import RouterService

log = logging.getLogger(__name__)

ARTIFACT_CHUNK_BYTES = 1 << 20  # 1 MiB chunks (reference streams the zip in
                                # chunks too, Client.java:174-223)


class LifecycleState(str, enum.Enum):
    READY = "ready"
    OPEN = "open"
    PREPARE = "prepare"
    INITIALIZED = "initialized"
    RUNNING = "running"
    FINISHED = "finished"
    CLOSED = "closed"


@dataclass
class RunConfig:
    """The full run configuration broadcast at OPEN.

    Replaces the reference config dict (``server.py:998-1013``: num_sample,
    max_length, core_pool_size, head/tail node, dependency, session_index,
    graph, skip_model_transmission, onnx) with named, typed fields.
    """

    model: str = "tinyllama-1.1b"
    task_type: str = "generation"          # generation | classification
    num_samples: int = 1
    max_new_tokens: int = 40               # reference max_length=40
    max_seq: int = 256                     # KV capacity on every stage
    pool_size: int = 1                     # in-flight microbatches
    device_graph: List[str] = field(default_factory=list)   # ring order, addr
    device_ids: List[str] = field(default_factory=list)     # ring order, ids
    # stage assignment: device_id -> [layer_start, layer_end)
    stage_ranges: Dict[str, List[int]] = field(default_factory=dict)
    # mesh axes for TPU devices within a stage: {"dp":1,"tp":8,"sp":1,...}
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    sampling: Dict[str, float] = field(default_factory=lambda: {
        "temperature": 0.7, "top_k": 7})   # reference k=7, temp=0.7
    skip_artifact_transfer: bool = False
    reload_sample_id: Optional[int] = None  # drain/resume (server.py:1011)
    plan_version: int = 0
    # reduced-precision KV cache storage on every stage (e.g.
    # "float8_e4m3fn"); None = the model dtype
    kv_cache_dtype: Optional[str] = None

    def to_payload(self) -> dict:
        return {
            "model": self.model, "task_type": self.task_type,
            "num_samples": self.num_samples,
            "max_new_tokens": self.max_new_tokens,
            "max_seq": self.max_seq,
            "pool_size": self.pool_size,
            "device_graph": self.device_graph,
            "device_ids": self.device_ids,
            "stage_ranges": self.stage_ranges,
            "mesh_axes": self.mesh_axes,
            "sampling": self.sampling,
            "skip_artifact_transfer": self.skip_artifact_transfer,
            "reload_sample_id": self.reload_sample_id,
            "plan_version": self.plan_version,
            "kv_cache_dtype": self.kv_cache_dtype,
        }

    @staticmethod
    def from_payload(p: dict) -> "RunConfig":
        return RunConfig(
            model=p["model"], task_type=p["task_type"],
            num_samples=p["num_samples"],
            max_new_tokens=p["max_new_tokens"],
            max_seq=p.get("max_seq", 256), pool_size=p["pool_size"],
            device_graph=list(p["device_graph"]),
            device_ids=list(p["device_ids"]),
            stage_ranges={k: list(v) for k, v in p["stage_ranges"].items()},
            mesh_axes=dict(p["mesh_axes"]), sampling=dict(p["sampling"]),
            skip_artifact_transfer=p["skip_artifact_transfer"],
            reload_sample_id=p.get("reload_sample_id"),
            plan_version=p.get("plan_version", 0),
            kv_cache_dtype=p.get("kv_cache_dtype"),
        )


# artifact provider: (device_id, artifact_name) -> bytes (or raise KeyError)
ArtifactProvider = Callable[[str, str], bytes]


class LifecycleServer(RouterService):
    """Server side of the FSM: drives every device through the state chain
    and releases them together at START."""

    name = "lifecycle"

    def __init__(self, config: RunConfig,
                 artifact_provider: Optional[ArtifactProvider] = None,
                 bind_host: str = "127.0.0.1", port: int = 0,
                 ctx: Optional[zmq.Context] = None):
        super().__init__(bind_host=bind_host, port=port, ctx=ctx)
        self.config = config
        self.artifact_provider = artifact_provider
        self.states: Dict[str, LifecycleState] = {}
        self._lock = threading.Lock()
        self.expected = set(config.device_ids)
        self.all_finished = threading.Event()
        self.all_running = threading.Event()
        # (device_id, name) -> (blob, sha256); lives while a pull-based
        # chunked download is in progress, dropped after the last chunk.
        self._artifact_cache: Dict = {}

    # -- message handling --------------------------------------------------

    def handle(self, dev_id: str, msg: Envelope) -> List[bytes]:
        if msg.type == MsgType.READY:
            # Ready → Open: send the full config (Client.java:57-84)
            self.states[dev_id] = LifecycleState.OPEN
            rl = get_run_log()
            if rl.enabled:
                rl.event("lifecycle", device=dev_id, state="open",
                         model=self.config.model,
                         num_devices=len(self.expected))
            get_flight_recorder().record("lifecycle", device=dev_id,
                                         state="open")
            return [make(MsgType.OPEN, config=self.config.to_payload())]
        if msg.type == MsgType.ARTIFACT_REQUEST:
            return self._artifact_chunk(dev_id, msg.get("name", ""),
                                        msg.get("index", 0))
        if msg.type == MsgType.INITIALIZED:
            # Initialized → barrier → Start (Client.java:103-121).  A device
            # re-initializing after the run started (mid-run rejoin) gets
            # its own START immediately; the barrier fires exactly once.
            if self.all_running.is_set():
                with self._lock:
                    self.states[dev_id] = LifecycleState.RUNNING
                return [make(MsgType.START)]
            with self._lock:
                self.states[dev_id] = LifecycleState.INITIALIZED
                ready = all(
                    self.states.get(d) == LifecycleState.INITIALIZED
                    for d in self.expected)
            rl = get_run_log()
            if rl.enabled:
                rl.event("lifecycle", device=dev_id, state="initialized")
            get_flight_recorder().record("lifecycle", device=dev_id,
                                         state="initialized")
            if ready:
                self._broadcast_start()
            return []
        if msg.type == MsgType.FINISH:
            with self._lock:
                self.states[dev_id] = LifecycleState.FINISHED
                done = all(self.states.get(d) == LifecycleState.FINISHED
                           for d in self.expected)
            rl = get_run_log()
            if rl.enabled:
                rl.event("lifecycle", device=dev_id, state="finished",
                         all_finished=done)
            get_flight_recorder().record("lifecycle", device=dev_id,
                                         state="finished",
                                         all_finished=done)
            if done:
                self.all_finished.set()
            return [make(MsgType.CLOSE)]
        return [make(MsgType.ERROR,
                     reason=f"unexpected {msg.type.value}")]

    def _artifact_chunk(self, dev_id: str, name: str,
                        index: int) -> List[bytes]:
        """Serve ONE chunk per request (pull-based, like the reference's
        "Request Data" handshake, ``Communication.java:712-716``).  One chunk
        in flight per device bounds memory and keeps the single ROUTER loop
        responsive for other devices' lifecycle traffic."""
        if self.artifact_provider is None:
            return [make(MsgType.ERROR, reason="no artifacts served")]
        key = (dev_id, name)
        cached = self._artifact_cache.get(key)
        if cached is None:
            try:
                blob = self.artifact_provider(dev_id, name)
            except KeyError:
                return [make(MsgType.ERROR,
                             reason=f"unknown artifact {name!r}")]
            cached = (blob, hashlib.sha256(blob).hexdigest())
            self._artifact_cache[key] = cached
        blob, digest = cached
        total = max(1, -(-len(blob) // ARTIFACT_CHUNK_BYTES))
        if not 0 <= index < total:
            return [make(MsgType.ERROR,
                         reason=f"chunk {index} out of range 0..{total-1}")]
        chunk = blob[index * ARTIFACT_CHUNK_BYTES:
                     (index + 1) * ARTIFACT_CHUNK_BYTES]
        last = index == total - 1
        if last:
            self._artifact_cache.pop(key, None)
        return [make(MsgType.ARTIFACT_CHUNK, name=name, index=index,
                     total=total, data=chunk,
                     sha256=digest if last else None)]

    def _broadcast_start(self) -> None:
        # Commit server-side state BEFORE any START hits the wire, so a
        # client that reacts instantly to START observes a consistent server.
        with self._lock:
            for dev_id in self.expected:
                self.states[dev_id] = LifecycleState.RUNNING
        self.all_running.set()
        rl = get_run_log()
        if rl.enabled:
            rl.event("lifecycle", state="running",
                     devices=sorted(self.expected))
        get_flight_recorder().record("lifecycle", state="running",
                                     devices=sorted(self.expected))
        for dev_id in self.expected:   # serve-thread only (see send_to)
            self.send_to(dev_id, make(MsgType.START))

    def wait_all_finished(self, timeout: Optional[float] = None) -> bool:
        return self.all_finished.wait(timeout)


class LifecycleClient:
    """Device side of the FSM (mirror of ``Client.communicationOpenClose``,
    ``Client.java:50-173``)."""

    def __init__(self, server_address: str, device_id: str,
                 timeout_ms: int = 10000,
                 ctx: Optional[zmq.Context] = None):
        self._ctx = ctx or zmq.Context.instance()
        self.device_id = device_id
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.IDENTITY, device_id.encode())
        self._sock.setsockopt(zmq.RCVTIMEO, timeout_ms)
        self._sock.setsockopt(zmq.SNDTIMEO, timeout_ms)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(f"tcp://{server_address}")
        self.state = LifecycleState.READY
        self.config: Optional[RunConfig] = None

    def _recv(self) -> Envelope:
        msg = decode(self._sock.recv())
        if msg.type == MsgType.ERROR:
            raise RuntimeError(f"lifecycle server error: {msg.get('reason')}")
        return msg

    def open(self) -> RunConfig:
        """Ready → Open: announce readiness, receive the RunConfig."""
        self._sock.send(make(MsgType.READY, device_id=self.device_id))
        msg = self._recv()
        if msg.type != MsgType.OPEN:
            raise RuntimeError(f"expected OPEN, got {msg.type.value}")
        self.config = RunConfig.from_payload(msg.get("config"))
        self.state = LifecycleState.OPEN
        return self.config

    def fetch_artifact(self, name: str) -> bytes:
        """Prepare: pull-based chunked download with sha256 verification
        (replaces ``Client.receiveModelFile``, ``Client.java:174-223``)."""
        parts: List[bytes] = []
        digest: Optional[str] = None
        index = 0
        while True:
            self._sock.send(make(MsgType.ARTIFACT_REQUEST, name=name,
                                 index=index))
            msg = self._recv()
            if msg.type != MsgType.ARTIFACT_CHUNK:
                raise RuntimeError(
                    f"expected ARTIFACT_CHUNK, got {msg.type.value}")
            parts.append(msg.get("data", b""))
            if msg.get("index") == msg.get("total") - 1:
                digest = msg.get("sha256")
                break
            index += 1
        blob = b"".join(parts)
        actual = hashlib.sha256(blob).hexdigest()
        if digest is not None and actual != digest:
            raise RuntimeError(
                f"artifact {name!r} checksum mismatch: {actual} != {digest}")
        self.state = LifecycleState.PREPARE
        return blob

    def initialized(self, wait_start: bool = True,
                    timeout_ms: Optional[int] = None) -> None:
        """Initialized → (barrier) → Start → Running
        (``Client.java:103-121``)."""
        self._sock.send(make(MsgType.INITIALIZED, device_id=self.device_id))
        self.state = LifecycleState.INITIALIZED
        if not wait_start:
            return
        if timeout_ms is not None:
            self._sock.setsockopt(zmq.RCVTIMEO, timeout_ms)
        msg = self._recv()
        if msg.type != MsgType.START:
            raise RuntimeError(f"expected START, got {msg.type.value}")
        self.state = LifecycleState.RUNNING

    def finish(self) -> None:
        """Finish → Close (``Client.java:156-171``)."""
        self._sock.send(make(MsgType.FINISH, device_id=self.device_id))
        msg = self._recv()
        if msg.type != MsgType.CLOSE:
            raise RuntimeError(f"expected CLOSE, got {msg.type.value}")
        self.state = LifecycleState.CLOSED

    def close(self) -> None:
        self._sock.close(linger=0)
