"""Shared ROUTER service base: bind, poll loop, thread lifecycle.

All control-plane endpoints (registration, lifecycle FSM, monitor) are
ZMQ ROUTER services with the same skeleton — bind (ephemeral port by
default), poll with timeout so shutdown is clean (no blocking ``recv(0)``,
reference defect #7), decode the envelope, dispatch, reply per identity.
Subclasses implement ``handle(dev_id, msg) -> list of reply frames``.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

import zmq

from .messages import Envelope, MsgType, decode, make

log = logging.getLogger(__name__)


class RouterService:
    """Threaded ROUTER endpoint with schema'd envelope dispatch."""

    name = "router"

    def __init__(self, bind_host: str = "127.0.0.1", port: int = 0,
                 ctx: Optional[zmq.Context] = None):
        self._ctx = ctx or zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.setsockopt(zmq.LINGER, 0)
        if port == 0:
            self.port = self._sock.bind_to_random_port(f"tcp://{bind_host}")
        else:
            self._sock.bind(f"tcp://{bind_host}:{port}")
            self.port = port
        self.address = f"{bind_host}:{self.port}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- subclass API ------------------------------------------------------

    def handle(self, dev_id: str, msg: Envelope) -> List[bytes]:
        """Process one message; return reply frames for this identity."""
        raise NotImplementedError

    def send_to(self, dev_id: str, raw: bytes) -> None:
        """Push a message to a connected identity (server-initiated sends,
        e.g. START broadcast).  Must only be called from the serve thread
        or while it is not running — ZMQ sockets are not thread-safe."""
        self._sock.send_multipart([dev_id.encode(), raw])

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"{self.name}-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3.0)
            self._thread = None
        self._sock.close(linger=0)

    def _serve(self) -> None:
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=100)):
                continue
            frames = self._sock.recv_multipart()
            if len(frames) < 2:
                continue
            identity, raw = frames[0], frames[-1]
            try:
                msg = decode(raw)
            except Exception as e:
                log.warning("%s: bad message: %s", self.name, e)
                self._sock.send_multipart(
                    [identity, make(MsgType.ERROR, reason=str(e))])
                continue
            try:
                replies = self.handle(identity.decode(), msg)
            except Exception as e:  # handler bug: report, keep serving
                log.exception("%s: handler error", self.name)
                replies = [make(MsgType.ERROR, reason=str(e))]
            for reply in replies:
                self._sock.send_multipart([identity, reply])
