"""Thread-safe device pool with heartbeat failure detection.

Re-implements the reference's ``DevicePoolManager`` (``server.py:38-301``)
and heartbeat sweep (``server.py:45-107,303-307``) as a typed, testable
component:

- register/update with duplicate detection (``server.py:131-198``),
- availability & allocation with header-first priority (``server.py:248-284``;
  the header leads the ring, so it is always placed first),
- heartbeat timestamps, a sweep that moves timed-out devices to a failed
  pool with ``failure_time``/``failure_reason`` (``server.py:73-100``),
- release of a task's devices back to the pool (``server.py:286-293``).

Differences from the reference (deliberate):
- The clock is injectable so timeout logic is unit-testable without sleeps
  (the reference hardcodes ``time.time()``).
- Failure events invoke registered callbacks so the elasticity layer can
  trigger re-planning (the reference only removes the device and lets the
  in-flight pipeline hang — SURVEY.md §5.3).
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class DeviceRole(str, enum.Enum):
    HEADER = "header"
    WORKER = "worker"
    TAIL = "tail"


@dataclass
class DeviceInfo:
    """One registered device (reference device dict, ``server.py:155-198``)."""

    device_id: str
    address: str                       # host:port of the device's data plane
    role: DeviceRole = DeviceRole.WORKER
    model: Optional[str] = None        # header requests carry the model name
    capabilities: Dict = field(default_factory=dict)  # memory/flops/platform
    registered_at: float = 0.0
    last_heartbeat: float = 0.0
    status: str = "available"          # available | allocated | failed
    task_id: Optional[str] = None
    failure_time: Optional[float] = None
    failure_reason: Optional[str] = None


class DevicePoolManager:
    """Registry + allocator + failure detector for the device fleet."""

    def __init__(self, heartbeat_timeout: float = 30.0,
                 clock: Callable[[], float] = time.time):
        self._lock = threading.RLock()
        self._clock = clock
        self.heartbeat_timeout = heartbeat_timeout
        self.devices: Dict[str, DeviceInfo] = {}
        self.failed_devices: Dict[str, DeviceInfo] = {}
        self._failure_callbacks: List[Callable[[DeviceInfo], None]] = []
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- registration (reference server.py:131-198) ------------------------

    def register_device(self, info: DeviceInfo) -> bool:
        """Register or refresh a device.  Returns False when another live
        device already claims the same address (duplicate detection,
        reference ``server.py:131-153``)."""
        now = self._clock()
        with self._lock:
            for other in self.devices.values():
                if (other.address == info.address
                        and other.device_id != info.device_id):
                    return False
            # A re-registering previously-failed device rejoins cleanly.
            self.failed_devices.pop(info.device_id, None)
            existing = self.devices.get(info.device_id)
            if existing is not None:
                existing.address = info.address
                existing.role = info.role
                existing.model = info.model or existing.model
                existing.capabilities.update(info.capabilities)
                existing.last_heartbeat = now
                if existing.status == "failed":
                    existing.status = "available"
            else:
                info.registered_at = now
                info.last_heartbeat = now
                info.status = "available"
                self.devices[info.device_id] = info
            return True

    def heartbeat(self, device_id: str) -> bool:
        with self._lock:
            dev = self.devices.get(device_id)
            if dev is None:
                return False
            dev.last_heartbeat = self._clock()
            return True

    # -- availability & allocation (reference server.py:221-293) -----------

    def get_available_devices(self) -> List[DeviceInfo]:
        with self._lock:
            return [d for d in self.devices.values()
                    if d.status == "available"]

    def allocate_devices_for_task(self, task_id: str, count: int
                                  ) -> Optional[List[DeviceInfo]]:
        """Allocate ``count`` devices, header first (reference
        ``server.py:261-267``: the header device leads the ring), then
        workers by registration order, tail last when one is present."""
        with self._lock:
            avail = self.get_available_devices()
            if len(avail) < count:
                return None
            headers = [d for d in avail if d.role == DeviceRole.HEADER]
            tails = [d for d in avail if d.role == DeviceRole.TAIL]
            workers = [d for d in avail
                       if d.role not in (DeviceRole.HEADER, DeviceRole.TAIL)]
            ordered = (sorted(headers, key=lambda d: d.registered_at)
                       + sorted(workers, key=lambda d: d.registered_at)
                       + sorted(tails, key=lambda d: d.registered_at))
            chosen = ordered[:count]
            # keep the tail at the end of the ring if one was chosen
            chosen.sort(key=lambda d: (d.role == DeviceRole.TAIL,
                                       d.role != DeviceRole.HEADER))
            for d in chosen:
                d.status = "allocated"
                d.task_id = task_id
            return chosen

    def release_task_devices(self, task_id: str) -> int:
        with self._lock:
            n = 0
            for d in self.devices.values():
                if d.task_id == task_id:
                    d.status = "available"
                    d.task_id = None
                    n += 1
            return n

    # -- failure detection (reference server.py:45-107,303-307) ------------

    def on_failure(self, cb: Callable[[DeviceInfo], None]) -> None:
        self._failure_callbacks.append(cb)

    def check_device_heartbeats(self) -> List[DeviceInfo]:
        """One sweep: time out stale devices into the failed pool.  Returns
        the newly failed devices (reference moves them with
        ``failure_time``/``failure_reason``, ``server.py:73-100``)."""
        now = self._clock()
        newly_failed = []
        with self._lock:
            for dev_id in list(self.devices):
                dev = self.devices[dev_id]
                if now - dev.last_heartbeat > self.heartbeat_timeout:
                    dev.status = "failed"
                    dev.failure_time = now
                    dev.failure_reason = (
                        f"heartbeat timeout "
                        f"({now - dev.last_heartbeat:.1f}s > "
                        f"{self.heartbeat_timeout}s)")
                    self.failed_devices[dev_id] = dev
                    del self.devices[dev_id]
                    newly_failed.append(dev)
        for dev in newly_failed:       # callbacks outside the lock
            for cb in self._failure_callbacks:
                cb(dev)
        return newly_failed

    def get_failed_devices(self) -> List[DeviceInfo]:
        with self._lock:
            return list(self.failed_devices.values())

    def start_sweeper(self, interval: float = 10.0) -> None:
        """Background sweep thread (reference 10 s sweep,
        ``server.py:46-47,303-307``)."""
        if self._sweeper is not None:
            return

        def loop():
            while not self._stop.wait(interval):
                self.check_device_heartbeats()

        self._sweeper = threading.Thread(target=loop, daemon=True,
                                         name="heartbeat-sweeper")
        self._sweeper.start()

    def stop_sweeper(self) -> None:
        self._stop.set()
        if self._sweeper is not None:
            self._sweeper.join(timeout=2.0)
            self._sweeper = None

    # -- status (reference GET_STATUS reply, server.py:393-465) ------------

    def status_snapshot(self) -> Dict:
        with self._lock:
            return {
                "devices": {
                    d.device_id: {
                        "address": d.address,
                        "role": d.role.value,
                        "model": d.model,
                        "status": d.status,
                        "task_id": d.task_id,
                        "last_heartbeat": d.last_heartbeat,
                    } for d in self.devices.values()
                },
                "failed": {
                    d.device_id: {
                        "failure_time": d.failure_time,
                        "failure_reason": d.failure_reason,
                    } for d in self.failed_devices.values()
                },
                "available": len(self.get_available_devices()),
                "total": len(self.devices),
            }
