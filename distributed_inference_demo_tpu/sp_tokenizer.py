"""SentencePiece ``.model`` support, from scratch (no sentencepiece dep).

The reference consumes SP protobuf blobs through its vendored C++ tree via
``FromBlobSentencePiece`` (``tokenizers_cpp.h:52-79``, used at
``cpp/inference.cpp:88-94``).  This module provides the same capability with
zero vendored code: a minimal protobuf **wire-format** parser for the three
ModelProto sections we need (pieces, TrainerSpec, NormalizerSpec), plus both
SP segmentation algorithms:

- **unigram** — Viterbi segmentation maximizing the sum of piece log-probs;
- **bpe** — score-driven greedy merging (highest-scoring merged piece first,
  NOT rank-ordered merges like HF BPE).

A matching encoder (``build_model_proto``) lets tests craft tiny ``.model``
files without the sentencepiece library and serves as the host-side
".model -> blob" lowering tool.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union


# ---------------------------------------------------------------------------
# Minimal protobuf wire format (decode + encode)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _write_varint(value: int) -> bytes:
    if value < 0:
        value += 1 << 64  # protobuf negative ints: two's complement 64-bit
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _signed(value: int) -> int:
    """Interpret a decoded varint as a signed 64-bit int."""
    return value - (1 << 64) if value >= (1 << 63) else value


def parse_message(buf: bytes) -> Dict[int, list]:
    """Decode one protobuf message into {field_number: [raw values]}.

    Values are ints for varint fields, bytes for length-delimited fields,
    and 4/8-byte structs left packed for fixed-width fields.
    """
    fields: Dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:      # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:    # 64-bit
            val, pos = buf[pos:pos + 8], pos + 8
        elif wtype == 2:    # length-delimited
            ln, pos = _read_varint(buf, pos)
            val, pos = buf[pos:pos + ln], pos + ln
        elif wtype == 5:    # 32-bit
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        fields.setdefault(fnum, []).append(val)
    return fields


def _emit_field(fnum: int, wtype: int, payload: bytes) -> bytes:
    return _write_varint((fnum << 3) | wtype) + payload


def emit_varint_field(fnum: int, value: int) -> bytes:
    return _emit_field(fnum, 0, _write_varint(value))


def emit_bytes_field(fnum: int, value: bytes) -> bytes:
    return _emit_field(fnum, 2, _write_varint(len(value)) + value)


def emit_float_field(fnum: int, value: float) -> bytes:
    return _emit_field(fnum, 5, struct.pack("<f", value))


# ---------------------------------------------------------------------------
# ModelProto schema subset (sentencepiece_model.proto)
# ---------------------------------------------------------------------------

# SentencePiece.type enum
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6
# TrainerSpec.model_type enum
UNIGRAM, BPE = 1, 2


@dataclass
class SPModel:
    """Parsed subset of a sentencepiece ModelProto."""

    pieces: List[Tuple[str, float, int]]  # (piece, score, type)
    model_type: int = UNIGRAM
    unk_id: int = 0
    bos_id: int = 1
    eos_id: int = 2
    add_dummy_prefix: bool = True
    escape_whitespaces: bool = True
    byte_fallback: bool = False


def parse_model_proto(data: Union[bytes, str, Path]) -> SPModel:
    """Parse a ``.model`` blob (or path) into an SPModel."""
    if isinstance(data, (str, Path)):
        data = Path(data).read_bytes()
    root = parse_message(data)

    pieces: List[Tuple[str, float, int]] = []
    for raw in root.get(1, []):          # repeated SentencePiece pieces = 1
        f = parse_message(raw)
        piece = f[1][0].decode("utf-8") if 1 in f else ""
        score = struct.unpack("<f", f[2][0])[0] if 2 in f else 0.0
        ptype = _signed(f[3][0]) if 3 in f else NORMAL
        pieces.append((piece, score, ptype))

    model = SPModel(pieces=pieces)
    if 2 in root:                        # TrainerSpec trainer_spec = 2
        t = parse_message(root[2][0])
        if 3 in t:
            model.model_type = _signed(t[3][0])
        if 35 in t:                      # byte_fallback = 35 (bool)
            model.byte_fallback = bool(t[35][0])
        if 40 in t:
            model.unk_id = _signed(t[40][0])
        if 41 in t:
            model.bos_id = _signed(t[41][0])
        if 42 in t:
            model.eos_id = _signed(t[42][0])
    if 3 in root:                        # NormalizerSpec normalizer_spec = 3
        nz = parse_message(root[3][0])
        if 3 in nz:
            model.add_dummy_prefix = bool(nz[3][0])
        if 5 in nz:
            model.escape_whitespaces = bool(nz[5][0])
    if not model.byte_fallback:
        model.byte_fallback = any(t == BYTE for _, _, t in pieces)
    return model


def build_model_proto(pieces: Sequence[Tuple[str, float, int]],
                      model_type: int = UNIGRAM,
                      unk_id: int = 0, bos_id: int = 1, eos_id: int = 2,
                      add_dummy_prefix: bool = True,
                      escape_whitespaces: bool = True) -> bytes:
    """Encode an SP ModelProto blob (test fixtures / lowering tool)."""
    out = bytearray()
    for piece, score, ptype in pieces:
        body = (emit_bytes_field(1, piece.encode("utf-8"))
                + emit_float_field(2, score)
                + emit_varint_field(3, ptype))
        out += emit_bytes_field(1, body)
    trainer = (emit_varint_field(3, model_type)
               + emit_varint_field(40, unk_id)
               + emit_varint_field(41, bos_id)
               + emit_varint_field(42, eos_id))
    out += emit_bytes_field(2, trainer)
    norm = (emit_varint_field(3, int(add_dummy_prefix))
            + emit_varint_field(5, int(escape_whitespaces)))
    out += emit_bytes_field(3, norm)
    return bytes(out)


# ---------------------------------------------------------------------------
# Segmentation
# ---------------------------------------------------------------------------

_META = "▁"  # ▁
_UNK_PENALTY = 10.0


class SPTokenizer:
    """Encode/Decode for a parsed SPModel (unigram Viterbi or score-BPE).

    Surface-compatible with the facade impls in ``tokenizer.py``
    (encode / decode / token_to_id / id_to_token / vocab_size).
    """

    def __init__(self, model: SPModel):
        self.model = model
        self.piece_to_id: Dict[str, int] = {}
        self.scores: Dict[str, float] = {}
        self.specials: Dict[str, int] = {}
        self.byte_pieces: Dict[int, int] = {}
        self.max_piece_len = 1
        for i, (piece, score, ptype) in enumerate(model.pieces):
            if piece not in self.piece_to_id:
                self.piece_to_id[piece] = i
            if ptype in (NORMAL, USER_DEFINED):
                self.scores[piece] = score
                self.max_piece_len = max(self.max_piece_len, len(piece))
            elif ptype == CONTROL:
                self.specials[piece] = i
            elif ptype == BYTE and len(piece) == 6:  # "<0xAB>"
                self.byte_pieces[int(piece[3:5], 16)] = i
        self.min_score = min(self.scores.values()) if self.scores else 0.0
        self._special_list = sorted(self.specials, key=len, reverse=True)

    # -- normalization ----------------------------------------------------
    def _normalize(self, text: str) -> str:
        if self.model.escape_whitespaces:
            text = text.replace(" ", _META)
        if self.model.add_dummy_prefix and text:
            # unconditional, like sentencepiece: ' ab' -> '▁▁ab'
            text = _META + text
        return text

    # -- unigram Viterbi --------------------------------------------------
    def _segment_unigram(self, s: str) -> List[str]:
        n = len(s)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back = [0] * (n + 1)
        best[0] = 0.0
        unk_score = self.min_score - _UNK_PENALTY
        for i in range(1, n + 1):
            lo = max(0, i - self.max_piece_len)
            for j in range(lo, i):
                if best[j] == NEG:
                    continue
                sc = self.scores.get(s[j:i])
                if sc is None:
                    if i - j == 1:
                        sc = unk_score  # single-char unknown fallback
                    else:
                        continue
                if best[j] + sc > best[i]:
                    best[i] = best[j] + sc
                    back[i] = j
        out: List[str] = []
        i = n
        while i > 0:
            j = back[i]
            out.append(s[j:i])
            i = j
        out.reverse()
        return out

    # -- score-driven BPE -------------------------------------------------
    def _segment_bpe(self, s: str) -> List[str]:
        """Priority-queue merge, O(n log n): pop the highest-scoring live
        adjacent pair (leftmost on ties), merge, requeue the two pairs the
        merge created.  Stale heap entries are detected by snapshot
        comparison against the linked list."""
        import heapq

        n = len(s)
        if n <= 1:
            return list(s)
        sym = list(s)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(0, n - 1))
        alive = [True] * n
        heap: List[Tuple[float, int, int, str]] = []

        def push(i: int) -> None:
            j = nxt[i]
            if i == -1 or j == -1:
                return
            merged = sym[i] + sym[j]
            sc = self.scores.get(merged)
            if sc is not None:
                heapq.heappush(heap, (-sc, i, j, merged))

        for i in range(n - 1):
            push(i)
        while heap:
            _, i, j, merged = heapq.heappop(heap)
            if not (alive[i] and alive[j]) or nxt[i] != j \
                    or sym[i] + sym[j] != merged:
                continue  # stale entry
            sym[i] = merged
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] != -1:
                prv[nxt[j]] = i
            push(prv[i])
            push(i)
        out = []
        i = 0
        while i != -1:
            out.append(sym[i])
            i = nxt[i]
        return out

    # -- public surface ---------------------------------------------------
    def _encode_plain(self, text: str, out: List[int]) -> None:
        s = self._normalize(text)
        if not s:
            return
        seg = (self._segment_bpe(s) if self.model.model_type == BPE
               else self._segment_unigram(s))
        for piece in seg:
            i = self.piece_to_id.get(piece)
            if i is not None and piece in self.scores:
                out.append(i)
            elif self.model.byte_fallback and self.byte_pieces:
                for b in piece.encode("utf-8"):
                    out.append(self.byte_pieces.get(b, self.model.unk_id))
            else:
                out.append(self.model.unk_id)

    def encode(self, text: str) -> List[int]:
        out: List[int] = []
        pending: List[str] = []
        pos, n = 0, len(text)
        while pos < n:
            for spc in self._special_list:
                if text.startswith(spc, pos):
                    if pending:
                        self._encode_plain("".join(pending), out)
                        pending = []
                    out.append(self.specials[spc])
                    pos += len(spc)
                    break
            else:
                pending.append(text[pos])
                pos += 1
        if pending:
            self._encode_plain("".join(pending), out)
        return out

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        pieces = self.model.pieces
        data = bytearray()
        for i in ids:
            i = int(i)
            if not 0 <= i < len(pieces):
                continue
            piece, _, ptype = pieces[i]
            if ptype == CONTROL or ptype == UNKNOWN:
                if not skip_special:
                    data += piece.encode("utf-8")
                continue
            if ptype == BYTE and len(piece) == 6:
                data.append(int(piece[3:5], 16))
                continue
            data += piece.encode("utf-8")
        s = data.decode("utf-8", errors="replace")
        s = s.replace(_META, " ")
        if self.model.add_dummy_prefix and s.startswith(" "):
            s = s[1:]
        return s

    def token_to_id(self, tok: str) -> int:
        return self.piece_to_id.get(tok, -1)

    def id_to_token(self, i: int) -> Optional[str]:
        i = int(i)
        if 0 <= i < len(self.model.pieces):
            return self.model.pieces[i][0]
        return None

    def vocab_size(self) -> int:
        return len(self.model.pieces)
