"""The integrated root-server application: register → profile → plan →
distribute → run → serve.

This is the composition the reference's ``server.py`` ``__main__`` block
performs (``server.py:583-1052``): a device collection window, a monitor
round, partition planning from measured profiles, config + weight broadcast
through the lifecycle FSM, then the running pipeline behind an HTTP
endpoint.  Round 1 built and tested every piece; this module is the one
runnable program where they meet (VERDICT r1 item 3).

Server flow (``ServerApp.run``):

1. ``RegistrationService`` + ``DevicePoolManager`` with heartbeat sweeper
   (reference ``server.py:310-473,45-107``).
2. Collection window: wait for ``num_workers`` registrations, or — after at
   least one worker — a quiet window with no new arrivals
   (``server.py:709-762``, TIMEOUT=10 s quiet window).
3. Monitor round: workers' ``MonitorAgent`` probes feed the
   ``MonitorAggregator``; the server contributes its own probe report
   (``server.py:849-858``; ``MonitorService.kt``).
4. ``plan_partition`` over the measured profiles — the cost-model planner
   the reference left commented out (``server.py:879-891``) — with the
   server (header) pinned as stage 0.
5. ``LifecycleServer`` OPEN broadcasts the schema'd RunConfig; each worker
   pulls its **stage weight blob** over the chunked artifact channel
   (replacing the ONNX-zip shipping, ``server.py:910-957``) — weights come
   from the server's checkpoint/seed, never from per-worker seeds.
6. Barrier START; the server becomes the pipeline header and serves HTTP.

Worker flow (``run_auto_worker``): bind data transport → register →
heartbeats → monitor round → lifecycle OPEN → fetch stage weights →
connect ring edges from the config → INITIALIZED → serve the stage loop.
The reference equivalent is ``BackgroundService.onStartCommand`` end-to-end
(SURVEY.md §3.2) without the hand-wired topology of ``serve --chain``.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

log = logging.getLogger(__name__)


@dataclass
class ServerPorts:
    registry: str
    monitor: str
    lifecycle: str
    http: str
    data: str


class ServerApp:
    """Composed control plane + pipeline header + HTTP endpoint."""

    def __init__(self, model: str, num_workers: int,
                 checkpoint: str = "", weights_seed: int = 0,
                 max_seq: int = 256, max_new_tokens: int = 40,
                 greedy: bool = False, temperature: float = 0.7,
                 top_k: int = 7, min_p: float = 0.0,
                 bind_host: str = "127.0.0.1",
                 http_host: str = "127.0.0.1", http_port: int = 0,
                 collect_window: float = 10.0,
                 collect_timeout: float = 120.0,
                 monitor_timeout: float = 60.0,
                 step_timeout: float = 120.0,
                 device_id: str = "header",
                 kv_cache_dtype: Optional[str] = None,
                 pool_size: int = 1):
        self.model = model
        self.num_workers = num_workers
        self.checkpoint = checkpoint
        self.weights_seed = weights_seed
        self.max_seq = max_seq
        self.max_new_tokens = max_new_tokens
        self.greedy = greedy
        self.temperature = temperature
        self.top_k = top_k
        self.min_p = min_p
        self.bind_host = bind_host
        self.http_host = http_host
        self.http_port = http_port
        self.collect_window = collect_window
        self.collect_timeout = collect_timeout
        self.monitor_timeout = monitor_timeout
        self.step_timeout = step_timeout
        self.device_id = device_id
        self.kv_cache_dtype = kv_cache_dtype
        self.pool_size = pool_size

        self.ports: Optional[ServerPorts] = None
        self.plan = None
        self._services = []
        self._http = None
        self._header = None
        self._transport = None

    # ------------------------------------------------------------------

    def _sampling(self):
        from .ops.sampling import SamplingParams
        if self.greedy:
            return SamplingParams(greedy=True)
        return SamplingParams(temperature=self.temperature,
                              top_k=self.top_k, min_p=self.min_p)

    def _collect_devices(self, pool) -> List:
        """Reference collection-window semantics (``server.py:709-762``):
        run until ``num_workers`` devices registered, or — once at least one
        is in — until no new device arrives for ``collect_window`` s.

        Polling backs off exponentially with jitter (5 ms → 1 s cap)
        instead of hammering the pool lock at a fixed 50 Hz for the whole
        window; any arrival resets the backoff so a burst of late
        registrations is still picked up promptly.  A deadline expiry
        emits a structured run-log event naming the devices that DID
        register, so the postmortem question "which workers never showed
        up" is answerable from the log alone."""
        import random as _random

        from .telemetry.runlog import get_run_log

        deadline = time.monotonic() + self.collect_timeout
        last_count, last_change = 0, time.monotonic()
        sleep_s, max_sleep = 0.005, 1.0
        registered: List[str] = []
        while time.monotonic() < deadline:
            devs = pool.get_available_devices()
            registered = [d.device_id for d in devs]
            if len(devs) >= self.num_workers:
                return devs[:self.num_workers]
            if len(devs) != last_count:
                last_count, last_change = len(devs), time.monotonic()
                sleep_s = 0.005          # arrivals reset the backoff
            if devs and time.monotonic() - last_change > self.collect_window:
                log.info("collection window closed with %d/%d workers",
                         len(devs), self.num_workers)
                return devs
            time.sleep(min(sleep_s * (1.0 + _random.random()),
                           max(0.0, deadline - time.monotonic()),
                           max_sleep))
            sleep_s = min(sleep_s * 2, max_sleep)
        get_run_log().event(
            "device_collect_timeout",
            want=self.num_workers, got=last_count,
            registered=sorted(registered),
            missing=self.num_workers - last_count,
            collect_timeout_s=self.collect_timeout)
        raise TimeoutError(
            f"no {self.num_workers} workers within {self.collect_timeout}s "
            f"(got {last_count}: {sorted(registered)})")

    def _self_report(self) -> dict:
        """The server's own probe report (it is the header device)."""
        import jax
        from .monitor.probes import flops_probe, memory_info
        platform = jax.default_backend()
        return {
            "latency": {}, "bandwidth": {},
            "memory": memory_info(),
            "flops": flops_probe(),
            "platform": platform,
            "chips": jax.device_count() if platform == "tpu" else 1,
        }

    # ------------------------------------------------------------------

    def run(self, ready_cb=None, serve: bool = True) -> int:
        import jax

        from .control.lifecycle import LifecycleServer, RunConfig
        from .control.pool import DevicePoolManager
        from .control.service import RegistrationService
        from .comm.transport import ZmqTransport
        from .models.base import StageSpec, slice_stage
        from .models.loader import load_or_init, stage_params_to_bytes
        from .models.registry import get_model_config
        from .monitor.aggregator import MonitorAggregator, MonitorService
        from .planner.cost_model import model_cost_profile
        from .planner.planner import plan_partition
        from .runtime.distributed import PipelineHeader, StageRuntime
        from .runtime.http_server import HeaderBackend, InferenceHTTPServer

        cfg = get_model_config(self.model)

        # -- 1. registration plane + data transport ------------------------
        pool = DevicePoolManager()
        pool.start_sweeper()
        registry = RegistrationService(pool, bind_host=self.bind_host)
        registry.start()
        self._services.append(registry)
        transport = ZmqTransport(self.device_id, bind_host=self.bind_host)
        self._transport = transport
        print(f"SERVER_REGISTRY {registry.address}", flush=True)

        # -- 2. collection window ------------------------------------------
        log.info("collecting devices (want %d)...", self.num_workers)
        devices = self._collect_devices(pool)
        worker_ids = [d.device_id for d in devices]
        addresses = {d.device_id: d.address for d in devices}
        addresses[self.device_id] = transport.address
        log.info("collected workers: %s", worker_ids)

        # -- 3. monitor round ----------------------------------------------
        agg = MonitorAggregator(expected=[self.device_id] + worker_ids)
        monitor = MonitorService(agg, bind_host=self.bind_host)
        monitor.start()
        self._services.append(monitor)
        registry.publish_endpoint("monitor", monitor.address)
        print(f"SERVER_MONITOR {monitor.address}", flush=True)
        agg.add_report(self.device_id, self._self_report())
        if not agg.is_monitor_ready.wait(self.monitor_timeout):
            missing = [d for d in worker_ids if d not in agg.reports]
            log.warning("monitor round incomplete (missing %s); planning "
                        "with defaults for them", missing)
        ring = [self.device_id] + worker_ids
        profiles = agg.device_profiles(addresses, ring_order=ring)

        # -- 4. plan -------------------------------------------------------
        self.plan = plan_partition(
            cfg, self.model, profiles,
            profile=model_cost_profile(cfg, ctx=self.max_seq))
        log.info("plan: %s", self.plan.stage_ranges)
        print(f"SERVER_PLAN {json.dumps(self.plan.stage_ranges)}",
              flush=True)

        # -- 5. weights + lifecycle ----------------------------------------
        # float tree: the artifact channel ships float weights and every
        # stage (this header included) quantizes its own slice locally
        full = load_or_init(self.model, cfg, self.checkpoint or None,
                            seed=self.weights_seed, quantize=False)
        specs = self.plan.stage_specs()
        by_dev: Dict[str, StageSpec] = dict(zip(self.plan.device_ids, specs))

        def artifact_provider(dev_id: str, name: str) -> bytes:
            want = f"stage:{dev_id}"
            if name != want or dev_id not in by_dev:
                raise KeyError(name)
            return stage_params_to_bytes(
                slice_stage(full, cfg, by_dev[dev_id]))

        config = RunConfig(
            model=self.model, max_new_tokens=self.max_new_tokens,
            max_seq=self.max_seq, pool_size=self.pool_size,
            device_graph=[addresses[d] for d in self.plan.device_ids],
            device_ids=list(self.plan.device_ids),
            stage_ranges=self.plan.stage_ranges,
            mesh_axes={}, sampling=(
                {"greedy": 1.0} if self.greedy else
                {"temperature": self.temperature, "top_k": self.top_k,
                 "min_p": self.min_p}),
            plan_version=self.plan.plan_version,
            kv_cache_dtype=self.kv_cache_dtype)
        lifecycle = LifecycleServer(config, artifact_provider,
                                    bind_host=self.bind_host)
        lifecycle.expected = set(self.plan.device_ids) - {self.device_id}
        lifecycle.start()
        self._services.append(lifecycle)
        registry.publish_endpoint("lifecycle", lifecycle.address)
        print(f"SERVER_LIFECYCLE {lifecycle.address}", flush=True)

        # -- 6. header pipeline + HTTP -------------------------------------
        from .ops.quant import maybe_quantize
        my_spec = by_dev[self.device_id]
        if not my_spec.is_first:
            raise RuntimeError("planner must pin the server as stage 0")
        runtime = StageRuntime(
            cfg, my_spec,
            maybe_quantize(slice_stage(full, cfg, my_spec), cfg),
            self.max_seq, self._sampling(),
            kv_cache_dtype=self.kv_cache_dtype)
        next_idx = self.plan.device_ids.index(self.device_id) + 1
        next_id = self.plan.device_ids[next_idx]
        transport.connect(next_id, addresses[next_id])
        header = PipelineHeader(runtime, transport, next_id=next_id,
                                step_timeout=self.step_timeout)
        self._header = header

        if not lifecycle.all_running.wait(self.monitor_timeout):
            raise TimeoutError("workers never reached INITIALIZED")
        log.info("pipeline running: %s", self.plan.device_ids)

        if self.pool_size > 1:
            # dynamic batching: concurrent HTTP requests group into
            # generate_many windows (runtime/dynamic_batch.py)
            from .runtime.dynamic_batch import DynamicBatchingHeaderBackend
            backend = DynamicBatchingHeaderBackend(
                header, max_seq=self.max_seq, num_stages=len(specs),
                pool_size=self.pool_size)
        else:
            backend = HeaderBackend(header, max_seq=self.max_seq,
                                    num_stages=len(specs))
        self._backend = backend
        self._http = InferenceHTTPServer(
            backend, host=self.http_host, port=self.http_port,
            model_name=self.model, default_max_new=self.max_new_tokens)
        print(f"HTTP_READY http://{self._http.host}:{self._http.port}",
              flush=True)
        if ready_cb is not None:
            ready_cb(self)
        if serve:
            try:
                self._http.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                self.shutdown()
        return 0

    def shutdown(self) -> None:
        # close the scheduler-threaded backend FIRST: it is the
        # transport's one consumer — stopping the pipeline under a
        # mid-window scheduler would violate that invariant, and queued
        # HTTP waiters must get their 'backend closed' error
        if getattr(self, "_backend", None) is not None:
            if hasattr(self._backend, "close"):
                try:
                    self._backend.close()
                except Exception:
                    pass
            self._backend = None
        if self._header is not None:
            try:
                self._header.shutdown_pipeline()
            except Exception:
                pass
            self._header = None
        if self._http is not None:
            self._http.shutdown()
            self._http = None
        for svc in self._services:
            try:
                svc.stop()
            except Exception:
                pass
        self._services.clear()
        if self._transport is not None:
            self._transport.close()
            self._transport = None


# ---------------------------------------------------------------------------
# auto worker
# ---------------------------------------------------------------------------

def run_auto_worker(registry: str, device_id: str,
                    bind_host: str = "127.0.0.1",
                    port: int = 0, step_timeout: float = 120.0,
                    monitor_rounds: int = 8,
                    bootstrap_timeout: float = 120.0) -> int:
    """Fully automatic worker: no topology, no layer ranges, no seed-shared
    weights — everything arrives from the server.  Only the registry
    address is needed; the monitor and lifecycle planes are discovered
    through it as the server's bootstrap progresses."""
    from .comm.transport import ZmqTransport
    from .control.lifecycle import LifecycleClient
    from .control.pool import DeviceRole
    from .control.service import RegistrationClient
    from .models.base import StageSpec
    from .models.loader import stage_params_from_bytes
    from .models.registry import get_model_config
    from .monitor.agent import MonitorAgent
    from .ops.sampling import SamplingParams
    from .runtime.distributed import PipelineWorker, StageRuntime

    transport = ZmqTransport(device_id, bind_host=bind_host, port=port)
    reg = RegistrationClient(registry, device_id, transport.address,
                             role=DeviceRole.WORKER)
    if not reg.register():
        print(f"registration failed for {device_id}", file=sys.stderr)
        return 1
    reg.start_heartbeats()
    print(f"WORKER_REGISTERED {device_id} {transport.address}", flush=True)

    monitor = reg.wait_for_endpoints(["monitor"],
                                     timeout=bootstrap_timeout)["monitor"]
    agent = MonitorAgent(monitor, device_id, host=bind_host)
    agent.run(max_rounds=monitor_rounds)
    print(f"WORKER_MONITORED {device_id}", flush=True)

    lifecycle = reg.wait_for_endpoints(
        ["lifecycle"], timeout=bootstrap_timeout)["lifecycle"]
    lc = LifecycleClient(lifecycle, device_id, timeout_ms=60000)
    config = lc.open()
    cfg = get_model_config(config.model)
    ids = config.device_ids
    idx = ids.index(device_id)
    lo, hi = config.stage_ranges[device_id]
    spec = StageSpec(idx, len(ids), lo, hi)

    if config.skip_artifact_transfer:
        raise RuntimeError("auto worker requires artifact transfer")
    from .ops.quant import maybe_quantize
    blob = lc.fetch_artifact(f"stage:{device_id}")
    params = maybe_quantize(stage_params_from_bytes(blob), cfg)
    print(f"WORKER_WEIGHTS {device_id} {len(blob)}B layers[{lo},{hi})",
          flush=True)

    s = config.sampling
    sampling = (SamplingParams(greedy=True) if s.get("greedy") else
                SamplingParams(temperature=s.get("temperature", 0.7),
                               top_k=int(s.get("top_k", 7)),
                               min_p=s.get("min_p", 0.0)))
    runtime = StageRuntime(cfg, spec, params, max_seq=config.max_seq,
                           sampling=sampling,
                           kv_cache_dtype=config.kv_cache_dtype)

    header_id = ids[0]
    transport.connect(header_id, config.device_graph[0])
    next_id = None
    if idx + 1 < len(ids):
        next_id = ids[idx + 1]
        transport.connect(next_id, config.device_graph[idx + 1])
    worker = PipelineWorker(runtime, transport, next_id=next_id,
                            header_id=header_id, step_timeout=step_timeout)

    lc.initialized(wait_start=True, timeout_ms=120000)
    print(f"WORKER_RUNNING {device_id}", flush=True)
    try:
        worker.serve_forever()
    finally:
        try:
            lc.finish()
        except Exception:
            pass
        lc.close()
        reg.close()
        agent.close()
        transport.close()
    return 0
