"""Tokenizer facade: Encode/Decode/TokenToId/IdToToken/GetVocabSize.

Surface parity with the reference's abstract tokenizer
(``cpp/tokenizers-cpp/include/tokenizers_cpp.h:25-48``), which it backs with
a Rust HF tokenizer + vendored sentencepiece.  Rust isn't in this image, so
here the backends are:

- ``native``  — the C++ BPE engine (``comm/native/tokenizer.cc``, ctypes);
- ``python``  — a pure-Python twin of the same spec (this file), used as
  fallback and as the executable specification in tests;
- ``hf``      — the HuggingFace ``tokenizers`` library when present
  (already in the image via transformers), for exactness on exotic
  tokenizer.json configs.

All three consume standard HF ``tokenizer.json``; for the native backend the
JSON is lowered host-side into a line-based blob (no JSON parser in C++).

Schemes covered (enough for the whole model catalog, ``models/registry.py``):
``bytelevel`` (BLOOM/GPT-2 byte-level BPE) and ``metaspace``
(llama/mistral sentencepiece-style BPE with <0xXX> byte fallback).
"""

from __future__ import annotations

import ctypes
import functools
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union


# ---------------------------------------------------------------------------
# GPT-2 byte <-> unicode alphabet (matches transformers bytes_to_unicode)
# ---------------------------------------------------------------------------

@functools.lru_cache()
def _byte_to_unicode() -> Dict[int, str]:
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


@functools.lru_cache()
def _unicode_to_byte() -> Dict[str, int]:
    return {v: k for k, v in _byte_to_unicode().items()}


# ---------------------------------------------------------------------------
# tokenizer.json -> spec
# ---------------------------------------------------------------------------

class TokenizerSpec:
    """Parsed tokenizer model: vocab, merges, scheme, specials."""

    def __init__(self, vocab: Dict[str, int], merges: List[Tuple[str, str]],
                 scheme: str, byte_fallback: bool = False,
                 prepend: bool = False, unk_id: int = -1,
                 specials: Optional[Dict[str, int]] = None,
                 bos_id: Optional[int] = None, eos_id: Optional[int] = None):
        self.vocab = vocab
        self.merges = merges
        self.scheme = scheme
        self.byte_fallback = byte_fallback
        self.prepend = prepend
        self.unk_id = unk_id
        self.specials = specials or {}
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.id_to_tok: Dict[int, str] = {}
        for tok, i in vocab.items():
            self.id_to_tok[i] = tok
        for tok, i in self.specials.items():
            self.id_to_tok.setdefault(i, tok)

    @staticmethod
    def from_json(data: Union[str, dict]) -> "TokenizerSpec":
        """Lower an HF tokenizer.json into a spec.

        Scheme detection mirrors what the reference's blob factories switch
        on (FromBlobJSON vs FromBlobSentencePiece vs FromBlobByteLevelBPE,
        ``tokenizers_cpp.h:52-79``): the pre_tokenizer/decoder types.
        """
        if isinstance(data, str):
            data = json.loads(data)
        model = data.get("model", {})
        if model.get("type") not in (None, "BPE"):
            raise ValueError(f"unsupported model type {model.get('type')!r}")
        vocab = dict(model.get("vocab", {}))
        raw_merges = model.get("merges", [])
        merges: List[Tuple[str, str]] = []
        for m in raw_merges:
            if isinstance(m, str):
                left, _, right = m.partition(" ")
                merges.append((left, right))
            else:
                merges.append((m[0], m[1]))

        def _types(section) -> List[str]:
            if section is None:
                return []
            if section.get("type") == "Sequence":
                return [p.get("type") for p in
                        section.get("pretokenizers",
                                    section.get("processors",
                                                section.get("decoders", [])))]
            return [section.get("type")]

        pre = _types(data.get("pre_tokenizer"))
        scheme = "none"
        prepend = False
        if "ByteLevel" in pre:
            scheme = "bytelevel"
        elif "Metaspace" in pre:
            scheme = "metaspace"
            pt = data.get("pre_tokenizer", {})
            parts = ([pt] if pt.get("type") == "Metaspace"
                     else pt.get("pretokenizers", []))
            for p in parts:
                if p.get("type") == "Metaspace":
                    prepend = p.get("prepend_scheme", "always") != "never"
        elif model.get("byte_fallback"):
            scheme = "metaspace"
            prepend = True

        specials = {}
        for tok in data.get("added_tokens", []):
            if tok.get("special"):
                specials[tok["content"]] = tok["id"]
                vocab.setdefault(tok["content"], tok["id"])

        unk = model.get("unk_token")
        unk_id = vocab.get(unk, -1) if unk else -1
        bos_id = next((i for t, i in specials.items()
                       if t in ("<s>", "<|begin_of_text|>", "<bos>")), None)
        eos_id = next((i for t, i in specials.items()
                       if t in ("</s>", "<|end_of_text|>", "<eos>",
                                "<|endoftext|>")), None)
        return TokenizerSpec(vocab, merges, scheme,
                             byte_fallback=bool(model.get("byte_fallback")),
                             prepend=prepend, unk_id=unk_id,
                             specials=specials, bos_id=bos_id, eos_id=eos_id)

    def to_blob(self) -> str:
        """Serialize for the C++ engine (see tokenizer.cc parse_blob)."""
        def esc(s: str) -> str:
            return (s.replace("\\", "\\\\").replace("\n", "\\n")
                    .replace("\t", "\\t"))

        lines = [
            f"scheme\t{self.scheme}",
            f"fallback\t{1 if self.byte_fallback else 0}",
            f"prepend\t{1 if self.prepend else 0}",
            f"unk\t{self.unk_id}",
            f"ntok\t{len(self.vocab)}",
        ]
        for tok, i in self.vocab.items():
            lines.append(f"{i}\t{esc(tok)}")
        lines.append(f"nmerge\t{len(self.merges)}")
        for left, right in self.merges:
            lines.append(f"{esc(left)}\t{esc(right)}")
        lines.append(f"nspecial\t{len(self.specials)}")
        for tok, i in self.specials.items():
            lines.append(f"{i}\t{esc(tok)}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Pure-Python twin of the C++ engine (executable spec; fallback backend)
# ---------------------------------------------------------------------------

_WS = set(" \t\n\r\x0b\x0c\xa0  ") | {chr(c) for c in
                                                range(0x2000, 0x200B)}


def _is_ws(c: str) -> bool:
    return c in _WS


def _is_digit(c: str) -> bool:
    return "0" <= c <= "9"


def _is_letter(c: str) -> bool:
    # identical simplification to tokenizer.cc is_letter()
    return ("a" <= c <= "z") or ("A" <= c <= "Z") or (
        ord(c) >= 0x80 and not _is_ws(c))


def pretok_gpt2(text: str) -> List[str]:
    """Simplified GPT-2 pre-tokenization (twin of tokenizer.cc pretok_gpt2)."""
    out: List[str] = []
    n = len(text)
    p = 0
    while p < n:
        c = text[p]
        if c == "'" and p + 1 < n:
            nxt = text[p + 1].lower()
            if nxt in "stmd":
                out.append(text[p:p + 2]); p += 2; continue
            if p + 2 < n and text[p + 1:p + 3].lower() in ("re", "ve", "ll"):
                out.append(text[p:p + 3]); p += 3; continue
        start = p
        lead_space = c == " " and p + 1 < n and not _is_ws(text[p + 1])
        q = p + (1 if lead_space else 0)
        if q < n and _is_letter(text[q]):
            while q < n and _is_letter(text[q]):
                q += 1
            out.append(text[start:q]); p = q; continue
        if q < n and _is_digit(text[q]):
            while q < n and _is_digit(text[q]):
                q += 1
            out.append(text[start:q]); p = q; continue
        if q < n and not _is_ws(text[q]):
            while (q < n and not _is_ws(text[q]) and not _is_letter(text[q])
                   and not _is_digit(text[q])):
                q += 1
            out.append(text[start:q]); p = q; continue
        w = p
        while w < n and _is_ws(text[w]):
            w += 1
        if w < n and w - p > 1:
            out.append(text[p:w - 1]); p = w - 1
        else:
            out.append(text[p:w]); p = w
    return out


def pretok_metaspace(text: str, prepend: bool) -> List[str]:
    meta = "▁"
    s = meta if (prepend and text and not text.startswith(" ")) else ""
    s += text.replace(" ", meta)
    pieces: List[str] = []
    cur = ""
    for ch in s:
        if ch == meta and cur:
            pieces.append(cur)
            cur = ""
        cur += ch
    if cur:
        pieces.append(cur)
    return pieces


class PyBPETokenizer:
    """Pure-Python BPE engine implementing the same spec as tokenizer.cc."""

    def __init__(self, spec: TokenizerSpec):
        self.spec = spec
        self.rank = {pair: i for i, pair in enumerate(spec.merges)}
        self._special_list = sorted(spec.specials, key=len, reverse=True)

    # -- BPE core --
    def _bpe(self, syms: List[str]) -> List[str]:
        while len(syms) > 1:
            best, best_i = None, -1
            for i in range(len(syms) - 1):
                r = self.rank.get((syms[i], syms[i + 1]))
                if r is not None and (best is None or r < best):
                    best, best_i = r, i
            if best is None:
                break
            syms = (syms[:best_i] + [syms[best_i] + syms[best_i + 1]]
                    + syms[best_i + 2:])
        return syms

    def _emit(self, toks: List[str], out: List[int]):
        sp = self.spec
        for tok in toks:
            i = sp.vocab.get(tok)
            if i is not None:
                out.append(i)
            elif sp.byte_fallback:
                for b in tok.encode("utf-8"):
                    fb = f"<0x{b:02X}>"
                    j = sp.vocab.get(fb)
                    if j is not None:
                        out.append(j)
                    elif sp.unk_id >= 0:
                        out.append(sp.unk_id)
            elif sp.unk_id >= 0:
                out.append(sp.unk_id)

    def _encode_plain(self, text: str, out: List[int]):
        sp = self.spec
        if sp.scheme == "bytelevel":
            b2u = _byte_to_unicode()
            for word in pretok_gpt2(text):
                syms = [b2u[b] for b in word.encode("utf-8")]
                self._emit(self._bpe(syms), out)
        elif sp.scheme == "metaspace":
            for word in pretok_metaspace(text, sp.prepend):
                self._emit(self._bpe(list(word)), out)
        else:
            self._emit(self._bpe(list(text)), out)

    # -- public surface --
    def encode(self, text: str) -> List[int]:
        out: List[int] = []
        pending = []
        pos = 0
        n = len(text)
        while pos < n:
            for spc in self._special_list:
                if text.startswith(spc, pos):
                    if pending:
                        self._encode_plain("".join(pending), out)
                        pending = []
                    out.append(self.spec.specials[spc])
                    pos += len(spc)
                    break
            else:
                pending.append(text[pos])
                pos += 1
        if pending:
            self._encode_plain("".join(pending), out)
        return out

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        sp = self.spec
        special_toks = set(sp.specials)
        if sp.scheme == "bytelevel":
            u2b = _unicode_to_byte()
            data = bytearray()
            for i in ids:
                tok = sp.id_to_tok.get(int(i))
                if tok is None:
                    continue
                if tok in special_toks:
                    if not skip_special:
                        data += tok.encode("utf-8")
                    continue
                for ch in tok:
                    b = u2b.get(ch)
                    if b is not None:
                        data.append(b)
                    else:
                        data += ch.encode("utf-8")
            return data.decode("utf-8", errors="replace")
        data = bytearray()
        for i in ids:
            tok = sp.id_to_tok.get(int(i))
            if tok is None:
                continue
            if tok in special_toks:
                if not skip_special:
                    data += tok.encode("utf-8")
                continue
            if (len(tok) == 6 and tok.startswith("<0x") and tok.endswith(">")):
                try:
                    data.append(int(tok[3:5], 16))
                    continue
                except ValueError:
                    pass
            data += tok.encode("utf-8")
        s = data.decode("utf-8", errors="replace")
        if sp.scheme == "metaspace":
            s = s.replace("▁", " ")
            if sp.prepend and s.startswith(" "):
                s = s[1:]
        return s

    def token_to_id(self, tok: str) -> int:
        return self.spec.vocab.get(tok, -1)

    def id_to_token(self, i: int) -> Optional[str]:
        return self.spec.id_to_tok.get(int(i))

    def vocab_size(self) -> int:
        return max(self.spec.id_to_tok) + 1 if self.spec.id_to_tok else 0


# ---------------------------------------------------------------------------
# Native (C++) backend via ctypes
# ---------------------------------------------------------------------------

class NativeTokenizer:
    """ctypes wrapper over comm/native/tokenizer.cc (same surface)."""

    def __init__(self, spec: TokenizerSpec):
        from .comm.native.build import build
        self.spec = spec
        self._lib = lib = ctypes.CDLL(str(build()))
        lib.dwt_tok_new.restype = ctypes.c_void_p
        lib.dwt_tok_new.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dwt_tok_free.argtypes = [ctypes.c_void_p]
        lib.dwt_tok_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
        lib.dwt_tok_ids_len.restype = ctypes.c_uint64
        lib.dwt_tok_ids_len.argtypes = [ctypes.c_void_p]
        lib.dwt_tok_ids.restype = ctypes.POINTER(ctypes.c_int32)
        lib.dwt_tok_ids.argtypes = [ctypes.c_void_p]
        lib.dwt_tok_decode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64,
            ctypes.c_int]
        lib.dwt_tok_str_len.restype = ctypes.c_uint64
        lib.dwt_tok_str_len.argtypes = [ctypes.c_void_p]
        lib.dwt_tok_str.restype = ctypes.c_void_p  # raw ptr; read via string_at
        lib.dwt_tok_str.argtypes = [ctypes.c_void_p]
        lib.dwt_tok_token_to_id.restype = ctypes.c_int32
        lib.dwt_tok_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_uint64]
        lib.dwt_tok_id_to_token.restype = ctypes.c_int
        lib.dwt_tok_id_to_token.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.dwt_tok_vocab_size.restype = ctypes.c_uint64
        lib.dwt_tok_vocab_size.argtypes = [ctypes.c_void_p]
        blob = spec.to_blob().encode("utf-8")
        self._h = lib.dwt_tok_new(blob, len(blob))
        if not self._h:
            raise ValueError("native tokenizer rejected blob")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.dwt_tok_free(h)
            self._h = None

    def encode(self, text: str) -> List[int]:
        raw = text.encode("utf-8")
        self._lib.dwt_tok_encode(self._h, raw, len(raw))
        n = self._lib.dwt_tok_ids_len(self._h)
        ptr = self._lib.dwt_tok_ids(self._h)
        return [ptr[i] for i in range(n)]

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        arr = (ctypes.c_int32 * len(ids))(*[int(i) for i in ids])
        self._lib.dwt_tok_decode(self._h, arr, len(ids),
                                 1 if skip_special else 0)
        n = self._lib.dwt_tok_str_len(self._h)
        ptr = self._lib.dwt_tok_str(self._h)
        if n == 0 or not ptr:
            return ""
        return ctypes.string_at(ptr, n).decode("utf-8", errors="replace")

    def token_to_id(self, tok: str) -> int:
        raw = tok.encode("utf-8")
        return self._lib.dwt_tok_token_to_id(self._h, raw, len(raw))

    def id_to_token(self, i: int) -> Optional[str]:
        ok = self._lib.dwt_tok_id_to_token(self._h, int(i))
        if not ok:
            return None
        n = self._lib.dwt_tok_str_len(self._h)
        ptr = self._lib.dwt_tok_str(self._h)
        return ctypes.string_at(ptr, n).decode("utf-8") if ptr else ""

    def vocab_size(self) -> int:
        return self._lib.dwt_tok_vocab_size(self._h)


# ---------------------------------------------------------------------------
# Facade
# ---------------------------------------------------------------------------

class Tokenizer:
    """Unified tokenizer with backend selection + bos/eos convenience.

    ``backend``: "native" (C++, default, falls back to python if the build
    fails), "python", or "hf" (HuggingFace tokenizers passthrough).
    """

    def __init__(self, impl, spec: TokenizerSpec, backend: str):
        self._impl = impl
        self.spec = spec
        self.backend = backend

    @staticmethod
    def from_sentencepiece(data: Union[bytes, str, Path]) -> "Tokenizer":
        """Load a sentencepiece ``.model`` protobuf blob (reference:
        ``FromBlobSentencePiece``, ``tokenizers_cpp.h:52-79``).  Parsing and
        segmentation are from scratch — see ``sp_tokenizer.py``."""
        from .sp_tokenizer import SPTokenizer, parse_model_proto
        if isinstance(data, (str, Path)):
            data = Path(data).read_bytes()
        model = parse_model_proto(data)
        impl = SPTokenizer(model)
        nb = len(model.pieces)
        spec = TokenizerSpec(
            vocab=dict(impl.piece_to_id), merges=[], scheme="metaspace",
            byte_fallback=model.byte_fallback,
            prepend=model.add_dummy_prefix, unk_id=model.unk_id,
            specials=dict(impl.specials),
            bos_id=model.bos_id if 0 <= model.bos_id < nb else None,
            eos_id=model.eos_id if 0 <= model.eos_id < nb else None)
        return Tokenizer(impl, spec, "sentencepiece")

    @staticmethod
    def from_file(path: Union[str, Path],
                  backend: str = "native") -> "Tokenizer":
        """Auto-detect: ``.model`` protobuf -> sentencepiece;
        otherwise HF tokenizer.json."""
        p = Path(path)
        raw = p.read_bytes()
        text_head = raw.lstrip(b"\xef\xbb\xbf \t\r\n")[:1]
        if p.suffix == ".model" or text_head != b"{":
            return Tokenizer.from_sentencepiece(raw)
        return Tokenizer.from_json(raw.decode("utf-8-sig"), backend=backend)

    @staticmethod
    def from_json(data: Union[str, dict, Path],
                  backend: str = "native") -> "Tokenizer":
        if isinstance(data, Path) or (
                isinstance(data, str) and len(data) < 4096 and
                not data.lstrip().startswith("{") and Path(data).exists()):
            data = Path(data).read_text()
        if backend == "hf":
            try:
                from tokenizers import Tokenizer as HFTok
            except ImportError as e:  # pragma: no cover
                raise RuntimeError("hf backend unavailable") from e
            raw = data if isinstance(data, str) else json.dumps(data)
            spec = TokenizerSpec.from_json(raw)
            return Tokenizer(_HFAdapter(HFTok.from_str(raw)), spec, "hf")
        spec = TokenizerSpec.from_json(data)
        if backend == "native":
            try:
                return Tokenizer(NativeTokenizer(spec), spec, "native")
            except Exception:
                backend = "python"
        if backend == "python":
            return Tokenizer(PyBPETokenizer(spec), spec, "python")
        raise ValueError(f"unknown backend {backend!r}")

    # tokenizers_cpp.h:25-48 surface
    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        ids = list(self._impl.encode(text))
        if add_bos and self.spec.bos_id is not None:
            ids = [self.spec.bos_id] + ids
        if add_eos and self.spec.eos_id is not None:
            ids = ids + [self.spec.eos_id]
        return ids

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        return self._impl.decode(ids, skip_special)

    def token_to_id(self, tok: str) -> int:
        return self._impl.token_to_id(tok)

    def id_to_token(self, i: int) -> Optional[str]:
        return self._impl.id_to_token(i)

    def vocab_size(self) -> int:
        return self._impl.vocab_size()

    @property
    def bos_id(self) -> Optional[int]:
        return self.spec.bos_id

    @property
    def eos_id(self) -> Optional[int]:
        return self.spec.eos_id

    def is_eos(self, token_id: int) -> bool:
        """EOS check by id (the reference compares the decoded string to
        "</s>" per token — ``native-lib.cpp:1485-1495``; comparing ids is
        both faster and correct for multi-eos vocabularies)."""
        return self.spec.eos_id is not None and token_id == self.spec.eos_id


class _HFAdapter:
    def __init__(self, tok):
        self._tok = tok

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids, skip_special=True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special)

    def token_to_id(self, tok: str) -> int:
        i = self._tok.token_to_id(tok)
        return -1 if i is None else i

    def id_to_token(self, i: int):
        return self._tok.id_to_token(int(i))

    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


class StreamDetokenizer:
    """Incremental detokenization for streaming surfaces — ONE owner of
    the boundary rules (the HTTP server's per-row "text" deltas and the
    chat REPL both use it; a per-token ``decode([t])`` would garble
    multi-token UTF-8 and drop sentencepiece inter-token spaces).

    ``push(tok)`` returns the newly printable delta of the full-sequence
    decode, holding back a trailing U+FFFD (a split UTF-8 sequence still
    waiting for its continuation bytes).  ``flush()`` returns whatever
    the holdback kept once the stream ends — the final token may
    legitimately decode to a replacement char.  The re-decode is linear
    per step; a windowed delta would have to re-implement every scheme's
    boundary rules (metaspace strips position-0 spaces) for a cost that
    only matters far past chat lengths."""

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids = []
        self._emitted = ""

    def push(self, tok: int) -> str:
        self._ids.append(int(tok))
        full = self._tok.decode(self._ids)
        while full.endswith("�"):
            full = full[:-1]
        piece = full[len(self._emitted):]
        self._emitted = full
        return piece

    def flush(self) -> str:
        if not self._ids:
            return ""
        full = self._tok.decode(self._ids)
        piece = full[len(self._emitted):]
        self._emitted = full
        return piece
