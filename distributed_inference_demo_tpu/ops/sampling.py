"""Token sampling: temperature / top-k / top-p / greedy, jit-friendly.

TPU-native replacement for the reference's ``StaticDecoding`` C++ sampler
(``cpp/decoding.cpp:24-66``: top-k over the last position via partial_sort,
renormalize, discrete_distribution) and its mislabeled ``GreedyDecoding``
(actually top-k=6 sampling, ``cpp/inference.cpp:107-143``).  All variants are
static-shape jnp programs so they fuse into the tail stage's jitted step —
no host round-trip per token.  The reference's temperature support exists but
is commented out (``decoding.cpp:51-52``); here it works.
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.tree_util.register_dataclass,
         data_fields=[], meta_fields=["temperature", "top_k", "top_p",
                                      "min_p", "greedy"])
@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.7   # reference default: BackgroundService.java:113
    top_k: int = 7             # reference default k=7
    top_p: float = 1.0
    min_p: float = 0.0         # keep tokens with prob >= min_p * max_prob
    greedy: bool = False

    def __post_init__(self):
        # min_p > 1 would mask even the max-probability token (the fused
        # and full-vocab paths then disagree on a meaningless output);
        # reject at construction, where the CLI renders it as a one-line
        # config error
        if not 0.0 <= self.min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {self.min_p}")


def pad_stop_ids(stop_token_ids) -> jnp.ndarray:
    """Stop-token ids as the traced ``[S]`` int32 vector the device
    decode loops consume (``-1`` = empty slot, matching the eos
    sentinel convention).  ``None``/empty becomes a single ``-1`` slot
    so every engine compiles ONE loop shape whether or not stops are
    configured."""
    ids = sorted(set(int(t) for t in (stop_token_ids or ())))
    if any(t < 0 for t in ids):
        raise ValueError(f"stop_token_ids must be >= 0, got {ids}")
    return jnp.asarray(ids or [-1], jnp.int32)


def match_stop_ids(tok: jnp.ndarray, stop_ids: jnp.ndarray) -> jnp.ndarray:
    """[b] sampled tokens vs the padded ``[S]`` stop-id vector -> [b]
    bool (True where the token IS a stop id).  Pure compare-and-any, so
    it fuses into the decode loops' step body; ``-1`` slots can never
    match (token ids are non-negative)."""
    return jnp.any((tok[:, None] == stop_ids[None, :])
                   & (stop_ids[None, :] >= 0), axis=-1)


def kth_largest(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """The k-th largest value of [..., vocab] logits (counting
    duplicates, exactly ``lax.top_k(x, k)[0][..., -1]``), as k
    argmax-and-mask passes instead of a sort.

    Decode's top-k filter needs only this one VALUE per row, but
    ``lax.top_k`` pays a full-vocab sort per step — per-row VPU work that
    grows with batch and shows up as the large-batch roofline erosion in
    the bench sweep (tools/decode_profile_probe.py measures both paths
    on-chip).  k-1 (argmax, mask-one-element) rounds plus a final max are
    O(k*V) elementwise/reduce work with no sort; each round masks only
    the FIRST occurrence of the current max (argmax's tie rule), so
    duplicate logit values count toward k exactly as in top_k.  For
    large k the unrolled rounds lose to the sort — callers gate on k."""
    x = logits
    iota = jnp.arange(x.shape[-1])
    for _ in range(k - 1):
        idx = jnp.argmax(x, axis=-1, keepdims=True)
        x = jnp.where(iota == idx, -jnp.inf, x)
    return jnp.max(x, axis=-1, keepdims=True)


def topk_vals_idx(logits: jnp.ndarray, k: int, with_mask: bool = False):
    """Exact top-k (values, indices) of [..., vocab] logits via k
    argmax-and-mask passes — no full-vocab sort.  Ties resolve to the
    first occurrence per round, i.e. the same index set as
    ``lax.top_k``.  Same O(k*V) elementwise shape as :func:`kth_largest`
    (which keeps only the k-th VALUE); this variant also carries the
    indices so the sampler can draw over k candidates instead of the
    whole vocab.  ``with_mask`` additionally returns the boolean
    membership mask over the vocab axis (accumulated for free during the
    passes — it is exactly the set of removed maxima)."""
    x = logits
    iota = jnp.arange(x.shape[-1])
    vals, idxs = [], []
    member = jnp.zeros(x.shape, bool)
    for _ in range(k):
        i = jnp.argmax(x, axis=-1)
        vals.append(jnp.take_along_axis(x, i[..., None], axis=-1)[..., 0])
        idxs.append(i)
        hit = iota == i[..., None]
        member = member | hit
        x = jnp.where(hit, -jnp.inf, x)
    out = (jnp.stack(vals, axis=-1), jnp.stack(idxs, axis=-1))
    return out + (member,) if with_mask else out


def topk_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Boolean membership mask of the exactly-k first-occurrence top-k
    over [..., vocab] — the ONE tie semantic shared by
    :func:`filtered_logits` and :func:`sample_logits`'s fused draw (a
    value-threshold mask would keep MORE than k tokens when logits tie
    at the k-th boundary, silently diverging from the fused draw's
    distribution).  Small k: iterative passes; large k: ``lax.top_k``
    (same first-occurrence tie rule) + scatter.  Crossover at 16: the
    unrolled argmax/mask/take rounds triple per-round ops vs a sort at
    k=32 (compile time and program size grow linearly with k), while the
    serving defaults (k<=8) stay comfortably on the sort-free path."""
    if k <= 16:
        return topk_vals_idx(logits, k, with_mask=True)[2]
    _, idx = jax.lax.top_k(logits, k)
    flat = idx.reshape(-1, k)
    m = jnp.zeros((flat.shape[0], logits.shape[-1]), bool)
    m = m.at[jnp.arange(flat.shape[0])[:, None], flat].set(True)
    return m.reshape(logits.shape)


def _temperature_scaled(logits: jnp.ndarray,
                        params: SamplingParams) -> jnp.ndarray:
    """f32 + temperature preamble shared by filtered_logits and the fused
    draw — one owner, so the two distribution-identical paths cannot
    drift."""
    logits = logits.astype(jnp.float32)
    if params.temperature != 1.0:
        logits = logits / jnp.maximum(params.temperature, 1e-6)
    return logits


def filtered_logits(logits: jnp.ndarray,
                    params: SamplingParams) -> jnp.ndarray:
    """Apply temperature / top-k / top-p to [..., vocab] logits.

    ``softmax(filtered_logits(l, p))`` IS the sampling distribution of
    ``sample_logits(l, rng, p)`` — speculative decoding's accept/resample
    rule (runtime/speculative.py) needs that distribution explicitly for
    both the draft and the target, so the filter lives here, next to the
    sampler it must stay consistent with.  Not meaningful for greedy
    (argmax needs no distribution).
    """
    # top-k membership is computed on the NATIVE-dtype logits, BEFORE
    # temperature scaling — the same selection rule as sample_logits'
    # fused draw, so the two paths keep identical candidate sets by
    # construction (scaling first could collapse 1-ulp-apart f32 values
    # into a boundary tie and flip the kept set).  Exactly-k
    # first-occurrence membership (topk_mask), NOT a value threshold,
    # which would keep extra boundary-tied tokens.
    keep = (topk_mask(logits, params.top_k)
            if 0 < params.top_k < logits.shape[-1] else None)
    logits = _temperature_scaled(logits, params)
    if keep is not None:
        logits = jnp.where(keep, logits, -jnp.inf)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_mask = cum - probs > params.top_p
        cutoff = jnp.where(cutoff_mask, -jnp.inf, sorted_logits)
        threshold = jnp.min(jnp.where(jnp.isfinite(cutoff), cutoff, jnp.inf),
                            axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)

    if params.min_p > 0.0:
        # min-p: keep tokens whose probability is >= min_p * max_prob on
        # the temperature-scaled distribution.  prob_i / prob_max =
        # exp(logit_i - logit_max), so the filter is a pure max + compare
        # — no sort, no cumsum (why min-p scales where top-p doesn't).
        # The max logit survives every earlier mask, so the threshold is
        # order-independent w.r.t. top-k/top-p.
        thr = (jnp.max(logits, axis=-1, keepdims=True)
               + jnp.log(params.min_p))
        logits = jnp.where(logits < thr, -jnp.inf, logits)
    return logits


def sample_logits(logits: jnp.ndarray, rng: jax.Array,
                  params: SamplingParams) -> jnp.ndarray:
    """Sample next-token ids from [batch, vocab] logits -> [batch] int32.

    Small-k top-k sampling (the serving default, k=7) draws the
    categorical over the [batch, k] candidate VALUES and gathers the
    chosen index, instead of masking the vocab and drawing over
    [batch, vocab] — saves the full-vocab gumbel+softmax passes that
    grow with batch (see tools/sampling_cost_probe.py).  The sampling
    DISTRIBUTION is identical to ``softmax(filtered_logits(...))`` (the
    contract speculative decoding's accept/resample rule depends on);
    only the RNG consumption pattern differs, so a fixed seed yields a
    different — equally distributed — sequence than the full-vocab
    draw would."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = params.top_k
    if 0 < k <= 16 and k < logits.shape[-1] and params.top_p >= 1.0:
        # select on the NATIVE dtype — the same rule filtered_logits
        # applies (its top-k mask is also computed pre-scaling), so the
        # candidate SET is identical by construction — then scale only
        # the [batch, k] values: no full-vocab f32 cast or divide pass
        vals, idx = topk_vals_idx(logits, k)
        vals = _temperature_scaled(vals, params)
        if params.min_p > 0.0:
            # vals are descending, so vals[..., :1] IS the global max
            # logit — the same threshold filtered_logits computes over
            # the full vocab (tokens min-p would mask outside the top-k
            # are already excluded), keeping the two paths
            # distribution-identical
            vals = jnp.where(
                vals < vals[..., :1] + jnp.log(params.min_p),
                -jnp.inf, vals)
        choice = jax.random.categorical(rng, vals, axis=-1)
        return jnp.take_along_axis(
            idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)
    return jax.random.categorical(
        rng, filtered_logits(logits, params), axis=-1).astype(jnp.int32)
