"""Token sampling: temperature / top-k / top-p / greedy, jit-friendly.

TPU-native replacement for the reference's ``StaticDecoding`` C++ sampler
(``cpp/decoding.cpp:24-66``: top-k over the last position via partial_sort,
renormalize, discrete_distribution) and its mislabeled ``GreedyDecoding``
(actually top-k=6 sampling, ``cpp/inference.cpp:107-143``).  All variants are
static-shape jnp programs so they fuse into the tail stage's jitted step —
no host round-trip per token.  The reference's temperature support exists but
is commented out (``decoding.cpp:51-52``); here it works.
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.tree_util.register_dataclass,
         data_fields=[], meta_fields=["temperature", "top_k", "top_p", "greedy"])
@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.7   # reference default: BackgroundService.java:113
    top_k: int = 7             # reference default k=7
    top_p: float = 1.0
    greedy: bool = False


def kth_largest(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """The k-th largest value of [..., vocab] logits (counting
    duplicates, exactly ``lax.top_k(x, k)[0][..., -1]``), as k
    argmax-and-mask passes instead of a sort.

    Decode's top-k filter needs only this one VALUE per row, but
    ``lax.top_k`` pays a full-vocab sort per step — per-row VPU work that
    grows with batch and shows up as the large-batch roofline erosion in
    the bench sweep (tools/decode_profile_probe.py measures both paths
    on-chip).  k-1 (argmax, mask-one-element) rounds plus a final max are
    O(k*V) elementwise/reduce work with no sort; each round masks only
    the FIRST occurrence of the current max (argmax's tie rule), so
    duplicate logit values count toward k exactly as in top_k.  For
    large k the unrolled rounds lose to the sort — callers gate on k."""
    x = logits
    iota = jnp.arange(x.shape[-1])
    for _ in range(k - 1):
        idx = jnp.argmax(x, axis=-1, keepdims=True)
        x = jnp.where(iota == idx, -jnp.inf, x)
    return jnp.max(x, axis=-1, keepdims=True)


def filtered_logits(logits: jnp.ndarray,
                    params: SamplingParams) -> jnp.ndarray:
    """Apply temperature / top-k / top-p to [..., vocab] logits.

    ``softmax(filtered_logits(l, p))`` IS the sampling distribution of
    ``sample_logits(l, rng, p)`` — speculative decoding's accept/resample
    rule (runtime/speculative.py) needs that distribution explicitly for
    both the draft and the target, so the filter lives here, next to the
    sampler it must stay consistent with.  Not meaningful for greedy
    (argmax needs no distribution).
    """
    logits = logits.astype(jnp.float32)
    if params.temperature != 1.0:
        logits = logits / jnp.maximum(params.temperature, 1e-6)

    if params.top_k > 0 and params.top_k < logits.shape[-1]:
        # small k (the serving default is 7): iterative exact kth value,
        # no full-vocab sort; large k: lax.top_k's sort wins
        kth = (kth_largest(logits, params.top_k)
               if params.top_k <= 32 else
               jax.lax.top_k(logits, params.top_k)[0][..., -1:])
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_mask = cum - probs > params.top_p
        cutoff = jnp.where(cutoff_mask, -jnp.inf, sorted_logits)
        threshold = jnp.min(jnp.where(jnp.isfinite(cutoff), cutoff, jnp.inf),
                            axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def sample_logits(logits: jnp.ndarray, rng: jax.Array,
                  params: SamplingParams) -> jnp.ndarray:
    """Sample next-token ids from [batch, vocab] logits -> [batch] int32."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, filtered_logits(logits, params), axis=-1).astype(jnp.int32)
