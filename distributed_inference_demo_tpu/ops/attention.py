"""KV-cached multi-head / grouped-query attention as a pure function.

The reference has *no* KV cache — every decode step re-runs the module on a
1-token sequence with no memory of the prompt (``Communication.java:322-327``,
acknowledged "repetitive generation issue" ``BackgroundService.java:195``).
Here the cache is the contract: ``attention`` always reads K/V from the
caller-provided cache buffers after inserting the current chunk, so prefill
(chunk = prompt) and decode (chunk = 1 token) are the same code path with
static shapes — one compiled program each.

Masking uses position arithmetic instead of materialized [L, L] boolean
masks where possible so XLA can fuse it into the softmax.

Supports GQA (num_kv_heads < num_heads) by logical head-group broadcast, and
ALiBi bias for the bloom family.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (bloom family), shape [num_heads]."""
    import math
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]
    if math.log2(num_heads).is_integer():
        slopes = pow2_slopes(num_heads)
    else:
        closest = 2 ** math.floor(math.log2(num_heads))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)
        slopes += extra[0::2][: num_heads - closest]
    return jnp.asarray(slopes, jnp.float32)


def attention(
    q: jnp.ndarray,             # [batch, chunk, num_heads, head_dim]
    k_cache: jnp.ndarray,       # [batch, num_kv_heads, max_seq, head_dim]
    v_cache: jnp.ndarray,       # [batch, num_kv_heads, max_seq, head_dim]
    q_positions: jnp.ndarray,   # [batch, chunk] absolute positions of q tokens
    cache_len: jnp.ndarray,     # scalar int32: valid length of the cache
    slopes: Optional[jnp.ndarray] = None,  # [num_heads] ALiBi, or None
) -> jnp.ndarray:
    """Causal attention of the current chunk against the full cache.

    Cache layout is head-major (see ``models.base.KVCache``).
    Returns [batch, chunk, num_heads, head_dim].
    """
    b, chunk, nh, hd = q.shape
    nkv = k_cache.shape[1]
    max_seq = k_cache.shape[2]
    groups = nh // nkv

    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    qf = q.astype(jnp.float32) * scale
    # [b, chunk, nkv, groups, hd]
    qf = qf.reshape(b, chunk, nkv, groups, hd)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)

    # scores: [b, nkv, groups, chunk, max_seq]
    scores = jnp.einsum("bqkgh,bksh->bkgqs", qf, kf)

    kv_pos = jnp.arange(max_seq)[None, None, :]                  # [1, 1, s]
    qpos = q_positions[:, :, None]                               # [b, q, 1]
    # causal + validity: a q token at position p attends to kv positions <= p
    # that are inside the filled cache region.
    valid = (kv_pos <= qpos) & (kv_pos < cache_len)              # [b, q, s]
    mask = valid[:, None, None, :, :]                            # [b,1,1,q,s]

    if slopes is not None:
        # ALiBi: bias = -slope * (qpos - kvpos); shape [b, nh, q, s]
        dist = (qpos - kv_pos).astype(jnp.float32)               # [b, q, s]
        bias = -slopes[None, :, None, None] * dist[:, None, :, :]
        scores = scores + bias.reshape(b, nkv, groups, chunk, max_seq)

    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bksh->bqkgh", probs, vf)
    return out.reshape(b, chunk, nh, hd).astype(q.dtype)


def prepare_kv_chunk(
    k_new: jnp.ndarray,    # [batch, chunk, nkv, hd] (projection layout)
    v_new: jnp.ndarray,
    k_dtype,
    v_dtype,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Validate + cast a projection-layout K/V chunk for a cache write —
    the ONE entry every cache-write path goes through (the dense
    ``update_kv_cache`` below and the paged block write in
    ``ops.paged_attention.write_paged_kv``), so the write contract is
    stated and checked in one place:

    **Stale-slot invariant.**  A cache write may land garbage at any
    position >= the row's valid length (padded prefill tails, freed
    batching slots, speculative overshoot) PROVIDED every position is
    rewritten before any query attends it — causal masking
    (``kv_pos <= q_position``) plus contiguous advance makes that safe.
    Writers must never touch a position < the row's valid length: stored
    prefix K/V is immutable (the KV-cache manager's copy-on-write
    sharing, dense AND paged, relies on it).
    """
    assert k_new.ndim == 4 and k_new.shape == v_new.shape, (
        "KV chunk must be projection-layout [batch, chunk, nkv, hd]; got "
        f"{k_new.shape} / {v_new.shape}")
    return k_new.astype(k_dtype), v_new.astype(v_dtype)


def update_kv_cache(
    k_cache: jnp.ndarray,  # [batch, nkv, max_seq, hd] (head-major)
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,    # [batch, chunk, nkv, hd] (projection layout)
    v_new: jnp.ndarray,
    start: jnp.ndarray,    # scalar int32 insert offset
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Insert the chunk's K/V at position ``start`` of every head's plane.

    The chunk arrives in projection layout [b, chunk, nkv, hd] (as produced
    by the QKV matmuls) and is transposed to the cache's head-major layout
    here — a [b, chunk, nkv, hd]-sized shuffle, O(chunk), not O(max_seq).
    Write contract (stale-slot invariant): see :func:`prepare_kv_chunk`.
    """
    zeros = jnp.zeros((), jnp.int32)
    k_new, v_new = prepare_kv_chunk(k_new, v_new, k_cache.dtype,
                                    v_cache.dtype)
    k_new = k_new.transpose(0, 2, 1, 3)
    v_new = v_new.transpose(0, 2, 1, 3)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_new, (zeros, zeros, start, zeros))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_new, (zeros, zeros, start, zeros))
    return k_cache, v_cache
