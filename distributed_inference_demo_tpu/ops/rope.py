"""Rotary position embeddings (llama family).

Computed on the fly from integer positions — no host-side tables to ship —
so the same jitted stage function serves prefill (``positions = [0..L)``)
and decode (``positions = [cache_len]``) with static shapes.
"""

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Per-channel inverse frequencies, shape [head_dim // 2]."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0
               ) -> jnp.ndarray:
    """Rotate q or k. x: [batch, seq, heads, head_dim]; positions: [batch, seq]."""
    dtype = x.dtype
    head_dim = x.shape[-1]
    freqs = rope_frequencies(head_dim, theta)  # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, hd/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [b, s, 1, hd/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(dtype)
