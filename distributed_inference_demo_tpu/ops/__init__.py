from .norms import rms_norm, layer_norm
from .rope import apply_rope, rope_frequencies
from .attention import attention, alibi_slopes
from .ring_attention import ring_self_attention, sp_decode_attention
from .sampling import sample_logits, SamplingParams

__all__ = [
    "rms_norm", "layer_norm", "apply_rope", "rope_frequencies",
    "attention", "alibi_slopes", "ring_self_attention",
    "sp_decode_attention", "sample_logits", "SamplingParams",
]
