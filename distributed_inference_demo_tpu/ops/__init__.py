from .norms import rms_norm, layer_norm
from .rope import apply_rope, rope_frequencies
from .attention import attention, alibi_slopes
from .sampling import sample_logits, SamplingParams

__all__ = [
    "rms_norm", "layer_norm", "apply_rope", "rope_frequencies",
    "attention", "alibi_slopes", "sample_logits", "SamplingParams",
]
