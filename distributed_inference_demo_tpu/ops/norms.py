"""Normalization layers as pure functions.

RMSNorm (llama family) and LayerNorm (bloom family, with bias — the bloom
blocks in the reference's exported ONNX modules use torch LayerNorm).
Accumulation in float32 regardless of activation dtype: on TPU the VPU does
fp32 math anyway and this avoids bf16 variance underflow.
"""

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
