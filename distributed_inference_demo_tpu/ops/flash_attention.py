"""Pallas TPU flash-attention kernel for the KV-cached decoder hot loop.

The reference's hot compute is an opaque ONNX ``Session.Run`` per module per
token (``cpp/inference.cpp:207-216``); here the hot op is written directly
for the TPU memory hierarchy: Q/K/V blocks stream HBM→VMEM, scores and the
online-softmax accumulator live in VMEM, and every matmul is shaped for the
MXU ([rows, hd] x [hd, block_k]).  One kernel covers both phases:

- **prefill**: q = the prompt chunk, cache holds the prompt's K/V;
- **decode**: q = one token (rows = GQA group size), same code path.

Layout trick for GQA: queries are regrouped to ``[b, nkv, chunk*g, hd]`` so
each grid program attends one kv-head's whole query group — K/V blocks are
loaded once per kv head (not once per q head), an (nh/nkv)× HBM-traffic
saving over a per-q-head loop, and the q-rows dimension is ``chunk*g`` which
keeps the MXU tiles tall even at decode (rows = g).

Causality is positional: q row ``r`` is the query at absolute position
``q_start + r//g``; kv column ``s`` is valid iff ``s < kv_len`` and
``s <= pos(r)``.  KV blocks entirely above the causal frontier are skipped
by bounding the inner loop, not masked — decode with a short cache does
O(kv_len) work regardless of ``max_seq``.

Numerics match ``ops.attention.attention`` (f32 softmax, same masking), so
the two are interchangeable; `attn_impl` hooks (models/decoder.py) select
the kernel on TPU and the jnp path elsewhere.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import update_kv_cache

_NEG = -1e30


def _live_kv_blocks(q_start, kv_len, row_blk_idx, rows_blk, groups, block_k):
    """Number of kv blocks below this row block's causal frontier (>= 1)."""
    max_pos = q_start + (row_blk_idx * rows_blk + rows_blk - 1) // groups
    upper = jnp.minimum(kv_len, max_pos + 1)
    return (upper + block_k - 1) // block_k


def _kernel(scalar_ref, q_ref, k_ref, v_ref, slopes_ref, o_ref,
            o_acc, m_acc, l_acc, *, block_k: int, groups: int,
            use_alibi: bool):
    """Grid (b, nkv, row_blocks, kv_blocks), kv innermost: one step streams
    one [block_k, hd] K/V block HBM→VMEM and folds it into the online-
    softmax accumulators held in VMEM scratch (which persists across the
    sequential grid on TPU).  KV blocks beyond a row block's causal frontier
    are neither fetched (index map clamps to the last live block — Mosaic
    skips the DMA when the block index repeats) nor computed (pl.when), so
    short-cache decode costs O(kv_len) HBM traffic, not O(max_seq).

    scalar_ref (SMEM, int32[2]): [q_start, kv_len].
    q_ref:      [1, 1, rows_blk, hd]   (rows = chunk * groups)
    k_ref/v_ref:[1, 1, block_k, hd]    (one streamed block of the kv plane)
    slopes_ref: [1, 1, groups] f32     (ALiBi slopes of this head group)
    o_ref:      [1, 1, rows_blk, hd]
    scratch: o_acc [rows_blk, hd] f32; m_acc/l_acc [rows_blk, 128] f32
    (lane-broadcast storage).
    """
    q_start = scalar_ref[0]
    kv_len = scalar_ref[1]
    rows_blk, hd = q_ref.shape[2], q_ref.shape[3]
    row_blk_idx = pl.program_id(2)
    ki = pl.program_id(3)
    num_ki = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    n_live = _live_kv_blocks(q_start, kv_len, row_blk_idx, rows_blk, groups,
                             block_k)

    @pl.when(ki < n_live)
    def _step():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        q = q * (1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32)))
        row = (row_blk_idx * rows_blk
               + jax.lax.broadcasted_iota(jnp.int32, (rows_blk, 1), 0))
        q_pos = q_start + row // groups                   # [rows_blk, 1]

        k_blk = k_ref[0, 0, :, :]
        v_blk = v_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)   # [rows, bk]
        kv_pos = (ki * block_k
                  + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        valid = (kv_pos <= q_pos) & (kv_pos < kv_len)     # [rows, bk]
        if use_alibi:
            slope = slopes_ref[0, 0, :]                   # [groups]
            slope_row = jnp.tile(slope, rows_blk // groups)[:, None]
            s = s - slope_row * (q_pos - kv_pos).astype(jnp.float32)
        s = jnp.where(valid, s, _NEG)

        m = jnp.max(m_acc[:], axis=-1, keepdims=True)     # [rows, 1]
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_acc[:] = o_acc[:] * alpha + jnp.dot(
            p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(ki == num_ki - 1)
    def _finalize():
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        o_ref[0, 0, :, :] = (o_acc[:]
                             / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pick_block(total: int, target: int) -> int:
    """Largest divisor of ``total`` that is <= target AND a multiple of 8.

    block_k is the sublane (second-to-minor) dimension of the streamed
    [block_k, hd] K/V tiles, so it must respect the TPU sublane granule of
    8 — an arbitrary divisor (e.g. 125 for total=1000) would hand Mosaic a
    misaligned tile.  Raises for totals not divisible by 8: pad max_seq to
    a multiple of 8 (the engine's KV capacity is caller-chosen) rather than
    silently running a misaligned kernel.
    """
    if total % 8:
        raise ValueError(
            f"flash attention requires max_seq divisible by 8, got {total}; "
            "pad the KV-cache capacity (engine max_seq) to a multiple of 8 "
            "or use the jnp attention backend")
    b = min(total, max(8, target - target % 8))
    while total % b or b % 8:
        b -= 1
    return b


@functools.partial(jax.jit, static_argnames=("block_k", "block_rows",
                                             "use_alibi", "interpret"))
def _flash_call(q_g, k_cache, v_cache, scalars, slopes, *, block_k,
                block_rows, use_alibi, interpret):
    b, nkv, rows, hd = q_g.shape
    max_seq = k_cache.shape[2]
    groups = slopes.shape[2]
    grid = (b, nkv, rows // block_rows, max_seq // block_k)

    def kv_map(bb, h, r, ki, s):
        # clamp to the causal frontier: beyond-frontier grid steps re-fetch
        # the same block (no DMA) and skip compute (pl.when in the kernel).
        live = _live_kv_blocks(s[0], s[1], r, block_rows, groups, block_k)
        return (bb, h, jnp.minimum(ki, live - 1), 0)

    return pl.pallas_call(
        functools.partial(_kernel, block_k=block_k, groups=groups,
                          use_alibi=use_alibi),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_rows, hd),
                             lambda bb, h, r, ki, s: (bb, h, r, 0)),
                pl.BlockSpec((1, 1, block_k, hd), kv_map),
                pl.BlockSpec((1, 1, block_k, hd), kv_map),
                pl.BlockSpec((1, 1, groups),
                             lambda bb, h, r, ki, s: (h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_rows, hd),
                                   lambda bb, h, r, ki, s: (bb, h, r, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_rows, hd), jnp.float32),
                pltpu.VMEM((block_rows, 128), jnp.float32),
                pltpu.VMEM((block_rows, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, nkv, rows, hd), q_g.dtype),
        interpret=interpret,
    )(scalars, q_g, k_cache, v_cache, slopes)


def flash_attention(
    q: jnp.ndarray,            # [b, chunk, nh, hd]
    k_cache: jnp.ndarray,      # [b, nkv, max_seq, hd] (head-major)
    v_cache: jnp.ndarray,
    q_start: jnp.ndarray,      # scalar int32: position of q[:, 0]
    kv_len: jnp.ndarray,       # scalar int32: valid cache length
    slopes: Optional[jnp.ndarray] = None,   # [nh] ALiBi slopes or None
    *,
    block_k: int = 512,
    block_rows_target: int = 2048,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in for ``ops.attention.attention`` with contiguous q positions
    (``q_positions = q_start + arange(chunk)`` — always true in the engine).

    Returns [b, chunk, nh, hd] in q.dtype.

    Default block sizes are tuned on TPU v5e (swept 128-512 x 256-2048 at
    chunk 2048): large kv blocks + tall row blocks keep the MXU fed and
    amortize the online-softmax bookkeeping — block_k=512/rows=2048 beat
    the old 128/512 defaults by ~1.3x and the jnp path at every
    prefill-sized chunk.
    """
    b, chunk, nh, hd = q.shape
    nkv, max_seq = k_cache.shape[1], k_cache.shape[2]
    g = nh // nkv

    # [b, chunk, nh, hd] -> [b, nkv, chunk*g, hd]: row r = (chunk r//g,
    # group member r%g); kv-head-major so each program loads K/V once.
    q_g = q.reshape(b, chunk, nkv, g, hd).transpose(0, 2, 1, 3, 4)
    q_g = q_g.reshape(b, nkv, chunk * g, hd)

    if slopes is None:
        slopes_g = jnp.zeros((nkv, 1, g), jnp.float32)  # zero slope: no bias
    else:
        slopes_g = slopes.astype(jnp.float32).reshape(nkv, 1, g)

    bk = _pick_block(max_seq, block_k)
    # Row blocks must hold whole query groups (so q_pos stays block-affine)
    # and satisfy the TPU sublane constraint: divisible by 8, or the whole
    # rows dimension.
    d = min(chunk, max(1, block_rows_target // g))
    while d > 1 and (chunk % d or (d * g) % 8):
        d -= 1
    br = d * g if (d * g) % 8 == 0 and chunk % d == 0 else chunk * g
    scalars = jnp.stack([jnp.asarray(q_start, jnp.int32),
                         jnp.asarray(kv_len, jnp.int32)])

    out = _flash_call(q_g, k_cache, v_cache, scalars, slopes_g,
                      block_k=bk, block_rows=br,
                      use_alibi=slopes is not None, interpret=interpret)
    out = out.reshape(b, nkv, chunk, g, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(b, chunk, nh, hd)


def make_flash_attn_impl(interpret: bool = False, min_chunk: int = 16):
    """Build an ``attn_impl`` hook (models/decoder.py): Pallas flash kernel
    for prefill-sized chunks, XLA-fused jnp attention for decode.

    Measured on TPU v5e (tinyllama shapes): flash prefill is ~2.3x the jnp
    path (no materialized [.., chunk, max_seq] score tensor), but decode
    (chunk=1, q rows = GQA group) is bandwidth-bound and XLA's fusion wins —
    so chunks below ``min_chunk`` take the jnp path.  ``chunk`` is static
    under jit, so the dispatch costs nothing.

    Assumes contiguous query positions (engine guarantee).
    """
    from .attention import attention

    def impl(q, k, v, k_cache, v_cache, positions, cache_start, slopes):
        k_cache, v_cache = update_kv_cache(k_cache, v_cache, k, v,
                                           cache_start)
        kv_len = cache_start + q.shape[1]
        if q.shape[1] >= min_chunk:
            out = flash_attention(q, k_cache, v_cache, cache_start, kv_len,
                                  slopes, interpret=interpret)
        else:
            out = attention(q, k_cache, v_cache, positions, kv_len, slopes)
        return out, k_cache, v_cache
    return impl
