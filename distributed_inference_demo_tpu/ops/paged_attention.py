"""Paged decode attention: per-sequence block tables over a device pool.

The PagedAttention memory model (vLLM, SOSP'23): instead of one dense
``[batch, nkv, max_seq, hd]`` cache row per sequence, K/V live in a
shared pool of fixed-size pages ``[num_pages, nkv, block_tokens, hd]``
(one pool per layer — the engines stack a leading layer axis) and each
sequence addresses its pages through a block table ``[batch, W]`` of
page ids.  Two consequences the dense layout cannot give:

- HBM is reserved per page actually allocated, not ``batch x max_seq``
  worst-case rows;
- two sequences sharing a prefix share the SAME pages (the radix tree in
  ``runtime/kvcache`` hands out the ids) — a prefix hit is a block-table
  entry, not a copy of any kind.

Sentinel convention: a table entry ``>= num_pages`` means "no page
here".  Writes through a sentinel DROP (jax scatter ``mode="drop"`` —
this is how freed batching slots and fused-block overshoot are routed
to nowhere); reads CLAMP (the gathered garbage is causally masked, and
pool pages always hold finite values, so masked garbage contributes
exact zeros).

Two interchangeable compute paths (same numerics as ``ops.attention``):

- :func:`paged_gather_attention` — pure XLA ``jnp.take`` gather of the
  table's pages into a linear view + the reference ``attention``.  Runs
  everywhere (``JAX_PLATFORMS=cpu`` tier-1 and interpret-mode tests
  exercise the same code path the TPU fallback uses).
- :func:`paged_flash_attention` — Pallas TPU decode kernel: grid
  ``(batch, nkv, W)``, the block table rides scalar prefetch so each
  grid step DMAs exactly one [block_tokens, hd] page HBM->VMEM (pages
  beyond a row's live count are index-clamped: Mosaic skips the repeat
  DMA, ``pl.when`` skips the compute), online-softmax accumulators in
  VMEM scratch — decode reads O(kv_len) HBM, never O(max_seq).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import attention, prepare_kv_chunk
from .quant import QuantizedKVPages, quantize_kv_like

_NEG = -1e30


def write_paged_kv(
    k_pages: jnp.ndarray,   # [num_pages, nkv, block_tokens, hd]
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,     # [batch, chunk, nkv, hd] (projection layout)
    v_new: jnp.ndarray,
    tables: jnp.ndarray,    # [batch, W] int32 page ids (>= num_pages = none)
    positions: jnp.ndarray  # [batch, chunk] absolute token positions
):
    """Scatter the chunk's K/V into its pages: token at position ``p`` of
    row ``b`` lands in page ``tables[b, p // bt]`` at offset ``p % bt``.

    Sentinel table entries route the write out of bounds, where scatter
    ``mode="drop"`` discards it — the paged twin of the dense layout's
    "stale writes land on the row's own dead columns".  A position PAST
    the table (``p // bt >= W`` — a padded prefill tail running off the
    end of a full-width table) is routed to the sentinel too: the naive
    ``take_along_axis`` would CLAMP the page index to the last table
    entry, and for a request whose table is fully populated that is a
    real page — the write would corrupt a live position ``p % bt`` deep
    into it.  Write contract (stale-slot invariant, shared with the
    dense path): :func:`ops.attention.prepare_kv_chunk`.
    """
    bt = k_pages.shape[2]
    if isinstance(k_pages, QuantizedKVPages):
        # quantize ONCE at write time, per token over head_dim: the
        # scale/zero sidecar leaves take the exact same scatter index
        # (their trailing axis is a broadcast singleton).
        k_new, v_new = prepare_kv_chunk(k_new, v_new, jnp.float32,
                                        jnp.float32)
    else:
        k_new, v_new = prepare_kv_chunk(k_new, v_new, k_pages.dtype,
                                        v_pages.dtype)
    qk = quantize_kv_like(k_pages, k_new)
    qv = quantize_kv_like(v_pages, v_new)
    num_pages, W = k_pages.shape[0], tables.shape[1]
    pidx = positions // bt                                       # [b, s]
    page = jnp.take_along_axis(tables, jnp.minimum(pidx, W - 1), axis=1)
    page = jnp.where(pidx < W, page, num_pages)  # past-table -> drop
    off = positions % bt                                         # [b, s]
    # advanced indices at dims (0, 2) around the head slice: the indexed
    # result layout [b, s, nkv, hd] is exactly the projection layout the
    # chunk arrives in — no transpose.
    scatter = lambda p, c: p.at[page, :, off].set(c, mode="drop")
    k_pages = jax.tree.map(scatter, k_pages, qk)
    v_pages = jax.tree.map(scatter, v_pages, qv)
    return k_pages, v_pages


def paged_gather_attention(
    q: jnp.ndarray,          # [batch, chunk, nh, hd]
    k_pages: jnp.ndarray,    # [num_pages, nkv, block_tokens, hd]
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,     # [batch, W] int32
    q_positions: jnp.ndarray,  # [batch, chunk]
    slopes: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Pure-XLA fallback: gather each row's pages into a linear
    ``[batch, nkv, W*bt, hd]`` view and run the reference ``attention``.

    Materializes the gathered view (a full cache copy per layer) — fine
    for CPU tests and small batches, which is exactly where it runs; the
    TPU path is the Pallas kernel.  Quantized pools gather the NARROW
    leaves through the table first, then dequantize the gathered view to
    f32 — the same per-element ``convert * scale (+ zero)`` the kernel
    runs in-register, so the two paths stay bit-exact."""
    num_pages, nkv, bt, hd = k_pages.shape
    safe = jnp.clip(tables, 0, num_pages - 1)
    gather = lambda p: jnp.take(p, safe, axis=0)  # [b, W, nkv, bt, ·]
    b, W = safe.shape
    if isinstance(k_pages, QuantizedKVPages):
        k_lin = jax.tree.map(gather, k_pages).dequantize(jnp.float32)
        v_lin = jax.tree.map(gather, v_pages).dequantize(jnp.float32)
    else:
        k_lin = gather(k_pages)
        v_lin = gather(v_pages)
    k_lin = k_lin.transpose(0, 2, 1, 3, 4).reshape(b, nkv, W * bt, hd)
    v_lin = v_lin.transpose(0, 2, 1, 3, 4).reshape(b, nkv, W * bt, hd)
    return attention(q, k_lin, v_lin, q_positions,
                     jnp.asarray(W * bt, jnp.int32), slopes)


# ---------------------------------------------------------------------------
# Pallas TPU decode kernel


def _paged_kernel(tab_ref, len_ref, q_ref, *refs, block_tokens: int,
                  groups: int, use_alibi: bool, quantized: bool):
    """Grid (b, nkv, W), page index innermost: each step folds one
    streamed [block_tokens, hd] page into the online-softmax accumulators
    (VMEM scratch persists across the sequential grid).  Rows are the
    q-head group members of one kv head (decode chunk = 1), all at the
    same query position ``kv_len - 1``.

    tab_ref (SMEM int32 [b, W]): the block tables; len_ref (SMEM int32
    [b]): per-row valid lengths AFTER the current token's insert.  With
    ``quantized`` the page refs are int8 and each is followed by its
    [bt, 1] f32 scale block (same page index map): the dequant happens
    in-register right after the narrow DMA — HBM traffic stays 1 byte +
    4/bt per element."""
    if quantized:
        (k_ref, ks_ref, v_ref, vs_ref, slopes_ref,
         o_ref, o_acc, m_acc, l_acc) = refs
    else:
        k_ref, v_ref, slopes_ref, o_ref, o_acc, m_acc, l_acc = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_j = pl.num_programs(2)
    rows, hd = q_ref.shape[2], q_ref.shape[3]
    kv_len = len_ref[b]
    bt = block_tokens

    @pl.when(j == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    n_live = (kv_len + bt - 1) // bt

    @pl.when(j < n_live)
    def _step():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        q = q * (1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32)))
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        if quantized:
            k_blk = k_blk * ks_ref[0, 0, :, :]      # [bt, hd] * [bt, 1]
            v_blk = v_blk * vs_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)     # [rows, bt]
        kv_pos = (j * bt
                  + jax.lax.broadcasted_iota(jnp.int32, (1, bt), 1))
        # every q row is the same decode position kv_len - 1, so the
        # causal bound and the validity bound coincide
        valid = kv_pos < kv_len                             # [1, bt]
        valid = jnp.broadcast_to(valid, (rows, bt))
        if use_alibi:
            slope = slopes_ref[0, 0, :][:, None]            # [rows, 1]
            dist = ((kv_len - 1) - kv_pos).astype(jnp.float32)
            s = s - slope * dist
        s = jnp.where(valid, s, _NEG)

        m = jnp.max(m_acc[:], axis=-1, keepdims=True)       # [rows, 1]
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_acc[:] = o_acc[:] * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(j == num_j - 1)
    def _finalize():
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        o_ref[0, 0, :, :] = (o_acc[:]
                             / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_tokens", "use_alibi",
                                    "interpret"))
def _paged_call(q_g, k_pages, v_pages, tables, kv_lens, slopes, *,
                block_tokens, use_alibi, interpret):
    b, nkv, rows, hd = q_g.shape
    quantized = isinstance(k_pages, QuantizedKVPages)
    num_pages = k_pages.shape[0]
    W = tables.shape[1]
    bt = block_tokens

    def page_map(bb, h, j, tab, lens):
        # clamp to the live frontier: beyond it the index repeats (no
        # DMA, pl.when skips compute); sentinel entries clamp in-range
        live = (lens[bb] + bt - 1) // bt
        jj = jnp.minimum(j, jnp.maximum(live - 1, 0))
        page = jnp.minimum(tab[bb, jj], num_pages - 1)
        return (page, h, 0, 0)

    q_spec = pl.BlockSpec((1, 1, rows, hd),
                          lambda bb, h, j, tab, lens: (bb, h, 0, 0))
    slopes_spec = pl.BlockSpec((1, 1, rows),
                               lambda bb, h, j, tab, lens: (h, 0, 0))
    page_spec = pl.BlockSpec((1, 1, bt, hd), page_map)
    if quantized:
        # the scale sidecar rides the SAME page index map — a [bt, 1]
        # f32 block DMA'd alongside its narrow page
        scale_spec = pl.BlockSpec((1, 1, bt, 1), page_map)
        in_specs = [q_spec, page_spec, scale_spec, page_spec,
                    scale_spec, slopes_spec]
        operands = (tables, kv_lens, q_g, k_pages.data, k_pages.scale,
                    v_pages.data, v_pages.scale, slopes)
    else:
        in_specs = [q_spec, page_spec, page_spec, slopes_spec]
        operands = (tables, kv_lens, q_g, k_pages, v_pages, slopes)

    return pl.pallas_call(
        functools.partial(_paged_kernel, block_tokens=bt, groups=rows,
                          use_alibi=use_alibi, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nkv, W),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, rows, hd),
                                   lambda bb, h, j, tab, lens:
                                   (bb, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, hd), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, nkv, rows, hd), q_g.dtype),
        interpret=interpret,
    )(*operands)


def paged_flash_attention(
    q: jnp.ndarray,          # [batch, 1, nh, hd] — decode chunk only
    k_pages: jnp.ndarray,    # [num_pages, nkv, block_tokens, hd]
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,     # [batch, W] int32
    kv_lens: jnp.ndarray,    # [batch] int32 valid length incl. this token
    slopes: Optional[jnp.ndarray] = None,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas paged decode attention; numerics match
    :func:`paged_gather_attention` (f32 online softmax, same masking).

    Requires ``block_tokens % 8 == 0`` (the page's token axis is the
    sublane dimension of the streamed tiles) and a 1-token chunk; the
    caller falls back to the gather path otherwise."""
    b, chunk, nh, hd = q.shape
    if chunk != 1:
        raise ValueError(f"paged_flash_attention is decode-only (chunk=1), "
                         f"got chunk={chunk}")
    if isinstance(k_pages, QuantizedKVPages) and k_pages.bits != 8:
        # int4's nibble lane-interleave is Mosaic-hostile (an unpack in
        # the lane dimension per element); int4 is the CAPACITY config
        # and always takes the gather path — a deliberate gate, see
        # docs/DESIGN.md §17.
        raise ValueError("the Pallas kernel streams bf16 or int8 pages; "
                         "int4 KV takes the XLA gather path")
    num_pages, nkv, bt, _ = k_pages.shape
    if bt % 8:
        raise ValueError(f"block_tokens must be a multiple of 8 for the "
                         f"Pallas kernel, got {bt}")
    g = nh // nkv
    rows = max(8, -(-g // 8) * 8)    # pad group rows to the sublane granule

    # [b, 1, nh, hd] -> [b, nkv, g, hd] (+ zero-pad rows): row r of head h
    # is q head h*g + r
    q_g = q.reshape(b, nkv, g, hd)
    if rows > g:
        q_g = jnp.pad(q_g, ((0, 0), (0, 0), (0, rows - g), (0, 0)))
    if slopes is None:
        slopes_g = jnp.zeros((nkv, 1, rows), jnp.float32)
    else:
        slopes_g = slopes.astype(jnp.float32).reshape(nkv, 1, g)
        slopes_g = jnp.pad(slopes_g, ((0, 0), (0, 0), (0, rows - g)))

    out = _paged_call(q_g, k_pages, v_pages,
                      tables.astype(jnp.int32),
                      kv_lens.astype(jnp.int32), slopes_g,
                      block_tokens=bt, use_alibi=slopes is not None,
                      interpret=interpret)
    return out[:, :, :g, :].reshape(b, 1, nh, hd)


# ---------------------------------------------------------------------------
# Pallas TPU prefill kernel (docs/DESIGN.md §19)


def _paged_prefill_kernel(tab_ref, start_ref, q_ref, *refs,
                          block_tokens: int, chunk: int, groups: int,
                          use_alibi: bool, quantized: bool):
    """Grid (b, nkv, W), page index innermost — the prefill twin of
    :func:`_paged_kernel`.  Rows are (chunk position, q-head group
    member) pairs: row ``r`` is query position ``start + r // g`` of
    q head ``h*g + r % g``, so the whole C-token segment of one kv
    head folds each streamed page into the online-softmax accumulators
    in ONE grid pass.  The causal bound is per ROW (``kv_pos <=
    start + r // g``), not the single shared decode position — in-chunk
    keys were already written to the pages by ``write_paged_kv``
    (write-before-attend inside the layer), so causality alone makes a
    query see exactly its prefix plus its own earlier in-chunk keys.

    tab_ref (SMEM int32 [b, W]): block tables; start_ref (SMEM int32
    [b]): per-row segment start offsets (position of chunk column 0)."""
    if quantized:
        (k_ref, ks_ref, v_ref, vs_ref, slopes_ref,
         o_ref, o_acc, m_acc, l_acc) = refs
    else:
        k_ref, v_ref, slopes_ref, o_ref, o_acc, m_acc, l_acc = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_j = pl.num_programs(2)
    rows, hd = q_ref.shape[2], q_ref.shape[3]
    start = start_ref[b]
    bt = block_tokens
    g = groups

    @pl.when(j == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    kv_len = start + chunk
    n_live = (kv_len + bt - 1) // bt

    @pl.when(j < n_live)
    def _step():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        q = q * (1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32)))
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        if quantized:
            k_blk = k_blk * ks_ref[0, 0, :, :]      # [bt, hd] * [bt, 1]
            v_blk = v_blk * vs_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)     # [rows, bt]
        kv_pos = (j * bt
                  + jax.lax.broadcasted_iota(jnp.int32, (1, bt), 1))
        # per-row query position: padding rows (r >= chunk*g) see a
        # position past the segment — their garbage output is sliced
        # away by the caller
        q_pos = (start
                 + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // g)
        valid = kv_pos <= q_pos                             # [rows, bt]
        if use_alibi:
            slope = slopes_ref[0, 0, :][:, None]            # [rows, 1]
            dist = (q_pos - kv_pos).astype(jnp.float32)
            s = s - slope * dist
        s = jnp.where(valid, s, _NEG)

        m = jnp.max(m_acc[:], axis=-1, keepdims=True)       # [rows, 1]
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_acc[:] = o_acc[:] * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        m_acc[:] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(j == num_j - 1)
    def _finalize():
        l = jnp.max(l_acc[:], axis=-1, keepdims=True)
        o_ref[0, 0, :, :] = (o_acc[:]
                             / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_tokens", "chunk", "groups",
                                    "use_alibi", "interpret"))
def _paged_prefill_call(q_g, k_pages, v_pages, tables, starts, slopes, *,
                        block_tokens, chunk, groups, use_alibi,
                        interpret):
    b, nkv, rows, hd = q_g.shape
    quantized = isinstance(k_pages, QuantizedKVPages)
    num_pages = k_pages.shape[0]
    W = tables.shape[1]
    bt = block_tokens

    def page_map(bb, h, j, tab, starts_):
        # clamp to the segment's live frontier (start + chunk tokens):
        # beyond it the index repeats (no DMA, pl.when skips compute);
        # sentinel entries clamp in-range
        live = (starts_[bb] + chunk + bt - 1) // bt
        jj = jnp.minimum(j, jnp.maximum(live - 1, 0))
        page = jnp.minimum(tab[bb, jj], num_pages - 1)
        return (page, h, 0, 0)

    q_spec = pl.BlockSpec((1, 1, rows, hd),
                          lambda bb, h, j, tab, starts_: (bb, h, 0, 0))
    slopes_spec = pl.BlockSpec((1, 1, rows),
                               lambda bb, h, j, tab, starts_: (h, 0, 0))
    page_spec = pl.BlockSpec((1, 1, bt, hd), page_map)
    if quantized:
        scale_spec = pl.BlockSpec((1, 1, bt, 1), page_map)
        in_specs = [q_spec, page_spec, scale_spec, page_spec,
                    scale_spec, slopes_spec]
        operands = (tables, starts, q_g, k_pages.data, k_pages.scale,
                    v_pages.data, v_pages.scale, slopes)
    else:
        in_specs = [q_spec, page_spec, page_spec, slopes_spec]
        operands = (tables, starts, q_g, k_pages, v_pages, slopes)

    return pl.pallas_call(
        functools.partial(_paged_prefill_kernel, block_tokens=bt,
                          chunk=chunk, groups=groups,
                          use_alibi=use_alibi, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nkv, W),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, rows, hd),
                                   lambda bb, h, j, tab, starts_:
                                   (bb, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, hd), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, nkv, rows, hd), q_g.dtype),
        interpret=interpret,
    )(*operands)


# one kernel invocation's query rows = chunk * group; past this the
# f32 VMEM accumulators (rows x hd + 2 x rows x 128) crowd the page
# stream — larger chunks take the gather path
PREFILL_KERNEL_MAX_ROWS = 512


def paged_prefill_attention(
    q: jnp.ndarray,          # [batch, chunk, nh, hd], chunk >= 1
    k_pages: jnp.ndarray,    # [num_pages, nkv, block_tokens, hd]
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,     # [batch, W] int32
    q_positions: jnp.ndarray,  # [batch, chunk]; CONTIGUOUS per row
    slopes: Optional[jnp.ndarray] = None,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas paged PREFILL attention: each row's chunk of queries
    attends causally over its own prior pages plus the in-chunk keys
    (already present — ``write_paged_kv`` runs before attention inside
    the layer).  Numerics match :func:`paged_gather_attention` (f32
    online softmax, same masking).

    Contract: ``q_positions[b] == q_positions[b, 0] + arange(chunk)``
    (every caller of the paged seam issues contiguous chunks); only the
    per-row start rides scalar prefetch, the rest is recovered from the
    static chunk length.  Same page-dtype gates as the decode kernel:
    bf16 or int8 pages, ``block_tokens % 8 == 0``; int4 takes the
    gather path."""
    b, chunk, nh, hd = q.shape
    if isinstance(k_pages, QuantizedKVPages) and k_pages.bits != 8:
        raise ValueError("the Pallas kernel streams bf16 or int8 pages; "
                         "int4 KV takes the XLA gather path")
    num_pages, nkv, bt, _ = k_pages.shape
    if bt % 8:
        raise ValueError(f"block_tokens must be a multiple of 8 for the "
                         f"Pallas kernel, got {bt}")
    g = nh // nkv
    rows_real = chunk * g
    rows = max(8, -(-rows_real // 8) * 8)
    if rows > PREFILL_KERNEL_MAX_ROWS:
        raise ValueError(
            f"prefill kernel rows {rows} (chunk {chunk} x group {g}) "
            f"exceed {PREFILL_KERNEL_MAX_ROWS}; use the gather path")

    # [b, chunk, nh, hd] -> [b, nkv, chunk*g, hd]: row c*g + r of kv
    # head h is chunk position c of q head h*g + r
    q_g = q.reshape(b, chunk, nkv, g, hd).transpose(0, 2, 1, 3, 4)
    q_g = q_g.reshape(b, nkv, rows_real, hd)
    if rows > rows_real:
        q_g = jnp.pad(q_g, ((0, 0), (0, 0), (0, rows - rows_real),
                            (0, 0)))
    if slopes is None:
        slopes_g = jnp.zeros((nkv, 1, rows), jnp.float32)
    else:
        # per-row slope = slopes[h*g + r % g]: the g-vector repeats
        # once per chunk position
        slopes_g = jnp.tile(
            slopes.astype(jnp.float32).reshape(nkv, 1, g),
            (1, 1, chunk))
        slopes_g = jnp.pad(slopes_g,
                           ((0, 0), (0, 0), (0, rows - rows_real)))

    out = _paged_prefill_call(
        q_g, k_pages, v_pages, tables.astype(jnp.int32),
        q_positions[:, 0].astype(jnp.int32), slopes_g,
        block_tokens=bt, chunk=chunk, groups=g,
        use_alibi=slopes is not None, interpret=interpret)
    out = out[:, :, :rows_real, :].reshape(b, nkv, chunk, g, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, chunk, nh, hd)


# ---------------------------------------------------------------------------
# the attn_impl seam (models/decoder.py hook)


def make_paged_attn_impl(block_tokens: int, backend: str = "auto",
                         interpret: bool = False):
    """``(impl, bind)``: an attention hook for paged-layout caches plus
    the binder that hands it the block tables.

    The decoder's ``attn_impl`` signature has no table slot, so the
    caller's jitted program binds the traced table array immediately
    before invoking the forward — ``bind(tables)`` at the top of the
    traced body, then ``fwd(...)``; the impl reads the binding during
    tracing (the layer scan closes over it as a loop constant).

    ``backend``: "auto" (Pallas on TPU, XLA gather elsewhere), "xla", or
    "pallas".  The Pallas decode kernel covers 1-token chunks and the
    prefill kernel covers multi-token chunks up to
    ``PREFILL_KERNEL_MAX_ROWS`` query rows, both with 8-aligned pages;
    anything else takes the gather path.
    """
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown paged attention backend {backend!r}; "
                         "expected 'auto', 'xla', or 'pallas'")
    bound = {}

    def bind(tables):
        bound["tables"] = tables

    def impl(q, k, v, k_pages, v_pages, positions, cache_start, slopes):
        tables = bound["tables"]
        k_pages, v_pages = write_paged_kv(k_pages, v_pages, k, v,
                                          tables, positions)
        use_pallas = (backend == "pallas"
                      or (backend == "auto"
                          and jax.default_backend() == "tpu"))
        # the pool's own type selects the numerics — no kv_dtype
        # threading through the seam: int4 never takes the kernel, int8
        # needs 32-aligned pages on real hardware (the int8 min tile's
        # sublane granule; forced-"pallas" test runs interpret and may
        # use smaller pages)
        bt = k_pages.shape[2]
        if isinstance(k_pages, QuantizedKVPages):
            kernel_ok = (k_pages.bits == 8
                         and (bt % 32 == 0 or backend == "pallas")
                         and bt % 8 == 0)
        else:
            kernel_ok = bt % 8 == 0
        chunk = q.shape[1]
        groups = q.shape[2] // k.shape[2]
        if (use_pallas and chunk == 1 and kernel_ok):
            kv_lens = positions[:, -1] + 1
            out = paged_flash_attention(q, k_pages, v_pages, tables,
                                        kv_lens, slopes,
                                        interpret=interpret)
        elif (use_pallas and chunk > 1 and kernel_ok
              and -(-(chunk * groups) // 8) * 8 <= PREFILL_KERNEL_MAX_ROWS):
            out = paged_prefill_attention(q, k_pages, v_pages, tables,
                                          positions, slopes,
                                          interpret=interpret)
        else:
            out = paged_gather_attention(q, k_pages, v_pages, tables,
                                         positions, slopes)
        return out, k_pages, v_pages

    return impl, bind
