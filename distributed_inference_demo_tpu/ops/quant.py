"""Weight-only int8 quantization.

The reference ships separate int8 ONNX exports per model
(``data/Data.kt:19-33`` ``-int8`` variants; ModelCard ``quantization_option``,
``server.py:831``).  TPU-native version: weights live in HBM as int8 with a
float32 per-output-channel scale (half the HBM bytes and bandwidth of bf16 —
decode is bandwidth-bound, so this is a throughput feature, not just a memory
one), and are dequantized on the fly inside the matmul — XLA fuses the
``convert + multiply`` into the MXU feed, so there is no materialized bf16
copy.

``QuantizedArray`` is a pytree whose leaves both carry the stacked-layer
leading axis, so pipeline-stage slicing (``base.slice_stage``) works on
quantized params unchanged.
"""

import os
from dataclasses import dataclass
from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.tree_util.register_dataclass,
         data_fields=["q", "scale"], meta_fields=[])
@dataclass
class QuantizedArray:
    """int8 values + float32 scale broadcastable over the last axis."""

    q: jax.Array      # int8, original shape
    scale: jax.Array  # float32, shape = (*1s, last_dim)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_array(w: jax.Array, stacked: bool = False) -> QuantizedArray:
    """Symmetric per-output-channel (last axis) int8 quantization.

    With ``stacked=True`` the leading axis is the pipeline layer stack and
    gets its own scales, so both leaves keep the layer axis (required for
    lax.scan over layers and for stage slicing).
    """
    wf = w.astype(jnp.float32)
    reduce_from = 1 if stacked else 0
    axes = tuple(range(reduce_from, w.ndim - 1))
    absmax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale)


@partial(jax.tree_util.register_dataclass,
         data_fields=["q", "scale"], meta_fields=["group"])
@dataclass
class QuantizedArray4:
    """Packed int4 values + group-wise float32 scales.

    Half the HBM bytes of int8 again: decode streams every weight byte
    once per step, so at the bandwidth-bound batch sizes int4 is the
    throughput configuration above int8.  Two int4 values pack into one
    uint8 NIBBLE-wise along the INPUT axis (axis -2) — explicit packing,
    not jnp.int4, so the storage halving holds on every backend.  The
    15-level grid needs finer scale granularity than int8's per-output-
    channel: scales are per ``group`` input positions per output channel
    (GPTQ-style group-wise), costing 4/group extra bytes per weight.

    Layout: ``q``: uint8 ``(..., in/2, out)`` (low nibble = even input
    index, high = odd); ``scale``: f32 ``(..., in/group, 1, out)``.
    Leading axes (layer stack, experts) ride along untouched, so
    ``base.slice_stage`` works unchanged — like :class:`QuantizedArray`.
    """

    q: jax.Array
    scale: jax.Array
    group: int

    @property
    def shape(self):
        return (*self.q.shape[:-2], self.q.shape[-2] * 2,
                self.q.shape[-1])

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        lo = (self.q & 0xF).astype(jnp.int8) - 8
        hi = (self.q >> 4).astype(jnp.int8) - 8
        v = jnp.stack([lo, hi], axis=-2)          # (..., in/2, 2, out)
        *lead, half, _, out = v.shape
        full = half * 2
        v = v.reshape(*lead, full, out).astype(jnp.float32)
        v = v.reshape(*lead, full // self.group, self.group, out)
        v = v * self.scale                        # (..., in/g, 1, out)
        return v.reshape(*lead, full, out).astype(dtype)


DEFAULT_INT4_GROUP = 64


def int4_group_for(inner: int) -> int:
    """The group size actually used for an input dim — ONE owner shared
    with the layer-chunked init (which rebuilds the QuantizedArray4
    wrapper outside the jitted quantize and must agree on the group)."""
    return min(DEFAULT_INT4_GROUP, inner)


def quantize_array4(w: jax.Array, group: int = None) -> QuantizedArray4:
    """Symmetric group-wise int4 quantization along the input axis
    (axis -2).  ``group`` defaults per :func:`int4_group_for`; the
    input size must be even (every decoder weight here is)."""
    wf = w.astype(jnp.float32)
    *lead, inner, out = wf.shape
    if inner % 2:
        raise ValueError(f"int4 packing needs an even input dim, got "
                         f"{inner}")
    group = int4_group_for(inner) if group is None else min(group, inner)
    if inner % group:
        raise ValueError(f"group={group} does not divide input dim "
                         f"{inner}")
    gw = wf.reshape(*lead, inner // group, group, out)
    absmax = jnp.max(jnp.abs(gw), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(gw / scale), -8, 7).astype(jnp.int8)
    q = q.reshape(*lead, inner, out)
    pairs = q.reshape(*lead, inner // 2, 2, out) + 8   # nibbles unsigned
    packed = (pairs[..., 0, :] | (pairs[..., 1, :] << 4)).astype(jnp.uint8)
    return QuantizedArray4(q=packed, scale=scale, group=group)


AnyQuantized = (QuantizedArray, QuantizedArray4)

# Weight keys worth quantizing: the large matmul operands.  Norm scales,
# biases and router gates stay in the model dtype (tiny, precision-critical).
_QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_layer_params(layers: dict, mode: str = "int8") -> dict:
    quant = (quantize_array4 if mode == "int4"
             else partial(quantize_array, stacked=True))
    return {k: (quant(v)
                if k in _QUANTIZABLE and not isinstance(v, AnyQuantized)
                else v)
            for k, v in layers.items()}


def maybe_quantize(params, cfg):
    """Apply the config's quantization mode to a full StageParams tree
    (no-op for "none").  The one shared site for the int8/int4 rewrap
    used by loader / checkpoint / tests."""
    if cfg.quantization not in ("int8", "int4"):
        return params
    from ..models.base import StageParams
    return StageParams(layers=quantize_layer_params(params.layers,
                                                    cfg.quantization),
                       embed=params.embed, final_norm=params.final_norm,
                       lm_head=params.lm_head)


# ---------------------------------------------------------------------------
# Quantized KV pages (docs/DESIGN.md §17)
#
# The page-pool twin of the weight rails above: K/V pages stored int8 or
# packed int4 with per-(token, kv-head) float32 scales riding alongside
# the block table.  Granularity is per-token over the head_dim axis —
# NOT the weights' per-output-channel — because a page is written once
# per token at insert time and never revisited: the token's own absmax
# is the only statistic available at write time, and it keeps the scale
# sidecar a trailing-singleton leaf so one sharding spec / one scatter
# index serves data and scales alike.

KV_DTYPES = ("bf16", "int8", "int4")


def resolve_kv_dtype(kv_dtype: Optional[str] = None) -> str:
    """``kv_dtype`` arg over ``DWT_KV_DTYPE`` env over "bf16" — the one
    owner of KV-width resolution (mirrors ``resolve_kv_layout``), called
    at every pool-creation site so the env knob reaches engines that
    never grew an explicit kwarg."""
    dt = kv_dtype or os.environ.get("DWT_KV_DTYPE", "") or "bf16"
    if dt not in KV_DTYPES:
        raise ValueError(
            f"unknown kv dtype {dt!r}; expected one of {KV_DTYPES}")
    return dt


@partial(jax.tree_util.register_dataclass,
         data_fields=["data", "scale", "zero"], meta_fields=["bits"])
@dataclass
class QuantizedKVPages:
    """Narrow KV pages + per-(…, token) scale sidecar over the last axis.

    ``data``: int8 ``(..., hd)`` (bits=8, symmetric) or uint8
    ``(..., hd/2)`` (bits=4, asymmetric, low nibble = even lane);
    ``scale``: f32 ``(..., 1)``; ``zero``: f32 ``(..., 1)`` minimum for
    int4, ``None`` for int8 (a ``None`` child vanishes from the pytree,
    so tree-mapped scatters/gathers and sharding-prefix specs see only
    real leaves).  Every leaf keeps the full leading-axis stack
    (``[L, N, H, bt, ·]`` pools, per-layer ``[N, H, bt, ·]`` slices,
    exported ``[n, L, H, bt, ·]`` runs), so the same tree-mapped page
    program serves them all.
    """

    data: jax.Array
    scale: jax.Array
    zero: Optional[jax.Array]
    bits: int

    @property
    def shape(self):
        """LOGICAL shape (full head_dim, nibbles unpacked)."""
        d = self.data.shape[-1] * (2 if self.bits == 4 else 1)
        return (*self.data.shape[:-1], d)

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def nbytes(self):
        return (self.data.nbytes + self.scale.nbytes
                + (0 if self.zero is None else self.zero.nbytes))

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        if self.bits == 8:
            return (self.data.astype(jnp.float32)
                    * self.scale).astype(dtype)
        lo = (self.data & 0xF).astype(jnp.float32)
        hi = (self.data >> 4).astype(jnp.float32)
        v = jnp.stack([lo, hi], axis=-1)            # (..., hd/2, 2)
        *lead, half, _ = v.shape
        v = v.reshape(*lead, half * 2)
        return (v * self.scale + self.zero).astype(dtype)


def quantize_kv_pages(x: jax.Array, bits: int) -> QuantizedKVPages:
    """Per-(…, token) quantization over the LAST axis (head_dim) —
    shape-agnostic, so pool leaves, projection chunks and exported block
    runs all go through this one owner.  int8 is symmetric on the
    weight rails' absmax/127 grid; int4's 15-level grid needs the
    asymmetric [min, max] span (a symmetric 7-level grid wastes half
    the codes whenever a token's channels share a sign)."""
    xf = x.astype(jnp.float32)
    if bits == 8:
        absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
        return QuantizedKVPages(data=q, scale=scale, zero=None, bits=8)
    if bits != 4:
        raise ValueError(f"kv quantization is int8 or int4, got {bits}")
    mn = jnp.min(xf, axis=-1, keepdims=True)
    mx = jnp.max(xf, axis=-1, keepdims=True)
    scale = jnp.maximum(mx - mn, 1e-8) / 15.0
    q = jnp.clip(jnp.round((xf - mn) / scale), 0, 15).astype(jnp.uint8)
    *lead, d = q.shape
    if d % 2:
        raise ValueError(f"int4 packing needs an even head_dim, got {d}")
    pairs = q.reshape(*lead, d // 2, 2)
    packed = (pairs[..., 0] | (pairs[..., 1] << 4)).astype(jnp.uint8)
    return QuantizedKVPages(data=packed, scale=scale, zero=mn, bits=4)


def quantize_kv_like(ref, x: jax.Array):
    """Payload matching the pool tensor ``ref``: a dtype cast for a
    plain pool, quantized leaves for a quantized one — so every page
    scatter site quantizes through one line."""
    if isinstance(ref, QuantizedKVPages):
        return quantize_kv_pages(x, ref.bits)
    return x.astype(ref.dtype)


def dequantize_kv(x, dtype=jnp.float32) -> jax.Array:
    """Full-width view of ``x`` (plain array or QuantizedKVPages)."""
    if isinstance(x, QuantizedKVPages):
        return x.dequantize(dtype)
    return x.astype(dtype)


def alloc_kv_pages(shape, kv_dtype: Optional[str], base_dtype):
    """One zeroed pool tensor for a ``(..., head_dim)`` page-pool shape:
    a plain ``base_dtype`` array for bf16, :class:`QuantizedKVPages`
    leaves for int8/int4.  Callers build the V pool with
    ``jax.tree.map(jnp.zeros_like, pk)`` — works for both."""
    kv_dtype = resolve_kv_dtype(kv_dtype)
    *lead, d = shape
    if kv_dtype == "bf16":
        return jnp.zeros(shape, base_dtype)
    if kv_dtype == "int8":
        return QuantizedKVPages(
            data=jnp.zeros((*lead, d), jnp.int8),
            scale=jnp.zeros((*lead, 1), jnp.float32),
            zero=None, bits=8)
    return QuantizedKVPages(
        data=jnp.zeros((*lead, d // 2), jnp.uint8),
        scale=jnp.zeros((*lead, 1), jnp.float32),
        zero=jnp.zeros((*lead, 1), jnp.float32), bits=4)


def kv_token_head_bytes(head_dim: int, kv_dtype: Optional[str],
                        base_dtype) -> int:
    """Bytes one (token, kv-head) of ONE tensor (K or V) occupies in the
    page pool, scale/zero sidecar INCLUDED — the single owner of the
    page-width arithmetic shared by the byte-budget admission
    (``make_kv_backend``) and the manager's accounting, so the two can
    never disagree about what a block costs."""
    kv_dtype = resolve_kv_dtype(kv_dtype)
    if kv_dtype == "bf16":
        return head_dim * np.dtype(base_dtype).itemsize
    if kv_dtype == "int8":
        return head_dim + 4                  # int8 lanes + f32 scale
    return head_dim // 2 + 8                 # packed nibbles + scale + zero


def kv_scale_token_head_bytes(kv_dtype: Optional[str]) -> int:
    """The sidecar-only share of :func:`kv_token_head_bytes` — what the
    ``dwt_kvcache_quant_scale_bytes`` gauge reports."""
    kv_dtype = resolve_kv_dtype(kv_dtype)
    return {"bf16": 0, "int8": 4, "int4": 8}[kv_dtype]


def dense(x: jax.Array,
          w: Union[jax.Array, QuantizedArray, QuantizedArray4],
          eq: str) -> jax.Array:
    """einsum that transparently handles quantized weights.

    Dequantizes to the activation dtype right at the contraction so XLA
    fuses the int8/int4 unpack + convert + scale into the matmul's
    operand feed — HBM sees only the quantized bytes.
    """
    if isinstance(w, AnyQuantized):
        w = w.dequantize(x.dtype)
    return jnp.einsum(eq, x, w)
