"""Weight-only int8 quantization.

The reference ships separate int8 ONNX exports per model
(``data/Data.kt:19-33`` ``-int8`` variants; ModelCard ``quantization_option``,
``server.py:831``).  TPU-native version: weights live in HBM as int8 with a
float32 per-output-channel scale (half the HBM bytes and bandwidth of bf16 —
decode is bandwidth-bound, so this is a throughput feature, not just a memory
one), and are dequantized on the fly inside the matmul — XLA fuses the
``convert + multiply`` into the MXU feed, so there is no materialized bf16
copy.

``QuantizedArray`` is a pytree whose leaves both carry the stacked-layer
leading axis, so pipeline-stage slicing (``base.slice_stage``) works on
quantized params unchanged.
"""

from dataclasses import dataclass
from functools import partial
from typing import Union

import jax
import jax.numpy as jnp


@partial(jax.tree_util.register_dataclass,
         data_fields=["q", "scale"], meta_fields=[])
@dataclass
class QuantizedArray:
    """int8 values + float32 scale broadcastable over the last axis."""

    q: jax.Array      # int8, original shape
    scale: jax.Array  # float32, shape = (*1s, last_dim)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_array(w: jax.Array, stacked: bool = False) -> QuantizedArray:
    """Symmetric per-output-channel (last axis) int8 quantization.

    With ``stacked=True`` the leading axis is the pipeline layer stack and
    gets its own scales, so both leaves keep the layer axis (required for
    lax.scan over layers and for stage slicing).
    """
    wf = w.astype(jnp.float32)
    reduce_from = 1 if stacked else 0
    axes = tuple(range(reduce_from, w.ndim - 1))
    absmax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale)


# Weight keys worth quantizing: the large matmul operands.  Norm scales,
# biases and router gates stay in the model dtype (tiny, precision-critical).
_QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_layer_params(layers: dict) -> dict:
    return {k: (quantize_array(v, stacked=True)
                if k in _QUANTIZABLE and not isinstance(v, QuantizedArray)
                else v)
            for k, v in layers.items()}


def maybe_quantize(params, cfg):
    """Apply the config's quantization mode to a full StageParams tree
    (no-op for "none").  The one shared site for the int8 rewrap used by
    loader / checkpoint / tests."""
    if cfg.quantization != "int8":
        return params
    from ..models.base import StageParams
    return StageParams(layers=quantize_layer_params(params.layers),
                       embed=params.embed, final_norm=params.final_norm,
                       lm_head=params.lm_head)


def dense(x: jax.Array, w: Union[jax.Array, QuantizedArray],
          eq: str) -> jax.Array:
    """einsum that transparently handles quantized weights.

    Dequantizes to the activation dtype right at the contraction so XLA
    fuses the int8->bf16 convert into the matmul's operand feed.
    """
    if isinstance(w, QuantizedArray):
        w = w.dequantize(x.dtype)
    return jnp.einsum(eq, x, w)
