"""Weight-only int8 quantization.

The reference ships separate int8 ONNX exports per model
(``data/Data.kt:19-33`` ``-int8`` variants; ModelCard ``quantization_option``,
``server.py:831``).  TPU-native version: weights live in HBM as int8 with a
float32 per-output-channel scale (half the HBM bytes and bandwidth of bf16 —
decode is bandwidth-bound, so this is a throughput feature, not just a memory
one), and are dequantized on the fly inside the matmul — XLA fuses the
``convert + multiply`` into the MXU feed, so there is no materialized bf16
copy.

``QuantizedArray`` is a pytree whose leaves both carry the stacked-layer
leading axis, so pipeline-stage slicing (``base.slice_stage``) works on
quantized params unchanged.
"""

from dataclasses import dataclass
from functools import partial
from typing import Union

import jax
import jax.numpy as jnp


@partial(jax.tree_util.register_dataclass,
         data_fields=["q", "scale"], meta_fields=[])
@dataclass
class QuantizedArray:
    """int8 values + float32 scale broadcastable over the last axis."""

    q: jax.Array      # int8, original shape
    scale: jax.Array  # float32, shape = (*1s, last_dim)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_array(w: jax.Array, stacked: bool = False) -> QuantizedArray:
    """Symmetric per-output-channel (last axis) int8 quantization.

    With ``stacked=True`` the leading axis is the pipeline layer stack and
    gets its own scales, so both leaves keep the layer axis (required for
    lax.scan over layers and for stage slicing).
    """
    wf = w.astype(jnp.float32)
    reduce_from = 1 if stacked else 0
    axes = tuple(range(reduce_from, w.ndim - 1))
    absmax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale)


@partial(jax.tree_util.register_dataclass,
         data_fields=["q", "scale"], meta_fields=["group"])
@dataclass
class QuantizedArray4:
    """Packed int4 values + group-wise float32 scales.

    Half the HBM bytes of int8 again: decode streams every weight byte
    once per step, so at the bandwidth-bound batch sizes int4 is the
    throughput configuration above int8.  Two int4 values pack into one
    uint8 NIBBLE-wise along the INPUT axis (axis -2) — explicit packing,
    not jnp.int4, so the storage halving holds on every backend.  The
    15-level grid needs finer scale granularity than int8's per-output-
    channel: scales are per ``group`` input positions per output channel
    (GPTQ-style group-wise), costing 4/group extra bytes per weight.

    Layout: ``q``: uint8 ``(..., in/2, out)`` (low nibble = even input
    index, high = odd); ``scale``: f32 ``(..., in/group, 1, out)``.
    Leading axes (layer stack, experts) ride along untouched, so
    ``base.slice_stage`` works unchanged — like :class:`QuantizedArray`.
    """

    q: jax.Array
    scale: jax.Array
    group: int

    @property
    def shape(self):
        return (*self.q.shape[:-2], self.q.shape[-2] * 2,
                self.q.shape[-1])

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        lo = (self.q & 0xF).astype(jnp.int8) - 8
        hi = (self.q >> 4).astype(jnp.int8) - 8
        v = jnp.stack([lo, hi], axis=-2)          # (..., in/2, 2, out)
        *lead, half, _, out = v.shape
        full = half * 2
        v = v.reshape(*lead, full, out).astype(jnp.float32)
        v = v.reshape(*lead, full // self.group, self.group, out)
        v = v * self.scale                        # (..., in/g, 1, out)
        return v.reshape(*lead, full, out).astype(dtype)


DEFAULT_INT4_GROUP = 64


def int4_group_for(inner: int) -> int:
    """The group size actually used for an input dim — ONE owner shared
    with the layer-chunked init (which rebuilds the QuantizedArray4
    wrapper outside the jitted quantize and must agree on the group)."""
    return min(DEFAULT_INT4_GROUP, inner)


def quantize_array4(w: jax.Array, group: int = None) -> QuantizedArray4:
    """Symmetric group-wise int4 quantization along the input axis
    (axis -2).  ``group`` defaults per :func:`int4_group_for`; the
    input size must be even (every decoder weight here is)."""
    wf = w.astype(jnp.float32)
    *lead, inner, out = wf.shape
    if inner % 2:
        raise ValueError(f"int4 packing needs an even input dim, got "
                         f"{inner}")
    group = int4_group_for(inner) if group is None else min(group, inner)
    if inner % group:
        raise ValueError(f"group={group} does not divide input dim "
                         f"{inner}")
    gw = wf.reshape(*lead, inner // group, group, out)
    absmax = jnp.max(jnp.abs(gw), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(gw / scale), -8, 7).astype(jnp.int8)
    q = q.reshape(*lead, inner, out)
    pairs = q.reshape(*lead, inner // 2, 2, out) + 8   # nibbles unsigned
    packed = (pairs[..., 0, :] | (pairs[..., 1, :] << 4)).astype(jnp.uint8)
    return QuantizedArray4(q=packed, scale=scale, group=group)


AnyQuantized = (QuantizedArray, QuantizedArray4)

# Weight keys worth quantizing: the large matmul operands.  Norm scales,
# biases and router gates stay in the model dtype (tiny, precision-critical).
_QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_layer_params(layers: dict, mode: str = "int8") -> dict:
    quant = (quantize_array4 if mode == "int4"
             else partial(quantize_array, stacked=True))
    return {k: (quant(v)
                if k in _QUANTIZABLE and not isinstance(v, AnyQuantized)
                else v)
            for k, v in layers.items()}


def maybe_quantize(params, cfg):
    """Apply the config's quantization mode to a full StageParams tree
    (no-op for "none").  The one shared site for the int8/int4 rewrap
    used by loader / checkpoint / tests."""
    if cfg.quantization not in ("int8", "int4"):
        return params
    from ..models.base import StageParams
    return StageParams(layers=quantize_layer_params(params.layers,
                                                    cfg.quantization),
                       embed=params.embed, final_norm=params.final_norm,
                       lm_head=params.lm_head)


def dense(x: jax.Array,
          w: Union[jax.Array, QuantizedArray, QuantizedArray4],
          eq: str) -> jax.Array:
    """einsum that transparently handles quantized weights.

    Dequantizes to the activation dtype right at the contraction so XLA
    fuses the int8/int4 unpack + convert + scale into the matmul's
    operand feed — HBM sees only the quantized bytes.
    """
    if isinstance(w, AnyQuantized):
        w = w.dequantize(x.dtype)
    return jnp.einsum(eq, x, w)
