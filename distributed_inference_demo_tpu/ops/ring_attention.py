"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has **no** long-context support at all — ``max_length`` is 40
(``server.py:1001``) and there is no sequence parallelism of any kind
(SURVEY.md §5.7).  This module is the from-scratch TPU design: the sequence
dimension is sharded over the ``sp`` mesh axis, each device holds one
contiguous chunk, and causal self-attention is computed **blockwise** with an
online-softmax accumulator while K/V blocks rotate around the ring via
``lax.ppermute`` (one ICI hop per step).  Peak memory per device is
O(seq/sp_size) for activations and KV — sequence length scales linearly with
the mesh axis.

Two entry points:

- :func:`ring_self_attention` — causal self-attention for prefill/training,
  q/k/v sharded by sequence chunk.  FLOPs overlap with the ppermute transfer
  because XLA schedules the collective-permute asynchronously against the
  next block's matmuls.
- :func:`sp_decode_attention` — single-position decode against a
  sequence-sharded KV cache: every rank attends its local cache shard and
  the partial softmax statistics are combined exactly with a log-sum-exp
  reduction (``pmax`` + ``psum``) — no KV movement at all during decode.

Both support GQA (kv heads broadcast over query-head groups) and ALiBi bias
(bloom family), matching ``ops.attention``.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from .._jax_compat import axis_size

_NEG = -1e30


def _split_heads(q: jnp.ndarray, nkv: int) -> jnp.ndarray:
    """[b, l, nh, hd] -> [b, l, nkv, groups, hd] for GQA broadcast."""
    b, l, nh, hd = q.shape
    return q.reshape(b, l, nkv, nh // nkv, hd)


def _block_scores(qf: jnp.ndarray, kf: jnp.ndarray) -> jnp.ndarray:
    """qf [b,lq,nkv,g,hd] x kf [b,lk,nkv,hd] -> [b,nkv,g,lq,lk] (f32)."""
    return jnp.einsum("bqkgh,bskh->bkgqs", qf, kf)


def _bias_and_mask(scores: jnp.ndarray, q_pos: jnp.ndarray,
                   kv_pos: jnp.ndarray, kv_valid: jnp.ndarray,
                   slopes: Optional[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Apply causal mask (+ optional ALiBi) to a score block.

    q_pos: [lq] global positions.  kv_pos: [lk] global positions.
    kv_valid: [lk] bool (filled cache slots).  Returns (scores, valid) with
    masked entries set to _NEG; valid has shape [1,1,1,lq,lk].
    """
    b, nkv, g, lq, lk = scores.shape
    causal = kv_pos[None, :] <= q_pos[:, None]              # [lq, lk]
    valid = (causal & kv_valid[None, :])[None, None, None]  # [1,1,1,lq,lk]
    if slopes is not None:
        # slopes: [nh] == [nkv*g]; bias = -slope * (q_pos - kv_pos)
        dist = (q_pos[:, None] - kv_pos[None, :]).astype(jnp.float32)
        bias = -slopes.reshape(1, nkv, g, 1, 1) * dist[None, None, None]
        scores = scores + bias
    return jnp.where(valid, scores, _NEG), valid


def _online_update(o: jnp.ndarray, m: jnp.ndarray, l: jnp.ndarray,
                   scores: jnp.ndarray, valid: jnp.ndarray,
                   vf: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One flash-attention accumulator step.

    o: [b,nkv,g,lq,hd] unnormalized output, m: [b,nkv,g,lq] running max,
    l: [b,nkv,g,lq] running denominator.  scores already masked to _NEG;
    ``valid`` broadcastable to scores.  vf: [b,lk,nkv,hd] f32.
    """
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # exp(_NEG - m_new) would be exp(0)=1 when a whole row is masked and
    # m_new is still _NEG — zero masked probabilities explicitly.
    p = jnp.where(valid, jnp.exp(scores - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bkgqs,bskh->bkgqh", p, vf)
    o_new = o * alpha[..., None] + pv
    return o_new, m_new, l_new


def ring_self_attention(
    q: jnp.ndarray,           # [b, lq, nh, hd] local sequence chunk
    k: jnp.ndarray,           # [b, lk, nkv, hd] local chunk
    v: jnp.ndarray,           # [b, lk, nkv, hd]
    axis_name: str,           # the sp mesh axis (call inside shard_map)
    chunk_offset: Optional[jnp.ndarray] = None,  # global start of this chunk
    slopes: Optional[jnp.ndarray] = None,        # [nh] ALiBi slopes
) -> jnp.ndarray:
    """Causal self-attention with sequence sharded over ``axis_name``.

    Device ``i`` owns tokens ``[i*lq, (i+1)*lq)`` (contiguous layout) unless
    ``chunk_offset`` overrides the global start.  K/V blocks rotate around
    the ring; after ``sp_size`` steps every device has attended its queries
    to every causally-visible key.  Returns [b, lq, nh, hd] in q.dtype.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, lq, nh, hd = q.shape
    nkv = k.shape[2]
    g = nh // nkv

    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    qf = _split_heads(q.astype(jnp.float32) * scale, nkv)
    q_off = (idx * lq) if chunk_offset is None else chunk_offset
    q_pos = q_off + jnp.arange(lq)

    o = jnp.zeros((b, nkv, g, lq, hd), jnp.float32)
    m = jnp.full((b, nkv, g, lq), _NEG, jnp.float32)
    l = jnp.zeros((b, nkv, g, lq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    all_valid = jnp.ones((k.shape[1],), bool)

    def attend(o, m, l, kb, vb, kv_off):
        kv_pos = kv_off + jnp.arange(k.shape[1])
        scores = _block_scores(qf, kb.astype(jnp.float32))
        scores, valid = _bias_and_mask(scores, q_pos, kv_pos, all_valid,
                                       slopes)
        return _online_update(o, m, l, scores, valid,
                              vb.astype(jnp.float32))

    def step(s, carry):
        o, m, l, kb, vb, kv_off = carry
        # rotate first (blocks travel in their native dtype — half the ICI
        # bytes of an f32 ring for bf16 KV), then attend the arrived block.
        kb, vb, kv_off = jax.lax.ppermute((kb, vb, kv_off), axis_name, perm)
        o, m, l = attend(o, m, l, kb, vb, kv_off)
        return o, m, l, kb, vb, kv_off

    kv_off0 = (idx * k.shape[1]) if chunk_offset is None else chunk_offset
    kv_off0 = jnp.asarray(kv_off0, jnp.int32)
    # local block first, then n-1 rotate-attend steps: no wasted final hop.
    o, m, l = attend(o, m, l, k, v, kv_off0)
    carry = (o, m, l, k, v, kv_off0)
    o, m, l, *_ = jax.lax.fori_loop(0, n - 1, step, carry)
    out = o / jnp.maximum(l, 1e-30)[..., None]         # [b, nkv, g, lq, hd]
    out = out.transpose(0, 3, 1, 2, 4)                 # [b, lq, nkv, g, hd]
    return out.reshape(b, lq, nh, hd).astype(q.dtype)


def sp_decode_attention(
    q: jnp.ndarray,           # [b, lq, nh, hd] (replicated across sp ranks)
    k_shard: jnp.ndarray,     # [b, nkv, s_loc, hd] local cache shard
    v_shard: jnp.ndarray,     #   (head-major, see models.base.KVCache)
    kv_pos: jnp.ndarray,      # [s_loc] int32 global positions, -1 = empty
    q_positions: jnp.ndarray, # [b, lq] global positions of the queries
    axis_name: str,
    slopes: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Decode attention against a sequence-sharded KV cache.

    Every rank computes partial attention over its cache shard, then the
    partial softmax statistics are merged exactly across the ``sp`` axis:
    ``m* = pmax(m)``, ``l* = psum(l·e^{m-m*})``, ``o* = psum(o·e^{m-m*})/l*``.
    Only O(heads·hd) bytes cross the ICI per step — no KV movement.
    """
    b, lq, nh, hd = q.shape
    nkv = k_shard.shape[1]
    g = nh // nkv

    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    qf = _split_heads(q.astype(jnp.float32) * scale, nkv)
    kf = k_shard.astype(jnp.float32)
    vf = v_shard.astype(jnp.float32)

    scores = jnp.einsum("bqkgh,bksh->bkgqs", qf, kf)     # [b,nkv,g,lq,s]
    kv_valid = kv_pos >= 0
    # causal over global positions, per batch row
    causal = kv_pos[None, None, :] <= q_positions[:, :, None]   # [b, lq, s]
    valid = (causal & kv_valid[None, None, :])[:, None, None]   # [b,1,1,lq,s]
    if slopes is not None:
        dist = (q_positions[:, :, None] - kv_pos[None, None, :]
                ).astype(jnp.float32)                           # [b, lq, s]
        scores = scores + (-slopes.reshape(1, nkv, g, 1, 1)
                           * dist[:, None, None])
    scores = jnp.where(valid, scores, _NEG)

    m_loc = jnp.max(scores, axis=-1)                     # [b,nkv,g,lq]
    p = jnp.where(valid, jnp.exp(scores - m_loc[..., None]), 0.0)
    l_loc = jnp.sum(p, axis=-1)
    o_loc = jnp.einsum("bkgqs,bksh->bkgqh", p, vf)

    m_glob = jax.lax.pmax(m_loc, axis_name)
    alpha = jnp.exp(m_loc - m_glob)
    l_glob = jax.lax.psum(l_loc * alpha, axis_name)
    o_glob = jax.lax.psum(o_loc * alpha[..., None], axis_name)
    out = o_glob / jnp.maximum(l_glob, 1e-30)[..., None]  # [b,nkv,g,lq,hd]
    out = out.transpose(0, 3, 1, 2, 4)                    # [b,lq,nkv,g,hd]
    return out.reshape(b, lq, nh, hd).astype(q.dtype)
