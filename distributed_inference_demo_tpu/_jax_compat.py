"""jax API compat shims — ONE owner for every call site in the repo.

jax >= 0.6 promotes ``shard_map`` to the top level (replication check
spelled ``check_vma``) and adds ``jax.lax.axis_size``; earlier releases
keep ``shard_map`` in ``jax.experimental.shard_map`` under ``check_rep``
and spell axis size as the classic ``psum(1, axis)`` idiom (which
constant-folds to a static int).  Call sites use the new spellings; this
module translates downward so the repo runs on both.

Deliberately free of intra-package imports: ``models`` and ``parallel``
both consume it, so it must sit below both in the import graph.
"""

import jax

try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma,
                                 **kw)

try:
    axis_size = jax.lax.axis_size
except AttributeError:
    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)
