"""Benchmark harness: north-star metrics on the real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric (BASELINE.md config #1): decode tokens/sec on
TinyLlama-1.1B, single chip, vs the measured 2-process CPU socket-pipeline
baseline of the SAME model/batch (``tools/cpu_baseline.py`` →
``tools/cpu_baseline.json``).  North-star target: >= 10x.

Extra measurements (reported inside the same JSON object):

- prefill tokens/sec (TinyLlama);
- Llama-3-8B single-chip decode tok/s at int8 and (HBM permitting) bf16 —
  BASELINE.md's flagship model;
- inter-shard activation latency p50/p95 across a live 2-process socket
  pipeline (device header + CPU worker — BASELINE config #2's
  heterogeneous shape), derived from the hot-loop stats
  (``runtime/stats.py``; reference timers ``Communication.java:859-896``).

Each leg is independent: failures are reported as {"error": ...} for that
leg instead of killing the bench.
"""

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
BASELINE_PATH = REPO / "tools" / "cpu_baseline.json"

# Fallback when tools/cpu_baseline.json is absent on the bench host:
# measured by tools/cpu_baseline.py on the build host (1-core x86_64 VM,
# see that file's JSON for full provenance).
FALLBACK_BASELINE = {"tokens_per_sec": None, "source": "missing"}


def _load_baseline() -> dict:
    if BASELINE_PATH.exists():
        data = json.loads(BASELINE_PATH.read_text())
        data["source"] = "tools/cpu_baseline.json"
        return data
    return dict(FALLBACK_BASELINE)


def _bench_engine(model: str, batch: int, prompt_len: int, new_tokens: int,
                  quant: bool = False) -> dict:
    """Single-chip decode + prefill throughput via InferenceEngine."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.quant import maybe_quantize
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    name = model + ("-int8" if quant else "")
    cfg = get_model_config(name)
    # quantize at creation time: peak HBM stays near the int8 footprint
    # instead of materializing the bf16 tree first (which would OOM exactly
    # the chips int8 exists to fit on)
    params = init_full_params(jax.random.PRNGKey(0), cfg, quantize=quant)
    params = maybe_quantize(params, cfg)  # no-op for already-wrapped leaves
    engine = InferenceEngine(
        cfg, params, max_seq=prompt_len + new_tokens,
        sampling=SamplingParams(temperature=0.7, top_k=7))  # ref defaults

    prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
              % 1000).astype(np.int32)
    engine.generate(prompt, new_tokens, seed=0)           # compile warmup
    result = engine.generate(prompt, new_tokens, seed=0)  # steady state
    decode_tps = result.tokens_per_second

    # prefill throughput: time prefill alone on a fresh cache
    import jax as _jax
    cache = engine.new_cache(batch)
    t0 = time.perf_counter()
    logits, cache = engine._prefill(engine.params, prompt, cache)
    _jax.block_until_ready(logits)
    prefill_s = time.perf_counter() - t0
    prefill_tps = batch * prompt_len / prefill_s

    return {
        "model": name,
        "decode_tokens_per_sec": round(decode_tps, 2),
        "prefill_tokens_per_sec": round(prefill_tps, 2),
        "batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens,
        "dtype": "int8" if quant else cfg.dtype_name,
    }


def _bench_pipeline_latency(model: str, batch: int, prompt_len: int,
                            new_tokens: int) -> dict:
    """2-process socket pipeline: this process (default backend — the TPU
    when present) is the header, a spawned CPU process is the tail.
    Inter-shard activation latency is derived per token as
    ``(ring RTT - tail compute p50) / 2`` — the RTT covers exactly two
    socket hops (hidden out, token back) around the tail's compute."""
    import subprocess

    import numpy as np
    import jax
    from distributed_inference_demo_tpu.comm.transport import ZmqTransport
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import (
        slice_stage, split_layer_ranges)
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.distributed import (
        PipelineHeader, StageRuntime)

    cfg = get_model_config(model)
    specs = split_layer_ranges(cfg.num_layers, 2)
    max_seq = prompt_len + new_tokens
    sampling = SamplingParams(temperature=0.7, top_k=7)

    header_transport = ZmqTransport("header")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_inference_demo_tpu.runtime.worker_main",
         "--model", model, "--stage-id", "1", "--num-stages", "2",
         "--layer-start", str(specs[1].layer_start),
         "--layer-end", str(specs[1].layer_end),
         "--device-id", "w1", "--port", "0",
         "--header", f"header@{header_transport.address}",
         "--max-seq", str(max_seq), "--dtype", "float32",
         "--temperature", "0.7", "--top-k", "7",
         "--step-timeout", "600"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=str(REPO))
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("WORKER_READY w1 "), line
        header_transport.connect("w1", line.split()[-1])

        full = init_full_params(jax.random.PRNGKey(0), cfg)
        header = PipelineHeader(
            StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                         max_seq, sampling),
            header_transport, next_id="w1", step_timeout=600)
        prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
                  % 1000).astype(np.int32)
        header.generate(prompt, 4)          # warmup/compile
        header.reset_stats()
        t0 = time.perf_counter()
        header.generate(prompt, new_tokens)
        dt = time.perf_counter() - t0
        stats = header.collect_stats(num_stages=2, timeout=30)
        header.shutdown_pipeline()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
        header_transport.close()

    h = stats[0]
    tail = stats[1] if len(stats) > 1 else {}
    tail_p50 = tail.get("compute_p50_ms", 0.0)
    tail_p95 = tail.get("compute_p95_ms", 0.0)
    out = {
        "model": model, "batch": batch, "num_stages": 2,
        "pipeline_tokens_per_sec": round(batch * new_tokens / dt, 2),
        "ring_rtt_p50_ms": h.get("ring_rtt_p50_ms"),
        "ring_rtt_p95_ms": h.get("ring_rtt_p95_ms"),
        "tail_compute_p50_ms": tail_p50,
        "stage_stats": stats,
    }
    if h.get("ring_rtt_p50_ms") is not None:
        out["activation_hop_p50_ms"] = round(
            max(0.0, (h["ring_rtt_p50_ms"] - tail_p50) / 2), 3)
        out["activation_hop_p95_ms"] = round(
            max(0.0, (h["ring_rtt_p95_ms"] - tail_p95) / 2), 3)
    return out


def _leg(fn, *args, **kw):
    try:
        return fn(*args, **kw)
    except Exception as e:      # report, don't kill the bench
        return {"error": f"{type(e).__name__}: {e}"}


def main() -> None:
    import jax

    model = os.environ.get("BENCH_MODEL", "tinyllama-1.1b")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_PROMPT", "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
    flagship = os.environ.get("BENCH_FLAGSHIP", "llama-3-8b")
    skip_flagship = os.environ.get("BENCH_SKIP_FLAGSHIP", "") == "1"
    skip_pipeline = os.environ.get("BENCH_SKIP_PIPELINE", "") == "1"

    device = jax.devices()[0].device_kind
    baseline = _load_baseline()

    headline = _leg(_bench_engine, model, batch, prompt_len, new_tokens)

    extras = {"device": device, "baseline": {
        k: baseline.get(k) for k in
        ("tokens_per_sec", "model", "dtype", "batch", "host", "cpu",
         "measured_at", "source")}}
    if not skip_flagship:
        extras["flagship_int8"] = _leg(
            _bench_engine, flagship, batch, prompt_len,
            min(new_tokens, 32), quant=True)
        extras["flagship_bf16"] = _leg(
            _bench_engine, flagship, batch, prompt_len,
            min(new_tokens, 32), quant=False)
    if not skip_pipeline:
        extras["pipeline"] = _leg(
            _bench_pipeline_latency, model, batch, prompt_len,
            min(new_tokens, 32))

    tps = headline.get("decode_tokens_per_sec")
    base_tps = baseline.get("tokens_per_sec")
    # only a same-model/same-batch comparison is meaningful; anything else
    # reports null rather than a mislabeled multiplier
    comparable = (baseline.get("model") == model
                  and baseline.get("batch") == batch)
    vs = (round(tps / base_tps, 2)
          if tps is not None and base_tps and comparable else None)

    print(json.dumps({
        "metric": f"decode tokens/sec ({model}, "
                  f"{headline.get('dtype', '?')}, batch={batch}, "
                  f"prompt={prompt_len}, new={new_tokens}, "
                  f"device={device}) vs measured 2-process CPU "
                  f"socket-pipeline baseline (same model/batch)",
        "value": tps,
        "unit": "tokens/sec",
        "vs_baseline": vs,
        "headline": headline,
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
