"""Benchmark harness: north-star metrics on the real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric (BASELINE.md config #1): decode tokens/sec on
TinyLlama-1.1B, single chip, vs the measured 2-process CPU socket-pipeline
baseline of the SAME model/batch (``tools/cpu_baseline.py`` →
``tools/cpu_baseline.json``).  North-star target: >= 10x.

Extra legs (each reported inside the same JSON object):

- ``headline_int8``: int8 TinyLlama decode (half the HBM bytes/step —
  decode is bandwidth-bound, so this is the throughput configuration);
- ``sweep``: batch sweep 8/32/64 at bf16 and int8, each with achieved
  HBM GB/s (= weights_bytes x steps/s) so the roofline gap is visible;
- ``flagship_int8`` / ``flagship_bf16``: Llama-3-8B single-chip decode —
  BASELINE.md's flagship model (bf16 weights exceed a 16 GB chip: the leg
  reports "does not fit" from a host-side precheck instead of OOMing);
- ``pipeline``: inter-shard activation latency p50/p95 across a live
  2-process socket pipeline (device header + CPU worker — BASELINE
  config #2's heterogeneous shape), from the hot-loop stats
  (``runtime/stats.py``; reference timers ``Communication.java:859-896``);
- ``prefill_long``: long-prompt prefill, Pallas flash kernel vs jnp
  attention, 2k-8k tokens;
- ``speculative``: draft/verify decoding vs plain decode on the same
  workload (draft = int8 quantization of the same seed weights), with
  acceptance rate and speedup;
- ``prompt_lookup``: draft-free n-gram speculation at batch 1 on a
  repetitive prompt, vs plain decode;
- ``batching``: continuous-batching aggregate throughput (24 requests
  into 8 slots) vs sequential plain batches, plus the block KV cache's
  hit/reuse counters on a shared-prefix workload;
- ``prefix_reuse``: the block KV cache (runtime/kvcache) on a
  repeated-shared-prefix workload — hit rate, reused tokens, and
  measured prefill-seconds saved (cache-off vs cache-on wall delta);
- ``tiered_prefix``: the §21 host-RAM/disk KV tier vs re-prefill when
  the shared-prefix working set exceeds the device pool — revisit TTFT
  p95, promotion h2d bytes, per-tier hit rates, greedy bit-identity,
  and the three-tier zero-leak check;
- ``paged_decode``: paged vs dense KV layout on the batching engine —
  decode tok/s ratio, reserved-vs-actually-allocated cache HBM at a
  serving-realistic max_seq, and the primed phase's h2d_bytes == 0
  zero-copy-prefix-hit check (docs/DESIGN.md §11);
- ``long_context``: 32k-token single-chip generation via chunked prefill
  + flash attention (prefill and decode tok/s at full context).

**Process isolation:** every leg runs in a fresh subprocess (`--leg` mode)
with its own TPU context, so one leg's allocations or failure can never
poison the next (the round-2 bench lost all three flagship legs to exactly
that).  The parent process never initializes JAX.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
BASELINE_PATH = REPO / "tools" / "cpu_baseline.json"
# the round's incremental-session artifact (tools/measure_session.py) —
# ONE owner for the name, shared with the session harness; bump per round
PRIOR_ARTIFACT_NAME = "BENCH_SELF_r05.json"
# older rounds' artifacts, consulted ONLY for legs the current round's
# session never landed — each borrowed leg is stamped with the artifact
# it came from, so old numbers can't masquerade as this round's
PRIOR_ARTIFACT_FALLBACKS = ["BENCH_SELF_r04.json", "BENCH_SELF_r03.json"]
# extras keys that are session bookkeeping, not measured legs
_NON_LEG_EXTRAS = {"baseline", "device", "prior_legs", "prior_note",
                   "probe_history", "measured_ceiling_gbs",
                   "probe_spread_gbs", "headline_live_error", "error",
                   "micro", "roofline_ledger"}

# Approximate HBM bandwidth by device kind, for roofline fractions in the
# report (sources: public TPU specs; v5e ~819 GB/s, v4 ~1228 GB/s).
HBM_GBS = {"TPU v5 lite": 819.0, "TPU v5": 819.0, "TPU v4": 1228.0,
           "TPU v5p": 2765.0, "TPU v6 lite": 1640.0}


def _load_baseline() -> dict:
    if BASELINE_PATH.exists():
        data = json.loads(BASELINE_PATH.read_text())
        data["source"] = "tools/cpu_baseline.json"
        return data
    return {"tokens_per_sec": None, "source": "missing"}


def _device_kind():
    import jax
    return jax.devices()[0].device_kind


def _hbm_limit_bytes():
    """Per-device HBM capacity if the backend exposes it, else None."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
        return stats.get("bytes_limit")
    except Exception:
        return None


def _with_bandwidth(result: dict, weights_bytes: int, device: str) -> dict:
    """Annotate a decode result with achieved HBM GB/s and roofline frac.

    Decode is weight-streaming-bound: every step reads all weights once,
    so achieved_gbs = weights_bytes * steps/s is a lower bound on HBM
    traffic actually sustained (cache reads add more)."""
    tps = result.get("decode_tokens_per_sec")
    batch = result.get("batch")
    if not tps or not batch:
        return result
    steps_per_sec = tps / batch
    gbs = weights_bytes * steps_per_sec / 1e9
    result["weights_gb"] = round(weights_bytes / 1e9, 3)
    result["achieved_gbs"] = round(gbs, 1)
    roof = HBM_GBS.get(device)
    if roof:
        result["hbm_roofline_frac"] = round(gbs / roof, 3)
        result["hbm_gbs_assumed"] = roof
    return result


def measured_ceiling(roofline: dict, probe_history=None):
    """The session's measured HBM ceiling: max of the roofline leg's
    best round and every per-leg health probe
    (tools/measure_session.py records those in ``probe_history``).
    ONE owner shared by the incremental session and the monolithic
    end-of-round run — the r04 artifact's headline beat its own single
    'measured ceiling' because that probe ran through a degraded
    tunnel."""
    cands = [(roofline or {}).get("hbm_read_gbs")]
    cands += [p.get("hbm_gbs") for p in probe_history or []
              if isinstance(p, dict)]
    cands = [c for c in cands if c]
    return round(max(cands), 1) if cands else None


# -- persistent best-ever roofline ledger (docs/DESIGN.md §9/§13) ----------
# Committed JSON keyed by device kind.  Session probes measure the
# TUNNEL's mood as much as the chip (r05: probes 168-312 GB/s while the
# headline workload sustained 526.9); the ledger persists the best
# evidence EVER seen for the chip, so one degraded session can no longer
# manufacture a "ceiling" every real workload beats.

ROOFLINE_LEDGER_PATH = REPO / "ROOFLINE_LEDGER.json"


def load_roofline_ledger(device=None):
    """The committed ledger dict, or one device's entry (None if
    absent/unreadable — a missing ledger degrades to session-only
    ceilings, never an error)."""
    try:
        data = json.loads(ROOFLINE_LEDGER_PATH.read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        data = {}
    if not isinstance(data, dict):
        data = {}
    if device is None:
        return data
    entry = data.get(device)
    return entry if isinstance(entry, dict) else None


def update_roofline_ledger(device, gbs, source: str) -> bool:
    """Raise ``device``'s best-ever HBM number (monotone max — the
    ledger only ever improves, so a degraded-tunnel session can never
    LOWER the declared ceiling).  Returns True when the file changed;
    callers that commit artifacts commit the ledger alongside."""
    if not device or not gbs:
        return False
    data = load_roofline_ledger()
    cur = data.get(device)
    best = cur.get("hbm_gbs", 0) if isinstance(cur, dict) else 0
    if best >= gbs:
        return False
    data[device] = {
        "hbm_gbs": round(float(gbs), 1), "source": source,
        "updated": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    ROOFLINE_LEDGER_PATH.write_text(
        json.dumps(data, indent=1, sort_keys=True) + "\n")
    return True


def declared_ceiling(device, session_gbs):
    """THE ceiling decode legs are judged against:
    ``max(session probes, committed ledger)``.  Returns
    ``(ceiling_or_None, ledger_gbs_or_None)``."""
    entry = load_roofline_ledger(device)
    ledger = entry.get("hbm_gbs") if entry else None
    cands = [c for c in (session_gbs, ledger) if c]
    return (round(max(cands), 1) if cands else None), ledger


def apply_measured_frac(leg, ceiling, device=None) -> None:
    """Annotate a decode leg with achieved/declared-ceiling.  A
    ``frac_measured > 1`` is STRUCTURALLY IMPOSSIBLE: a leg that beats
    the declared ceiling has itself measured a higher sustainable HBM
    rate (achieved_gbs is real weight-stream traffic, a lower bound),
    so the ledger is RAISED to the achieved number, the leg reports
    frac 1.0, and the raise is stamped — no more r05-class 1.691
    "fractions" that are actually apologies for degraded probes."""
    if isinstance(leg, dict) and leg.get("achieved_gbs") and ceiling:
        frac = round(leg["achieved_gbs"] / ceiling, 3)
        leg.pop("ceiling_suspect", None)       # pre-r06 name
        leg.pop("probe_inconsistent", None)    # r06 pre-ledger name
        if frac > 1.0:
            dev = device or leg.get("device")
            update_roofline_ledger(
                dev, leg["achieved_gbs"],
                source=f"achieved_gbs of a decode leg "
                       f"({leg.get('model', '?')} b{leg.get('batch', '?')}"
                       f" {leg.get('dtype', '?')}): weight-stream lower "
                       "bound sustained by a real workload")
            leg["hbm_roofline_frac_measured"] = 1.0
            leg["ledger_raised"] = (
                f"achieved {leg['achieved_gbs']} GB/s exceeded the "
                f"declared ceiling ({ceiling} GB/s): the workload IS the "
                "better bandwidth measurement, so the roofline ledger "
                "was raised to it (frac > 1 is impossible by "
                "construction)")
        else:
            leg["hbm_roofline_frac_measured"] = frac
            leg.pop("ledger_raised", None)


def apply_declared_ceiling(headline, extras, device, session, source,
                           skip_headline: bool = False):
    """One owner for the declared-ceiling judgement, shared by bench
    ``main()`` and ``tools/measure_session.merge``: raise the committed
    ledger to the session probe max, declare ``max(session, ledger)``,
    stamp the provenance into ``extras['roofline_ledger']``, and apply
    the measured fraction to every leg that reports ``achieved_gbs``
    (headline, int8/flagship legs, sweep points, int4 sub-legs).

    ``skip_headline``: the headline dict belongs to a DIFFERENT session
    (bench's prior-headline substitution) — its fraction must keep that
    session's ceiling, not this run's.  Returns the declared ceiling, or
    None when neither the session nor the ledger has evidence."""
    if session:
        update_roofline_ledger(device, session, source=source)
    measured, ledger = declared_ceiling(device, session)
    if not measured:
        return None
    extras["measured_ceiling_gbs"] = measured
    # provenance stamp: which side of max() declared this ceiling
    extras["roofline_ledger"] = {
        "device": device, "session_probe_gbs": session,
        "ledger_gbs": ledger, "declared_ceiling_gbs": measured,
        "path": ROOFLINE_LEDGER_PATH.name}
    if not skip_headline:
        apply_measured_frac(headline, measured, device)
    for key in ("headline_int8", "flagship_int8", "flagship_bf16"):
        apply_measured_frac(extras.get(key, {}) or {}, measured, device)
    for pt in (extras.get("sweep", {}) or {}).get("points", []):
        apply_measured_frac(pt, measured, device)
    for sub in (extras.get("int4", {}) or {}).values():
        apply_measured_frac(sub, measured, device)
    return measured


def _bench_engine(model: str, batch: int, prompt_len: int, new_tokens: int,
                  quant=False, latency: bool = False) -> dict:
    """Single-chip decode + prefill throughput via InferenceEngine.
    ``quant``: False | True (int8) | "int8" | "int4".  ``latency`` adds
    per-request TTFT/TPOT percentiles (one extra compiled program — the
    streamed step — so only the headline legs pay for it)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    mode = "int8" if quant is True else quant
    name = model + (f"-{mode}" if mode else "")
    cfg = get_model_config(name)
    # layer-chunked init+quantize: peak HBM stays near the int8 footprint
    # instead of materializing the float tree first (which would OOM exactly
    # the chips int8 exists to fit on) — models/decoder.py:_init_quantized
    params = init_full_params(jax.random.PRNGKey(0), cfg,
                              quantize=bool(mode))
    engine = InferenceEngine(
        cfg, params, max_seq=prompt_len + new_tokens,
        sampling=SamplingParams(temperature=0.7, top_k=7))  # ref defaults

    prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
              % 1000).astype(np.int32)
    engine.generate(prompt, new_tokens, seed=0)           # compile warmup
    result = engine.generate(prompt, new_tokens, seed=0)  # steady state
    decode_tps = result.tokens_per_second

    # prefill throughput: best of 3 single dispatches on fresh caches
    # (np.asarray as the fence — axon's block_until_ready returns early,
    # see _leg_prefill_long).  One dispatch is maximally exposed to
    # tunnel jitter — the r04 artifact's 2.8x prefill "regression" vs
    # r03 was a single sample taken while the tunnel was degrading; the
    # per-round list makes that failure mode visible in the artifact.
    rounds = []
    for _ in range(3):
        cache = engine.new_cache(batch)           # fresh, outside timing
        t0 = time.perf_counter()
        logits, cache = engine._prefill(engine.params, prompt, cache)
        np.asarray(logits)
        rounds.append(time.perf_counter() - t0)
    prefill_tps = batch * prompt_len / min(rounds)

    out = {
        "model": name,
        "decode_tokens_per_sec": round(decode_tps, 2),
        # per decode STEP (the fused scan advances the whole batch one
        # position per step, so steps/s = tok/s / batch) — the number the
        # large-batch roofline-erosion analysis decomposes: cache-read
        # bytes grow with batch while weight bytes stay fixed
        "decode_step_ms": round(1000.0 * batch / decode_tps, 3),
        "prefill_tokens_per_sec": round(prefill_tps, 2),
        "prefill_round_ms": [round(r * 1000, 1) for r in rounds],
        "batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens,
        "dtype": mode if mode else cfg.dtype_name,
    }
    if latency:
        out["latency"] = _latency_percentiles(engine, prompt[:1],
                                              min(new_tokens, 16))
    out = _with_bandwidth(out, params.nbytes(), _device_kind())
    # cache-READ traffic estimate per second: each decode step attends
    # the whole valid context, so cache bytes grow linearly with batch
    # while the weight stream stays fixed — the decomposition behind the
    # large-batch roofline erosion (achieved_gbs counts weights only)
    kv_bytes_per_pos = (cfg.num_layers * 2 * cfg.num_kv_heads
                        * cfg.head_dim
                        * (engine.kv_cache_dtype or cfg.dtype).itemsize)
    avg_ctx = prompt_len + new_tokens / 2
    steps_per_sec = decode_tps / batch
    out["cache_read_gbs_est"] = round(
        batch * avg_ctx * kv_bytes_per_pos * steps_per_sec / 1e9, 1)
    if out.get("achieved_gbs"):
        out["total_gbs_est"] = round(
            out["achieved_gbs"] + out["cache_read_gbs_est"], 1)
    return out


def _latency_percentiles(engine, prompt, new_tokens: int,
                         requests: int = 8) -> dict:
    """Per-request TTFT/TPOT p50/p95/p99 over ``requests`` sequential
    single-row STREAMED generations (the SLO view of the same engine the
    throughput numbers describe: TTFT = prefill + first streamed step,
    TPOT = mean inter-token gap per request).  Feeds the
    ``BENCH_SELF_*.json`` perf trajectory so latency regressions show up
    per PR, not just tok/s."""
    from distributed_inference_demo_tpu.runtime.stats import _percentile

    ttfts, tpots = [], []
    for i in range(requests):
        t0 = time.perf_counter()
        t_first = t_last = None
        n = 0
        for _ in engine.generate_stream(prompt, new_tokens, seed=i):
            t_last = time.perf_counter()
            if t_first is None:
                t_first = t_last
            n += 1
        if t_first is None:
            continue
        if i == 0:
            # first request compiles the streamed step: warmup, not data
            continue
        ttfts.append(t_first - t0)
        if n > 1:
            tpots.append((t_last - t_first) / (n - 1))
    out = {"requests": len(ttfts), "new_tokens": new_tokens}
    for name, xs in (("ttft", ttfts), ("tpot", tpots)):
        xs = sorted(xs)
        for q in (50, 95, 99):
            out[f"{name}_p{q}_ms"] = (
                round(_percentile(xs, q) * 1e3, 3) if xs else None)
    return out


def _weights_bytes_estimate(model: str) -> int:
    """Host-side parameter-count estimate (no device allocation)."""
    from distributed_inference_demo_tpu.models import get_model_config
    cfg = get_model_config(model)
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = H * nh * hd + 2 * H * nkv * hd + nh * hd * H
    mlp = 3 * H * I if cfg.family != "bloom" else 2 * H * I
    if cfg.num_experts:
        mlp *= cfg.num_experts
    per_layer = attn + mlp
    embed = cfg.vocab_size * H * (1 if cfg.tie_embeddings else 2)
    if cfg.quantization == "int8":
        bpp = 1.0
    elif cfg.quantization == "int4":
        # 2 weights/byte + f32 group scales (ops/quant.DEFAULT_INT4_GROUP)
        bpp = 0.5 + 4.0 / 64
    else:
        bpp = jnp_bytes(cfg.dtype_name)
    # embeddings/head stay at the model dtype even under quantization
    return int(L * per_layer * bpp) + embed * jnp_bytes(cfg.dtype_name)


def jnp_bytes(dtype_name: str) -> int:
    import numpy as np
    return np.dtype(dtype_name if dtype_name != "bfloat16" else "uint16").itemsize


# fallback HBM capacity by device kind when the backend exposes no
# memory_stats (the axon tunnel doesn't)
HBM_CAP_GB = {"TPU v5 lite": 16.0, "TPU v5": 16.0, "TPU v4": 32.0,
              "TPU v5p": 95.0, "TPU v6 lite": 32.0}


def _leg_flagship(model: str, batch: int, prompt_len: int, new_tokens: int,
                  quant) -> dict:
    mode = "int8" if quant is True else quant
    name = model + (f"-{mode}" if mode else "")
    need = _weights_bytes_estimate(name)
    limit = _hbm_limit_bytes()
    if limit is None:
        cap = HBM_CAP_GB.get(_device_kind())
        limit = cap * 1e9 if cap else None
    if limit and need > limit * 0.92:  # leave room for cache + compiled code
        return {"model": name,
                "skipped": f"does not fit: ~{need / 1e9:.1f} GB weights vs "
                           f"{limit / 1e9:.1f} GB HBM"}
    return _bench_engine(model, batch, prompt_len, new_tokens, quant=quant)


def _bench_batching_kv(model: str, batch: int, prompt_len: int,
                       new_tokens: int, quant=False,
                       kv_dtype: str = "bf16") -> dict:
    """One (weight-dtype x kv-dtype) sweep point on the paged-native
    batching engine.  The kv-dtype axis can only be measured HERE: the
    plain engine's dense working cache never touches the page pool, so
    threading ``kv_dtype`` through ``_bench_engine`` would time a no-op
    (docs/DESIGN.md §17)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    mode = "int8" if quant is True else quant
    name = model + (f"-{mode}" if mode else "")
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(0), cfg,
                              quantize=bool(mode))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1000, size=(prompt_len,)).astype(np.int32)
               for _ in range(batch)]
    with ContinuousBatchingEngine(
            cfg, params, max_seq=prompt_len + new_tokens, max_batch=batch,
            sampling=SamplingParams(temperature=0.7, top_k=7),
            kv_layout="paged", kv_dtype=kv_dtype) as eng:
        eng.submit(prompts[0], 4).wait(timeout=600)       # compile warmup
        eng.submit(prompts[-1], 4).wait(timeout=600)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, new_tokens) for p in prompts]
        for r in reqs:
            r.wait(timeout=900)
        dt = time.perf_counter() - t0
        mgr = eng.kv_cache
        return {
            "model": name, "engine": "batching-paged",
            "kv_dtype": kv_dtype, "batch": batch,
            "prompt_len": prompt_len, "new_tokens": new_tokens,
            "decode_tokens_per_sec": round(batch * new_tokens / dt, 2),
            "block_bytes": int(mgr.block_bytes),
            "pool_capacity_bytes": int(eng._pk.nbytes + eng._pv.nbytes),
        }


def _leg_sweep(model: str, prompt_len: int, new_tokens: int,
               quants=(False, True), batches=(32, 64),
               kv_dtypes=()) -> dict:
    """Batch sweep at bf16 and int8 with achieved GB/s per point.
    Points are isolated: one OOMing batch size must not discard the rest.
    (b=8 is omitted — the headline/headline_int8 legs already cover it —
    to keep total bench wall-clock inside the driver's window.)

    ``kv_dtypes`` adds the §17 weight-dtype x kv-dtype cross at the
    largest batch, measured on the paged batching engine (the only
    engine whose decode reads the page pool): one point per
    (quant, kv_dtype) pair in ``kv_points``."""
    points = []
    for quant in quants:
        for batch in batches:
            try:
                points.append(_bench_engine(model, batch, prompt_len,
                                            new_tokens, quant=quant))
            except Exception as e:
                points.append({"model": model, "batch": batch,
                               "dtype": "int8" if quant else "bf16",
                               "error": f"{type(e).__name__}: {e}"})
    out = {"points": points}
    if kv_dtypes:
        kv_points = []
        batch = max(batches)
        for quant in quants:
            for kvd in kv_dtypes:
                try:
                    kv_points.append(_bench_batching_kv(
                        model, batch, prompt_len, new_tokens,
                        quant=quant, kv_dtype=kvd))
                except Exception as e:
                    mode = "int8" if quant is True else quant
                    kv_points.append({
                        "model": model + (f"-{mode}" if mode else ""),
                        "batch": batch, "kv_dtype": kvd,
                        "error": f"{type(e).__name__}: {e}"})
        out["kv_points"] = kv_points
    return out


def _leg_roofline_probe(reps: int = 32, rounds_n: int = 3) -> dict:
    """Measure THIS chip's achievable ceilings (one dispatch each; the
    axon tunnel adds ~9 ms per dispatch, so loops run on device):

    - ``hbm_read_gbs``: pure-HBM read bandwidth (1 GiB reduce x32).
    - ``dispatch_floor_ms``: per-call tunnel/dispatch latency (tiny op).

    Decode tok/s legs report roofline fractions against BOTH the paper
    spec and this measured ceiling — on the round-3 bench chip the
    measured ceiling was ~505 GB/s vs the 819 GB/s v5e paper number,
    i.e. the 'missing' roofline fraction was spec-vs-silicon, not the
    decode program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    big = jnp.ones((1 << 29,), jnp.bfloat16)   # 1 GiB

    @jax.jit
    def red_many(x):
        # the scan input feeds each read so the reduce is NOT
        # loop-invariant (LICM would otherwise hoist it and inflate the
        # reported bandwidth 32x)
        def rep(acc, j):
            return acc + jnp.sum((x + j).astype(jnp.float32)), None
        acc, _ = jax.lax.scan(rep, 0.0, jnp.arange(reps, dtype=x.dtype))
        return acc

    float(red_many(big))                        # compile
    # best-of-3: the tunnel's effective bandwidth varies run to run
    # (132 vs 505 GB/s observed) — the MAX is the ceiling, the spread is
    # reported so roofline fractions can be read with due suspicion
    rounds = []
    for _ in range(rounds_n):
        t0 = time.perf_counter()
        s = red_many(big)
        float(s)
        rounds.append(big.nbytes * reps / (time.perf_counter() - t0) / 1e9)
    hbm = max(rounds)
    ordered = sorted(rounds)
    median = ordered[len(ordered) // 2]

    @jax.jit
    def tiny(x):
        return x + 1.0

    float(tiny(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(8):
        y = tiny(jnp.float32(0))
    float(y)
    floor_ms = (time.perf_counter() - t0) / 8 * 1000

    return {"hbm_read_gbs": round(hbm, 1),
            "hbm_read_gbs_min": round(min(rounds), 1),
            "hbm_read_gbs_median": round(median, 1),
            "hbm_read_gbs_rounds": [round(r, 1) for r in rounds],
            "dispatch_floor_ms": round(floor_ms, 2)}


def _leg_prefill_long(model: str, seqs=(2048, 8192)) -> dict:
    """Long-prompt prefill: Pallas flash kernel vs jnp attention.

    >= 100k tokens of work per measurement; this is where the L1 kernel
    story must show up in an artifact (decode chunks route to the XLA path
    by design — make_flash_attn_impl min_chunk)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    out = {"model": model, "points": []}
    # 4096 omitted: two more multi-minute tunnel compiles for a point
    # between the two endpoints (r3 measured flash 1.17x there)
    for seq in seqs:
        # small batch x long prompt: the long-context serving shape (and
        # where flash's causal block-skipping matters); reps make up the
        # >=128k tokens of measured work
        batch = 8
        point = {"prompt_len": seq, "batch": batch}
        for backend in ("flash", "jnp"):
            try:
                engine = InferenceEngine(cfg, params, max_seq=seq,
                                         attn_backend=backend)
                prompt = (np.arange(batch * seq).reshape(batch, seq)
                          % 1000).astype(np.int32)
                cache = engine.new_cache(batch)
                logits, _ = engine._prefill(engine.params, prompt, cache)
                np.asarray(logits)             # compile warmup, hard sync
                reps = max(2, 131072 // (batch * seq))
                t0 = time.perf_counter()
                for _ in range(reps):
                    cache = engine.new_cache(batch)
                    logits, cache = engine._prefill(engine.params, prompt,
                                                    cache)
                # np.asarray, not block_until_ready: the experimental axon
                # platform returns from block_until_ready before the device
                # finishes, inflating tok/s ~2000x; a host transfer is the
                # only trustworthy fence there.
                np.asarray(logits)
                dt = (time.perf_counter() - t0) / reps
                point[backend + "_tokens_per_sec"] = round(
                    batch * seq / dt, 1)
            except Exception as e:  # per-point, per-backend isolation
                point[backend + "_error"] = (
                    f"{type(e).__name__}: {e}"[:300])
        if ("flash_tokens_per_sec" in point
                and "jnp_tokens_per_sec" in point):
            point["flash_speedup"] = round(
                point["flash_tokens_per_sec"]
                / point["jnp_tokens_per_sec"], 3)
        out["points"].append(point)
    return out


def _leg_long_context(model: str) -> dict:
    """Single-chip long-context generation at 32k tokens: chunked prefill
    (ONE compiled 2048-token chunk shape regardless of prompt length,
    bounding activation memory) + flash attention + KV-cached decode at
    full context.  The sequence-parallel strategies (ring / Ulysses)
    cover contexts beyond one chip and are certified by the multichip
    dryrun's engine-parity checks; this leg is the real-hardware
    long-context number (SURVEY §5.7 — absent in the reference, whose
    max_length was 40)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    ctx = int(os.environ.get("BENCH_LONG_CTX", "32768"))
    new, chunk = 64, min(2048, ctx // 2)
    plen = ctx - new
    engine = InferenceEngine(cfg, params, max_seq=ctx,
                             sampling=SamplingParams(greedy=True),
                             prefill_chunk=chunk)
    prompt = (np.arange(plen) % 1000).astype(np.int32)[None, :]

    import jax.numpy as jnp

    engine.generate(prompt, new, seed=0)            # compile warmup
    cache = engine.new_cache(1)
    t0 = time.perf_counter()
    logits, cache = engine._run_prefill(jnp.asarray(prompt), cache)
    np.asarray(logits)                               # hard fence
    prefill_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    toks, _, _ = engine._decode(engine.params, logits, cache,
                                jax.random.PRNGKey(0),
                                engine._eos_scalar(), new, False)
    np.asarray(toks)
    decode_s = time.perf_counter() - t0
    return {
        "model": model, "batch": 1, "context": ctx, "prompt_len": plen,
        "new_tokens": new, "prefill_chunk": chunk,
        "attn_backend": engine.attn_backend,
        "prefill_tokens_per_sec": round(plen / prefill_s, 1),
        "decode_tokens_per_sec": round(new / decode_s, 2),
    }


def _leg_decode_fused(model: str, prompt_len: int, new_tokens: int,
                      batches=(1, 8), blocks=(1, 4, 16)) -> dict:
    """The device-resident decode loop (docs/DESIGN.md §13): streamed
    decode tok/s + MEASURED host dispatches/token at batch x
    stream_block K.  K=1 is the per-token path — its dispatches/token
    is exactly 1 and its tok/s exposes the host dispatch floor
    (BENCH_SELF_r05: 15.31 ms/dispatch vs a ~4.2 ms decode step); the
    K>1 points show the floor amortizing as dispatches/token ≈ 1/K.
    Greedy-bit-identity across K is pinned by tier-1 tests; this leg
    measures only speed."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    out = {"model": model, "prompt_len": prompt_len,
           "new_tokens": new_tokens, "points": []}
    for batch in batches:
        prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
                  % 1000).astype(np.int32)
        for K in blocks:
            try:
                engine = InferenceEngine(
                    cfg, params, max_seq=prompt_len + new_tokens,
                    sampling=SamplingParams(temperature=0.7, top_k=7),
                    stream_block=K)
                for _ in engine.generate_stream(prompt, new_tokens,
                                                seed=0):
                    pass                        # compile warmup
                engine.loop_stats = {"host_dispatches": 0,
                                     "device_loop_steps": 0}
                t_first = t_last = None
                n = 0
                for _ in engine.generate_stream(prompt, new_tokens,
                                                seed=0):
                    t_last = time.perf_counter()
                    if t_first is None:
                        t_first = t_last
                    n += 1
                point = {"batch": batch, "stream_block": K, "tokens": n,
                         **engine.loop_stats}
                point["dispatches_per_token"] = round(
                    engine.loop_stats["host_dispatches"] / max(n, 1), 4)
                if n > 1:
                    point["decode_tokens_per_sec"] = round(
                        batch * (n - 1) / (t_last - t_first), 2)
                out["points"].append(point)
            except Exception as e:   # per-point isolation
                out["points"].append({"batch": batch, "stream_block": K,
                                      "error": f"{type(e).__name__}: "
                                               f"{e}"[:300]})
    best = [p.get("decode_tokens_per_sec") for p in out["points"]
            if p.get("decode_tokens_per_sec")]
    if best:
        out["best_decode_tokens_per_sec"] = max(best)
    return out


def _leg_pipeline(model: str, batch: int, prompt_len: int,
                  new_tokens: int) -> dict:
    """2-process socket pipeline: this process (default backend — the TPU
    when present) is the header, a spawned CPU process is the tail.
    Inter-shard activation latency is derived per token as
    ``(ring RTT - tail compute p50) / 2`` — the RTT covers exactly two
    socket hops (hidden out, token back) around the tail's compute."""
    import numpy as np
    import jax
    from distributed_inference_demo_tpu.comm.transport import ZmqTransport
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import (
        slice_stage, split_layer_ranges)
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.distributed import (
        PipelineHeader, StageRuntime)

    cfg = get_model_config(model)
    specs = split_layer_ranges(cfg.num_layers, 2)
    max_seq = prompt_len + new_tokens
    sampling = SamplingParams(temperature=0.7, top_k=7)

    header_transport = ZmqTransport("header")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_inference_demo_tpu.runtime.worker_main",
         "--model", model, "--stage-id", "1", "--num-stages", "2",
         "--layer-start", str(specs[1].layer_start),
         "--layer-end", str(specs[1].layer_end),
         "--device-id", "w1", "--port", "0",
         "--header", f"header@{header_transport.address}",
         "--max-seq", str(max_seq), "--dtype", "float32",
         "--temperature", "0.7", "--top-k", "7",
         "--step-timeout", "600"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=str(REPO))
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("WORKER_READY w1 "), line
        header_transport.connect("w1", line.split()[-1])

        full = init_full_params(jax.random.PRNGKey(0), cfg)
        header = PipelineHeader(
            StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                         max_seq, sampling),
            header_transport, next_id="w1", step_timeout=600)
        prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
                  % 1000).astype(np.int32)
        header.generate(prompt, 4)          # warmup/compile
        header.reset_stats()
        t0 = time.perf_counter()
        header.generate(prompt, new_tokens)
        dt = time.perf_counter() - t0
        stats = header.collect_stats(num_stages=2, timeout=30)
        # dynamic-batching phase: the same 4 requests serialized vs
        # interleaved (pool_size rids in flight — the serve --pool-size
        # capability measured on the live 2-process pipeline; prompt
        # shapes match the warmup so no new compiles)
        pool_pts = {}
        for pool in (1, 4):
            t1 = time.perf_counter()
            header.generate_many([prompt] * 4, new_tokens, pool_size=pool)
            pool_pts[f"pool{pool}_tokens_per_sec"] = round(
                4 * batch * new_tokens / (time.perf_counter() - t1), 2)
        header.shutdown_pipeline()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
        header_transport.close()

    h = stats[0]
    tail = stats[1] if len(stats) > 1 else {}
    tail_p50 = tail.get("compute_p50_ms", 0.0)
    out = {
        "model": model, "batch": batch, "num_stages": 2,
        # per-step dispatch to a TUNNELED header device (~10 ms/call)
        # dominates this tok/s; the framework's own cost is the
        # activation_hop percentiles below (BASELINE config #2's metric)
        "note": "tokens_per_sec is tunnel-dispatch-bound when the header "
                "runs on the tunneled TPU; activation_hop_* is the "
                "framework metric",
        "pipeline_tokens_per_sec": round(batch * new_tokens / dt, 2),
        "dynamic_batching_4req": dict(
            pool_pts,
            speedup=round(pool_pts["pool4_tokens_per_sec"]
                          / pool_pts["pool1_tokens_per_sec"], 3)),
        "ring_rtt_p50_ms": h.get("ring_rtt_p50_ms"),
        "ring_rtt_p95_ms": h.get("ring_rtt_p95_ms"),
        "tail_compute_p50_ms": tail_p50,
        "stage_stats": stats,
    }
    _paired_hop_percentiles(h, tail, out)
    return out


class _LineReader:
    """Reads a subprocess's stdout on a daemon thread into a queue, so
    waits can time out reliably.  (select() on the pipe fd is wrong with a
    buffered TextIOWrapper: readline() may pull several lines into the
    Python buffer, leaving the fd empty while the awaited line sits
    buffered; blocking readline() can't time out at all.)"""

    _EOF = object()

    def __init__(self, proc):
        import queue
        import threading
        self.proc = proc
        self.q: "queue.Queue" = queue.Queue()

        def pump():
            for line in proc.stdout:
                self.q.put(line)
            self.q.put(self._EOF)   # death declared only past this marker

        threading.Thread(target=pump, daemon=True).start()

    def read_until(self, prefix: str, timeout: float = 300.0) -> str:
        import queue
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise RuntimeError(f"{prefix!r} not seen within {timeout}s")
            try:
                line = self.q.get(timeout=min(left, 0.5))
            except queue.Empty:
                continue
            if line is self._EOF:
                # the pump drained every line the process ever wrote (no
                # poll/queue race): it is gone and the line never came
                raise RuntimeError(
                    f"process exited (rc={self.proc.poll()}) without "
                    f"printing {prefix!r}")
            line = line.strip()
            if line.startswith(prefix):
                return line


def _paired_hop_percentiles(header_stats: dict, tail_stats: dict,
                            out: dict) -> None:
    """Per-hop activation latency from PAIRED per-step samples: with one
    request in flight, header rtt sample i and tail compute sample i are
    the same token step, so (rtt_i - compute_i)/2 cancels the tail's
    compute variance (aggregate p50s can't — a slow CPU tail's jitter
    swamps the hop and clamps the estimate to 0)."""
    rtts = header_stats.get("rtt_samples_ms") or []
    comps = tail_stats.get("compute_samples_ms") or []
    n = min(len(rtts), len(comps))
    if n:
        hops = sorted(max(0.0, (r - c) / 2)
                      for r, c in zip(rtts[-n:], comps[-n:]))
        out["activation_hop_p50_ms"] = round(hops[n // 2], 3)
        out["activation_hop_p95_ms"] = round(
            hops[min(n - 1, int(0.95 * n))], 3)


def _leg_speculative(model: str, batch: int, prompt_len: int,
                     new_tokens: int) -> dict:
    """Speculative decoding vs plain decode on the SAME workload.

    Without real weights, the draft is the int8 quantization of the SAME
    seed-init target (identical PRNGKey -> identical float tree ->
    quantized): a faithful cheap approximation of the target, so greedy
    acceptance measures real argmax agreement and the draft's cost is
    genuinely about half the target's HBM stream.  Acceptance on real
    checkpoints is a weights property; this leg pins the MECHANICS
    (round cost, speedup at the measured acceptance)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                        SpeculativeEngine)
    from distributed_inference_demo_tpu.runtime.speculative import stats_json

    cfg = get_model_config(model)
    draft_cfg = get_model_config(model + "-int8")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_full_params(jax.random.PRNGKey(0), draft_cfg,
                                    quantize=True)
    sampling = SamplingParams(greedy=True)
    max_seq = prompt_len + new_tokens
    prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
              % 1000).astype(np.int32)

    engine = InferenceEngine(cfg, params, max_seq=max_seq, sampling=sampling)
    engine.generate(prompt, new_tokens, seed=0)            # compile
    plain = engine.generate(prompt, new_tokens, seed=0)

    num_draft = 4
    spec = SpeculativeEngine(cfg, params, draft_cfg, draft_params,
                             max_seq=max_seq, sampling=sampling,
                             num_draft=num_draft)
    spec.generate(prompt, new_tokens, seed=0)              # compile
    res, stats = spec.generate(prompt, new_tokens, seed=0)

    return {
        "model": model, "draft": model + "-int8 (same seed weights)",
        "batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens,
        "sampling": "greedy",
        "plain_tokens_per_sec": round(plain.tokens_per_second, 2),
        "spec_tokens_per_sec": round(res.tokens_per_second, 2),
        "speedup": round(res.tokens_per_second
                         / plain.tokens_per_second, 3),
        "spec_stats": stats_json(stats, num_draft),
    }


def _leg_prompt_lookup(model: str, new_tokens: int) -> dict:
    """Prompt-lookup (draft-free) speculation vs plain decode, batch 1.

    The prompt is a REPEATED n-gram block — the shape PLD exists for
    (quotes, code identifiers, summarization).  Whether the model's
    greedy continuation re-uses context spans is a weights property;
    seed-init weights are adversarial for acceptance, so the leg's
    value is the mechanics cost (rounds/s, speedup at the measured
    acceptance), not an acceptance ceiling."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    from distributed_inference_demo_tpu.runtime.prompt_lookup import (
        PromptLookupEngine)
    from distributed_inference_demo_tpu.runtime.speculative import stats_json

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(greedy=True)
    prompt_len = 128
    max_seq = prompt_len + new_tokens
    block = np.arange(16) * 37 % 1000              # one 16-token motif
    prompt = np.tile(block, prompt_len // 16)[None, :].astype(np.int32)

    engine = InferenceEngine(cfg, params, max_seq=max_seq, sampling=sampling)
    engine.generate(prompt, new_tokens, seed=0)            # compile
    plain = engine.generate(prompt, new_tokens, seed=0)

    num_draft = 4
    pld = PromptLookupEngine(cfg, params, max_seq=max_seq,
                             sampling=sampling, num_draft=num_draft)
    pld.generate(prompt, new_tokens, seed=0)               # compile
    res, stats = pld.generate(prompt, new_tokens, seed=0)

    return {
        "model": model, "batch": 1, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "sampling": "greedy",
        "prompt_shape": "16-token motif tiled x8",
        "plain_tokens_per_sec": round(plain.tokens_per_second, 2),
        "pld_tokens_per_sec": round(res.tokens_per_second, 2),
        "speedup": round(res.tokens_per_second
                         / plain.tokens_per_second, 3),
        "spec_stats": stats_json(stats, num_draft),
    }


def _leg_batching(model: str, prompt_len: int, new_tokens: int) -> dict:
    """Continuous batching aggregate throughput + automatic prefix cache.

    Phase A: 24 distinct-prompt requests submitted at once into 8 slots
    (aggregate tok/s with slot churn — admissions interleave with decode
    steps).  The plain-engine comparison runs the same 24 requests as 3
    sequential batch-8 ``generate`` calls on the same weights.
    Phase B: 8 requests sharing a long prefix — reports the prefix
    cache's hit/reuse counters and its aggregate tok/s."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(temperature=0.7, top_k=7)
    slots, n_req = 8, 24
    # covers phase B's 128-token prompts even when BENCH_PROMPT is small
    max_seq = max(prompt_len, 128) + new_tokens
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, 1000, size=(n_req, prompt_len)).astype(
        np.int32)

    plain = InferenceEngine(cfg, params, max_seq=max_seq, sampling=sampling)
    plain.generate(prompts[:slots], new_tokens, seed=0)    # compile
    t0 = time.perf_counter()
    for i in range(0, n_req, slots):
        plain.generate(prompts[i:i + slots], new_tokens, seed=0)
    plain_dt = time.perf_counter() - t0
    plain_tps = n_req * new_tokens / plain_dt

    out = {"model": model, "slots": slots, "requests": n_req,
           "prompt_len": prompt_len, "new_tokens": new_tokens,
           "plain_sequential_tokens_per_sec": round(plain_tps, 2)}

    with ContinuousBatchingEngine(
            cfg, params, max_seq=max_seq, max_batch=slots,
            sampling=sampling,
            # default pool (B x table_width): the dense-era explicit 64
            # blocks sized a PREFIX cache; on the paged-native scheduler
            # the pool IS the decode cache and 64 blocks would make page
            # pressure, not batching, the measured bottleneck
            kv_block_tokens=16) as eng:
        # warmups cover EVERY compile either timed phase can reach:
        # (a) sub-16-token prompt: step + admit + zero_row + bucket 32,
        #     without polluting the block cache (below one block);
        # (b) a 128-token throwaway: bucket 128 (also stores its blocks);
        # (c) (b)'s prefix + fresh tail: the block-HIT path
        #     (_load_prefix + suffix bucket) — phase B's steady state
        warm = rng.integers(0, 1000, size=(128,)).astype(np.int32)
        eng.submit(warm[:8], 4).wait(timeout=600)
        eng.submit(warm, 4).wait(timeout=600)
        eng.submit(np.concatenate([
            warm[:96], rng.integers(0, 1000, size=(32,))]).astype(np.int32),
            4).wait(timeout=600)
        # (d) a phase-A-shaped prompt, so ITS bucket is compiled even when
        #     BENCH_PROMPT lands past 128 (stores one random prompt's
        #     blocks; phase A's random prompts can't hit them — the
        #     common prefix stays below one block)
        eng.submit(rng.integers(0, 1000, size=(prompt_len,)).astype(
            np.int32), 4).wait(timeout=600)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, new_tokens) for p in prompts]
        for r in reqs:
            r.wait(timeout=900)
        dt = time.perf_counter() - t0
        out["batching_tokens_per_sec"] = round(n_req * new_tokens / dt, 2)
        out["vs_plain_sequential"] = round(
            (n_req * new_tokens / dt) / plain_tps, 3)

        # Phase B: shared 96-token prefix (6 whole 16-token blocks),
        # distinct 32-token tails (the bucket layout keeps prompt_len
        # at 128)
        base = dict(eng.kv_cache.stats)
        shared = rng.integers(0, 1000, size=(96,))
        pre_prompts = [np.concatenate([
            shared, rng.integers(0, 1000, size=(32,))]).astype(np.int32)
            for _ in range(slots)]
        t0 = time.perf_counter()
        reqs = [eng.submit(p, new_tokens) for p in pre_prompts]
        for r in reqs:
            r.wait(timeout=900)
        dt = time.perf_counter() - t0
        out["prefix_phase_tokens_per_sec"] = round(
            slots * new_tokens / dt, 2)
        out["kvcache_stats"] = {
            k: eng.kv_cache.stats[k] - base.get(k, 0)
            for k in eng.kv_cache.stats}

    # Phase B2: the fused decode-block throughput mode (one host sync
    # per 8 steps) on the phase-A workload — on a high-dispatch-latency
    # device this is where batching stops being dispatch-bound
    try:
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=sampling, kv_cache_blocks=0,
                decode_block=8) as eng:
            eng.submit(prompts[0][:8], 4).wait(timeout=600)   # warm 32
            eng.submit(prompts[0], 4).wait(timeout=600)       # warm 128
            t0 = time.perf_counter()
            reqs = [eng.submit(p, new_tokens) for p in prompts]
            for r in reqs:
                r.wait(timeout=900)
            dt = time.perf_counter() - t0
            out["decode_block8_tokens_per_sec"] = round(
                n_req * new_tokens / dt, 2)
    except Exception as e:   # phase isolation
        out["decode_block8_error"] = f"{type(e).__name__}: {e}"

    # Phase C: the composed serving shape — speculative decoding inside
    # the slot loop (int8 self-draft, as in the speculative leg), same
    # phase-A workload, greedy (the composition's parity mode)
    try:
        draft_cfg = get_model_config(model + "-int8")
        draft_params = init_full_params(jax.random.PRNGKey(0), draft_cfg,
                                        quantize=True)
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=SamplingParams(greedy=True), kv_cache_blocks=0,
                draft_cfg=draft_cfg, draft_params=draft_params,
                num_draft=4) as eng:
            eng.submit(prompts[0][:8], 4).wait(timeout=600)   # warm 32
            eng.submit(prompts[0], 4).wait(timeout=600)       # warm 128
            eng.reset_stats()     # warmup rounds out of the measurement
            t0 = time.perf_counter()
            reqs = [eng.submit(p, new_tokens) for p in prompts]
            for r in reqs:
                r.wait(timeout=900)
            dt = time.perf_counter() - t0
            st = eng.stats()["speculative"]
            out["spec_batching"] = {
                "draft": model + "-int8 (same seed weights)",
                "sampling": "greedy",
                "tokens_per_sec": round(n_req * new_tokens / dt, 2),
                "num_draft": st["num_draft"], "rounds": st["rounds"],
                "acceptance_rate": st["acceptance_rate"],
            }
    except Exception as e:   # phase isolation: A/B numbers survive
        out["spec_batching"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _leg_mixed_batching(model: str, prompt_len: int = 256,
                        new_tokens: int = 48, slots: int = 8,
                        n_req: int = 24, prefill_chunk: int = 32,
                        decode_block: int = 4,
                        token_budget: int = 0,
                        arrival_s: float = 0.02,
                        block_tokens: int = 16) -> dict:
    """Mixed token-budget dispatch vs the alternating baseline
    (docs/DESIGN.md §19) under a fixed arrival load.

    Both modes serve the SAME schedule: ``slots - 1`` long-decode
    background rows pin the batch, then ``n_req`` chunk-heavy prompts
    arrive at a fixed interval.  The baseline is the serialized
    interleave this repo shipped pre-§19 (chunk dispatches alternating
    with decode steps, fused-loop suppression while an admission is in
    flight); mixed packs the chunks INTO the fused decode dispatches
    under the token budget.  Reported per mode: aggregate tok/s over
    the measured window (arrival-stream tokens PLUS the background
    rows' tokens produced inside it — the baseline's suppression
    stalls the background decode during every admission, and that
    stalled decode is exactly the cost §19 removes), TTFT p95 (engine
    reservoir, background rows excluded by the post-warmup reset),
    and dispatches/step — the 1/K-vs-1 structural signature the §19
    acceptance pins."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(greedy=True)
    budget = token_budget or slots * decode_block + 2 * prefill_chunk
    bg_rows = max(1, slots - 1)
    # background rows must outlive the arrival stream; they are
    # cancelled once the measured requests finish
    bg_new = max(64, n_req * new_tokens)
    max_seq = max(prompt_len + new_tokens, 8 + bg_new)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, 1000, size=(n_req, prompt_len)).astype(
        np.int32)
    warm = rng.integers(0, 1000, size=(2, prompt_len)).astype(np.int32)

    def run(mixed: bool) -> dict:
        kw = {"mixed_token_budget": budget} if mixed else {}
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=sampling, prefill_chunk=prefill_chunk,
                decode_block=decode_block, kv_block_tokens=block_tokens,
                **kw) as eng:
            # compile pass 1: a full-shape admission on an idle engine
            eng.submit(warm[0], 2).wait(timeout=600)
            bg = [eng.submit(np.asarray([7, i + 1, 3], np.int32), bg_new)
                  for i in range(bg_rows)]
            deadline = time.monotonic() + 600
            for r in bg:               # every background row decoding
                while not r.tokens:
                    if time.monotonic() > deadline:
                        raise TimeoutError("background rows never "
                                           "admitted")
                    time.sleep(0.002)
            # compile pass 2: an admission UNDER decode load — the
            # baseline's suppressed per-token step and the mixed
            # engine's no-finals slab variant both compile here, not
            # inside the measured window
            eng.submit(warm[1], 2).wait(timeout=600)
            eng.reset_stats()
            bg_before = sum(len(r.tokens) for r in bg)
            t0 = time.perf_counter()
            reqs = []
            for p in prompts:
                reqs.append(eng.submit(p, new_tokens))
                if arrival_s:
                    time.sleep(arrival_s)
            for r in reqs:
                r.wait(timeout=900)
            dt = time.perf_counter() - t0
            bg_tokens = sum(len(r.tokens) for r in bg) - bg_before
            st = eng.stats()
            ls = dict(eng.loop_stats)
            for r in bg:
                r.cancel()
            for r in bg:
                try:
                    r.wait(timeout=600)
                except Exception:
                    pass
            out = {
                "tokens_per_sec": round(
                    (n_req * new_tokens + bg_tokens) / dt, 2),
                "stream_tokens_per_sec": round(
                    n_req * new_tokens / dt, 2),
                "background_tokens": bg_tokens,
                "ttft_p95_ms": st["latency"].get("ttft_p95_ms"),
                "host_dispatches": ls["host_dispatches"],
                "device_loop_steps": ls["device_loop_steps"],
                "dispatches_per_step": round(
                    ls["host_dispatches"]
                    / max(1, ls["device_loop_steps"]), 4),
            }
            if mixed:
                out["mixed_dispatches"] = st["mixed"]["dispatches"]
                out["prefill_tokens"] = st["mixed"]["prefill_tokens"]
                out["budget_utilization"] = st["mixed"][
                    "budget_utilization"]
            mgr = eng.kv_cache
            out["leaked_blocks"] = (mgr.used_blocks
                                    - mgr.tree.block_count)
            return out

    baseline = run(mixed=False)
    mixed = run(mixed=True)
    return {
        "model": model, "slots": slots, "requests": n_req,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "prefill_chunk": prefill_chunk, "decode_block": decode_block,
        "token_budget": budget, "arrival_s": arrival_s,
        "background_rows": bg_rows,
        "baseline": baseline, "mixed": mixed,
        "mixed_wins_tokens_per_sec": (
            mixed["tokens_per_sec"] > baseline["tokens_per_sec"]),
        "mixed_ttft_p95_le_baseline": (
            mixed["ttft_p95_ms"] is not None
            and baseline["ttft_p95_ms"] is not None
            and mixed["ttft_p95_ms"] <= baseline["ttft_p95_ms"]),
    }


def _leg_spec_mixed(model: str, prompt_len: int = 192,
                    new_tokens: int = 32, slots: int = 8,
                    n_req: int = 16, prefill_chunk: int = 32,
                    decode_block: int = 4, num_draft: int = 4,
                    token_budget: int = 0,
                    arrival_s: float = 0.02,
                    block_tokens: int = 16,
                    bg_prompt_len: int = 32) -> dict:
    """Speculation INSIDE the mixed dispatch (docs/DESIGN.md §22) vs the
    two single-feature configurations it fuses.

    One schedule, three engines: ``slots - 1`` long-decode background
    rows pin the batch while ``n_req`` chunk-heavy motif-tiled prompts
    arrive at a fixed interval.  All prompts are tiled 16-token motifs —
    the n-gram shape prompt-lookup speculation exists for — so the
    proposer has real lookup structure; measured acceptance on
    seed-init weights stays a weights property (adversarial for
    agreement), so the leg's value is the MECHANICS: what fusing
    draft/verify into the packed dispatch does to aggregate tok/s,
    TTFT p95, and dispatches/step on the same arrival load.

    - ``spec_only``: prompt-lookup speculation with serialized chunked
      prefill (the pre-§22 shipping configuration — every arriving
      chunk is its own dispatch between speculative rounds).
    - ``mixed_only``: §19 token-budget packing, no speculation.
    - ``spec_mixed``: ONE program carries prefill segments + decode +
      draft/verify, adaptive per-row K (§22).

    Gates: ``spec_mixed_wins_tokens_per_sec`` (beats BOTH baselines)
    and ``ttft_p95_le_mixed_only`` (fusing speculation must not buy
    throughput with arrival latency).  The spec arms also report the
    §22 shrink observables (``k_row_buckets``, acceptance)."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(greedy=True)
    # the default §19 budget (slots * decode_block + 2 chunks) prices a
    # DECODE row at decode_block tokens; §22 prices a spec row at
    # (K_row + 1) * decode_block, so a budget sized for plain decode
    # leaves no prefill room once the batch speculates — size it for
    # the spec pricing and give every arm the same knob
    budget = token_budget or (slots * (num_draft + 1) * decode_block
                              + 2 * prefill_chunk)
    bg_rows = max(1, slots - 1)
    # background rows must OUTLIVE the arrival stream in every mode: a
    # speculating row emits up to K+1 tokens per round, and a row that
    # finishes mid-window both zeroes its arm's background tokens and
    # dumps its pre-window TTFT (submit-to-first-token spans the warmup
    # compiles) into the measured latency reservoir.  Budget from the
    # worst case: the window is bounded by the per-request dispatch
    # count (decode blocks + prefill chunks + admission slack) and a
    # spec row emits at most (K+1) * decode_block tokens per dispatch.
    per_req_dispatches = ((new_tokens + decode_block - 1) // decode_block
                          + (prompt_len + prefill_chunk - 1)
                          // prefill_chunk + 8)
    bg_new = ((num_draft + 1) * decode_block
              * n_req * per_req_dispatches)
    max_seq = max(prompt_len + new_tokens, bg_prompt_len + bg_new)
    rng = np.random.default_rng(0)

    def motif_prompt(length):
        # per-request DISTINCT motif (identical prompts would let the
        # block cache collapse the prefill work the leg measures)
        motif = rng.integers(0, 1000, size=(16,))
        return np.tile(motif, max(1, length // 16))[:length].astype(
            np.int32)

    prompts = [motif_prompt(prompt_len) for _ in range(n_req)]
    # background prompts are RANDOM (no n-gram structure): their
    # near-zero lookup acceptance is the §22 shrink workload — the
    # adaptive controller walks their K_row toward bucket 1, which is
    # exactly the ``k_row_buckets`` observable the spec arms report
    bg_prompts = [rng.integers(0, 1000, size=(bg_prompt_len,)).astype(
        np.int32) for _ in range(bg_rows)]
    warm = [motif_prompt(prompt_len) for _ in range(2)]

    def run(mode: str) -> dict:
        kw = {}
        if mode != "spec_only":
            kw["mixed_token_budget"] = budget
        if mode != "mixed_only":
            kw.update(prompt_lookup=True, num_draft=num_draft)
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=sampling, prefill_chunk=prefill_chunk,
                decode_block=decode_block, kv_block_tokens=block_tokens,
                **kw) as eng:
            # compile pass 1: a full-shape admission on an idle engine
            eng.submit(warm[0], 2).wait(timeout=600)
            bg = [eng.submit(p, bg_new) for p in bg_prompts]
            deadline = time.monotonic() + 600
            for r in bg:               # every background row decoding
                while not r.tokens:
                    if time.monotonic() > deadline:
                        raise TimeoutError("background rows never "
                                           "admitted")
                    time.sleep(0.002)
            # compile pass 2: an admission UNDER decode/spec load — the
            # packed-with-rounds and no-finals program variants both
            # compile here, not inside the measured window
            eng.submit(warm[1], 2).wait(timeout=600)
            eng.reset_stats()
            bg_before = sum(len(r.tokens) for r in bg)
            t0 = time.perf_counter()
            reqs = []
            for p in prompts:
                reqs.append(eng.submit(p, new_tokens))
                if arrival_s:
                    time.sleep(arrival_s)
            for r in reqs:
                r.wait(timeout=900)
            dt = time.perf_counter() - t0
            bg_tokens = sum(len(r.tokens) for r in bg) - bg_before
            st = eng.stats()
            ls = dict(eng.loop_stats)
            for r in bg:
                r.cancel()
            for r in bg:
                try:
                    r.wait(timeout=600)
                except Exception:
                    pass
            out = {
                "tokens_per_sec": round(
                    (n_req * new_tokens + bg_tokens) / dt, 2),
                "stream_tokens_per_sec": round(
                    n_req * new_tokens / dt, 2),
                "background_tokens": bg_tokens,
                "ttft_p95_ms": st["latency"].get("ttft_p95_ms"),
                "host_dispatches": ls["host_dispatches"],
                "device_loop_steps": ls["device_loop_steps"],
                "dispatches_per_step": round(
                    ls["host_dispatches"]
                    / max(1, ls["device_loop_steps"]), 4),
            }
            if mode != "spec_only":
                out["mixed_dispatches"] = st["mixed"]["dispatches"]
                out["prefill_tokens"] = st["mixed"]["prefill_tokens"]
                out["budget_utilization"] = st["mixed"][
                    "budget_utilization"]
            if mode != "mixed_only":
                sp = st["speculative"]
                # the §22 shrink observables: per-bucket occupancy of
                # the active rows' K_row + measured acceptance
                out["spec"] = {
                    "drafted": sp["drafted"],
                    "accepted": sp["accepted"],
                    "acceptance_rate": sp["acceptance_rate"],
                    "adaptive": sp["adaptive"],
                    "k_row_buckets": sp["k_row_buckets"],
                }
            mgr = eng.kv_cache
            out["leaked_blocks"] = (mgr.used_blocks
                                    - mgr.tree.block_count)
            return out

    spec_only = run("spec_only")
    mixed_only = run("mixed_only")
    spec_mixed = run("spec_mixed")
    return {
        "model": model, "slots": slots, "requests": n_req,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "prefill_chunk": prefill_chunk, "decode_block": decode_block,
        "num_draft": num_draft, "token_budget": budget,
        "arrival_s": arrival_s, "background_rows": bg_rows,
        "prompt_shape": "16-token motif tiled, distinct per request",
        "spec_only": spec_only, "mixed_only": mixed_only,
        "spec_mixed": spec_mixed,
        # the §22 acceptance gates: the fused program must beat BOTH
        # single-feature configurations on aggregate throughput without
        # regressing arrival TTFT vs the mixed-only packer
        "spec_mixed_wins_tokens_per_sec": (
            spec_mixed["tokens_per_sec"] > spec_only["tokens_per_sec"]
            and spec_mixed["tokens_per_sec"]
            > mixed_only["tokens_per_sec"]),
        "ttft_p95_le_mixed_only": (
            spec_mixed["ttft_p95_ms"] is not None
            and mixed_only["ttft_p95_ms"] is not None
            and spec_mixed["ttft_p95_ms"] <= mixed_only["ttft_p95_ms"]),
    }


def _leg_prefix_reuse(model: str, new_tokens: int, slots: int = 8,
                      n_req: int = 16, shared_len: int = 96,
                      tail_len: int = 32, block_tokens: int = 16,
                      kv_blocks: int = 0) -> dict:
    """Block-level KV cache (runtime/kvcache) on a repeated-shared-prefix
    workload: hit rate, reused tokens, and prefill seconds SAVED — the
    prefill-amortization number shared-prefix serving (chat system
    prompts, few-shot templates) turns on.

    The same workload runs twice through the batching engine — cache OFF
    then cache ON — after identical warmup and a priming request, so
    ``prefill_seconds_saved`` is a measured wall delta on identical
    decode work, not an estimate from token counts."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(temperature=0.7, top_k=7)
    max_seq = shared_len + tail_len + new_tokens
    rng = np.random.default_rng(0)
    shared = rng.integers(0, 1000, size=(shared_len,))

    def prompt():
        return np.concatenate(
            [shared, rng.integers(0, 1000, size=(tail_len,))]
        ).astype(np.int32)

    prime = prompt()
    prompts = [prompt() for _ in range(n_req)]
    # the no-reuse baseline: same shape, all-distinct prefixes — random
    # prompts share no whole block, so every admission prefills in full.
    # (The paged-native scheduler has no cache-off mode to compare
    # against: the pool IS the decode cache, so "off" is modeled by a
    # workload that cannot hit, not by a disabled subsystem.)
    cold_prompts = [rng.integers(0, 1000,
                                 size=(shared_len + tail_len,)).astype(
                                     np.int32) for _ in range(n_req)]

    def run(wave):
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=sampling, kv_cache_blocks=kv_blocks,
                kv_block_tokens=block_tokens) as eng:
            # identical warmup both runs: the priming request stores the
            # shared blocks and compiles the cold admission path; the
            # second covers the hit path (warm wave) / re-admission
            # (cold wave) so neither timed wave pays a compile the
            # other didn't
            eng.submit(prime, 4).wait(timeout=600)
            eng.submit(prompts[0], 4).wait(timeout=600)
            eng.reset_stats()
            t0 = time.perf_counter()
            reqs = [eng.submit(p, new_tokens) for p in wave]
            for r in reqs:
                r.wait(timeout=900)
            dt = time.perf_counter() - t0
            return dt, eng.kv_cache.snapshot()

    cold_dt, _ = run(cold_prompts)
    warm_dt, snap = run(prompts)
    lookups = snap["hits"] + snap["misses"]
    return {
        "model": model, "slots": slots, "requests": n_req,
        "shared_prefix_tokens": shared_len, "tail_tokens": tail_len,
        "new_tokens": new_tokens, "block_tokens": block_tokens,
        "kv_blocks": kv_blocks,
        "hit_rate": round(snap["hits"] / lookups, 3) if lookups else None,
        "reused_tokens": snap["partial_hit_tokens"],
        "cold_seconds": round(cold_dt, 3),
        "warm_seconds": round(warm_dt, 3),
        "prefill_seconds_saved": round(cold_dt - warm_dt, 3),
        "tokens_per_sec_cold": round(n_req * new_tokens / cold_dt, 2),
        "tokens_per_sec_warm": round(n_req * new_tokens / warm_dt, 2),
        "blocks_resident": snap["blocks_used"],
        "evicted_blocks": snap["evicted_blocks"],
    }


def _leg_tiered_prefix(model: str, new_tokens: int, slots: int = 2,
                       groups: int = 6, revisits: int = 3,
                       shared_len: int = 96, tail_len: int = 16,
                       block_tokens: int = 16, kv_blocks: int = 24,
                       host_groups: int = 3) -> dict:
    """Tiered KV (docs/DESIGN.md §21) vs re-prefill on a
    working-set-over-HBM workload: ``groups`` distinct shared prefixes
    whose trees cannot all stay resident in a ``kv_blocks``-block device
    pool, revisited after eviction.

    Phase A (tiering OFF) pays a full re-prefill on every revisit of an
    evicted prefix.  Phase B (tiering ON, host ring sized to hold
    ``host_groups`` of the ``groups`` prefixes so the REST spill to the
    disk segment) promotes the demoted pages back through the staged
    adopt seam instead.  Same prompts, same greedy sampling, same pool:
    the gates are

    - ``tiered_wins_ttft_p95``: revisit TTFT p95 with tiering beats
      re-prefill;
    - ``promote_h2d_bytes`` > 0: the promotion path actually moved
      bytes (phase A's h2d stays 0 — nothing else may touch the host
      bounce);
    - ``bit_identical``: greedy revisit tokens match across phases —
      a promoted prefix is the SAME cache state, not an approximation;
    - ``three_tier_zero_leak``: at leg end the device pool's used
      blocks equal tree-owned blocks and the host/disk ledgers pass
      :meth:`TieredKVStore.check` (host XOR disk, exact byte sums,
      consistent disk free list).
    """
    import tempfile

    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.stats import _percentile

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    new_tokens = min(new_tokens, 16)
    max_seq = shared_len + tail_len + new_tokens + block_tokens
    rng = np.random.default_rng(7)
    shared = [rng.integers(2, cfg.vocab_size - 1, size=(shared_len,))
              .astype(np.int32) for _ in range(groups)]
    # revisit tails fixed up front so BOTH phases replay the identical
    # prompt sequence (the bit-identity gate compares token-for-token)
    tails = [[rng.integers(2, cfg.vocab_size - 1, size=(tail_len,))
              .astype(np.int32) for _ in range(revisits)]
             for _ in range(groups)]
    # the warm prompt has the SAME shape as a group prompt so the
    # promote-path warmup below compiles the same adopt-scatter block
    # count the measured revisits dispatch
    warm = rng.integers(2, cfg.vocab_size - 1,
                        size=(shared_len + tail_len,)).astype(np.int32)
    blocks_per_group = -(-(shared_len + tail_len + new_tokens)
                         // block_tokens)

    def run(tier_kwargs):
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=greedy, kv_cache_blocks=kv_blocks,
                kv_block_tokens=block_tokens, **tier_kwargs) as eng:
            # compile the admission/prefill/decode programs before
            # timing; the warm blocks sit in-tree identically in both
            # phases (oldest, so they evict first either way)
            eng.submit(warm, new_tokens).wait(timeout=600)
            eng.kv_cache.reset_stats()
            # round 1: touch every group once; the small pool evicts
            # older groups as later ones admit (demoting in phase B)
            for g in range(groups):
                eng.submit(np.concatenate([shared[g], tails[g][0]]),
                           new_tokens).wait(timeout=900)
            # promote-path warmup, symmetric across phases: the warm
            # prefix was evicted by round 1, so resubmitting it here
            # compiles the adopt-scatter programs (phase B) / replays a
            # re-prefill (phase A) OUTSIDE the measured wave — same
            # discipline as warming prefill before timing it
            eng.submit(warm, new_tokens).wait(timeout=900)
            # round 2: revisit every group — evicted prefixes re-prefill
            # (phase A) or promote from the tier (phase B).  Revisit
            # round 0 is the steady-state round: it flushes out the
            # remaining demote/promote compile variants (the export and
            # adopt scatters bucket to powers of two, but a leaf size
            # class first seen mid-wave would still stall one TTFT on a
            # compile); rounds >= 1 are the measured ones.  Tokens from
            # EVERY round feed the bit-identity gate.
            ttfts, toks = [], []
            for rv in range(revisits):
                for g in range(groups):
                    r = eng.submit(
                        np.concatenate([shared[g], tails[g][rv]]),
                        new_tokens)
                    r.wait(timeout=900)
                    if rv >= 1:
                        ttfts.append(r.t_first - r.t_submit)
                    toks.append(list(r.tokens))
            snap = eng.kv_cache.snapshot()
            leaked = snap["blocks_used"] - snap["tree_blocks"]
            tier_ok = True
            if eng.kv_cache.tier is not None:
                try:
                    eng.kv_cache.tier.check()
                except AssertionError:
                    tier_ok = False
            return {"ttfts": ttfts, "tokens": toks, "snap": snap,
                    "leaked_blocks": leaked, "tier_ledger_ok": tier_ok}

    cold = run({})
    # size the host ring off the REAL pool geometry (quantized pools
    # carry scale sidecars; 1.25x covers them at int4's worst ratio)
    per_block = cold["snap"]["capacity_bytes"] // max(kv_blocks, 1)
    host_bytes = int(per_block * blocks_per_group * host_groups * 1.25)
    disk_bytes = int(per_block * blocks_per_group * groups * 1.5)
    with tempfile.TemporaryDirectory(prefix="dwt-tier-") as td:
        tiered = run({"kv_host_tier_bytes": host_bytes,
                      "kv_disk_tier_path": os.path.join(td, "kv.seg"),
                      "kv_disk_tier_bytes": disk_bytes})

    def pcts(xs):
        xs = sorted(xs)
        return {"requests": len(xs),
                "ttft_p50_ms": round(_percentile(xs, 50) * 1e3, 2),
                "ttft_p95_ms": round(_percentile(xs, 95) * 1e3, 2)}

    a, b = pcts(cold["ttfts"]), pcts(tiered["ttfts"])
    frag = tiered["snap"].get("tier") or {}
    hits = frag.get("host_hits", 0) + frag.get("disk_hits", 0)
    out = {
        "model": model, "slots": slots, "groups": groups,
        "revisits": revisits, "shared_prefix_tokens": shared_len,
        "tail_tokens": tail_len, "new_tokens": new_tokens,
        "block_tokens": block_tokens, "kv_blocks": kv_blocks,
        "host_tier_bytes": host_bytes, "disk_tier_bytes": disk_bytes,
        "reprefill": a, "tiered": b,
        "tiered_wins_ttft_p95": b["ttft_p95_ms"] < a["ttft_p95_ms"],
        "ttft_p95_speedup": round(a["ttft_p95_ms"] / b["ttft_p95_ms"], 3)
        if b["ttft_p95_ms"] else None,
        "promote_h2d_bytes": tiered["snap"]["h2d_bytes"],
        "reprefill_h2d_bytes": cold["snap"]["h2d_bytes"],
        "demoted_blocks": frag.get("demoted_blocks", 0),
        "promoted_blocks": frag.get("promoted_blocks", 0),
        "spilled_blocks": frag.get("spilled_blocks", 0),
        "dropped_blocks": frag.get("dropped_blocks", 0),
        "tier_hits": {"host": frag.get("host_hits", 0),
                      "disk": frag.get("disk_hits", 0)},
        # which tier the promoted blocks came from (host ring vs the
        # disk segment below it)
        "tier_hit_share": ({
            "host": round(frag.get("host_hits", 0) / hits, 3),
            "disk": round(frag.get("disk_hits", 0) / hits, 3)}
            if hits else None),
        "bit_identical": cold["tokens"] == tiered["tokens"],
        "three_tier_zero_leak": (cold["leaked_blocks"] == 0
                                 and tiered["leaked_blocks"] == 0
                                 and tiered["tier_ledger_ok"]),
        "leaked_blocks": {"reprefill": cold["leaked_blocks"],
                          "tiered": tiered["leaked_blocks"]},
    }
    return out


def _leg_paged_decode(model: str, new_tokens: int, slots: int = 8,
                      prompt_len: int = 64, max_seq: int = 1024,
                      block_tokens: int = 16, n_req: int = 0,
                      shared_len: int = 48,
                      kv_dtypes=("int8", "int4")) -> dict:
    """Paged KV on the (paged-native) batching engine vs dense-layout
    reservation (docs/DESIGN.md §11/§14): decode tok/s parity AND the
    HBM story the paged layout exists for — at a serving-realistic
    ``max_seq`` a dense cache reserves ``B x max_seq`` rows up front
    while the paged engine allocates blocks per request actually in
    flight.

    Phases, one workload shape (distinct prompts, then a shared-prefix
    wave on the paged engine):

    - dense reference: the plain InferenceEngine at batch = slots —
      its working cache is dense ``B x max_seq`` rows (the dense pool
      layout is deleted; the working cache shape is the reference),
      so its cache bytes are measured off the real buffers, not
      estimated;
    - paged: tok/s + pool capacity + PEAK blocks/bytes in use (polled
      while the wave decodes) + the analytic max-concurrent-sequences
      at the dense reference's HBM budget;
    - admissible: at the dense reservation byte budget, the max
      admissible batch at 4k/8k/32k sequence budgets — dense reserves
      the full row per request, paged reserves the blocks the workload
      shape actually touches (strictly larger batches, the §14
      acceptance gate);
    - paged primed: radix hits on the paged path — ``h2d_bytes`` must
      stay 0 (hits are block-table references, nothing crosses the
      host boundary);
    - kv-dtype axis (docs/DESIGN.md §17): the same wave on int8/int4
      page pools — tok/s plus the per-dtype admissible table, whose
      narrower ``block_bytes`` (scale sidecar included) must admit a
      STRICTLY larger batch than bf16 at the same fixed byte budget."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import (
        pad_cache_capacity)
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    sampling = SamplingParams(temperature=0.7, top_k=7)
    n_req = n_req or slots * 2
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1000, size=(prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    shared = rng.integers(0, 1000, size=(shared_len,))

    def shared_prompt():
        tail = rng.integers(0, 1000, size=(prompt_len - shared_len,))
        return np.concatenate([shared, tail]).astype(np.int32)

    def run_wave(eng, wave):
        """Submit a wave, poll block occupancy while it decodes (the
        peak is the honest 'blocks actually allocated' number — after
        the wave only tree-cached blocks remain)."""
        eng.reset_stats()
        peak_blocks = 0
        t0 = time.perf_counter()
        reqs = [eng.submit(p, new_tokens) for p in wave]
        while not all(r.done.is_set() for r in reqs):
            if eng.kv_cache is not None:
                peak_blocks = max(peak_blocks,
                                  eng.kv_cache.snapshot()["blocks_used"])
            time.sleep(0.02)
        for r in reqs:
            r.wait(timeout=900)
        dt = time.perf_counter() - t0
        return dt, peak_blocks

    out = {"model": model, "slots": slots, "requests": n_req,
           "prompt_len": prompt_len, "new_tokens": new_tokens,
           "max_seq": max_seq, "block_tokens": block_tokens}

    # phase 1: the dense-reservation reference — the plain engine's
    # working cache is dense B x max_seq rows regardless of pool
    # layout (the dense pool layout itself is deleted), so its real
    # buffers at batch = slots ARE the dense reservation, measured not
    # estimated
    dense_eng = InferenceEngine(cfg, params, max_seq=max_seq,
                                sampling=sampling)
    batch_prompts = np.stack(prompts[:slots])
    dense_eng.generate(batch_prompts, new_tokens, seed=0)     # compile
    dense_cache = dense_eng.new_cache(slots)
    dense_bytes = int(dense_cache.keys.nbytes + dense_cache.values.nbytes)
    del dense_cache
    t0 = time.perf_counter()
    for i in range(0, n_req, slots):
        dense_eng.generate(np.stack(prompts[i:i + slots]), new_tokens,
                           seed=0)
    dense_dt = time.perf_counter() - t0
    del dense_eng
    out["dense"] = {
        "engine": "InferenceEngine dense-row working cache (reference)",
        "tokens_per_sec": round(n_req * new_tokens / dense_dt, 2),
        "cache_reserved_bytes": dense_bytes,
        "reserved_tokens": slots * max_seq,
    }

    # phase 2 + 3: paged (pool sized to the dense-equivalent budget)
    with ContinuousBatchingEngine(
            cfg, params, max_seq=max_seq, max_batch=slots,
            sampling=sampling, kv_layout="paged",
            kv_block_tokens=block_tokens) as eng:
        eng.submit(prompts[0], 4).wait(timeout=600)      # compile warmup
        eng.submit(prompts[1], 4).wait(timeout=600)
        dt, peak_blocks = run_wave(eng, prompts)
        mgr = eng.kv_cache
        blocks_per_req = -(-(prompt_len + new_tokens) // block_tokens)
        out["paged"] = {
            "tokens_per_sec": round(n_req * new_tokens / dt, 2),
            "pool_capacity_bytes": int(eng._pk.nbytes + eng._pv.nbytes),
            "pool_blocks": mgr.num_blocks,
            "block_bytes": int(mgr.block_bytes),
            "peak_blocks_in_use": int(peak_blocks),
            "peak_bytes_in_use": int(peak_blocks * mgr.block_bytes),
            "blocks_per_request": blocks_per_req,
            # at the dense run's HBM budget, how many sequences of THIS
            # shape fit: dense pins max_batch rows; paged packs blocks
            "max_seqs_at_dense_budget": int(
                dense_bytes // (blocks_per_req * mgr.block_bytes)),
            "dense_max_seqs": slots,
        }
        out["paged_vs_dense_decode"] = round(
            out["paged"]["tokens_per_sec"]
            / out["dense"]["tokens_per_sec"], 3)
        out["cache_bytes_ratio"] = round(
            out["paged"]["peak_bytes_in_use"] / dense_bytes, 3)

        # the §14 acceptance table: at the dense reservation's byte
        # budget, the max admissible batch per sequence budget — dense
        # pins a padded max_seq row per request; paged pins only the
        # blocks this workload shape (prompt + new) actually touches.
        # Parameterized on block_bytes so the §17 kv-dtype phase below
        # reuses the same arithmetic with its narrower pages.
        itemsize = np.dtype(cfg.dtype).itemsize
        kv_row_unit = 2 * cfg.num_layers * cfg.num_kv_heads \
            * cfg.head_dim * itemsize
        used_tokens = prompt_len + new_tokens

        def admissible_table(blk_bytes):
            tbl = {}
            for seq in (4096, 8192, 32768):
                dense_row = kv_row_unit * pad_cache_capacity(seq)
                paged_req = -(-used_tokens // block_tokens) * blk_bytes
                tbl[str(seq)] = {
                    "budget_bytes": dense_bytes,
                    "dense_max_batch": int(dense_bytes // dense_row),
                    "paged_max_batch": int(dense_bytes // paged_req),
                    "workload_tokens_per_request": used_tokens,
                }
            return tbl

        out["admissible"] = admissible_table(mgr.block_bytes)

        # phase 3: primed — shared-prefix wave; hits must move 0 bytes
        # through the host (the acceptance gate for the paged path)
        eng.submit(shared_prompt(), 4).wait(timeout=600)   # prime+compile
        dt, _ = run_wave(eng, [shared_prompt() for _ in range(n_req)])
        snap = mgr.snapshot()
        lookups = snap["hits"] + snap["misses"]
        out["paged_primed"] = {
            "tokens_per_sec": round(n_req * new_tokens / dt, 2),
            "hit_rate": (round(snap["hits"] / lookups, 3)
                         if lookups else None),
            "reused_tokens": snap["partial_hit_tokens"],
            "h2d_bytes": snap["h2d_bytes"],
        }

    # phase 4: the §17 kv-dtype axis — the same cold wave on quantized
    # page pools.  Each dtype's admissible table reuses the bf16 dense
    # budget, so paged_max_batch growing strictly with narrowing width
    # IS the byte-budget claim measured, not asserted.  (Each engine is
    # opened after the bf16 one closed: pools never coexist, so the leg
    # fits the same HBM the bf16 phase needed.)
    out["kv_dtype"] = {}
    for d in kv_dtypes:
        with ContinuousBatchingEngine(
                cfg, params, max_seq=max_seq, max_batch=slots,
                sampling=sampling, kv_layout="paged",
                kv_block_tokens=block_tokens, kv_dtype=d) as qeng:
            qeng.submit(prompts[0], 4).wait(timeout=600)  # compile warmup
            qeng.submit(prompts[1], 4).wait(timeout=600)
            dt, peak_q = run_wave(qeng, prompts)
            qmgr = qeng.kv_cache
            out["kv_dtype"][d] = {
                "tokens_per_sec": round(n_req * new_tokens / dt, 2),
                "vs_bf16_paged": round(
                    (n_req * new_tokens / dt)
                    / out["paged"]["tokens_per_sec"], 3),
                "block_bytes": int(qmgr.block_bytes),
                "scale_block_bytes": int(qmgr.scale_block_bytes),
                "pool_capacity_bytes": int(qeng._pk.nbytes
                                           + qeng._pv.nbytes),
                "peak_blocks_in_use": int(peak_q),
                "peak_bytes_in_use": int(peak_q * qmgr.block_bytes),
                "admissible": admissible_table(qmgr.block_bytes),
            }
    return out


def _leg_serving_relative(model: str, batch: int, prompt_len: int,
                          new_tokens: int, slots: int = 4,
                          n_req: int = 8) -> dict:
    """CPU-relative serving evidence (VERDICT r5 "Next round" #4): the
    serving-stack RATIOS that survive a hardware change — speculative
    speedup vs plain, prompt-lookup acceptance rate, batching aggregate
    throughput-per-slot vs the plain engine — measured wherever the leg
    runs and stamped with the platform.  Absolute tok/s here are NOT
    comparable to the TPU legs and the stamp says so
    (``relative_only``); what transfers is the mechanics: acceptance is
    an argmax-agreement property, per-slot scaling a scheduler
    property."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                        SpeculativeEngine)
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.prompt_lookup import (
        PromptLookupEngine)
    from distributed_inference_demo_tpu.runtime.speculative import stats_json

    cfg = get_model_config(model)
    draft_cfg = get_model_config(model + "-int8")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_full_params(jax.random.PRNGKey(0), draft_cfg,
                                    quantize=True)
    greedy = SamplingParams(greedy=True)
    max_seq = max(prompt_len, 64) + new_tokens
    prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
              % 1000).astype(np.int32)
    out = {"platform": jax.default_backend(), "relative_only": True,
           "model": model, "batch": batch, "prompt_len": prompt_len,
           "new_tokens": new_tokens}

    plain = InferenceEngine(cfg, params, max_seq=max_seq, sampling=greedy)
    plain.generate(prompt, new_tokens, seed=0)             # compile
    base = plain.generate(prompt, new_tokens, seed=0)
    out["plain_tokens_per_sec"] = round(base.tokens_per_second, 2)

    num_draft = 4
    spec = SpeculativeEngine(cfg, params, draft_cfg, draft_params,
                             max_seq=max_seq, sampling=greedy,
                             num_draft=num_draft)
    spec.generate(prompt, new_tokens, seed=0)              # compile
    sres, sstats = spec.generate(prompt, new_tokens, seed=0)
    out["speculative"] = dict(
        stats_json(sstats, num_draft),
        tokens_per_sec=round(sres.tokens_per_second, 2),
        speedup_vs_plain=round(sres.tokens_per_second
                               / base.tokens_per_second, 3))

    # prompt lookup on its natural shape: a repeated motif (acceptance
    # is what transfers; seed weights are adversarial for it)
    motif = (np.arange(16) * 37 % 1000).astype(np.int32)
    pl_len = max(32, min(prompt_len, max_seq - new_tokens) // 16 * 16)
    pl_prompt = np.tile(motif, pl_len // 16)[None, :]
    pld = PromptLookupEngine(cfg, params, max_seq=max_seq,
                             sampling=greedy, num_draft=num_draft)
    pld.generate(pl_prompt, new_tokens, seed=0)            # compile
    pres, pstats = pld.generate(pl_prompt, new_tokens, seed=0)
    out["prompt_lookup"] = dict(
        stats_json(pstats, num_draft),
        tokens_per_sec=round(pres.tokens_per_second, 2))

    # batching: aggregate throughput per slot vs one plain stream
    rng = np.random.default_rng(0)
    reqs_p = rng.integers(0, 1000, size=(n_req, prompt_len)).astype(
        np.int32)
    plain.generate(prompt[:1], new_tokens, seed=0)   # compile [1, plen]
    single = plain.generate(prompt[:1], new_tokens, seed=0)
    with ContinuousBatchingEngine(cfg, params, max_seq=max_seq,
                                  max_batch=slots,
                                  sampling=greedy) as eng:
        eng.submit(reqs_p[0], 2).wait(timeout=600)         # compile
        t0 = time.perf_counter()
        rs = [eng.submit(p, new_tokens) for p in reqs_p]
        for r in rs:
            r.wait(timeout=900)
        agg_tps = n_req * new_tokens / (time.perf_counter() - t0)
    out["batching"] = {
        "slots": slots, "requests": n_req,
        "aggregate_tokens_per_sec": round(agg_tps, 2),
        "throughput_per_slot": round(agg_tps / slots, 2),
        "per_slot_vs_plain_single": round(
            (agg_tps / slots) / single.tokens_per_second, 3),
    }
    return out


def _long_context_sp_points(model: str, new: int = 8) -> list:
    """>= 32k-context points for BOTH sp strategies (ring / Ulysses) at
    micro budget — the carried sweep satellite: the sequence-parallel
    long-context shape banks at least a micro number per strategy in
    the first healthy window.  Needs >= 2 local devices; stamps a skip
    otherwise.  Per-strategy isolation: one failing build (e.g. a head
    count Ulysses can't divide) must not lose the other point."""
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.parallel.mesh import local_sp_mesh

    ctx = int(os.environ.get("BENCH_LONG_CTX_SP", "32768"))
    if len(jax.devices()) < 2:
        return [{"skipped": "sequence parallelism needs >= 2 devices",
                 "context": ctx}]
    sp = 2
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    plen = (ctx - new) // sp * sp
    prompt = (np.arange(plen) % 1000).astype(np.int32)[None, :]
    points = []
    for strategy in ("ring", "ulysses"):
        point = {"strategy": strategy, "sp": sp, "context": ctx,
                 "prompt_len": plen, "new_tokens": new}
        try:
            if strategy == "ring":
                from distributed_inference_demo_tpu.parallel.sequence import (
                    make_sp_generate_fn)
                gen = make_sp_generate_fn(
                    cfg, local_sp_mesh(sp), max_seq=ctx,
                    num_new_tokens=new,
                    sampling=SamplingParams(greedy=True))
            else:
                from distributed_inference_demo_tpu.parallel.ulysses import (
                    make_ulysses_generate_fn)
                gen = make_ulysses_generate_fn(
                    cfg, local_sp_mesh(sp), max_seq=ctx,
                    num_new_tokens=new,
                    sampling=SamplingParams(greedy=True))
            mesh = local_sp_mesh(sp)
            with mesh:
                toks = np.asarray(gen(params, prompt,
                                      jax.random.PRNGKey(0)))  # compile
            t0 = time.perf_counter()
            with mesh:
                toks = np.asarray(gen(params, prompt,
                                      jax.random.PRNGKey(0)))
            dt = time.perf_counter() - t0
            point["tokens_per_sec"] = round(toks.size / dt, 2)
        except Exception as e:
            point["error"] = f"{type(e).__name__}: {e}"[:300]
        points.append(point)
    return points


def _leg_planner_pipeline(model: str, batch: int, prompt_len: int,
                          new_tokens: int) -> dict:
    """BASELINE config #2 measured through the COMPOSED product: the
    ``server`` app (collect window → monitor round → cost-model plan →
    artifact weight distribution) plus a bare ``worker --auto`` — not a
    hand-wired harness.  The server/header runs on this host's default
    backend (the TPU when present); the worker is a CPU process that
    knows only the registry address.  Reports the planner's layer ranges
    next to the measured throughput."""
    import json as _json
    import urllib.request

    env_worker = dict(os.environ, JAX_PLATFORMS="cpu",
                      PALLAS_AXON_POOL_IPS="",
                      XLA_FLAGS="--xla_force_host_platform_device_count=1")
    max_seq = prompt_len + new_tokens
    server = subprocess.Popen(
        [sys.executable, "-m", "distributed_inference_demo_tpu", "server",
         "--model", model, "--num-workers", "1",
         "--max-seq", str(max_seq), "--max-new-tokens", str(new_tokens),
         "--temperature", "0.7", "--top-k", "7",
         "--collect-timeout", "600", "--monitor-timeout", "600",
         "--step-timeout", "600"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=str(REPO))
    worker = None
    reader = _LineReader(server)
    try:
        registry = reader.read_until("SERVER_REGISTRY").split()[1]
        worker = subprocess.Popen(
            [sys.executable, "-m", "distributed_inference_demo_tpu",
             "worker", "--auto", "--registry", registry,
             "--device-id", "w1", "--step-timeout", "600"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env_worker, text=True, cwd=str(REPO))
        plan_line = reader.read_until("SERVER_PLAN", timeout=600)
        ranges = _json.loads(plan_line.split(" ", 1)[1])
        http = reader.read_until("HTTP_READY", timeout=600).split()[1]

        import numpy as np
        prompt = (np.arange(batch * prompt_len).reshape(batch, prompt_len)
                  % 1000).astype(int).tolist()

        def post(path, body, timeout=900):
            req = urllib.request.Request(
                http + path, data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return _json.loads(r.read())

        post("/generate", {"prompt_ids": prompt, "max_new_tokens": 2})
        post("/stats/reset", {})
        t0 = time.perf_counter()
        post("/generate", {"prompt_ids": prompt,
                           "max_new_tokens": new_tokens})
        dt = time.perf_counter() - t0
        with urllib.request.urlopen(http + "/stats", timeout=120) as r:
            stages = _json.loads(r.read())["stages"]
    finally:
        server.kill()
        if worker is not None:
            worker.kill()

    h = next((s for s in stages if s.get("role") == "header"), {})
    tail = next((s for s in stages if s.get("role") == "tail"), {})
    out = {
        "model": model, "batch": batch,
        # the leg process must NOT touch the TPU (the server subprocess
        # owns it — the device is exclusive), so no _device_kind() here
        "device": "server subprocess (default backend) + 1 CPU worker",
        "planner_layer_ranges": ranges,
        "pipeline_tokens_per_sec": round(batch * new_tokens / dt, 2),
        "ring_rtt_p50_ms": h.get("ring_rtt_p50_ms"),
        "tail_compute_p50_ms": tail.get("compute_p50_ms"),
    }
    _paired_hop_percentiles(h, tail, out)
    return out


# ---------------------------------------------------------------------------
# Leg dispatch (subprocess entry) + orchestrator
def _leg_int4(model: str, flagship: str, batch: int, prompt_len: int,
              new_tokens: int) -> dict:
    """Weight-only int4 decode (ops/quant.QuantizedArray4): nibble-packed
    weights at 2/byte + group-wise f32 scales = ~0.56 bytes/weight.
    Decode streams every weight byte once per step, so at the
    bandwidth-bound batch sizes int4 is the throughput configuration
    ABOVE int8 — the ratio vs the headline_int8/flagship_int8 legs (same
    shapes) is the packing payoff net of the in-feed unpack cost.
    Reference analog: the -int8 export variants (data/Data.kt:19-33);
    the reference has no int4 story."""
    out = {"headline_int4": _bench_engine(model, batch, prompt_len,
                                          new_tokens, quant="int4")}
    out["flagship_int4"] = _leg_flagship(flagship, batch, prompt_len,
                                         min(new_tokens, 64), quant="int4")
    return out


def _leg_moe(batch: int, prompt_len: int, new_tokens: int,
             moe_model: str = "mixtral-tpu-1b",
             dense_model: str = "mixtral-tpu-1b-dense") -> dict:
    """MoE decode on one chip (BASELINE config #4 at a chip-fitting
    scale, ~0.8 B params bf16).

    mixtral-tpu-1b (8 experts, top-2) against its dense FLOPs-matched
    twin (dense intermediate = 2x expert intermediate, i.e. the SAME
    active compute per token): the tok/s ratio isolates routing +
    dispatch cost.  The single-chip MoE layer computes all experts
    batched on the MXU and combines by gate weight
    (models/decoder.py:201-230), so the MoE side also streams ~4x the
    active expert weights per step — achieved_gbs shows how much of
    that the chip absorbs.  int8 is the throughput configuration."""
    moe = _bench_engine(moe_model, batch, prompt_len, new_tokens)
    moe_int8 = _bench_engine(moe_model, batch, prompt_len,
                             new_tokens, quant=True)
    dense = _bench_engine(dense_model, batch, prompt_len, new_tokens)
    out = {"moe_bf16": moe, "moe_int8": moe_int8,
           "dense_equal_active_flops_bf16": dense}
    if moe.get("decode_tokens_per_sec") and dense.get(
            "decode_tokens_per_sec"):
        out["moe_vs_dense_decode"] = round(
            moe["decode_tokens_per_sec"] / dense["decode_tokens_per_sec"],
            3)
    return out


def _leg_multimodal(batch: int, new_tokens: int,
                    scale: str = "llava15",
                    decoder_model: str = "tinyllama-1.1b") -> dict:
    """LLaVA-stage throughput (BASELINE config #5).

    Two measures: (a) the vision encoder alone at llava-1.5 scale
    (336px / patch 14 / hidden 1024 / 24 layers, bf16) in images/s —
    the edge-client stage's capacity; (b) e2e image+text generation on
    MultimodalEngine with a tinyllama-class decoder — vision prefix +
    combined prefill + fused decode, in decode tok/s.  The reference
    has no vision path (its closest concept is per-device module
    placement, server.py:831-832); SURVEY lists multimodal as a
    framework goal."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params)
    from distributed_inference_demo_tpu.models.vision import (
        VisionConfig, init_vision_params, vision_forward)
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.multimodal import (
        MultimodalEngine)

    # (a) llava-1.5-scale tower alone ("tiny" keeps the same code path
    # runnable on CPU for the leg's smoke test)
    if scale == "llava15":
        vcfg = VisionConfig(image_size=336, patch_size=14,
                            hidden_size=1024, num_layers=24, num_heads=16,
                            intermediate_size=4096,
                            dtype_name="bfloat16")
    else:
        vcfg = VisionConfig(image_size=32, patch_size=16, hidden_size=32,
                            num_layers=2, num_heads=2,
                            intermediate_size=64, dtype_name="float32")
    dcfg = get_model_config(decoder_model)
    rng = jax.random.PRNGKey(0)
    vparams = init_vision_params(rng, vcfg,
                                 decoder_hidden=dcfg.hidden_size)
    fwd = jax.jit(lambda p, img: vision_forward(p, vcfg, img))
    images = jnp.ones((batch, vcfg.image_size, vcfg.image_size, 3),
                      vcfg.dtype)
    np.asarray(fwd(vparams, images))                  # compile
    t0 = time.perf_counter()
    rounds = 4
    for _ in range(rounds):
        out_h = fwd(vparams, images)
    np.asarray(out_h)                                 # fence
    enc_s = (time.perf_counter() - t0) / rounds
    encoder = {
        "images_per_sec": round(batch / enc_s, 2),
        "batch": batch, "image_size": vcfg.image_size,
        "patches_per_image": vcfg.num_patches,
        "vit_layers": vcfg.num_layers, "dtype": vcfg.dtype_name,
        "projector_out_dim": dcfg.hidden_size,
    }

    # (b) e2e: small tower + a real decoder
    dparams = init_full_params(jax.random.PRNGKey(1), dcfg)
    if scale == "llava15":
        small_v = VisionConfig(image_size=224, patch_size=14,
                               hidden_size=256, num_layers=6, num_heads=8,
                               intermediate_size=1024,
                               dtype_name="bfloat16")
    else:
        small_v = vcfg
    svp = init_vision_params(jax.random.PRNGKey(2), small_v,
                             decoder_hidden=dcfg.hidden_size)
    b2 = min(batch, 4)
    n_img = small_v.num_patches
    text_len = min(32, dcfg.max_seq_len // 4)
    eng = MultimodalEngine(dcfg, dparams, small_v, svp,
                           max_seq=n_img + text_len + new_tokens,
                           sampling=SamplingParams(temperature=0.7,
                                                   top_k=7))
    side = small_v.image_size
    imgs = np.ones((b2, side, side, 3), np.float32)
    text = (np.arange(b2 * text_len).reshape(b2, text_len)
            % dcfg.vocab_size).astype(np.int32)
    eng.generate(imgs, text, new_tokens, seed=0)      # compile
    res = eng.generate(imgs, text, new_tokens, seed=0)
    e2e = {
        "decode_tokens_per_sec": round(res.tokens_per_second, 2),
        "batch": b2, "image_tokens": n_img, "text_tokens": text_len,
        "new_tokens": new_tokens, "decoder": decoder_model,
    }
    return {"vision_encoder_llava15_scale": encoder,
            "e2e_image_text_generate": e2e}


def _leg_fault_recovery(model: str, new_tokens: int = 24,
                        prompt_len: int = 8, max_seq: int = 64,
                        crash_after_msgs: int = 6,
                        num_stages: int = 3) -> dict:
    """Elastic recovery under an injected worker crash (comm/faults):
    a 3-stage loopback pipeline loses its middle stage mid-generation
    via a seeded ``crash_after`` fault plan; the leg measures the
    recovery path end to end — reshard latency, time from crash to the
    first post-recovery token, and the token streams' bit-identity with
    a fault-free run (the §12 chaos invariant, timed).

    Loopback on purpose: the number under test is the FRAMEWORK's
    detect→reshard→drain/resume cost, not socket noise; it is the same
    path a socket deployment runs (tests/test_chaos.py drives it under
    messier plans)."""
    import threading

    import jax
    import numpy as np
    from distributed_inference_demo_tpu.comm.faults import (
        FaultPlan, FaultRule, FaultyTransport, InjectedCrash)
    from distributed_inference_demo_tpu.comm.transport import (
        LoopbackNetwork, LoopbackTransport)
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import split_layer_ranges
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.elastic import (
        ElasticHeader, ElasticStageRuntime, ElasticWorker)

    cfg = get_model_config(model)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    greedy = SamplingParams(greedy=True)
    prompt = (np.arange(prompt_len)[None, :] % 97).astype(np.int32)
    ids = [f"s{i}" for i in range(num_stages)]

    def build(plan):
        net = LoopbackNetwork()
        transports = [LoopbackTransport(d, net) for d in ids]
        if plan is not None:
            # the crash plan wraps the MIDDLE stage's transport: the
            # n_msgs-th message through it raises InjectedCrash and the
            # serve thread dies like a real worker crash
            transports[1] = FaultyTransport(transports[1], plan)
        header = ElasticHeader(
            ElasticStageRuntime(cfg, specs[0], full, max_seq, greedy),
            transports[0], chain=list(ids), step_timeout=60,
            poll_interval=0.05)
        workers = [
            ElasticWorker(
                ElasticStageRuntime(cfg, specs[i], full, max_seq, greedy),
                transports[i],
                next_id=ids[i + 1] if i + 1 < num_stages else None,
                header_id=ids[0], step_timeout=60)
            for i in range(1, num_stages)]
        threads = []
        for w in workers:
            def serve(w=w):
                try:
                    w.serve_forever(30)
                except InjectedCrash:
                    pass          # the injected death IS the scenario
            t = threading.Thread(target=serve, daemon=True)
            t.start()
            threads.append(t)
        return header, workers, threads

    # -- fault-free reference run (also the compile warmup) ----------------
    header, _, threads = build(None)
    header.generate(prompt, 4)               # compile
    t0 = time.perf_counter()
    want = header.generate(prompt, new_tokens)
    clean_dt = time.perf_counter() - t0
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)

    # -- chaos run: s1 crashes after crash_after_msgs messages -------------
    plan = FaultPlan(seed=1234, rules=[
        FaultRule(kind="crash_after", n_msgs=crash_after_msgs)])
    header, workers, threads = build(plan)
    token_times = []
    t_crash = [None]
    t_signal = [None]
    reshard_s = [None]
    orig_reshard = header.reshard

    def timed_reshard(chain, in_flight=None, dead=()):
        r0 = time.perf_counter()
        orig_reshard(chain, in_flight, dead=dead)
        reshard_s[0] = time.perf_counter() - r0
    header.reshard = timed_reshard

    def supervise():
        # stands in for the heartbeat sweeper: the dead serve thread IS
        # the missed heartbeat (test_elastic wires the real sweeper)
        threads[0].join()
        t_crash[0] = time.perf_counter()
        header.signal_failure(ids[1])
        t_signal[0] = time.perf_counter()
    sup = threading.Thread(target=supervise, daemon=True)
    sup.start()

    t0 = time.perf_counter()
    got = header.generate_many(
        [prompt], new_tokens,
        on_token=lambda i, step, toks: token_times.append(
            (step, time.perf_counter())))[0]
    chaos_dt = time.perf_counter() - t0
    header.shutdown_pipeline()
    for t in threads[1:]:
        t.join(timeout=30)
    sup.join(timeout=30)

    identical = bool(np.array_equal(got, want))
    post = [ts for _, ts in token_times
            if t_crash[0] is not None and ts > t_crash[0]]
    recovery_s = (post[0] - t_crash[0]
                  if post and t_crash[0] is not None else None)
    tokens_after = len(post)
    return {
        "model": model, "num_stages": num_stages,
        "new_tokens": new_tokens, "crash_after_msgs": crash_after_msgs,
        "plan_seed": plan.seed,
        "injected_events": [e["kind"] for e in plan.events],
        "tokens_bit_identical_after_recovery": identical,
        "clean_seconds": round(clean_dt, 3),
        "chaos_seconds": round(chaos_dt, 3),
        "reshard_seconds": (round(reshard_s[0], 4)
                            if reshard_s[0] is not None else None),
        "crash_to_first_token_seconds": (round(recovery_s, 4)
                                         if recovery_s is not None
                                         else None),
        "tokens_to_recovery": (new_tokens - tokens_after
                               if t_crash[0] is not None else None),
        "recovery_overhead_seconds": round(chaos_dt - clean_dt, 3),
        "surviving_chain": list(header.chain),
    }


def _leg_disagg(model: str, slots: int = 8, bg: int = 7,
                n_req: int = 5, prompt_len: int = 256,
                prefill_chunk: int = 16, new_tokens: int = 4,
                bg_new: int = 4096, max_seq: int = 4096,
                block_tokens: int = 16,
                n_prefill_workers: int = 2) -> dict:
    """Disaggregated prefill/decode vs the colocated engine, measured
    where the split matters: **TTFT under concurrent decode load**
    (docs/DESIGN.md §15).

    Both phases run the same decode substrate — ``slots`` continuous-
    batching slots with ``bg`` of them pinned by long-running decode
    requests — and then admit ``n_req`` long-prompt requests:

    - *colocated*: the requests chunk-prefill on the SAME engine; every
      chunk interleaves one decode step of the busy batch (the §5
      chunked-admission contract), so TTFT pays the batch's decode for
      every chunk, serially per request.
    - *disaggregated*: the requests hand off to dedicated prefill
      workers (loopback transport), which chunk-prefill concurrently
      and stream KV pages to the decode worker as each chunk lands;
      the decode engine only runs the adopt + one suffix prefill.

    Loopback on purpose (same rationale as fault_recovery): the number
    under test is the scheduling structure, not socket noise.  The leg
    also reports the §15 acceptance gates: decode-side
    ``dwt_kvcache_h2d_bytes_total`` staying 0 for migrated pages, the
    page-leak invariant on both pools, and migrated/adopted page
    parity."""
    import threading

    import jax
    import numpy as np
    from distributed_inference_demo_tpu.comm.transport import (
        LoopbackNetwork, LoopbackTransport)
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.disagg import (
        DecodeWorker, DisaggCoordinator, PrefillWorker)
    from distributed_inference_demo_tpu.runtime.stats import _percentile

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    rng = np.random.default_rng(0)
    bg_prompt = (np.arange(24) % 89 + 2).astype(np.int32)
    bg_new = min(bg_new, max_seq - len(bg_prompt))
    # distinct long prompts: no radix hit may shortcut the prefill;
    # one extra prompt warms the compile caches WITHOUT seeding the
    # radix tree with a measured prompt's blocks
    prompts = [rng.integers(2, cfg.vocab_size - 1, prompt_len)
               .astype(np.int32) for _ in range(n_req + 1)]
    warm_prompt, prompts = prompts[0], prompts[1:]

    def pcts(ttfts):
        xs = sorted(ttfts)
        return {"requests": len(xs),
                "ttft_p50_ms": round(_percentile(xs, 50) * 1e3, 2),
                "ttft_p95_ms": round(_percentile(xs, 95) * 1e3, 2)}

    def engine_kwargs(chunk):
        return dict(max_seq=max_seq, max_batch=slots, sampling=greedy,
                    kv_cache_blocks=0, kv_block_tokens=block_tokens,
                    prefill_chunk=chunk)

    # -- colocated: prefill chunks interleave with the busy batch ----------
    eng = ContinuousBatchingEngine(cfg, params, **engine_kwargs(
        prefill_chunk))
    bg_reqs = [eng.submit(bg_prompt, bg_new) for _ in range(bg)]
    # warm the admission/prefill programs before timing (compile noise
    # would otherwise dominate the first request's TTFT)
    eng.submit(warm_prompt, 2).wait(timeout=600)
    reqs = [eng.submit(p, new_tokens) for p in prompts]
    for r in reqs:
        r.wait(timeout=600)
    colocated = pcts([r.t_first - r.t_submit for r in reqs])
    for r in bg_reqs:
        r.cancel()
    steps_colocated = eng.stats()["steps"]
    eng.close()

    # -- disaggregated: same decode load, prefill on its own workers -------
    net = LoopbackNetwork()
    tc = LoopbackTransport("coord", net)
    pids = [f"p{i}" for i in range(n_prefill_workers)]
    tps = [LoopbackTransport(pid, net) for pid in pids]
    td = LoopbackTransport("d0", net)
    # the decode engine needs no prefill_chunk: its longest admission
    # is a migrated request's <= one-block suffix
    deng = ContinuousBatchingEngine(cfg, params, **engine_kwargs(None))
    pws = [PrefillWorker(cfg, params, t, max_seq=max_seq,
                         prefill_chunk=prefill_chunk,
                         kv_block_tokens=block_tokens)
           for t in tps]
    dw = DecodeWorker(deng, td)
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in pws + [dw]]
    for t in threads:
        t.start()
    coord = DisaggCoordinator(tc, pids, "d0")
    bg_reqs = [deng.submit(bg_prompt, bg_new) for _ in range(bg)]
    # warm EVERY prefill worker (each has its own jit caches) with the
    # off-tree warm prompt before timing — round robin lands one each
    for wr in [coord.submit(warm_prompt, 2)
               for _ in range(n_prefill_workers)]:
        wr.wait(timeout=600)
    dreqs = [coord.submit(p, new_tokens) for p in prompts]
    for r in dreqs:
        r.wait(timeout=600)
    disagg = pcts([r.ttft_s for r in dreqs])
    for r in bg_reqs:
        r.cancel()
    for r in bg_reqs:
        try:
            r.wait(timeout=600)
        except Exception:
            pass
    time.sleep(0.2)            # let completions release their pages
    dsnap = deng.kv_cache.snapshot()
    psnaps = [pw.kv_cache.snapshot() for pw in pws]
    migrated = sum(pw.stats["migrated_pages"] for pw in pws)
    migration_ms = [pw.stats["last_migration_ms"] for pw in pws]
    disagg.update({
        "migrated_pages": migrated,
        "migrated_bytes": sum(pw.stats["migrated_bytes"]
                              for pw in pws),
        "adopted_pages": dw.stats["adopted_pages"],
        "retransmitted_frames": sum(pw.stats["retransmitted_frames"]
                                    for pw in pws),
        "last_migration_ms": max((m for m in migration_ms
                                  if m is not None), default=None),
        # the §15 zero-host-bounce gate: migrated pages join as
        # block-table references, never a dense-row H2D seed
        "decode_h2d_bytes": dsnap["h2d_bytes"],
        # leak invariants, both pools: idle used == tree-owned
        "decode_pool_leaked_blocks": (dsnap["blocks_used"]
                                      - dsnap["tree_blocks"]),
        "prefill_pool_leaked_blocks": sum(
            s["blocks_used"] - s["tree_blocks"] for s in psnaps),
    })
    for w in pws + [dw]:
        w.stop()
    coord.close()
    deng.close()

    return {
        "model": model, "slots": slots, "background_decodes": bg,
        "prompt_len": prompt_len, "prefill_chunk": prefill_chunk,
        "prefill_workers": n_prefill_workers,
        "colocated": dict(colocated, steps=steps_colocated),
        "disagg": disagg,
        "disagg_wins_ttft_p95": (disagg["ttft_p95_ms"]
                                 < colocated["ttft_p95_ms"]),
        "ttft_p95_speedup": round(
            colocated["ttft_p95_ms"] / disagg["ttft_p95_ms"], 3)
        if disagg["ttft_p95_ms"] else None,
    }


def _leg_gateway_routing(model: str, n_replicas: int = 3, groups: int = 6,
                         per_group: int = 6, prefix_len: int = 96,
                         suffix_len: int = 8, new_tokens: int = 16,
                         slots: int = 4, max_seq: int = 512,
                         block_tokens: int = 16,
                         kill_requests: int = 12) -> dict:
    """Cache-aware gateway routing vs round-robin over N loopback
    replicas, measured where the router matters (docs/DESIGN.md §16):
    **prefix reuse and TTFT under a grouped shared-prefix workload**.

    Three phases over the SAME replica fleet (real HTTP all the way —
    client → gateway → replica — so both policies pay the same proxy
    hop):

    - *round_robin*: the gateway's router is overridden to cycle
      through replicas, the classic L4 answer.  Group members scatter,
      so most requests re-prefill a prefix some OTHER replica already
      holds.
    - *cache_aware*: the real PrefixAwareRouter.  The first member of
      a group lands by rendezvous hash; every later member follows the
      routing-history index to the replica that already holds the
      prefix, paying only the suffix prefill.
    - *kill*: re-issue cache-aware-phase prompts while one replica
      drains away mid-soak.  Gates: every request completes
      bit-identically to its phase-2 answer or sheds as 503 — never a
      hang, never divergent tokens — and the eviction debounce moves
      ``dwt_gateway_replica_down_total``.

    Two more phases exercise LIVE MIGRATION (docs/DESIGN.md §18) over
    the two surviving replicas:

    - *live_rebalance*: a 2*slots burst lands entirely on one replica
      (maximal skew); the same burst re-runs with a rebalancer moving
      rows hot → light mid-decode, so the queued tail admits a wave
      early.  Gates: TTFT p95 strictly beats the no-migration run
      (completion p95 is reported as context — both replicas share
      one host's compute in this harness) and every stream is
      bit-identical.
    - *drain*: :class:`MigrationController` over the LIVE registry
      marks the hot replica draining and drives it empty.  Gate: every
      in-flight request completes off the drained replica,
      bit-identically.

    Phases use DISJOINT prompt groups (fresh prefixes per phase) so
    phase order cannot lend one policy the other's warm cache."""
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from http.client import HTTPConnection

    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.gateway import (
        GatewayHTTPServer, PrefixAwareRouter, ReplicaRegistry, RouteDecision)
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    from distributed_inference_demo_tpu.runtime.overload import (
        GatewayOverloaded)
    from distributed_inference_demo_tpu.runtime.stats import _percentile

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    rng = np.random.default_rng(11)
    min_prefix = min(block_tokens, prefix_len)

    def make_workload():
        """``groups`` shared prefixes x ``per_group`` unique suffixes,
        interleaved across groups (g0r0, g1r0, ..., g0r1, ...) — the
        order that maximally punishes a router that forgets where a
        group's prefix lives."""
        per = []
        for _ in range(groups):
            prefix = rng.integers(2, cfg.vocab_size - 1, prefix_len)
            per.append([np.concatenate([
                prefix, rng.integers(2, cfg.vocab_size - 1, suffix_len)])
                .astype(np.int32) for _ in range(per_group)])
        return [per[g][i] for i in range(per_group)
                for g in range(groups)]

    def send(host, port, prompt, timeout=600):
        """One streaming /generate; returns status, client-side TTFT,
        and the decoded row (None on non-200 / severed stream)."""
        conn = HTTPConnection(host, port, timeout=timeout)
        try:
            t0 = time.perf_counter()
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [prompt.tolist()],
                 "max_new_tokens": new_tokens, "stream": True}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                return resp.status, None, None
            toks, ttft, severed = [], None, False
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                if ttft is None:
                    ttft = time.perf_counter() - t0
                d = json.loads(line)
                if "error" in d:
                    severed = True
                    break
                tl = d.get("tokens")   # flat: one entry per batch row
                if tl:
                    toks.append(tl[0])
            return resp.status, ttft, None if severed else toks
        except Exception:
            return -1, None, None
        finally:
            conn.close()

    def kv_totals():
        out = {"partial_hit_tokens": 0, "hits": 0, "misses": 0}
        for eng in engines:
            kv = eng.stats()["kvcache"]
            for k in out:
                out[k] += kv[k]
        return out

    def phase_metrics(before, after, ttfts, prompt_tokens):
        d = {k: after[k] - before[k] for k in before}
        lookups = d["hits"] + d["misses"]
        xs = sorted(t for t in ttfts if t is not None)
        return {
            "requests": len(ttfts),
            "ttft_p50_ms": round(_percentile(xs, 50) * 1e3, 2),
            "ttft_p95_ms": round(_percentile(xs, 95) * 1e3, 2),
            # fraction of submitted prompt tokens served from a warm
            # radix tree (full hits won't happen — suffixes are unique
            # — so reused tokens ARE the prefix-routing signal)
            "prefix_hit_rate": round(
                d["partial_hit_tokens"] / prompt_tokens, 4)
            if prompt_tokens else 0.0,
            "reused_prefix_tokens": d["partial_hit_tokens"],
            "radix_lookups": lookups,
        }

    def scrape_counter(gw, name):
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        for ln in text.splitlines():
            if ln.startswith(name + " ") or ln.startswith(name + "{"):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    engines = [ContinuousBatchingEngine(
        cfg, params, max_seq=max_seq, max_batch=slots, sampling=greedy,
        kv_cache_blocks=0, kv_block_tokens=block_tokens)
        for _ in range(n_replicas)]
    servers = []
    for eng in engines:
        srv = InferenceHTTPServer(eng, port=0, model_name=model)
        srv.start()
        servers.append(srv)

    # warm every replica's compile caches on BOTH admission shapes the
    # measured phases hit — the full-prompt bucket and the suffix-only
    # bucket behind a prefix hit — with an off-workload prefix
    warm_prefix = rng.integers(2, cfg.vocab_size - 1, prefix_len)
    for srv in servers:
        for _ in range(2):     # second send takes the prefix-hit path
            suffix = rng.integers(2, cfg.vocab_size - 1, suffix_len)
            warm = np.concatenate([warm_prefix, suffix]).astype(np.int32)
            st, _, _ = send(srv.host, srv.port, warm)
            if st != 200:
                raise RuntimeError(f"warmup failed on {srv.host}:"
                                   f"{srv.port} (status {st})")

    registry = ReplicaRegistry(
        [(s.host, s.port) for s in servers], sustain=2,
        readmit_cooldown_s=60.0, probe_interval_s=0.3)

    class _RoundRobinRouter(PrefixAwareRouter):
        """The baseline: same gateway, same proxy, zero cache sense."""

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self._rr = 0

        def route(self, tokens):
            ups = sorted(self.registry.up_replicas())
            if not ups:
                raise GatewayOverloaded("no replica up", retry_after_s=2.0)
            rid = ups[self._rr % len(ups)]
            self._rr += 1
            return RouteDecision(rid, "hash", 0,
                                 [r for r in ups if r != rid])

    n_tok = groups * per_group * (prefix_len + suffix_len)
    results = {}

    # -- phase 1: round-robin baseline -------------------------------------
    gw = GatewayHTTPServer(registry, _RoundRobinRouter(
        registry, min_prefix_tokens=min_prefix,
        block_tokens=block_tokens), port=0)
    gw.start()
    before = kv_totals()
    ttfts = [send(gw.host, gw.port, p)[1] for p in make_workload()]
    results["round_robin"] = phase_metrics(before, kv_totals(), ttfts,
                                           n_tok)
    gw.shutdown()

    # -- phase 2: cache-aware (fresh prefixes) -----------------------------
    router = PrefixAwareRouter(registry, min_prefix_tokens=min_prefix,
                               block_tokens=block_tokens)
    gw = GatewayHTTPServer(registry, router, port=0, retry_limit=2)
    gw.start()
    aware_prompts = make_workload()
    before = kv_totals()
    aware = [send(gw.host, gw.port, p) for p in aware_prompts]
    results["cache_aware"] = phase_metrics(
        before, kv_totals(), [t for _, t, _ in aware], n_tok)

    # -- phase 3: kill one replica mid-soak (same gateway) -----------------
    down_before = scrape_counter(gw, "dwt_gateway_replica_down_total")
    expected = {tuple(p.tolist()): toks
                for p, (st, _, toks) in zip(aware_prompts, aware)
                if st == 200 and toks}
    replay = [p for p in aware_prompts
              if tuple(p.tolist()) in expected][:kill_requests]
    victim = servers[0]
    kill_after = max(1, len(replay) // 3)
    done = []

    def one(i, p):
        if i == kill_after:
            victim.shutdown()    # drain: in-flight finish, connects die
        st, _, toks = send(gw.host, gw.port, p)
        done.append((tuple(p.tolist()), st, toks))

    with ThreadPoolExecutor(max_workers=3) as ex:
        list(ex.map(lambda a: one(*a), enumerate(replay)))
    completed = sum(1 for _, st, _ in done if st == 200)
    shed = sum(1 for _, st, _ in done if st in (503, 429))
    hung_or_failed = len(done) - completed - shed
    identical = all(toks == expected[key]
                    for key, st, toks in done if st == 200)
    # the debounce is asynchronous (background probes, sustain strikes):
    # a short replay can outrun it, so wait for the prober to strike the
    # dead victim out before reading the eviction counter — bounded, so
    # a wedged prober fails the gate instead of hanging the leg
    victim_rid = f"{victim.host}:{victim.port}"
    deadline = time.perf_counter() + 15.0
    while registry.is_up(victim_rid) and time.perf_counter() < deadline:
        time.sleep(0.05)
    down_moved = (scrape_counter(gw, "dwt_gateway_replica_down_total")
                  - down_before) >= 1
    results["kill"] = {
        "requests": len(done), "completed": completed, "shed_503": shed,
        "hung_or_failed": hung_or_failed,
        "bit_identical": bool(identical),
        "replica_down_moved": bool(down_moved),
        "survivors": registry.up_replicas(),
    }

    # -- phase 4: live rebalance under skewed load (docs/DESIGN.md §18) ----
    # The two SURVIVOR replicas at the engine seam.  A burst of
    # 2*slots requests all lands on one replica ("hot") while the
    # other idles — the worst skew the router can hand the fleet.  The
    # baseline decodes the burst in serial admission waves; the
    # rebalance run moves rows hot → light MID-DECODE over the §18
    # migration protocol, so the queued tail admits a wave early.
    # Gates: completion-latency p95 strictly improves AND every stream
    # stays bit-identical to the unmigrated run.
    from distributed_inference_demo_tpu.comm.transport import (
        LoopbackNetwork, LoopbackTransport)
    from distributed_inference_demo_tpu.runtime.disagg import MigrationError
    from distributed_inference_demo_tpu.runtime.migration import (
        MigrationController, MigrationWorker)

    hot_srv, light_srv = servers[1], servers[2]
    hot_e, light_e = engines[1], engines[2]
    mnet = LoopbackNetwork()
    hot_w = MigrationWorker(hot_e, LoopbackTransport("hot", mnet),
                            ack_timeout=2.0)
    light_w = MigrationWorker(light_e, LoopbackTransport("light", mnet),
                              ack_timeout=2.0)
    mthreads = [threading.Thread(target=w.serve_forever, daemon=True)
                for w in (hot_w, light_w)]
    for t in mthreads:
        t.start()

    # fresh prompts, with a decode runway long enough that one
    # admission wave costs SEVERAL handoffs (~100ms each on loopback)
    # — below that ratio the protocol cannot pay for itself on any
    # fabric.  2*slots deep: every queued row can admit via a freed
    # slot, so the TTFT tail is handoff-bound, not wave-bound.
    mig_new = min(448, max_seq - 64)
    mig_prompts = [rng.integers(2, cfg.vocab_size - 1, 32)
                   .astype(np.int32) for _ in range(2 * slots)]

    def settle_idle(timeout=10.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if (not hot_e.active_requests()
                    and not light_e.active_requests()):
                return
            time.sleep(0.02)

    # warm the migration path itself: the first export/adopt pays jit
    # on both replicas (~100ms+ on CPU) that the timed runs must not
    def _warm_migration():
        req = hot_e.submit(rng.integers(2, cfg.vocab_size - 1, 32)
                           .astype(np.int32), mig_new)
        deadline = time.perf_counter() + 5.0
        while (not hot_w.pick_migratable(1)
               and time.perf_counter() < deadline):
            time.sleep(0.002)
        for r in hot_w.pick_migratable(1):
            try:
                hot_w.migrate_out(r, "light")
            except (KeyError, MigrationError):
                pass
        req.wait(600)
        settle_idle()

    _warm_migration()

    def run_burst(migrate):
        t0 = time.perf_counter()
        reqs = [hot_e.submit(p, mig_new) for p in mig_prompts]
        stop = threading.Event()
        claim = {"moved": 0, "inflight": 0}
        picked, clock = set(), threading.Lock()

        def rebalancer():
            # move rows while hot still has a QUEUE (the signal that
            # skew is costing whole admission waves) and light has a
            # free slot: each handoff frees a hot slot so a queued row
            # admits handoff-early instead of wave-late.  Skip rows
            # past 2/3 of their budget (the handoff would cost more
            # than the tail it frees); at most ``slots`` total moves.
            # Two movers run this loop so handoffs overlap — the claim
            # set keeps them off the same rid.
            while not stop.is_set():
                if hot_e.stats()["queue_depth"] == 0:
                    return       # burst fully admitted: skew resolved
                with clock:
                    if claim["moved"] + claim["inflight"] >= slots:
                        return
                    rid = None
                    if (len(light_e.active_requests())
                            + claim["inflight"]) < slots:
                        cands = [r for r in hot_w.pick_migratable(
                            slots, min_remaining=max(32, mig_new // 3))
                            if r not in picked]
                        if cands:
                            rid = cands[0]
                            picked.add(rid)
                            claim["inflight"] += 1
                if rid is None:
                    time.sleep(0.005)
                    continue
                ok = False
                try:
                    ok = hot_w.migrate_out(rid, "light")
                except (KeyError, MigrationError):
                    pass         # resolved locally first / target hiccup
                with clock:
                    claim["inflight"] -= 1
                    if ok:
                        claim["moved"] += 1

        movers = []
        if migrate:
            movers = [threading.Thread(target=rebalancer, daemon=True)
                      for _ in range(2)]
            for m in movers:
                m.start()
        ttft_at = [None] * len(reqs)
        done_at, errs = [None] * len(reqs), [None] * len(reqs)

        def waiter(i, r):
            try:
                while not r.tokens and not r.done.is_set():
                    time.sleep(0.002)
                ttft_at[i] = time.perf_counter()
                r.wait(600)
            except Exception as e:
                errs[i] = e
            done_at[i] = time.perf_counter()

        ws = [threading.Thread(target=waiter, args=(i, r), daemon=True)
              for i, r in enumerate(reqs)]
        for w in ws:
            w.start()
        for w in ws:
            w.join(timeout=600)
        stop.set()
        for m in movers:
            m.join(timeout=5)
        settle_idle()
        return ([t - t0 for t in ttft_at if t is not None],
                [d - t0 for d in done_at],
                [[int(t) for t in r.tokens] for r in reqs],
                claim["moved"], [e for e in errs if e is not None])

    base = run_burst(migrate=False)
    mig = run_burst(migrate=True)
    base_ttfts, base_lats, base_streams, _, base_errs = base
    mig_ttfts, mig_lats, mig_streams, n_moved, mig_errs = mig
    results["live_rebalance"] = {
        "requests": len(mig_prompts),
        "moved": n_moved,
        "errors": len(base_errs) + len(mig_errs),
        # the §18 gate is TTFT p95 — the queued tail admitting a wave
        # early is migration's win, and it survives this harness's one
        # confound: both replicas share ONE host's compute here, so
        # total decode throughput (hence completion p95, reported
        # below as context) cannot improve the way it does when the
        # replicas are separate machines
        "ttft_p95_no_migration_ms": round(
            _percentile(sorted(base_ttfts), 95) * 1e3, 2),
        "ttft_p95_migration_ms": round(
            _percentile(sorted(mig_ttfts), 95) * 1e3, 2),
        "completion_p95_no_migration_ms": round(
            _percentile(sorted(base_lats), 95) * 1e3, 2),
        "completion_p95_migration_ms": round(
            _percentile(sorted(mig_lats), 95) * 1e3, 2),
        "bit_identical": mig_streams == base_streams,
    }

    # -- phase 5: graceful drain (docs/DESIGN.md §18) -----------------------
    # The real control path end to end: MigrationController over the
    # live gateway registry marks hot DRAINING (no new routes, no
    # eviction strike) and drives it empty via the same migrate_out
    # mechanism.  Gate: every in-flight request completes off the
    # drained replica, streams still bit-identical.
    hot_rid = f"{hot_srv.host}:{hot_srv.port}"
    light_rid = f"{light_srv.host}:{light_srv.port}"
    workers, peers = {hot_rid: hot_w}, {light_rid: "light"}

    def mover(src, dst, n):
        w, to = workers.get(src), peers.get(dst)
        if w is None or to is None:
            return 0
        m = 0
        for r in w.pick_migratable(n):
            try:
                if w.migrate_out(r, to):
                    m += 1
            except (KeyError, MigrationError):
                pass
        return m

    ctrl = MigrationController(registry, mover, load_gap=2,
                               max_moves_per_round=slots)
    drain_reqs = [hot_e.submit(p, mig_new) for p in mig_prompts[:slots]]
    # let the registry's async load view catch up before draining, or
    # the drain loop can read a stale pre-burst zero and return early
    deadline = time.perf_counter() + 10.0
    while ctrl.load(hot_rid) == 0 and time.perf_counter() < deadline:
        time.sleep(0.05)
    drain_moved = ctrl.drain(hot_rid, deadline_s=60.0)
    drain_completed, drain_streams = 0, []
    for r in drain_reqs:
        try:
            toks = [int(t) for t in r.wait(600)]
            drain_completed += 1
        except Exception:
            toks = None
        drain_streams.append(toks)
    settle_idle()
    results["drain"] = {
        "inflight": len(drain_reqs),
        "moved": drain_moved,
        "completed": drain_completed,
        "bit_identical": drain_streams == base_streams[:slots],
        "hot_idle_after": not hot_e.active_requests(),
        "draining_flag": bool(registry.is_draining(hot_rid)),
    }

    hot_w.stop()
    light_w.stop()
    for t in mthreads:
        t.join(timeout=2)

    gw.shutdown()
    for srv, eng in zip(servers, engines):
        if srv is not victim:
            srv.shutdown()
        eng.close()

    rr, aw, kl = (results["round_robin"], results["cache_aware"],
                  results["kill"])
    lr, dr = results["live_rebalance"], results["drain"]
    return {
        "model": model, "replicas": n_replicas, "groups": groups,
        "per_group": per_group, "prefix_len": prefix_len,
        "suffix_len": suffix_len, "new_tokens": new_tokens, **results,
        # the §16 acceptance gates
        "cache_aware_wins_hit_rate": (aw["prefix_hit_rate"]
                                      > rr["prefix_hit_rate"]),
        "cache_aware_wins_ttft_p95": (aw["ttft_p95_ms"]
                                      < rr["ttft_p95_ms"]),
        "kill_zero_hangs": kl["hung_or_failed"] == 0,
        "kill_bit_identical": kl["bit_identical"],
        "kill_replica_down_moved": kl["replica_down_moved"],
        # the §18 acceptance gates
        "rebalance_p95_wins": (lr["moved"] >= 1
                               and lr["ttft_p95_migration_ms"]
                               < lr["ttft_p95_no_migration_ms"]),
        "rebalance_bit_identical": (lr["bit_identical"]
                                    and lr["errors"] == 0),
        "drain_all_completed": (dr["completed"] == dr["inflight"]
                                and dr["hot_idle_after"]
                                and dr["bit_identical"]),
    }


def _leg_stream_failover(model: str, n_req: int = 8, prompt_len: int = 96,
                         new_tokens: int = 24, slots: int = 4,
                         max_seq: int = 512, block_tokens: int = 8,
                         crash_after: int = 6,
                         seed_victim: int = 3) -> dict:
    """Zero-loss streams (docs/DESIGN.md §23): kill a replica mid-soak
    and measure the resume path end to end — real HTTP client →
    gateway → replica, greedy so bit-identity is checkable.

    Three phases over the SAME two-replica fleet:

    - *reference*: the unfailed run.  Every prompt streams to
      completion with both replicas healthy; the recorded streams are
      the bit-identity oracle for everything after.
    - *failover*: the victim replica is armed to die ``crash_after``
      tokens into every stream it serves (the §23 mid-stream error
      seam), ``seed_victim`` prompts are pinned to it via the routing
      index, and the soak re-runs with ``resume_limit=1``.  Gates:
      100% completion, zero error lines, every stream bit-identical to
      the reference with contiguous steps, resume attempts == resume
      successes, the SLO ledger books each replay as a resume pause
      with the timeline decomposition still summing exactly, and the
      registry strikes the victim out.  Reported: TTF-resumed-token
      p95 (detect → route → re-POST → replay) interpolated from the
      gateway's own ``dwt_gateway_resume_ttf_seconds`` histogram.
    - *documented_loss*: one pinned prompt through a fresh gateway
      with ``resume_limit=0``: the pre-§23 contract — delivered
      prefix + error line, never a hang — stays reachable and
      documented.

    Zero-leak gates close the leg on BOTH paths: the survivor (served
    every resume) and the victim (its crashed streams must return
    their pages, as a restarted process would want them)."""
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from http.client import HTTPConnection

    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.gateway import (
        GatewayHTTPServer, PrefixAwareRouter, ReplicaRegistry)
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    from distributed_inference_demo_tpu.runtime.stats import _percentile
    from distributed_inference_demo_tpu.telemetry.slo import (
        SloLedger, set_slo_ledger)

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    rng = np.random.default_rng(23)
    prompts = [rng.integers(2, cfg.vocab_size - 1, prompt_len)
               .astype(np.int32) for _ in range(n_req)]

    class _DyingBackend:
        """The victim: while armed, every stream dies ``crash_after``
        tokens in — the engine generator is closed eagerly so the dead
        path's pages come back the way a crashed process's restart
        would reclaim them."""

        def __init__(self, inner):
            self._inner = inner
            self.armed = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def generate_stream(self, *a, **kw):
            gen = self._inner.generate_stream(*a, **kw)
            try:
                for i, item in enumerate(gen):
                    if self.armed and i >= crash_after:
                        raise RuntimeError(
                            f"injected replica death after {i} tokens")
                    yield item
            finally:
                gen.close()

    def send(host, port, prompt):
        """One streaming /generate; returns (status, token list or
        None if an error line arrived, delivered-before-error count,
        step list)."""
        conn = HTTPConnection(host, port, timeout=600)
        try:
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [prompt.tolist()],
                 "max_new_tokens": new_tokens, "stream": True}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                return resp.status, None, 0, []
            toks, steps, errored = [], [], False
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "error" in d:
                    errored = True
                    break
                tl = d.get("tokens")
                if tl:
                    toks.append(tl[0])
                    steps.append(d.get("step"))
            return (resp.status, None if errored else toks, len(toks),
                    steps)
        except Exception:
            return -1, None, 0, []
        finally:
            conn.close()

    def scrape(gw):
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            return conn.getresponse().read().decode()
        finally:
            conn.close()

    def counter_val(text, name):
        for ln in text.splitlines():
            if ln.startswith(name + " ") or ln.startswith(name + "{"):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    def hist_p95(text, name):
        """PromQL-style histogram_quantile over the text exposition:
        cumulative le buckets, linear interpolation inside the bucket
        the 95th observation lands in."""
        pts = []
        for ln in text.splitlines():
            if ln.startswith(name + "_bucket{"):
                le = ln.split('le="', 1)[1].split('"', 1)[0]
                pts.append((float("inf") if le == "+Inf" else float(le),
                            float(ln.rsplit(" ", 1)[1])))
        pts.sort()
        total = pts[-1][1] if pts else 0.0
        if total <= 0:
            return None
        rank = 0.95 * total
        lo_b, lo_c = 0.0, 0.0
        for b, c in pts:
            if c >= rank:
                if b == float("inf"):
                    return round(lo_b * 1e3, 2)
                frac = (rank - lo_c) / max(c - lo_c, 1e-12)
                return round((lo_b + (b - lo_b) * frac) * 1e3, 2)
            lo_b, lo_c = b, c
        return None

    def settle_idle(timeout=30.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if not any(e.active_requests() for e in engines):
                return
            time.sleep(0.02)

    def no_leak(eng):
        mgr = eng.kv_cache
        return (mgr.used_blocks == mgr.tree.block_count
                and mgr.debug_state()["leased_nodes"] == 0)

    engines = [ContinuousBatchingEngine(
        cfg, params, max_seq=max_seq, max_batch=slots, sampling=greedy,
        kv_cache_blocks=0, kv_block_tokens=block_tokens)
        for _ in range(2)]
    victim_backend = _DyingBackend(engines[0])
    servers = []
    for backend in (victim_backend, engines[1]):
        srv = InferenceHTTPServer(backend, port=0, model_name=model)
        srv.start()
        servers.append(srv)
    victim_rid = f"{servers[0].host}:{servers[0].port}"

    # warm both replicas' compile caches off-workload, including the
    # resume admission shape (prompt + delivered prefix re-prefill)
    warm = rng.integers(2, cfg.vocab_size - 1, prompt_len) \
        .astype(np.int32)
    for srv in servers:
        st, _, _, _ = send(srv.host, srv.port, warm)
        if st != 200:
            raise RuntimeError(f"warmup failed on {srv.host}:{srv.port} "
                               f"(status {st})")

    def fresh_gateway(resume_limit):
        registry = ReplicaRegistry(
            [(s.host, s.port) for s in servers], sustain=2,
            readmit_cooldown_s=60.0, probe_interval_s=0.3)
        router = PrefixAwareRouter(registry,
                                   min_prefix_tokens=block_tokens,
                                   block_tokens=block_tokens)
        gw = GatewayHTTPServer(registry, router, port=0,
                               resume_limit=resume_limit)
        gw.start()
        return gw, registry, router

    results = {}

    # -- phase 1: reference (unfailed) --------------------------------------
    gw, registry, router = fresh_gateway(resume_limit=1)
    ref = [send(gw.host, gw.port, p) for p in prompts]
    gw.shutdown()
    settle_idle()
    if any(st != 200 or toks is None or len(toks) != new_tokens
           for st, toks, _, _ in ref):
        raise RuntimeError("reference phase did not complete cleanly")
    ref_streams = [toks for _, toks, _, _ in ref]
    results["reference"] = {"requests": n_req, "completed": n_req}

    # -- phase 2: failover soak (resume_limit=1, victim dies) ---------------
    led = SloLedger(ttft_slo_ms=60_000, tpot_slo_ms=60_000)
    set_slo_ledger(led)
    try:
        gw, registry, router = fresh_gateway(resume_limit=1)
        # pin a slice of the soak to the victim so streams are
        # guaranteed to be mid-flight on it when it starts dying
        for p in prompts[:seed_victim]:
            router.record(victim_rid, p.tolist())
        before = scrape(gw)
        victim_backend.armed = True
        out = [None] * n_req

        def one(i):
            out[i] = send(gw.host, gw.port, prompts[i])

        with ThreadPoolExecutor(max_workers=3) as ex:
            list(ex.map(one, range(n_req)))
        after = scrape(gw)
        settle_idle()
        victim_backend.armed = False

        completed = sum(1 for st, toks, _, _ in out
                        if st == 200 and toks is not None)
        identical = all(
            st == 200 and toks == ref_streams[i]
            for i, (st, toks, _, _) in enumerate(out))
        steps_contiguous = all(
            steps == list(range(len(toks or [])))
            for _, toks, _, steps in out)
        d = {name: counter_val(after, name) - counter_val(before, name)
             for name in ("dwt_gateway_resume_attempts_total",
                          "dwt_gateway_resume_succeeded_total",
                          "dwt_gateway_resume_exhausted_requests_total")}
        resumed_recs = [r for r in led.recent(4 * n_req)
                        if r.get("resumed")]
        decomposed = all(
            abs(r["ttft_s"] + r["per_token_s"] * (r["tokens"] - 1)
                + r["migration_pause_s"] + r["resume_pause_s"]
                - r["e2e_s"]) <= 1e-6 * max(r["e2e_s"], 1.0)
            for r in resumed_recs)
        results["failover"] = {
            "requests": n_req,
            "completed": completed,
            "bit_identical": bool(identical),
            "steps_contiguous": bool(steps_contiguous),
            "resume_attempts": int(d["dwt_gateway_resume_attempts_total"]),
            "resume_succeeded": int(
                d["dwt_gateway_resume_succeeded_total"]),
            "resume_exhausted": int(
                d["dwt_gateway_resume_exhausted_requests_total"]),
            "resume_ttf_p95_ms": hist_p95(
                after, "dwt_gateway_resume_ttf_seconds"),
            "slo_resumed_requests": len(resumed_recs),
            "slo_resume_pause_p95_ms": round(_percentile(
                sorted(r["resume_pause_s"] for r in resumed_recs), 95)
                * 1e3, 2) if resumed_recs else None,
            "slo_decomposition_exact": bool(decomposed),
            "victim_struck": not registry.is_up(victim_rid),
        }
        gw.shutdown()
    finally:
        set_slo_ledger(None)
        victim_backend.armed = False

    # -- phase 3: documented loss at resume_limit=0 -------------------------
    gw, registry, router = fresh_gateway(resume_limit=0)
    router.record(victim_rid, prompts[0].tolist())
    victim_backend.armed = True
    st, toks, delivered, _ = send(gw.host, gw.port, prompts[0])
    victim_backend.armed = False
    gw.shutdown()
    settle_idle()
    results["documented_loss"] = {
        "status": st,
        "error_line": toks is None,
        "delivered_before_error": delivered,
    }

    for srv in servers:
        srv.shutdown()
    leak_free = {"survivor": no_leak(engines[1]),
                 "victim": no_leak(engines[0])}
    for eng in engines:
        eng.close()

    fo, dl = results["failover"], results["documented_loss"]
    return {
        "model": model, "requests": n_req, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "crash_after": crash_after,
        **results,
        # the §23 acceptance gates
        "failover_completed_100pct": fo["completed"] == n_req,
        "failover_bit_identical": (fo["bit_identical"]
                                   and fo["steps_contiguous"]),
        "resume_all_succeeded": (fo["resume_attempts"] >= 1
                                 and fo["resume_succeeded"]
                                 == fo["resume_attempts"]
                                 and fo["resume_exhausted"] == 0),
        "slo_books_resume": (fo["slo_resumed_requests"]
                             == fo["resume_succeeded"]
                             and fo["slo_decomposition_exact"]),
        "loss_documented_at_limit_0": (dl["status"] == 200
                                       and dl["error_line"]
                                       and 1 <= dl[
                                           "delivered_before_error"]
                                       < new_tokens),
        "zero_leak_survivor": leak_free["survivor"],
        "zero_leak_victim": leak_free["victim"],
    }


# ---------------------------------------------------------------------------

def micro_shape(p: dict) -> dict:
    """The micro-prepass shape (tools/measure_session.py): the SAME
    model and leg structure at the smallest meaningful scale — 1 round,
    tiny token budgets — so a short healthy tunnel window can bank a
    coarse number for EVERY leg before the full-budget passes start
    (r03-r05 each lost most legs to mid-session tunnel wedges)."""
    return dict(p, batch=min(p["batch"], 2),
                prompt_len=min(p["prompt_len"], 32),
                new_tokens=min(p["new_tokens"], 8))


# headline-order legs that stamp the §20 cost-observatory block into
# their artifact (BENCH_SELF r06+): per-signature p50/p95 from the
# sampled dispatch profiler plus the compile ledger.  Each leg runs in
# a fresh subprocess (_spawn_leg), so the process-global observatory
# snapshot IS that leg's own dispatches — no cross-leg bleed.
_PROFILED_LEGS = {"headline", "headline_int8", "flagship_bf16",
                  "flagship_int8", "decode_fused", "batching",
                  "mixed_batching", "spec_mixed", "tiered_prefix"}


def _dispatch_profile_extras() -> dict:
    """The ``dispatch_profile`` artifact block: per-signature p50/p95
    (+ achieved GB/s where attributed) and compile counts, from this
    process's cost observatory.  Empty dict when nothing was profiled
    (DWT_PROFILE_SAMPLE_N=0, or a leg that never dispatched a tracked
    program) — the block is then omitted rather than stamped hollow."""
    try:
        from distributed_inference_demo_tpu.telemetry import profiling
        prof = profiling.get_profiler()
        sigs = prof.snapshot()
        comp = profiling.get_compile_tracker().snapshot()
    except Exception:
        return {}
    if not sigs and not comp:
        return {}
    return {"sample_n": prof.sample_n, "signatures": sigs,
            "compile": comp}


def run_leg(name: str, p: dict, micro: bool = False) -> dict:
    if micro:
        p = micro_shape(p)
    model, batch = p["model"], p["batch"]
    prompt_len, new_tokens = p["prompt_len"], p["new_tokens"]
    flagship = p["flagship"]
    try:
        if name == "headline":
            out = _bench_engine(model, batch, prompt_len, new_tokens,
                                latency=not micro)
        elif name == "headline_int8":
            out = _bench_engine(model, batch, prompt_len, new_tokens,
                                quant=True, latency=not micro)
        elif name == "sweep":
            # the FULL b8/32/64 x {bf16,int8,int4} grid at BOTH budgets
            # (carried satellite, promoted): the micro prepass banks
            # coarse numbers for every shape in the first healthy
            # window, and the full-budget pass now measures the same
            # grid properly — the narrower b32/64 x {bf16,int8} grid
            # left the b8 points and the int4 column micro-only for
            # two rounds running
            out = _leg_sweep(model, prompt_len, new_tokens,
                             quants=(False, True, "int4"),
                             batches=(8, 32, 64),
                             kv_dtypes=("bf16", "int8", "int4"))
        elif name == "flagship_int8":
            out = _leg_flagship(flagship, batch, prompt_len,
                                min(new_tokens, 64), quant=True)
        elif name == "flagship_bf16":
            out = _leg_flagship(flagship, batch, prompt_len,
                                min(new_tokens, 64), quant=False)
        elif name == "speculative":
            out = _leg_speculative(model, batch, prompt_len, new_tokens)
        elif name == "prompt_lookup":
            out = _leg_prompt_lookup(model, new_tokens)
        elif name == "batching":
            out = _leg_batching(model, prompt_len, min(new_tokens, 64))
        elif name == "mixed_batching":
            # the micro shape keeps the §19 gate structural on CPU:
            # 12-chunk prompts over 4 slots with 3 pinned decode rows,
            # all arrivals at once — the serialized baseline pays one
            # suppressed per-token dispatch per step PLUS one dispatch
            # per chunk, mixed pays ~1 per decode_block with the
            # chunks riding along
            out = (_leg_mixed_batching(model, prompt_len=96,
                                       new_tokens=16, slots=4, n_req=8,
                                       prefill_chunk=8, decode_block=4,
                                       arrival_s=0.0, block_tokens=8)
                   if micro else _leg_mixed_batching(model))
        elif name == "spec_mixed":
            # the micro shape keeps the §22 comparison structural on
            # CPU: motif-tiled chunky prompts over 4 slots with 3
            # pinned background rows, all arrivals at once, K=2 — the
            # three engine builds and the packed-with-rounds program
            # variants all exercise at the smallest meaningful scale
            out = (_leg_spec_mixed(model, prompt_len=96, new_tokens=8,
                                   slots=4, n_req=6, prefill_chunk=8,
                                   decode_block=4, num_draft=2,
                                   arrival_s=0.0, block_tokens=8)
                   if micro else _leg_spec_mixed(model))
        elif name == "prefix_reuse":
            out = _leg_prefix_reuse(model, min(new_tokens, 64))
        elif name == "tiered_prefix":
            # the micro shape keeps the §21 gate structural on CPU: a
            # 14-block pool under a 4-group working set (8 blocks per
            # group) thrashes every revisit, the 2-group host ring
            # forces the rest through the disk segment
            out = (_leg_tiered_prefix(model, min(new_tokens, 8),
                                      groups=4, revisits=2,
                                      shared_len=48, tail_len=8,
                                      block_tokens=8, kv_blocks=14,
                                      host_groups=2) if micro
                   else _leg_tiered_prefix(model, new_tokens))
        elif name == "paged_decode":
            out = _leg_paged_decode(model, new_tokens)
        elif name == "serving_relative":
            out = (_leg_serving_relative(model, batch, prompt_len,
                                         new_tokens, slots=2, n_req=4)
                   if micro else
                   _leg_serving_relative(model, batch, prompt_len,
                                         new_tokens))
        elif name == "decode_fused":
            out = (_leg_decode_fused(model, prompt_len, new_tokens,
                                     batches=(1,), blocks=(1, 4))
                   if micro else
                   _leg_decode_fused(model, prompt_len, new_tokens))
        elif name == "pipeline":
            out = _leg_pipeline(model, batch, prompt_len,
                                min(new_tokens, 32))
        elif name == "fault_recovery":
            out = (_leg_fault_recovery(model, new_tokens=8) if micro
                   else _leg_fault_recovery(model))
        elif name == "disagg":
            # the micro shape keeps decode SATURATED (7 of 8 slots
            # pinned): the interleaved-step stall the split removes is
            # only visible under real concurrent decode load
            out = (_leg_disagg(model, n_req=3, prompt_len=128,
                               prefill_chunk=8, max_seq=1024,
                               block_tokens=8) if micro
                   else _leg_disagg(model))
        elif name == "gateway_routing":
            # the micro shape keeps the structure (3 replicas, grouped
            # shared prefixes, a drained replica) at the smallest scale
            # where the TTFT-p95 gate stays structural: enough requests
            # per group that cache-aware's full prefills sit below the
            # percentile while round-robin's sit above it
            out = (_leg_gateway_routing(model, groups=2, per_group=20,
                                        prefix_len=300, suffix_len=8,
                                        new_tokens=4, slots=2,
                                        max_seq=512, block_tokens=16,
                                        kill_requests=4) if micro
                   else _leg_gateway_routing(model))
        elif name == "stream_failover":
            # the micro shape keeps the §23 gates structural on CPU:
            # two replicas, a 4-stream soak with 2 streams pinned to
            # the dying victim, death 2 tokens in — enough to cover
            # detect → re-route → replay → bit-identical suffix
            out = (_leg_stream_failover(model, n_req=4, prompt_len=32,
                                        new_tokens=8, slots=2,
                                        max_seq=256, block_tokens=8,
                                        crash_after=2, seed_victim=2)
                   if micro else _leg_stream_failover(model))
        elif name == "planner_pipeline":
            out = _leg_planner_pipeline(model, batch, prompt_len,
                                        min(new_tokens, 8))
        elif name == "prefill_long":
            out = (_leg_prefill_long(model, seqs=(512,)) if micro
                   else _leg_prefill_long(model))
        elif name == "long_context_sp":
            # the carried >=32k sequence-parallel satellite PROMOTED to
            # a full-budget headline-order leg: ring AND ulysses points
            # at >= 32k context (BENCH_LONG_CTX_SP overrides for CPU
            # structure tests), not just the micro prepass
            out = {"points": _long_context_sp_points(
                model, new=8 if micro else 64)}
            errs = [p for p in out["points"] if "error" in p]
            if errs and len(errs) == len(out["points"]):
                out["error"] = errs[0]["error"]
        elif name == "long_context":
            if micro:
                # one chunk-multiple context that still exercises the
                # chunked-prefill + full-context-decode structure; the
                # >= 32k sp strategy points ride the micro prepass too
                # (carried satellite) so both strategies bank a number
                # in the first healthy window
                os.environ.setdefault("BENCH_LONG_CTX", "4096")
            out = _leg_long_context(model)
            if micro:
                out["sp_points"] = _long_context_sp_points(model)
        elif name in ("roofline_probe", "roofline_probe_rerun"):
            # the rerun executes the SAME probe immediately after the
            # headline leg, so the ceiling the headline is judged
            # against was measured adjacent to it, not minutes earlier
            # through a different tunnel mood (the r05 artifact's 1.691
            # "fraction" came from exactly that gap)
            out = (_leg_roofline_probe(reps=8, rounds_n=1) if micro
                   else _leg_roofline_probe())
        elif name == "moe":
            out = _leg_moe(batch, prompt_len, min(new_tokens, 64))
        elif name == "multimodal":
            out = _leg_multimodal(batch, min(new_tokens, 64))
        elif name == "int4":
            out = _leg_int4(model, flagship, batch, prompt_len,
                            new_tokens)
        else:
            raise SystemExit(f"unknown leg {name!r}")
    except Exception as e:         # structured error, not a dead process
        out = {"error": f"{type(e).__name__}: {e}"}
    if name in _PROFILED_LEGS and "error" not in out:
        dp = _dispatch_profile_extras()
        if dp:
            out["dispatch_profile"] = dp
    if micro:
        # stamped so a micro number can never masquerade as a
        # full-budget measurement in the artifact
        out["micro"] = True
        out["micro_shape"] = {k: p[k] for k in ("batch", "prompt_len",
                                                "new_tokens")}
    if "device" not in out:
        # guarded + lazy: the planner leg sets its own device string (its
        # subprocess owns the exclusive TPU), and an error path must not
        # die here trying to init a backend
        try:
            out["device"] = _device_kind()
        except Exception:
            pass
    return out


def _load_prior() -> dict:
    """Measured legs from this round's incremental-session artifact
    (tools/measure_session.py), used ONLY to annotate a live run's failed
    legs: the r03 driver bench printed all-null because the tunnel was
    down at round end even though the same numbers had been measured
    hours earlier.  Prior results are always labeled as prior — they
    never masquerade as the live run's."""
    names = [os.environ.get("BENCH_PRIOR_ARTIFACT", PRIOR_ARTIFACT_NAME)]
    names += [n for n in PRIOR_ARTIFACT_FALLBACKS if n not in names]
    legs, sources, meta = {}, [], None
    for name in names:
        path = REPO / name
        try:
            art = json.loads(path.read_text())
            mtime = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  time.gmtime(path.stat().st_mtime))
        except (OSError, json.JSONDecodeError):
            continue
        # provenance rides every prior label: which file, written when —
        # so a stale artifact (e.g. a new round without the constant
        # bumped) is visible instead of masquerading as fresh
        art_src = f"{name} (written {mtime})"
        found = {}
        h = art.get("headline") or {}
        if h and "error" not in h:
            found["headline"] = h
        for k, v in (art.get("extras") or {}).items():
            if k in _NON_LEG_EXTRAS or k.endswith("_rerun"):
                continue
            if isinstance(v, dict) and v and "error" not in v:
                found[k] = v
        added = False
        for k, v in found.items():
            if k not in legs:          # newest artifact wins per leg
                legs[k] = dict(v)
                legs[k]["prior_source"] = art_src
                added = True
                if k == "headline":
                    # top-level metric/value travel with the artifact
                    # whose headline we borrowed (they were computed for
                    # THAT run — pairing them with another artifact's
                    # headline would mislabel the comparison)
                    meta = {"metric": art.get("metric"),
                            "value": art.get("value"),
                            "vs_baseline": art.get("vs_baseline"),
                            "note": art.get("note", "")}
        if added:
            sources.append(art_src)
    if not legs:
        return {}
    meta = meta or {"metric": None, "value": None, "vs_baseline": None,
                    "note": ""}
    return {"legs": legs, "note": meta["note"],
            "source": "; ".join(sources),
            "metric": meta["metric"], "value": meta["value"],
            "vs_baseline": meta["vs_baseline"]}


def headline_summary(headline: dict, params: dict, device: str) -> dict:
    """The artifact's top-level metric/value/vs_baseline/baseline block —
    ONE owner for the comparability caveats, shared by main() and the
    incremental session harness (tools/measure_session.py).

    Only a same-model/batch/prompt/new-tokens comparison is meaningful;
    anything else reports null rather than a mislabeled multiplier.  The
    one stated asymmetry is dtype: CPU runs f32 (its native dtype — bf16
    is emulated and slower there), TPU runs bf16."""
    baseline = _load_baseline()
    tps = headline.get("decode_tokens_per_sec")
    base_tps = baseline.get("tokens_per_sec")
    comparable = all(
        baseline.get(k) == params[k]
        for k in ("model", "batch", "prompt_len", "new_tokens"))
    vs = (round(tps / base_tps, 2)
          if tps is not None and base_tps and comparable else None)
    return {
        "metric": f"decode tokens/sec ({params['model']}, "
                  f"{headline.get('dtype', '?')}, batch={params['batch']}, "
                  f"prompt={params['prompt_len']}, "
                  f"new={params['new_tokens']}, "
                  f"device={device}) vs measured 2-process CPU "
                  f"socket-pipeline baseline (same model/batch/prompt/new; "
                  f"CPU at f32, its native dtype)",
        "value": tps,
        "vs_baseline": vs,
        "baseline": {k: baseline.get(k) for k in
                     ("tokens_per_sec", "model", "dtype", "batch", "host",
                      "cpu", "measured_at", "source")},
    }


def _run_group_killable(cmd, timeout: int):
    """Run ``cmd`` in its own process GROUP; on timeout kill the whole
    group (children included — e.g. the planner leg's server/worker hold
    the exclusive TPU and ports) and survive a D-state child on a wedged
    tunnel.  Returns (returncode_or_None_on_timeout, stdout, stderr).

    Every child gets JAX's persistent compilation cache pointed at a
    repo-local dir: leg wall-time over the tunnel is compile-dominated,
    and the cache makes a re-run of the same leg (watcher session now,
    driver bench at round end) nearly compile-free.  Harmless where the
    backend ignores it — a miss is just the normal path."""
    import signal

    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   str(REPO / ".jax_cache"))
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=str(REPO), start_new_session=True,
                            env=env)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, stdout, stderr
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass   # D-state on a wedged tunnel: report and move on anyway
        return None, "", ""


def _spawn_leg(name: str, params: dict, timeout: int = 900,
               micro: bool = False) -> dict:
    """Run one leg in a fresh process; parse the last stdout line as JSON."""
    rc, stdout, stderr = _run_group_killable(
        [sys.executable, str(REPO / "bench.py"), "--leg", name,
         "--params", json.dumps(params)]
        + (["--micro"] if micro else []), timeout)
    if rc is None:
        return {"error": f"leg timed out after {timeout}s"}
    lines = [l for l in stdout.strip().splitlines() if l.strip()]
    if rc != 0 or not lines:
        tail = (stderr or "").strip().splitlines()[-8:]
        return {"error": f"leg exited rc={rc}", "stderr_tail": tail}
    try:
        return json.loads(lines[-1])
    except json.JSONDecodeError:
        return {"error": f"unparseable leg output: {lines[-1][:200]}"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--leg")
    ap.add_argument("--params")
    ap.add_argument("--micro", action="store_true",
                    help="run the leg's micro variant (1 round, smallest "
                         "meaningful shape — the measurement session's "
                         "prepass)")
    ap.add_argument("--run-log", default=os.environ.get("BENCH_RUN_LOG",
                                                        ""),
                    help="append structured JSONL run-log events "
                         "(telemetry/runlog) for this bench run; leg "
                         "subprocesses inherit it via DWT_RUN_LOG and "
                         "write per-pid siblings")
    args = ap.parse_args()

    from distributed_inference_demo_tpu.telemetry.runlog import (
        NULL, RunLog, set_run_log)
    if args.run_log:
        runlog = RunLog(args.run_log)
        set_run_log(runlog)
        # engines inside leg subprocesses log their per-request
        # summaries next to ours (runlog suffixes the path per pid)
        os.environ["DWT_RUN_LOG"] = args.run_log
    else:
        # don't install NULL: a leg subprocess must keep get_run_log()'s
        # lazy DWT_RUN_LOG resolution (set by the orchestrator above)
        runlog = NULL

    params = {
        "model": os.environ.get("BENCH_MODEL", "tinyllama-1.1b"),
        "batch": int(os.environ.get("BENCH_BATCH", "8")),
        "prompt_len": int(os.environ.get("BENCH_PROMPT", "64")),
        "new_tokens": int(os.environ.get("BENCH_NEW_TOKENS", "128")),
        "flagship": os.environ.get("BENCH_FLAGSHIP", "llama-3-8b"),
    }
    if args.leg:  # subprocess mode: one leg, one JSON line
        if args.params:
            params.update(json.loads(args.params))
        print(json.dumps(run_leg(args.leg, params, micro=args.micro)))
        return

    # priority order: never-measured evidence first (speculative /
    # prompt_lookup / planner_pipeline / long_context), then the flagship
    # headline re-measurement, THEN the expensive multi-engine batching
    # leg (its 1500s budget must not starve the flagship under the
    # driver's deadline), then the already-proven tails
    legs = ["roofline_probe", "headline", "roofline_probe_rerun",
            "headline_int8", "decode_fused", "speculative",
            "prompt_lookup", "planner_pipeline", "long_context",
            "long_context_sp", "disagg", "gateway_routing",
            "stream_failover",
            "flagship_int8", "batching", "mixed_batching",
            "spec_mixed", "prefix_reuse", "tiered_prefix", "paged_decode",
            "serving_relative", "sweep", "flagship_bf16", "pipeline",
            "fault_recovery", "prefill_long", "moe", "multimodal",
            "int4"]
    for skip_var, leg_names in (
            ("BENCH_SKIP_FLAGSHIP", ["flagship_int8", "flagship_bf16"]),
            ("BENCH_SKIP_PIPELINE", ["pipeline", "planner_pipeline",
                                     "fault_recovery"]),
            ("BENCH_SKIP_SWEEP", ["sweep"]),
            ("BENCH_SKIP_SERVING", ["speculative", "prompt_lookup",
                                    "batching", "mixed_batching",
                                    "spec_mixed",
                                    "prefix_reuse", "tiered_prefix",
                                    "paged_decode",
                                    "serving_relative", "disagg",
                                    "gateway_routing",
                                    "stream_failover"]),
            ("BENCH_SKIP_LONGCTX", ["long_context", "long_context_sp"]),
            ("BENCH_SKIP_PREFILL", ["prefill_long"]),
            ("BENCH_SKIP_MOE_MM", ["moe", "multimodal"]),
            ("BENCH_SKIP_INT4", ["int4"])):
        if os.environ.get(skip_var, "") == "1":
            legs = [l for l in legs if l not in leg_names]
    only = os.environ.get("BENCH_ONLY")
    if only:
        legs = [l for l in legs if l in only.split(",")]

    # fast health probe: when the tunnel TPU is wedged (it hangs for long
    # stretches), fail every leg in ~2 minutes with a clear reason instead
    # of burning the whole deadline discovering it leg by leg
    rc, p_out, p_err = _run_group_killable(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].device_kind)"], timeout=180)
    backend_ok = rc == 0
    if rc is None:
        reason = "the device backend did not answer a 180s probe (hung?)"
    elif rc != 0:
        last = ((p_err or "").strip().splitlines() or ["?"])[-1]
        reason = f"device probe exited rc={rc}: {last}"
    if not backend_ok:
        runlog.event("bench_abort", reason=reason)
        out = {
            "metric": "decode tokens/sec (backend unreachable)",
            "value": None, "unit": "tokens/sec", "vs_baseline": None,
            "headline": {},
            "extras": {"error": f"backend unreachable, no leg attempted: "
                                f"{reason}"}}
        prior = _load_prior()
        if prior.get("legs"):
            # surface this round's incremental-session measurements so an
            # end-of-round tunnel outage can't zero the round's evidence;
            # every field says PRIOR
            out["value"] = prior["value"]
            out["vs_baseline"] = prior["vs_baseline"]
            out["metric"] = (
                (prior["metric"] or out["metric"])
                + f" [PRIOR measurement from {prior['source']}; the live "
                  "end-of-round run could not reach the device]")
            out["headline"] = prior["legs"].get("headline", {})
            out["extras"]["prior_legs"] = {
                k: v for k, v in prior["legs"].items() if k != "headline"}
            out["extras"]["prior_note"] = prior["note"]
        print(json.dumps(out))
        return

    # global deadline: the tunnel TPU hangs for many minutes at times, and
    # one JSON line MUST still be printed — remaining legs are skipped,
    # never the report (a round-3 run lost every number to an outer
    # timeout exactly this way)
    deadline = time.monotonic() + int(
        os.environ.get("BENCH_DEADLINE_S", "2700"))
    # the batching leg builds several engine instances (plain compare +
    # slot/decode-block/speculative phases), each with its own compiles —
    # give it more rope than the single-engine legs
    # paged_decode keeps the acceptance shape (new=128, unclamped) and
    # builds two engines + three waves — budget it like batching
    # gateway_routing runs three replica engines through three phases
    # (two routed soaks + the drain) — multi-engine, budget it likewise
    # tiered_prefix builds two engines (re-prefill reference + tiered)
    # and runs two routed rounds each — budget it like prefix_reuse
    # spec_mixed builds THREE engines (spec-only, mixed-only, fused)
    # over the same arrival stream — budget it like batching
    # stream_failover runs two replica engines through three routed
    # phases (reference soak, failover soak, documented loss) — budget
    # it like gateway_routing
    leg_timeouts = {"batching": 1500, "mixed_batching": 1500,
                    "spec_mixed": 1500,
                    "prefix_reuse": 1200, "tiered_prefix": 1200,
                    "paged_decode": 1500, "serving_relative": 1500,
                    "gateway_routing": 1500, "stream_failover": 1500}
    runlog.event("bench_start", params=params, legs=legs)
    results = {}
    for leg in legs:
        left = deadline - time.monotonic()
        if left <= 120:    # a leg needs real budget (compiles alone are ~2m)
            results[leg] = {"error": "skipped: bench deadline reached"}
            runlog.event("bench_leg", leg=leg, skipped=True,
                         error=results[leg]["error"])
            continue
        t0 = time.perf_counter()
        results[leg] = _spawn_leg(leg, params,
                                  timeout=min(leg_timeouts.get(leg, 900),
                                              int(left)))
        if isinstance(results[leg], dict):
            results[leg]["leg_seconds"] = round(time.perf_counter() - t0, 1)
        runlog.event("bench_leg", leg=leg,
                     seconds=round(time.perf_counter() - t0, 1),
                     error=(results[leg].get("error")
                            if isinstance(results[leg], dict) else None))

    headline = results.get("headline", {})
    # headline may have errored; any leg that reached the device knows it
    # (planner_pipeline excluded: its device field is a topology
    # description, not a chip identity)
    device = headline.get("device") or next(
        (r["device"] for name, r in results.items()
         if name != "planner_pipeline"
         and isinstance(r, dict) and r.get("device")), "unknown")
    summary = headline_summary(headline, params, device)

    # failed legs get this round's incremental-session result attached
    # (labeled PRIOR, never replacing the live error) so a mid-run tunnel
    # wedge can't zero out evidence that already exists
    prior = _load_prior()
    for leg, r in results.items():
        if (isinstance(r, dict) and "error" in r
                and leg in prior.get("legs", {})):
            r["prior_measurement"] = dict(prior["legs"][leg])
            r["prior_measurement"]["prior_note"] = (
                f"prior measurement from {prior['source']}; the live leg "
                "errored as recorded above")
    headline_is_prior = False
    if (summary["value"] is None and "headline" in prior.get("legs", {})
            and prior.get("metric")):
        # reuse the artifact's OWN stored metric/value/vs_baseline (they
        # were computed against the prior headline's params — recomputing
        # with this run's params could mislabel the comparison)
        summary = {"metric": prior["metric"]
                   + f" [PRIOR measurement from {prior['source']}; the "
                     "live headline leg errored]",
                   "value": prior["value"],
                   "vs_baseline": prior["vs_baseline"],
                   "baseline": summary["baseline"]}
        headline = prior["legs"]["headline"]
        headline_is_prior = True

    extras = {"device": device, "baseline": summary["baseline"]}
    extras.update({k: v for k, v in results.items() if k != "headline"})
    if headline_is_prior:
        # the substituted headline must not hide the live failure
        extras["headline_live_error"] = results.get("headline")

    # roofline fractions against THIS chip's measured HBM ceiling (the
    # paper-spec fraction stays in each leg as hbm_roofline_frac) —
    # shared helper with the incremental session.  The ceiling now
    # includes the probe RE-RUN adjacent to the headline leg, and the
    # full probe spread (min/median/max over >= 3 rounds per probe) is
    # reported so a degraded-tunnel session is visible in the artifact;
    # legs that still beat every probe get probe_inconsistent instead
    # of a >1.0 "fraction" (apply_measured_frac)
    rerun = results.get("roofline_probe_rerun", {}) or {}
    session = measured_ceiling(
        results.get("roofline_probe", {}),
        [{"hbm_gbs": r} for r in rerun.get("hbm_read_gbs_rounds", [])])
    all_rounds = sorted(
        (results.get("roofline_probe", {}) or {}).get(
            "hbm_read_gbs_rounds", [])
        + rerun.get("hbm_read_gbs_rounds", []))
    if all_rounds:
        extras["probe_spread_gbs"] = {
            "n": len(all_rounds),
            "min": round(all_rounds[0], 1),
            "median": round(all_rounds[len(all_rounds) // 2], 1),
            "max": round(all_rounds[-1], 1)}
    # the DECLARED ceiling is max(session probes, committed best-ever
    # ledger) — a degraded-tunnel session inherits the chip's real
    # ceiling instead of minting a lower one; session probes that beat
    # the ledger raise it for every future session
    # a prior headline keeps ITS session's measured-ceiling fraction;
    # this run's probe doesn't describe that session
    apply_declared_ceiling(headline, extras, device, session,
                           source="session roofline probe max",
                           skip_headline=headline_is_prior)

    runlog.event("bench_done", value=summary["value"],
                 vs_baseline=summary["vs_baseline"],
                 errored_legs=[k for k, v in results.items()
                               if isinstance(v, dict) and "error" in v])
    runlog.close()
    print(json.dumps({
        "metric": summary["metric"],
        "value": summary["value"],
        "unit": "tokens/sec",
        "vs_baseline": summary["vs_baseline"],
        "headline": headline,
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
