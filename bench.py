"""Benchmark harness: decode throughput on the flagship model, real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: decode tokens/sec on TinyLlama-1.1B (bf16, KV-cached, fused decode
scan) — BASELINE.json config #1's model.  ``vs_baseline`` compares against
the reference-shaped 2-worker CPU pipeline baseline (see CPU_BASELINE_TPS
provenance note below); the north-star target is >=10x.
"""

import json
import os
import sys
import time

# Reference-shaped baseline: TinyLlama-1.1B split across 2 localhost CPU
# worker processes (BASELINE.json config #1), measured with
# tools/cpu_baseline.py on this machine (see that file for the exact
# invocation).  Updated whenever the baseline harness is re-run.
CPU_BASELINE_TPS = 1.0  # placeholder until tools/cpu_baseline.py lands


def main():
    import jax
    import numpy as np
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    model = os.environ.get("BENCH_MODEL", "tinyllama-1.1b")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_PROMPT", "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))

    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(
        cfg, params, max_seq=prompt_len + new_tokens,
        sampling=SamplingParams(temperature=0.7, top_k=7))  # ref defaults

    prompt = np.arange(batch * prompt_len).reshape(batch, prompt_len) % 1000
    engine.generate(prompt, new_tokens, seed=0)        # compile warmup
    result = engine.generate(prompt, new_tokens, seed=0)  # steady-state
    tps = result.tokens_per_second

    print(json.dumps({
        "metric": f"decode tokens/sec ({model}, bf16, batch={batch}, "
                  f"prompt={prompt_len}, new={new_tokens}, "
                  f"device={jax.devices()[0].device_kind})",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / CPU_BASELINE_TPS, 2),
    }))


if __name__ == "__main__":
    main()
