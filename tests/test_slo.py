"""Per-tenant SLO ledger, trace-id hygiene, and anomaly-layer edges.

The ISSUE-16 unit layer (no engines, no sockets, no jax):

- the timeline decomposition identity: for every closed record,
  ``ttft + per_token*(tokens-1) + migration_pause == e2e`` exactly —
  a timeline that doesn't add up is a measurement bug;
- goodput judging: first token vs the TTFT SLO, decode tokens vs the
  TPOT SLO, errored requests all-bad, thresholds unset == always good;
- multi-window burn rates decaying under an injected clock;
- the ``new_trace_id`` fork/seed regression (module-``random`` state
  must not leak into trace ids);
- ``TraceRecorder.drain()`` racing ``record()`` (satellite d): every
  span exported exactly once, no crashes;
- anomaly SLO detectors under missing/NaN samples, and the multiwindow
  ``slo_burn`` detector;
- postmortem bundles carrying ``timelines.jsonl``.
"""

import json
import random
import sys
import threading
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_inference_demo_tpu.telemetry.anomaly import (
    AnomalyDetector, Thresholds)
from distributed_inference_demo_tpu.telemetry.postmortem import (
    PostmortemWriter)
from distributed_inference_demo_tpu.telemetry.slo import (
    SloLedger, sanitize_tenant, set_slo_ledger)
from distributed_inference_demo_tpu.telemetry.tracing import (
    TraceRecorder, new_trace_id)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def ledger():
    """Fresh process-default ledger with known thresholds + clock;
    restored after the test so engine tests see a clean default."""
    clk = _Clock()
    led = SloLedger(ttft_slo_ms=100.0, tpot_slo_ms=10.0, target=0.9,
                    clock=clk)
    led.clock = clk            # convenience handle for tests
    set_slo_ledger(led)
    yield led
    set_slo_ledger(None)


# ---------------------------------------------------------------------------
# ledger math


@pytest.mark.quick
def test_timeline_decomposition_sums_exactly(ledger):
    rec = ledger.close_request(
        rid="r1", tenant="acme", trace_id=0xABCD, queue_wait_s=0.02,
        ttft_s=0.08, e2e_s=1.30, tokens=12, migration_pause_s=0.25,
        migrated=True, replica="engine:aa")
    # the identity the module promises, exact by construction
    lhs = (rec["ttft_s"] + rec["per_token_s"] * (rec["tokens"] - 1)
           + rec["migration_pause_s"])
    assert lhs == pytest.approx(rec["e2e_s"], abs=1e-12)
    assert rec["prefill_s"] == pytest.approx(0.06)
    assert rec["decode_s"] == pytest.approx(1.22)
    assert rec["per_token_s"] == pytest.approx((1.30 - 0.08 - 0.25) / 11)
    assert rec["tenant"] == "acme" and rec["migrated"] is True
    assert rec["trace_id"] == f"{0xABCD:016x}"
    # clamps: ttft >= queue_wait, e2e >= ttft + pause
    rec2 = ledger.close_request(rid="r2", queue_wait_s=0.5, ttft_s=0.1,
                                e2e_s=0.0, tokens=2,
                                migration_pause_s=0.2)
    assert rec2["ttft_s"] == 0.5
    assert rec2["e2e_s"] == pytest.approx(0.7)
    assert rec2["per_token_s"] == 0.0    # decode == pause: clamped to 0


@pytest.mark.quick
def test_goodput_judging_ttft_tpot_and_errors(ledger):
    # fully good: ttft 50ms <= 100ms, per-token ~5ms <= 10ms
    rec = ledger.close_request(rid="g", tenant="t", ttft_s=0.05,
                               e2e_s=0.05 + 0.005 * 9, tokens=10)
    assert rec["good_tokens"] == 10
    # late first token: only the first token is bad
    rec = ledger.close_request(rid="b1", tenant="t", ttft_s=0.5,
                               e2e_s=0.5 + 0.005 * 9, tokens=10)
    assert rec["good_tokens"] == 9
    # slow decode: first token good, all decode tokens bad
    rec = ledger.close_request(rid="b2", tenant="t", ttft_s=0.05,
                               e2e_s=0.05 + 0.05 * 9, tokens=10)
    assert rec["good_tokens"] == 1
    # an errored request's tokens all count against the budget
    rec = ledger.close_request(rid="err", tenant="t", ttft_s=0.05,
                               e2e_s=0.1, tokens=10, error="Boom")
    assert rec["good_tokens"] == 0 and rec["error"] == "Boom"
    # migration pause is EXCLUDED from per-token judging: a 2s pause
    # inside an otherwise-fast decode stays good
    rec = ledger.close_request(rid="m", tenant="t", ttft_s=0.05,
                               e2e_s=0.05 + 0.005 * 9 + 2.0, tokens=10,
                               migration_pause_s=2.0, migrated=True)
    assert rec["good_tokens"] == 10
    # thresholds unset -> everything good
    open_led = SloLedger(ttft_slo_ms=0, tpot_slo_ms=0, target=0.9)
    rec = open_led.close_request(rid="x", ttft_s=9.0, e2e_s=99.0,
                                 tokens=5)
    assert rec["good_tokens"] == 5


@pytest.mark.quick
def test_burn_windows_decay_with_injected_clock(ledger):
    clk = ledger.clock
    # all-bad request: 10 tokens, every one violating (error)
    ledger.close_request(rid="a", tenant="acme", ttft_s=0.05, e2e_s=0.1,
                         tokens=10, error="X")
    burn = ledger.burn_rates("acme")
    # bad fraction 1.0 over budget (1 - 0.9) = burn 10.0 on both windows
    assert burn["5m"] == pytest.approx(10.0)
    assert burn["1h"] == pytest.approx(10.0)
    # good traffic dilutes the fraction: 10 bad / 40 total = 0.25
    for i in range(3):
        ledger.close_request(rid=f"g{i}", tenant="acme", ttft_s=0.05,
                             e2e_s=0.05 + 0.005 * 9, tokens=10)
    burn = ledger.burn_rates("acme")
    assert burn["5m"] == pytest.approx(2.5)
    # past the 5m window the short burn clears, the 1h one remembers
    clk.t += 301.0
    burn = ledger.burn_rates("acme")
    assert burn["5m"] == 0.0
    assert burn["1h"] == pytest.approx(2.5)
    # past the 1h window everything decays
    clk.t += 3600.0
    burn = ledger.burn_rates("acme")
    assert burn == {"5m": 0.0, "1h": 0.0}
    # summary carries the same numbers for /stats + the anomaly layer
    s = ledger.summary()
    assert s["tenants"]["acme"]["requests"] == 4
    assert s["tenants"]["acme"]["goodput_ratio"] == pytest.approx(0.75)
    assert s["tenants"]["acme"]["burn"] == {"5m": 0.0, "1h": 0.0}
    assert s["slo"]["ttft_ms"] == 100.0


@pytest.mark.quick
def test_sanitize_tenant_clamps_untrusted_identities():
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant("") == "default"
    assert sanitize_tenant("  ") == "default"
    assert sanitize_tenant("acme-prod") == "acme-prod"
    assert sanitize_tenant("team@org/svc:a.b") == "team@org/svc:a.b"
    assert sanitize_tenant('ev"il\n{label}') == "ev_il__label_"
    assert len(sanitize_tenant("x" * 500)) == 64


# ---------------------------------------------------------------------------
# trace-id hygiene (satellite a)


@pytest.mark.quick
def test_new_trace_id_ignores_module_random_seed():
    """The unseeded-module-random-survives-fork regression: two
    processes forked after import used to share ``random``'s state and
    mint identical id sequences.  Seeding the module RNG to the same
    state twice is the in-process equivalent — ids must still differ
    (SystemRandom reads the kernel CSPRNG, not Python state)."""
    random.seed(42)
    a = new_trace_id()
    random.seed(42)
    b = new_trace_id()
    assert a != b
    assert a & 1 and b & 1                  # nonzero guarantee
    # span-id bases are fork-safe for the same reason
    random.seed(42)
    r1 = TraceRecorder("p")
    random.seed(42)
    r2 = TraceRecorder("p")
    assert r1.next_span_id() != r2.next_span_id()


@pytest.mark.quick
def test_trace_recorder_drain_races_record():
    """Satellite d: concurrent ``record()`` while another thread
    ``drain()``s — every span lands in exactly one drain, none lost,
    none duplicated, no exception."""
    rec = TraceRecorder("race", max_spans=100_000)
    n_threads, per_thread = 4, 500
    drained = []
    stop = threading.Event()

    def writer(base):
        for i in range(per_thread):
            rec.record("s", trace_id=1, idx=base + i)

    def drainer():
        while not stop.is_set():
            drained.extend(rec.drain())
        drained.extend(rec.drain())

    threads = [threading.Thread(target=writer, args=(t * per_thread,))
               for t in range(n_threads)]
    dt = threading.Thread(target=drainer)
    dt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    dt.join()
    drained.extend(rec.drain())
    seen = sorted(s["args"]["idx"] for s in drained)
    assert seen == list(range(n_threads * per_thread))


# ---------------------------------------------------------------------------
# anomaly-layer edges (satellite d)


def _lat_stats(**lat):
    return {"latency": lat, "queue_depth": 0, "steps": 0}


@pytest.mark.quick
def test_slo_detector_missing_and_nan_samples_restart_the_streak():
    """A missing or NaN p95 is 'no data': it can never FIRE the SLO
    detector, and it restarts the sustain streak (sustain means
    CONSECUTIVE breaches) — two old breaches plus a later noisy sample
    must not add up to a firing."""
    det = AnomalyDetector(Thresholds(ttft_slo_ms=100.0, sustain=3,
                                     cooldown_s=0.0), clock=_Clock())
    breach = _lat_stats(ttft_p95_ms=500.0)
    assert det.observe(breach) == []
    assert det.observe(breach) == []
    # gap: the reservoir reset and the key vanished
    assert det.observe(_lat_stats()) == []
    assert det.observe(breach) == []          # streak restarted at 1
    assert det.observe(breach) == []
    out = det.observe(breach)                 # 3 consecutive: fires
    assert [a.kind for a in out] == ["slo_ttft"]
    # NaN behaves exactly like missing: never fires, restarts streak
    det2 = AnomalyDetector(Thresholds(tpot_slo_ms=10.0, sustain=2,
                                      cooldown_s=0.0), clock=_Clock())
    nan = _lat_stats(per_token_p95_ms=float("nan"))
    assert det2.observe(nan) == []
    assert det2.observe(_lat_stats(per_token_p95_ms=50.0)) == []
    assert det2.observe(nan) == []            # breach streak reset
    assert det2.observe(_lat_stats(per_token_p95_ms=50.0)) == []
    out = det2.observe(_lat_stats(per_token_p95_ms=50.0))
    assert [a.kind for a in out] == ["slo_tpot"]


@pytest.mark.quick
def test_slo_burn_detector_needs_every_window_hot():
    """Multiwindow burn alerting: the ``slo_burn`` detector fires only
    when EVERY window breaches (5m blip alone or long-recovered 1h
    alone stay quiet), keyed per tenant, NaN windows unusable."""
    det = AnomalyDetector(Thresholds(burn_rate=2.0, sustain=2,
                                     cooldown_s=0.0), clock=_Clock())

    def stats(burns):
        return {"latency": {}, "queue_depth": 0, "steps": 0,
                "slo": {"tenants": {
                    t: {"burn": b} for t, b in burns.items()}}}

    hot = {"5m": 3.0, "1h": 2.5}
    assert det.observe(stats({"acme": hot})) == []
    out = det.observe(stats({"acme": hot}))
    assert [a.kind for a in out] == ["slo_burn"]
    assert out[0].detail["tenant"] == "acme"
    # short-window blip alone: never fires, clears acme's streak
    blip = {"5m": 9.0, "1h": 0.1}
    assert det.observe(stats({"acme": blip})) == []
    assert det.observe(stats({"acme": blip})) == []
    # per-tenant keying: one hot tenant can't borrow another's streak
    assert det.observe(stats({"acme": hot, "beta": hot})) == []
    out = det.observe(stats({"beta": hot}))
    assert [(a.kind, a.detail["tenant"]) for a in out] == [
        ("slo_burn", "beta")]
    # NaN window: unusable sample, no fire
    det2 = AnomalyDetector(Thresholds(burn_rate=2.0, sustain=1,
                                      cooldown_s=0.0), clock=_Clock())
    assert det2.observe(stats(
        {"acme": {"5m": float("nan"), "1h": 9.0}})) == []
    # threshold 0 (default) disables the detector entirely
    det3 = AnomalyDetector(Thresholds(sustain=1, cooldown_s=0.0),
                           clock=_Clock())
    assert det3.observe(stats({"acme": {"5m": 99.0, "1h": 99.0}})) == []


# ---------------------------------------------------------------------------
# postmortem bundles carry timelines (tentpole seam)


@pytest.mark.quick
def test_postmortem_bundle_includes_timelines_jsonl(tmp_path, ledger):
    ledger.close_request(rid="pm1", tenant="acme", ttft_s=0.05,
                         e2e_s=0.2, tokens=4, migration_pause_s=0.01,
                         migrated=True)
    w = PostmortemWriter(str(tmp_path), proc="test")
    bundle = w.write_bundle("test_reason")
    assert bundle is not None
    lines = (Path(bundle) / "timelines.jsonl").read_text().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert any(r["rid"] == "pm1" and r["migrated"] for r in recs)
    lhs = (recs[-1]["ttft_s"]
           + recs[-1]["per_token_s"] * (recs[-1]["tokens"] - 1)
           + recs[-1]["migration_pause_s"])
    assert lhs == pytest.approx(recs[-1]["e2e_s"], abs=1e-9)
