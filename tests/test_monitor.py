"""Monitor subsystem tests: probes, aggregation, full agent round over
localhost sockets."""

import pytest

from distributed_inference_demo_tpu.monitor import (
    BandwidthServer, MonitorAgent, MonitorAggregator, MonitorService,
    bandwidth_probe, flops_probe, memory_info, tcp_latency_probe)


# ------------------------------------------------------------------ probes

def test_memory_info_sane():
    mem = memory_info()
    assert mem["total"] > (1 << 30)          # >1 GB host
    assert 0 < mem["available"] <= mem["total"]


def test_flops_probe_positive():
    flops = flops_probe(size=256, warmups=1)
    assert flops > 1e8                        # any real machine beats this


def test_bandwidth_probe_localhost():
    srv = BandwidthServer()
    srv.start()
    try:
        bw = bandwidth_probe("127.0.0.1", srv.port, duration=0.05)
        assert bw is not None and bw > 1e6    # loopback >> 1 MB/s
        lat = tcp_latency_probe("127.0.0.1", srv.port)
        assert lat is not None and lat < 0.5
    finally:
        srv.stop()


def test_latency_probe_unreachable():
    assert tcp_latency_probe("127.0.0.1", 1, attempts=1, timeout=0.2) is None
    assert bandwidth_probe("127.0.0.1", 1, timeout=0.2) is None


# ------------------------------------------------------------- aggregation

def test_aggregator_ready_and_profiles():
    agg = MonitorAggregator(["d0", "d1"])
    agg.add_report("d0", {
        "latency": {"d1": 0.002}, "bandwidth": {"d1": 5e8},
        "memory": {"total": 32 << 30, "available": 8 << 30},
        "flops": 2e12, "platform": "cpu", "chips": 1})
    assert not agg.is_monitor_ready.is_set()
    agg.add_report("d1", {
        "latency": {"d0": 0.003}, "bandwidth": {"d0": 4e8},
        "memory": {"total": 16 << 30, "available": 4 << 30},
        "flops": 9e13, "platform": "tpu", "chips": 8})
    assert agg.is_monitor_ready.is_set()

    profs = agg.device_profiles({"d0": "a:1", "d1": "b:2"})
    assert profs[0].device_id == "d0"
    assert profs[0].flops_per_sec == 2e12
    assert profs[0].memory_bytes == 8 << 30   # planner uses available
    assert profs[0].egress_bandwidth == 5e8   # toward next in ring (d1)
    assert profs[1].platform == "tpu" and profs[1].chips == 8
    assert profs[1].egress_bandwidth == 4e8   # ring wraps d1 -> d0


def test_aggregator_defaults_for_missing_measurements():
    agg = MonitorAggregator(["d0"])
    agg.add_report("d0", {})
    p = agg.device_profiles({"d0": "a:1"})[0]
    assert p.flops_per_sec > 0 and p.memory_bytes > 0
    assert p.egress_bandwidth > 0


# ------------------------------------------------- end-to-end monitor round

def test_monitor_round_end_to_end():
    agg = MonitorAggregator(["dev-a", "dev-b"])
    svc = MonitorService(agg)
    svc.start()
    agents = [
        MonitorAgent(svc.address, "dev-a", measure_flops=False,
                     bandwidth_duration=0.03),
        MonitorAgent(svc.address, "dev-b", measure_flops=False,
                     bandwidth_duration=0.03),
    ]
    try:
        threads = [a.run_async(max_rounds=10) for a in agents]
        assert agg.is_monitor_ready.wait(timeout=20)
        for t in threads:
            t.join(timeout=20)
            assert not t.is_alive()
        info = agg.get_monitor_info()
        assert set(info) == {"dev-a", "dev-b"}
        for rep in info.values():
            assert rep["memory"]["total"] > 0
        # at least one direction measured real localhost bandwidth
        assert any(rep["bandwidth"] for rep in info.values())
    finally:
        for a in agents:
            a.close()
        svc.stop()
