"""Tiered KV (runtime/kvcache/tiered.py, docs/DESIGN.md §21): the
host-RAM/disk capacity tier below the device page pool.

Three layers, cheapest first:

- pure unit tests over the TieredKVStore ring (demote/take roundtrips
  bit-identical across {bf16, int8, int4} leaf layouts, LRU budget
  spill/drop, digest publishing, the check() accounting invariants) —
  no jax;
- manager-level promotion seam (promote_prefix over a real paged pool:
  alloc-pressure skip, take-race skip, honest h2d accounting);
- engine-level end-to-end: eviction demotes, a re-submitted prefix
  promotes, greedy tokens stay bit-identical to the cold run, and the
  three-tier leak invariant (device used == tree blocks, tier ledger
  exact) closes on finish/cancel/close.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_inference_demo_tpu.runtime.kvcache import (  # noqa: E402
    TieredKVStore, resolve_tier_config)
from distributed_inference_demo_tpu.runtime.kvcache.tiered import (  # noqa: E402
    chain_digests)

BT = 4


def _keys(tokens):
    toks = list(tokens)
    return [tuple(toks[i * BT:(i + 1) * BT])
            for i in range(len(toks) // BT)]


def _payload(n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    shape = (n, 2, 2, BT, 8)                    # [n, L, H, bt, D]
    k = rng.standard_normal(shape).astype(dtype)
    v = rng.standard_normal(shape).astype(dtype)
    return k, v


def _quant_payload(n, bits, seed=0):
    from distributed_inference_demo_tpu.ops.quant import QuantizedKVPages
    rng = np.random.default_rng(seed)
    d = 8 // 2 if bits == 4 else 8
    dt = np.uint8 if bits == 4 else np.int8
    shape = (n, 2, 2, BT, d)

    def one():
        data = rng.integers(0, 255, shape).astype(dt)
        scale = rng.standard_normal((n, 2, 2, BT, 1)).astype(np.float32)
        zero = (rng.standard_normal((n, 2, 2, BT, 1)).astype(np.float32)
                if bits == 4 else None)
        return QuantizedKVPages(data, scale, zero, bits)

    return one(), one()


def _assert_blocks_equal(a, b):
    from distributed_inference_demo_tpu.ops.quant import QuantizedKVPages
    if isinstance(a, QuantizedKVPages):
        assert isinstance(b, QuantizedKVPages) and a.bits == b.bits
        np.testing.assert_array_equal(np.asarray(a.data),
                                      np.asarray(b.data))
        np.testing.assert_array_equal(np.asarray(a.scale),
                                      np.asarray(b.scale))
        if a.zero is not None:
            np.testing.assert_array_equal(np.asarray(a.zero),
                                          np.asarray(b.zero))
    else:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# unit: the store itself (no jax)
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_resolve_tier_config_args_env_and_rejection(monkeypatch):
    monkeypatch.delenv("DWT_KV_HOST_TIER_BYTES", raising=False)
    monkeypatch.delenv("DWT_KV_DISK_TIER_PATH", raising=False)
    monkeypatch.delenv("DWT_KV_DISK_TIER_BYTES", raising=False)
    assert resolve_tier_config() == (0, None, 0)
    monkeypatch.setenv("DWT_KV_HOST_TIER_BYTES", "4096")
    assert resolve_tier_config() == (4096, None, 0)
    # explicit arg wins over env (the §17 funnel)
    assert resolve_tier_config(host_bytes=8192) == (8192, None, 0)
    # a disk path without a byte budget is no segment
    assert resolve_tier_config(8192, "/tmp/x", 0) == (8192, None, 0)
    assert resolve_tier_config(8192, "/tmp/x", 1 << 20) == (
        8192, "/tmp/x", 1 << 20)
    # the disk tier sits BELOW the host ring: host off + disk on is a
    # config error, loudly
    with pytest.raises(ValueError, match="BELOW the host ring"):
        resolve_tier_config(0, "/tmp/x", 1 << 20)


@pytest.mark.quick
def test_demote_take_roundtrip_host_bit_identity():
    t = TieredKVStore(1 << 20, BT)
    toks = list(range(3 * BT))
    k, v = _payload(3)
    assert t.demote(_keys(toks), k, v) == 3
    snap = t.snapshot()
    assert snap["host_blocks"] == 3 and snap["disk_blocks"] == 0
    assert snap["host_resident_bytes"] == 6 * k[0].nbytes
    # match walks from the device-covered start, capped below len
    run = t.match(np.asarray(toks + [99]), 0)
    assert len(run) == 3
    kb, vb, nbytes, n = t.take(run)
    assert n == 3 and nbytes == 6 * k[0].nbytes
    _assert_blocks_equal(kb, k)
    _assert_blocks_equal(vb, v)
    # move semantics: the entries are gone
    assert t.match(np.asarray(toks + [99]), 0) == []
    assert t.snapshot()["host_blocks"] == 0
    assert t.host_resident_bytes == 0
    assert t.stats["host_hits"] == 3
    t.check()


@pytest.mark.quick
@pytest.mark.parametrize("bits", [8, 4])
def test_quantized_leaves_roundtrip_verbatim(bits, tmp_path):
    """int8/int4 payloads (data + scale [+ zero]) survive demote/take
    VERBATIM — through the host ring AND through a disk spill — so a
    promoted page is bit-identical to the page that was evicted (no
    dequant round trip anywhere in the tier)."""
    k, v = _quant_payload(2, bits)
    toks = list(range(2 * BT))
    for disk in (False, True):
        kw = ({"disk_path": str(tmp_path / f"seg{bits}{disk}.kv"),
               "disk_bytes": 1 << 20} if disk else {})
        entry_bytes = (k.data[0].nbytes + k.scale[0].nbytes
                       + (k.zero[0].nbytes if k.zero is not None else 0))
        # host budget of ONE entry pair forces a spill when disk is on
        budget = (2 * entry_bytes + 1) if disk else (1 << 20)
        t = TieredKVStore(budget, BT, **kw)
        assert t.demote(_keys(toks), k, v) == 2
        if disk:
            assert t.snapshot()["disk_blocks"] >= 1
            assert t.stats["spilled_blocks"] >= 1
        t.check()
        kb, vb, _, n = t.take(t.match(np.asarray(toks + [9]), 0))
        assert n == 2
        _assert_blocks_equal(kb, k)
        _assert_blocks_equal(vb, v)
        t.check()
        t.close()


@pytest.mark.quick
def test_lru_budget_drops_oldest_without_disk():
    k, v = _payload(1)
    entry = 2 * k[0].nbytes
    t = TieredKVStore(2 * entry, BT)            # room for exactly 2
    for i in range(4):
        toks = list(range(100 * i, 100 * i + BT))
        ki, vi = _payload(1, seed=i)
        t.demote(_keys(toks), ki, vi)
    snap = t.snapshot()
    assert snap["host_blocks"] == 2
    assert t.stats["dropped_blocks"] == 2
    # the SURVIVORS are the newest two
    assert t.match(np.asarray(list(range(300, 304)) + [0]), 0)
    assert not t.match(np.asarray(list(range(0, 4)) + [0]), 0)
    t.check()


@pytest.mark.quick
def test_disk_overflow_drops_oldest_and_recycles_slots(tmp_path):
    k, v = _payload(1)
    entry = 2 * k[0].nbytes
    t = TieredKVStore(entry, BT,
                      disk_path=str(tmp_path / "seg.kv"),
                      disk_bytes=2 * entry)
    for i in range(5):                          # 1 host + 2 disk fit
        toks = list(range(100 * i, 100 * i + BT))
        ki, vi = _payload(1, seed=i)
        t.demote(_keys(toks), ki, vi)
    snap = t.snapshot()
    assert snap["host_blocks"] == 1 and snap["disk_blocks"] == 2
    assert t.stats["dropped_blocks"] == 2
    t.check()
    # a disk take frees its slot for the next spill
    run = t.match(np.asarray(list(range(200, 204)) + [0]), 0)
    assert run and t.take(run)[3] == 1
    assert t.stats["disk_hits"] == 1
    t.check()
    t.close()


@pytest.mark.quick
def test_digest_is_truncated_hex_and_capped():
    t = TieredKVStore(1 << 24, BT, digest_cap=3)
    for i in range(5):
        toks = list(range(10 * i, 10 * i + BT))
        ki, vi = _payload(1, seed=i)
        t.demote(_keys(toks), ki, vi)
    d = t.digest()
    assert d["block_tokens"] == BT
    assert len(d["digests"]) == 3               # newest-first cap
    assert all(len(x) == 16 and int(x, 16) >= 0 for x in d["digests"])
    # byte-compatible with chain_digests + the router's truncation
    newest = _keys(list(range(40, 44)))
    assert chain_digests(newest)[0].hex()[:16] == d["digests"][-1]


@pytest.mark.quick
def test_match_respects_start_and_stops_at_holes():
    t = TieredKVStore(1 << 24, BT)
    toks = list(range(4 * BT))
    k, v = _payload(4)
    t.demote(_keys(toks), k, v)
    # start past the end of coverage
    assert t.match(np.asarray(toks + [7]), 4) == []
    # start inside the run: only the continuation comes back
    assert len(t.match(np.asarray(toks + [7]), 2)) == 2
    # a hole stops the run: drop block 1, then match from 0 sees just
    # block 0
    dg = chain_digests(_keys(toks))
    with t._lock:
        t._drop_locked(dg[1])
    assert len(t.match(np.asarray(toks + [7]), 0)) == 1


# ---------------------------------------------------------------------------
# manager-level: the promotion seam over a real paged pool
# ---------------------------------------------------------------------------

def _paged_pool(num_blocks=8, bt=BT):
    import jax
    import jax.numpy as jnp

    from distributed_inference_demo_tpu.runtime.kvcache import (
        PagedKVCacheManager)
    mgr = PagedKVCacheManager(num_layers=2, num_kv_heads=2, head_dim=8,
                              num_blocks=num_blocks, block_tokens=bt,
                              dtype=np.float32)
    pk = jnp.zeros((2, num_blocks, 2, bt, 8), jnp.float32)
    pv = jax.tree.map(jnp.zeros_like, pk)
    return mgr, pk, pv


@pytest.mark.quick
def test_promote_prefix_restores_tree_and_counts_h2d():
    from distributed_inference_demo_tpu.runtime.kvcache import (
        promote_prefix)
    mgr, pk, pv = _paged_pool()
    tier = TieredKVStore(1 << 24, BT)
    mgr.tier = tier
    toks = list(range(50, 50 + 3 * BT))
    k, v = _payload(3)
    tier.demote(_keys(toks), k, v)
    prompt = np.asarray(toks + [1])
    assert mgr.peek(prompt) == 0
    pk, pv, promoted = promote_prefix(mgr, tier, pk, pv, prompt)
    assert promoted == 3 * BT
    # the promoted blocks are ordinary tree state now: match hits, the
    # tier is empty, and the h2d really happened
    assert mgr.peek(prompt) == 3 * BT
    hit = mgr.match(prompt)
    assert hit is not None and hit.tokens == 3 * BT
    hit.release()
    assert mgr.used_blocks == mgr.tree.block_count == 3
    snap = mgr.snapshot()
    assert snap["h2d_bytes"] == tier.stats["promoted_bytes"] > 0
    assert snap["tier"]["promoted_blocks"] == 3
    assert snap["tier"]["host_blocks"] == 0
    # and the promoted page BYTES are the demoted ones, verbatim
    import jax.numpy as jnp

    from distributed_inference_demo_tpu.runtime.kvcache.device import (
        export_blocks_from_pages)
    ids = mgr.match(prompt)
    kb, _ = export_blocks_from_pages(
        pk, pv, jnp.asarray(ids.block_ids, jnp.int32))
    _assert_blocks_equal(kb, k)
    ids.release()
    tier.check()


@pytest.mark.quick
def test_promote_skips_on_alloc_pressure_and_take_race():
    from distributed_inference_demo_tpu.runtime.kvcache import (
        promote_prefix)
    mgr, pk, pv = _paged_pool(num_blocks=4)
    tier = TieredKVStore(1 << 24, BT)
    toks = list(range(3 * BT))
    k, v = _payload(3)
    tier.demote(_keys(toks), k, v)
    # every page request-owned: alloc is infeasible -> promote skips,
    # nothing leaks, the tier keeps its entries for the next chance
    held = mgr.alloc(3)
    pk, pv, promoted = promote_prefix(mgr, tier, pk, pv,
                                      np.asarray(toks + [1]))
    assert promoted == 0 and tier.snapshot()["host_blocks"] == 3
    assert mgr.used_blocks == 3
    mgr.free(held)
    # take-race: the entries vanish between match and take (a second
    # engine thread, in production) -> ids freed, no leak, no crash
    real_take = tier.take
    tier.take = lambda run: None
    pk, pv, promoted = promote_prefix(mgr, tier, pk, pv,
                                      np.asarray(toks + [1]))
    assert promoted == 0 and mgr.used_blocks == 0
    tier.take = real_take
    tier.check()


@pytest.mark.quick
def test_manager_eviction_demotes_through_hook():
    """The full eviction->demotion seam at manager level: stored pages
    whose leaf gets LRU-evicted land in the tier, keyed so the SAME
    prompt matches them back, with the payload bytes the pages held."""
    import jax.numpy as jnp

    from distributed_inference_demo_tpu.runtime.kvcache import (
        make_demote_hook)
    from distributed_inference_demo_tpu.runtime.kvcache.device import (
        adopt_blocks_into_pages)
    mgr, pk, pv = _paged_pool(num_blocks=4)
    tier = TieredKVStore(1 << 24, BT)
    state = {}
    mgr.tier = tier
    mgr.demote_hook = make_demote_hook(tier,
                                       lambda: (state["pk"], state["pv"]))
    # store prompt A's 2 blocks with known payload
    toks_a = list(range(2 * BT))
    k, v = _payload(2, seed=3)
    ids = mgr.alloc(2)
    pk, pv = adopt_blocks_into_pages(
        pk, pv, jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(np.asarray(ids, np.int32)))
    state["pk"], state["pv"] = pk, pv
    _, lease = mgr.store_shared(np.asarray(toks_a), ids)
    lease.release()
    # demand forces eviction of A's leaf -> the hook demotes it
    got = mgr.alloc(4)
    assert got is not None and mgr.stats["evicted_blocks"] == 2
    assert tier.stats["demoted_blocks"] == 2
    assert tier.stats["demote_errors"] == 0
    run = tier.match(np.asarray(toks_a + [9]), 0)
    kb, vb, _, n = tier.take(run)
    assert n == 2
    _assert_blocks_equal(kb, k)
    _assert_blocks_equal(vb, v)
    mgr.free(got)
    tier.check()


# ---------------------------------------------------------------------------
# engine-level: end-to-end demote -> promote with bit-identity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def params():
    import jax

    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params)
    return init_full_params(jax.random.PRNGKey(0),
                            get_model_config("llama-test"))


def _engine(params, **kw):
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    kw.setdefault("max_seq", 96)
    kw.setdefault("sampling", SamplingParams(greedy=True))
    return InferenceEngine(get_model_config("llama-test"), params, **kw)


PROMPT_A = np.asarray([list(range(2, 22)) + [51, 52, 53]])   # 5 blocks
PROMPT_B = np.asarray([list(range(60, 80)) + [1, 2, 3]])


def test_engine_evict_demotes_resubmit_promotes_bit_identical(
        params, monkeypatch):
    """The §21 headline at engine level: a pool too small for two
    working sets demotes the first prompt's blocks on eviction; its
    re-run promotes them back (h2d counted, tier hit counted) and the
    greedy tokens match the cold run bit-for-bit."""
    monkeypatch.setenv("DWT_KV_HOST_TIER_BYTES", str(1 << 22))
    # 7 blocks x 4 tokens: A stores 5, B's store evicts some of A
    eng = _engine(params, kv_cache_blocks=7, kv_block_tokens=4)
    tier = eng.kv_cache.tier
    assert tier is not None
    cold = eng.generate(PROMPT_A, 8)
    eng.generate(PROMPT_B, 8)                    # evicts -> demotes
    assert tier.stats["demoted_blocks"] > 0
    assert tier.stats["demote_errors"] == 0
    promoted = eng.generate(PROMPT_A, 8)
    np.testing.assert_array_equal(cold.tokens, promoted.tokens)
    snap = eng.kv_cache.snapshot()
    assert tier.stats["promoted_blocks"] > 0
    assert snap["h2d_bytes"] == tier.stats["promoted_bytes"] > 0
    assert snap["tier"]["host_hits"] > 0
    # three-tier leak close: device pages tree-owned, tier ledger exact
    mgr = eng.kv_cache.mgr
    assert mgr.used_blocks == mgr.tree.block_count
    assert eng.kv_cache.debug_state()["leased_nodes"] == 0
    tier.check()
    # close drops the tier with the pool it shadows
    eng.kv_cache.close()
    assert eng.kv_cache.tier is None and mgr.demote_hook is None


def test_batching_engine_tier_end_to_end(params):
    """ContinuousBatchingEngine with explicit tier kwargs: oversubscribed
    admissions demote + promote across requests, tokens stay exact,
    /stats carries the tier fragment + digest for the gateway, the HBM
    ledger gains (and on close loses) the host_tier owner, and the
    three-tier leak invariant closes after every request."""
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.telemetry import profiling
    oracle = _engine(params)
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(5)]
    eng = ContinuousBatchingEngine(
        get_model_config("llama-test"), params, max_seq=64, max_batch=4,
        sampling=SamplingParams(greedy=True), prompt_buckets=(16,),
        kv_layout="paged", kv_cache_blocks=8, kv_block_tokens=4,
        kv_host_tier_bytes=1 << 22)
    with eng:
        tier = eng._kv_tier
        assert tier is not None and eng.kv_cache.tier is tier
        reqs = [eng.submit(p, 18) for p in prompts]
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                r.wait(timeout=300),
                oracle.generate(np.asarray(p)[None, :], 18).tokens[0])
        # oversubscription (4 slots x 2 blocks > 8 pool blocks after
        # stores) demoted at least one evicted leaf
        assert tier.stats["demoted_blocks"] > 0
        assert tier.stats["demote_errors"] == 0
        # re-submit the first prompt: its demoted prefix promotes back
        r = eng.submit(prompts[0], 18)
        np.testing.assert_array_equal(
            r.wait(timeout=300),
            oracle.generate(np.asarray(prompts[0])[None, :],
                            18).tokens[0])
        snap = eng.stats()["kvcache"]
        assert "tier" in snap and "digest" in snap["tier"]
        assert all(len(d) == 16 for d in snap["tier"]["digest"])
        if tier.stats["promoted_blocks"]:
            assert snap["h2d_bytes"] > 0
        mgr = eng.kv_cache
        assert mgr.used_blocks == mgr.tree.block_count
        tier.check()
        assert "host_tier" in profiling.get_hbm_watermarks().watermarks()
    # close(): tier dies with the engine, ledger owner retired
    assert "host_tier" not in profiling.get_hbm_watermarks().watermarks()


def test_tier_fragment_bridges_to_catalog():
    from distributed_inference_demo_tpu.telemetry import catalog
    t = TieredKVStore(1 << 20, BT)
    k, v = _payload(2)
    t.demote(_keys(list(range(2 * BT))), k, v)
    frag = t.snapshot()
    catalog.update_kvcache_tier_series(frag)

    def val(metric, **labels):
        for _, lab, v in metric.samples():
            if all(dict(lab).get(k) == w for k, w in labels.items()):
                return v
        raise AssertionError(f"no sample {labels}")

    assert val(catalog.KVCACHE_TIER_RESIDENT_BLOCKS, tier="host") == 2
    assert val(catalog.KVCACHE_TIER_RESIDENT_BYTES,
               tier="host") == t.host_resident_bytes
    assert val(catalog.KVCACHE_TIER_DEMOTED_BLOCKS) == 2


# ---------------------------------------------------------------------------
# tools/fleet_top.py --kv: the per-replica tier-occupancy section


def _fleet_top():
    import importlib.util
    path = (Path(__file__).resolve().parents[1] / "tools"
            / "fleet_top.py")
    spec = importlib.util.spec_from_file_location("fleet_top", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_top_kv_section_crash_safe_without_tier_series():
    """A fleet with tiering off (or pre-§21 replicas) exports no
    dwt_kvcache_tier_* series: the --kv section renders its placeholder
    line instead of crashing — same contract as --profile."""
    ft = _fleet_top()
    samples = ft.parse_metrics(
        'dwt_slo_requests_total{tenant="a",replica="r0"} 3\n'
        'dwt_gateway_fleet_scrape_age_seconds{replica="r0"} 0.5\n')
    rows = ft.kv_tier_rows(samples)
    assert rows == []
    page = ft.render_kv(rows)
    assert "no dwt_kvcache_tier_* series exported" in page


def test_fleet_top_kv_rows_from_federated_series():
    ft = _fleet_top()
    text = "\n".join([
        'dwt_kvcache_tier_resident_blocks{tier="host",replica="r0"} 6',
        'dwt_kvcache_tier_resident_bytes{tier="host",replica="r0"} 6144',
        'dwt_kvcache_tier_capacity_bytes{tier="host",replica="r0"} 8192',
        'dwt_kvcache_tier_hits_total{tier="host",replica="r0"} 11',
        'dwt_kvcache_tier_resident_blocks{tier="disk",replica="r0"} 2',
        'dwt_kvcache_tier_resident_bytes{tier="disk",replica="r0"} 2048',
        'dwt_kvcache_tier_capacity_bytes{tier="disk",replica="r0"} 4096',
        'dwt_kvcache_tier_hits_total{tier="disk",replica="r0"} 3',
        'dwt_kvcache_tier_demoted_blocks_total{replica="r0"} 9',
        'dwt_kvcache_tier_promoted_blocks_total{replica="r0"} 7',
        'dwt_kvcache_tier_spilled_blocks_total{replica="r0"} 2',
        'dwt_kvcache_tier_dropped_blocks_total{replica="r0"} 0',
        'dwt_kvcache_tier_resident_blocks{tier="host",replica="r1"} 0',
        'dwt_kvcache_tier_resident_bytes{tier="host",replica="r1"} 0',
        'dwt_kvcache_tier_capacity_bytes{tier="host",replica="r1"} 8192',
    ])
    rows = ft.kv_tier_rows(ft.parse_metrics(text))
    assert [r["replica"] for r in rows] == ["r0", "r1"]
    r0 = rows[0]
    assert r0["tiers"]["host"] == {"blocks": 6.0, "bytes": 6144.0,
                                   "cap": 8192.0, "hits": 11.0}
    assert r0["tiers"]["disk"]["bytes"] == 2048.0
    assert (r0["demoted"], r0["promoted"],
            r0["spilled"], r0["dropped"]) == (9.0, 7.0, 2.0, 0.0)
    page = ft.render_kv(rows)
    assert "r0" in page and "host" in page and "disk" in page
    assert "75.0%" in page            # 6144 / 8192
    # the empty-but-capacitied r1 host ring renders 0% — not a NaN crash
    assert "r1" in page and "0.0%" in page
