"""Numerical parity against the HuggingFace reference implementations.

Every other model test in this suite is self-consistency (prefill vs decode,
pipeline vs engine) — a sign error in RoPE or ALiBi would pass all of them.
These tests earn external trust the way the reference implicitly does by
consuming HF exports (reference ``server.py:831-832``): instantiate the
*torch* reference model for each family on random weights, map its state
dict through ``models/loader.py``, and require logit-level agreement from
our jax decoder — for the full prompt (prefill path) and for the last token
produced via the KV-cached decode path.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_inference_demo_tpu.models import (  # noqa: E402
    KVCache, StageSpec, get_model_config)
from distributed_inference_demo_tpu.models.decoder import (  # noqa: E402
    stage_forward)
from distributed_inference_demo_tpu.models.loader import (  # noqa: E402
    params_from_state_dict)


def _hf_model(name):
    """Build the HF twin of one of our tiny test configs."""
    cfg = get_model_config(name)
    if cfg.family in ("llama", "qwen2"):
        if cfg.family == "qwen2":
            hf_cfg = transformers.Qwen2Config(
                vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                num_hidden_layers=cfg.num_layers,
                num_attention_heads=cfg.num_heads,
                num_key_value_heads=cfg.num_kv_heads,
                intermediate_size=cfg.intermediate_size,
                max_position_embeddings=cfg.max_seq_len,
                rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
                tie_word_embeddings=cfg.tie_embeddings)
            return cfg, transformers.Qwen2ForCausalLM(hf_cfg).float().eval()
        hf_cfg = transformers.LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.num_kv_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_seq_len,
            rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
            attention_bias=False, mlp_bias=False,
            tie_word_embeddings=cfg.tie_embeddings)
        model = transformers.LlamaForCausalLM(hf_cfg)
    elif cfg.family == "gemma":
        hf_cfg = transformers.GemmaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.num_kv_heads,
            intermediate_size=cfg.intermediate_size,
            head_dim=cfg.head_dim, hidden_act="gelu_pytorch_tanh",
            hidden_activation="gelu_pytorch_tanh",
            max_position_embeddings=cfg.max_seq_len,
            rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
            tie_word_embeddings=cfg.tie_embeddings,
            attention_bias=False)
        model = transformers.GemmaForCausalLM(hf_cfg)
    elif cfg.family == "bloom":
        hf_cfg = transformers.BloomConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            n_layer=cfg.num_layers, n_head=cfg.num_heads,
            layer_norm_epsilon=cfg.norm_eps)
        model = transformers.BloomForCausalLM(hf_cfg)
    elif cfg.family == "mixtral":
        hf_cfg = transformers.MixtralConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.num_kv_heads,
            intermediate_size=cfg.intermediate_size,
            num_local_experts=cfg.num_experts,
            num_experts_per_tok=cfg.experts_per_token,
            max_position_embeddings=cfg.max_seq_len,
            rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
            tie_word_embeddings=cfg.tie_embeddings)
        model = transformers.MixtralForCausalLM(hf_cfg)
    else:
        raise AssertionError(cfg.family)
    model = model.float().eval()
    return cfg, model


def _our_params(cfg, model):
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    return params_from_state_dict(sd, cfg)


def _hf_logits(model, ids):
    with torch.no_grad():
        out = model(input_ids=torch.from_numpy(ids).long())
    return out.logits.float().numpy()


PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56, 200, 131]], dtype=np.int32)

FAMILIES = ["llama-test", "qwen2-test", "gemma-test", "bloom-test",
            "mixtral-test"]


@pytest.mark.parametrize("name", FAMILIES)
def test_prefill_logits_match_transformers(name):
    torch.manual_seed(0)
    cfg, model = _hf_model(name)
    params = _our_params(cfg, model)
    want = _hf_logits(model, PROMPT)

    spec = StageSpec(0, 1, 0, cfg.num_layers)
    pos = jnp.broadcast_to(jnp.arange(PROMPT.shape[1]), PROMPT.shape)
    got, _ = stage_forward(params, cfg, spec, jnp.asarray(PROMPT),
                           KVCache.create(cfg, cfg.num_layers, 1, 32), pos)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", FAMILIES)
def test_decode_step_matches_transformers(name):
    """KV-cached decode: prefill on the first n-1 tokens, decode token n;
    the decode-path logits must equal HF's full-sequence last-position
    logits (catches cache layout / position-offset bugs prefill can't)."""
    torch.manual_seed(0)
    cfg, model = _hf_model(name)
    params = _our_params(cfg, model)
    want = _hf_logits(model, PROMPT)[:, -1, :]

    spec = StageSpec(0, 1, 0, cfg.num_layers)
    head, last = PROMPT[:, :-1], PROMPT[:, -1:]
    pos_head = jnp.broadcast_to(jnp.arange(head.shape[1]), head.shape)
    cache = KVCache.create(cfg, cfg.num_layers, 1, 32)
    _, cache = stage_forward(params, cfg, spec, jnp.asarray(head), cache,
                             pos_head)
    pos_last = jnp.full((1, 1), head.shape[1])
    got, _ = stage_forward(params, cfg, spec, jnp.asarray(last), cache,
                           pos_last)
    np.testing.assert_allclose(np.asarray(got)[:, -1, :], want,
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", FAMILIES)
def test_save_pretrained_roundtrip_loads(name, tmp_path):
    """load_or_init consumes an HF ``save_pretrained`` safetensors directory
    for every family (closes the reference's ModelCard load path for
    bloom/mixtral, SURVEY.md §2.2)."""
    from distributed_inference_demo_tpu.models.loader import load_or_init
    torch.manual_seed(0)
    cfg, model = _hf_model(name)
    model.save_pretrained(tmp_path, safe_serialization=True)
    params = load_or_init(name, cfg, checkpoint_dir=str(tmp_path))
    want = _hf_logits(model, PROMPT)

    spec = StageSpec(0, 1, 0, cfg.num_layers)
    pos = jnp.broadcast_to(jnp.arange(PROMPT.shape[1]), PROMPT.shape)
    got, _ = stage_forward(params, cfg, spec, jnp.asarray(PROMPT),
                           KVCache.create(cfg, cfg.num_layers, 1, 32), pos)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", FAMILIES)
def test_checkpoint_to_serving_e2e(name, tmp_path):
    """The whole checkpoint->serving story in one test per family:
    HF ``save_pretrained`` safetensors -> load_or_init -> the CLI's
    engine path -> greedy generation that MATCHES the torch reference's
    own greedy decode token-for-token (the reference's ModelCard
    load/split/serve pipeline, SURVEY.md §2.2, as a product-surface
    check rather than a logit fragment)."""
    import io
    import json as _json
    from contextlib import redirect_stdout

    from distributed_inference_demo_tpu import cli

    torch.manual_seed(0)
    cfg, model = _hf_model(name)
    model.save_pretrained(tmp_path, safe_serialization=True)

    new_tokens = 8
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor(np.asarray(PROMPT)), do_sample=False,
            max_new_tokens=new_tokens, use_cache=True)
    want = hf_out[0, PROMPT.shape[1]:].tolist()

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main([
            "generate", "--model", name, "--checkpoint", str(tmp_path),
            "--prompt-ids", ",".join(str(int(t)) for t in PROMPT[0]),
            "--max-new-tokens", str(new_tokens), "--greedy",
            "--max-seq", "32", "--attn-backend", "jnp"])
    assert rc == 0
    got = _json.loads(buf.getvalue())["tokens"][0]
    assert got == want


# ---------------------------------------------------------------------------
# vision tower vs HF CLIPVisionModel (the LLaVA stage-0 geometry)

def _tiny_clip():
    from distributed_inference_demo_tpu.models.vision import VisionConfig
    vcfg = VisionConfig(image_size=28, patch_size=14, hidden_size=32,
                        num_layers=3, num_heads=4, intermediate_size=64,
                        dtype_name="float32", clip_arch=True,
                        feature_layer=-2, hidden_act="quick_gelu")
    hf_cfg = transformers.CLIPVisionConfig(
        image_size=28, patch_size=14, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        hidden_act="quick_gelu", layer_norm_eps=vcfg.norm_eps)
    model = transformers.CLIPVisionModel(hf_cfg).float().eval()
    return vcfg, model


@pytest.mark.slow
def test_vision_tower_matches_clip():
    """clip_arch + feature_layer=-2 reproduces HF hidden_states[-2] minus
    the class token — the exact feature LLaVA-1.5 projects.  The weights
    travel through the checkpoint mapper, so this also pins the state
    dict name/transpose mapping.  The (seed-initialized) projector is
    applied to the HF features with the same jnp math, so any feature
    mismatch surfaces as an output mismatch."""
    from distributed_inference_demo_tpu.models.loader import (
        vision_params_from_clip_state_dict)
    from distributed_inference_demo_tpu.models.vision import vision_forward

    vcfg, model = _tiny_clip()
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    params = vision_params_from_clip_state_dict(sd, vcfg, decoder_hidden=16)
    rs = np.random.RandomState(0)
    pixels = rs.randn(2, 28, 28, 3).astype(np.float32)
    with torch.no_grad():
        hf = model(pixel_values=torch.from_numpy(
            pixels.transpose(0, 3, 1, 2)), output_hidden_states=True)
    want = hf.hidden_states[-2][:, 1:].numpy()          # drop cls

    got = np.asarray(vision_forward(params, vcfg, jnp.asarray(pixels)))
    h = jnp.asarray(want) @ params["proj_w1"] + params["proj_b1"]
    h = jax.nn.gelu(h.astype(jnp.float32)).astype(jnp.float32)
    expected = np.asarray(h @ params["proj_w2"] + params["proj_b2"])
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_vision_clip_rejects_plain_tower():
    from distributed_inference_demo_tpu.models.loader import (
        vision_params_from_clip_state_dict)
    from distributed_inference_demo_tpu.models.vision import VisionConfig
    vcfg = VisionConfig(image_size=28, patch_size=14, hidden_size=32,
                        num_layers=2, num_heads=4, intermediate_size=64)
    with pytest.raises(ValueError, match="clip_arch"):
        vision_params_from_clip_state_dict({}, vcfg, decoder_hidden=16)
