"""Fleet observability plane (ISSUE 16): federation, stitch, SLO e2e.

Two layers:

- unit tests over the federation text surgery (``relabel_sample``,
  ``merge_exposition``) and the :class:`FleetScraper` debounce /
  bounded-staleness cache — injected clock + fetcher, no sockets;
- THE acceptance e2e: two tenants stream through gateway -> replica
  with one request live-migrated mid-decode; ``/metrics/fleet`` shows
  per-tenant goodput and burn-rate series with ``replica=`` labels
  from both replicas; ``/trace/fleet`` yields ONE Chrome trace whose
  gateway-proxy, engine, and migration spans share the request's trace
  id; the migrated request's ``/timeline`` record shows the migration
  pause with a TTFT/TPOT decomposition summing to e2e; greedy output
  stays bit-identical and both pools end leak-free.
"""

import json
import re
import subprocess
import sys
import threading
import time
from http.client import HTTPConnection
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.runtime.gateway import (
    GatewayHTTPServer, PrefixAwareRouter, ReplicaRegistry)
from distributed_inference_demo_tpu.runtime.gateway.federation import (
    FleetScraper, merge_exposition, relabel_sample)
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)
from distributed_inference_demo_tpu.runtime.migration import MigrationWorker
from distributed_inference_demo_tpu.telemetry import catalog as _catalog
from distributed_inference_demo_tpu.telemetry.slo import (
    SloLedger, set_slo_ledger)

GREEDY = SamplingParams(greedy=True)
CFG = get_model_config("llama-test")
PROMPT = (np.arange(17) % 50 + 3).astype(np.int32)
MAX_NEW = 96


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


# ---------------------------------------------------------------------------
# unit: exposition text surgery
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_relabel_sample_variants():
    assert (relabel_sample('dwt_x_total{tenant="a"} 3', "h:1")
            == 'dwt_x_total{replica="h:1",tenant="a"} 3')
    assert (relabel_sample("dwt_x_total 3 1700000000", "h:1")
            == 'dwt_x_total{replica="h:1"} 3 1700000000')
    assert (relabel_sample("dwt_x_total{} 3", "h:1")
            == 'dwt_x_total{replica="h:1"} 3')
    # the injected label goes FIRST: a label value containing "{" or
    # an escaped quote can't confuse the splice
    tricky = 'dwt_x_total{k="a{b\\"c"} 1'
    assert (relabel_sample(tricky, "h:1")
            == 'dwt_x_total{replica="h:1",k="a{b\\"c"} 1')
    # rid itself is escaped into a valid label value
    assert 'replica="q\\"r"' in relabel_sample("m 1", 'q"r')


@pytest.mark.quick
def test_merge_exposition_dedups_headers_and_groups_families():
    gw = ("# HELP dwt_f_total doc\n# TYPE dwt_f_total counter\n"
          'dwt_f_total{route="/x"} 1\n')
    rep = ("# HELP dwt_f_total doc\n# TYPE dwt_f_total counter\n"
           'dwt_f_total{route="/x"} 5\n'
           "# HELP dwt_g_seconds other\n# TYPE dwt_g_seconds histogram\n"
           'dwt_g_seconds_bucket{le="+Inf"} 2\n'
           "dwt_g_seconds_sum 0.1\ndwt_g_seconds_count 2\n")
    page = merge_exposition([(None, gw), ("r:1", rep)])
    # headers appear once, first-wins
    assert page.count("# HELP dwt_f_total") == 1
    assert page.count("# TYPE dwt_f_total") == 1
    # gateway's own samples stay bare; the replica's gain replica=
    assert 'dwt_f_total{route="/x"} 1' in page
    assert 'dwt_f_total{replica="r:1",route="/x"} 5' in page
    # histogram children follow their family header (contiguity): every
    # sample of a family sits between its header and the next one
    assert 'dwt_g_seconds_bucket{replica="r:1",le="+Inf"} 2' in page
    f_block = page.split("# HELP dwt_g_seconds")[0]
    assert "dwt_g_seconds" not in f_block.replace(
        "# HELP dwt_g_seconds", "")
    assert page.index("dwt_f_total{replica") < page.index(
        "# HELP dwt_g_seconds")


class _FakeRegistry:
    def __init__(self, rids):
        self.rids = list(rids)

    def replica_ids(self):
        return list(self.rids)

    def endpoint(self, rid):
        host, port = rid.rsplit(":", 1)
        return host, int(port)


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.mark.quick
def test_fleet_scraper_debounce_staleness_and_holes():
    clk = _Clock()
    calls = []
    fail = {"flag": False}

    def fetcher(host, port):
        calls.append((host, port))
        if fail["flag"]:
            raise RuntimeError("replica down")
        return ("# HELP dwt_u_total doc\n# TYPE dwt_u_total counter\n"
                "dwt_u_total 7\n")

    fs = FleetScraper(_FakeRegistry(["h:9"]), min_interval_s=1.0,
                      max_stale_s=30.0, clock=clk, fetcher=fetcher)
    own = "# HELP dwt_o_total d\n# TYPE dwt_o_total counter\ndwt_o_total 1\n"
    page = fs.scrape_fleet(own)
    assert 'dwt_u_total{replica="h:9"} 7' in page
    assert "dwt_o_total 1" in page            # gateway stays bare
    # debounce: a second scrape inside the window reuses the cache
    clk.t += 0.5
    fs.scrape_fleet(own)
    assert len(calls) == 1
    # fetch failures inside max_stale serve the last good text
    fail["flag"] = True
    clk.t += 2.0
    page = fs.scrape_fleet(own)
    assert len(calls) == 2                    # attempted, failed
    assert 'dwt_u_total{replica="h:9"} 7' in page
    assert ('dwt_gateway_fleet_failed_scrapes_total{replica="h:9"} 1'
            in _catalog.REGISTRY.render())
    # beyond max_stale the section degrades to a visible hole
    clk.t += 60.0
    page = fs.scrape_fleet(own)
    assert "dwt_u_total" not in page
    assert "# replica h:9: no scrape within 30s" in page
    # recovery repopulates
    fail["flag"] = False
    clk.t += 2.0
    assert 'dwt_u_total{replica="h:9"} 7' in fs.scrape_fleet(own)
    assert fs.debug_state()["h:9"]["cached"] is True


# ---------------------------------------------------------------------------
# the acceptance e2e
# ---------------------------------------------------------------------------


def _get(host, port, path, timeout=60):
    conn = HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _post_stream(host, port, body, headers=None, timeout=300):
    conn = HTTPConnection(host, port, timeout=timeout)
    try:
        hs = {"Content-Type": "application/json"}
        hs.update(headers or {})
        conn.request("POST", "/generate", body=json.dumps(body),
                     headers=hs)
        resp = conn.getresponse()
        rhead = dict(resp.getheaders())
        if resp.status != 200:
            return resp.status, rhead, [json.loads(resp.read())]
        lines = []
        while True:
            ln = resp.readline()
            if not ln:
                break
            ln = ln.strip()
            if ln:
                lines.append(json.loads(ln))
        return resp.status, rhead, lines
    finally:
        conn.close()


def _drain(gw, rid, flag=True):
    conn = HTTPConnection(gw.host, gw.port, timeout=30)
    try:
        conn.request("POST", "/drain", body=json.dumps(
            {"replica": rid, "draining": flag}))
        assert conn.getresponse().status == 200
    finally:
        conn.close()


def _idle_no_leaks(*engines):
    deadline = time.monotonic() + 5.0
    while True:
        snaps = [e.kv_cache.snapshot() for e in engines]
        if all(s["blocks_used"] == s["tree_blocks"] for s in snaps):
            return
        if time.monotonic() > deadline:
            raise AssertionError("page leak: " + ", ".join(
                f"{s['blocks_used']}/{s['tree_blocks']}" for s in snaps))
        time.sleep(0.05)


# tier-1 budget: the scraper/relabel/merge quick tests pin the fleet
# plane; the two-tenant live-migration soak rides the slow lane
@pytest.mark.slow
def test_two_tenant_fleet_with_live_migration_end_to_end(params):
    """ISSUE-16 acceptance: see module docstring."""
    set_slo_ledger(SloLedger(ttft_slo_ms=0, tpot_slo_ms=0, target=0.99))
    ref_eng = ContinuousBatchingEngine(
        CFG, params, max_seq=160, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=32, kv_block_tokens=8)
    try:
        reference = [int(t) for t in ref_eng.submit(PROMPT,
                                                    MAX_NEW).wait(120)]
    finally:
        ref_eng.close()

    engines = [ContinuousBatchingEngine(
        CFG, params, max_seq=160, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=32, kv_block_tokens=8) for _ in range(2)]
    net = LoopbackNetwork()
    workers = [MigrationWorker(eng, LoopbackTransport(name, net),
                               ack_timeout=10.0)
               for eng, name in zip(engines, ("r1", "r2"))]
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    servers = []
    for eng in engines:
        srv = InferenceHTTPServer(eng, port=0)
        srv.start()
        servers.append(srv)
    rids = [f"{s.host}:{s.port}" for s in servers]
    registry = ReplicaRegistry([(s.host, s.port) for s in servers],
                               sustain=3, probe_interval_s=0.2)
    router = PrefixAwareRouter(registry, min_prefix_tokens=8,
                               block_tokens=8)
    gw = GatewayHTTPServer(registry, router, port=0,
                           fleet_scrape_interval_s=0.0)
    gw.start()
    try:
        # ---- tenant-a: long stream pinned to replica 1 by draining 2
        _drain(gw, rids[1], True)
        result_a = {}

        def run_a():
            result_a["resp"] = _post_stream(
                gw.host, gw.port,
                {"prompt_ids": [[int(t) for t in PROMPT]],
                 "max_new_tokens": MAX_NEW, "stream": True,
                 "tenant": "tenant-a"})

        ta = threading.Thread(target=run_a)
        ta.start()
        deadline = time.monotonic() + 60.0
        migratable = []
        while not migratable and time.monotonic() < deadline:
            migratable = workers[0].pick_migratable(4)
            time.sleep(0.002)
        assert migratable, "tenant-a request never became migratable"

        # ---- flip the drain: tenant-b lands on replica 2, and the
        # live request migrates there mid-decode
        _drain(gw, rids[1], False)
        _drain(gw, rids[0], True)
        assert workers[0].migrate_out(migratable[0], "r2") is True

        st, headers, _ = _post_stream(
            gw.host, gw.port,
            {"prompt_ids": [[int(t) + 1 for t in PROMPT]],
             "max_new_tokens": 8, "stream": True},
            headers={"X-DWT-Tenant": "tenant-b"})
        assert st == 200
        assert headers["X-DWT-Replica"] == rids[1]

        ta.join(timeout=180)
        assert not ta.is_alive()
        st, _, lines = result_a["resp"]
        assert st == 200
        assert "error" not in lines[-1]
        # greedy bit-identity across the gateway hop AND the migration
        assert [d["tokens"][0] for d in lines] == reference
        _idle_no_leaks(*engines)

        # ---- /metrics/fleet: per-tenant series with replica= labels
        # from BOTH replicas, goodput + burn-rate present
        st, body = _get(gw.host, gw.port, "/metrics/fleet")
        assert st == 200
        page = body.decode()
        for rid in rids:
            assert re.search(
                r'dwt_slo_tokens_total\{replica="%s",tenant="tenant-a"\}'
                % re.escape(rid), page), rid
            assert f'dwt_gateway_fleet_scrapes_total{{replica="{rid}"}}' \
                in page
        assert re.search(
            r'dwt_slo_good_tokens_total\{replica=[^}]*'
            r'tenant="tenant-a"\} 96', page)
        assert re.search(
            r'dwt_slo_burn_rate_ratio\{replica=[^}]*tenant="tenant-a",'
            r'window="5m"\}', page)
        assert re.search(
            r'dwt_slo_migrated_requests_total\{replica=[^}]*'
            r'tenant="tenant-a"\} 1', page)
        assert 'tenant="tenant-b"' in page
        # headers dedup across gateway + 2 replica sections
        assert page.count("# HELP dwt_slo_tokens_total") == 1

        # ---- /trace/fleet: ONE Chrome trace; the migrated request's
        # gateway-proxy, engine, and migration spans share a trace id
        st, body = _get(gw.host, gw.port, "/trace/fleet")
        assert st == 200
        trace = json.loads(body)
        events = trace["traceEvents"]
        by_name = {}
        for ev in events:
            if ev.get("ph") == "X":
                by_name.setdefault(ev["name"], set()).add(
                    ev["args"]["trace_id"])
        gw_tids = by_name.get("gateway.proxy", set())
        eng_tids = (by_name.get("engine.prefill", set())
                    | by_name.get("engine.decode", set()))
        mig_tids = (by_name.get("migration_export", set())
                    & by_name.get("migration_handoff", set())
                    & by_name.get("migration_adopt", set()))
        stitched = gw_tids & eng_tids & mig_tids
        assert len(stitched) == 1, (gw_tids, eng_tids, mig_tids)
        # distinct process lanes: gateway + both engines + migration
        procs = {ev["args"]["name"] for ev in events
                 if ev.get("ph") == "M"
                 and ev.get("name") == "process_name"}
        assert "gateway" in procs
        assert len([p for p in procs if p.startswith("engine:")]) == 2
        assert any(p.startswith("migration:") for p in procs)

        # ---- /timeline on the SOURCE replica: the migrated record
        # decomposes, pause visible, sums to e2e
        st, body = _get(servers[0].host, servers[0].port,
                        "/timeline?n=32")
        assert st == 200
        tl = json.loads(body)
        recs = [r for r in tl["recent"]
                if r["tenant"] == "tenant-a" and r["migrated"]]
        assert len(recs) == 1
        r = recs[0]
        assert r["tokens"] == MAX_NEW
        assert r["migration_pause_s"] > 0.0
        assert r["trace_id"] in stitched
        lhs = (r["ttft_s"] + r["per_token_s"] * (r["tokens"] - 1)
               + r["migration_pause_s"])
        assert lhs == pytest.approx(r["e2e_s"], abs=1e-9)
        assert tl["tenants"]["tenant-a"]["migrated"] == 1

        # ---- gateway /debugz carries the probed fleet SLO summary
        deadline = time.monotonic() + 10.0
        fleet_slo = {}
        while time.monotonic() < deadline:
            st, body = _get(gw.host, gw.port, "/debugz")
            assert st == 200
            fleet_slo = json.loads(body)["fleet_slo"]
            if any("tenant-a" in v.get("tenants", {})
                   for v in fleet_slo.values()):
                break
            time.sleep(0.2)
        assert any("tenant-a" in v.get("tenants", {})
                   for v in fleet_slo.values())

        # ---- tools/fleet_top.py renders the same page (--once mode)
        proc = subprocess.run(
            [sys.executable, "tools/fleet_top.py",
             "--gateway", f"{gw.host}:{gw.port}", "--once"],
            cwd=str(Path(__file__).resolve().parent.parent),
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "tenant-a" in proc.stdout
        assert "tenant-b" in proc.stdout
        assert rids[0] in proc.stdout
    finally:
        gw.shutdown()
        for w in workers:
            w.stop()
        for t in threads:
            t.join(timeout=2)
        for srv, eng in zip(servers, engines):
            srv.shutdown()
            eng.close()
        set_slo_ledger(None)
