"""Wire codec tests: Python reference impl, native C++ impl, cross-compat.

The reference's codec had no tests at all (SURVEY.md §4); its known defect
(native-endian size_t fields, Appendix B #9) is exactly what these lock in
against regressing.
"""

import numpy as np
import pytest

import ml_dtypes

from distributed_inference_demo_tpu.comm import wire
from distributed_inference_demo_tpu.comm import native_codec


CASES = [
    [],
    [np.arange(12, dtype=np.float32).reshape(3, 4)],
    [np.zeros((2, 0, 3), np.int64)],  # zero-size dim
    [np.float64(3.5).reshape(())],    # scalar, ndims=0
    [np.arange(6, dtype=np.int8),
     np.ones((2, 2), np.float16),
     np.array([[True, False]], bool),
     np.arange(5, dtype=np.uint32)],
    [np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 4)],
]


@pytest.mark.parametrize("arrays", CASES, ids=range(len(CASES)))
@pytest.mark.quick
def test_python_roundtrip(arrays):
    blob = wire.serialize_tensors(arrays, flags=7)
    msg = wire.deserialize_tensors(blob)
    assert msg.flags == 7
    assert len(msg.tensors) == len(arrays)
    for a, b in zip(arrays, msg.tensors):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), b)


def test_native_available():
    assert native_codec.available(), "native codec failed to build/load"


@pytest.mark.parametrize("arrays", CASES, ids=range(len(CASES)))
def test_native_python_byte_identical(arrays):
    py = wire.serialize_tensors(arrays, flags=3)
    nat = native_codec.serialize_tensors(arrays, flags=3)
    assert py == nat  # byte-for-byte identical wire output


@pytest.mark.parametrize("arrays", CASES, ids=range(len(CASES)))
def test_cross_decode(arrays):
    # python-encoded → native-decoded and vice versa
    py_blob = wire.serialize_tensors(arrays)
    nat_msg = native_codec.deserialize_tensors(py_blob)
    for a, b in zip(arrays, nat_msg.tensors):
        np.testing.assert_array_equal(np.asarray(a), b)
    nat_blob = native_codec.serialize_tensors(arrays)
    py_msg = wire.deserialize_tensors(nat_blob)
    for a, b in zip(arrays, py_msg.tensors):
        np.testing.assert_array_equal(np.asarray(a), b)


def test_big_endian_input_normalized():
    a = np.arange(4, dtype=">i4")  # big-endian input
    msg = wire.deserialize_tensors(wire.serialize_tensors([a]))
    assert msg.tensors[0].dtype == np.dtype("<i4")
    np.testing.assert_array_equal(msg.tensors[0], a)


@pytest.mark.skipif(not native_codec.available(), reason="native codec absent")
def test_big_endian_input_normalized_native():
    # The native serializer must byteswap too, not just pass raw bytes with
    # a little-endian dtype tag.
    a = np.arange(4, dtype=">i4")
    for blob in (native_codec.serialize_tensors([a]),):
        for decoded in (wire.deserialize_tensors(blob),
                        native_codec.deserialize_tensors(blob)):
            np.testing.assert_array_equal(decoded.tensors[0], [0, 1, 2, 3])


@pytest.mark.skipif(not native_codec.available(), reason="native codec absent")
def test_dim_product_overflow_rejected_both_impls():
    # Crafted message: one F32 tensor claiming dims=[2^62] with nbytes=0.
    # count * itemsize wraps to 0 in u64; both decoders must reject it.
    import struct
    blob = (struct.pack("<4sBBHI", wire.MAGIC, wire.VERSION, 0, 0, 1)
            + struct.pack("<BBHQ", int(wire.DType.F32), 1, 0, 0)
            + struct.pack("<Q", 1 << 62))
    with pytest.raises(wire.WireError):
        wire.deserialize_tensors(blob)
    with pytest.raises(wire.WireError):
        native_codec.deserialize_tensors(blob)


@pytest.mark.skipif(not native_codec.available(), reason="native codec absent")
def test_huge_nbytes_offset_wrap_rejected_both_impls():
    # u8 tensor with count == nbytes == 2^64-1: the dim product is
    # consistent, but off + nbytes would wrap u64; remainder-based bounds
    # checking must reject it.
    import struct
    huge = (1 << 64) - 1
    blob = (struct.pack("<4sBBHI", wire.MAGIC, wire.VERSION, 0, 0, 1)
            + struct.pack("<BBHQ", int(wire.DType.U8), 1, 0, huge)
            + struct.pack("<Q", huge))
    with pytest.raises(wire.WireError):
        wire.deserialize_tensors(blob)
    with pytest.raises(wire.WireError):
        native_codec.deserialize_tensors(blob)


@pytest.mark.skipif(not native_codec.available(), reason="native codec absent")
def test_native_decode_returns_writable_arrays():
    blob = wire.serialize_tensors([np.arange(6, dtype=np.float32)])
    arr = native_codec.deserialize_tensors(blob).tensors[0]
    arr[0] = 42.0  # must not raise (decoded arrays own writable memory)
    assert arr[0] == 42.0


@pytest.mark.parametrize("mutate", [
    lambda b: b[:3],                        # shorter than header
    lambda b: b"XXXX" + b[4:],              # bad magic
    lambda b: b[:4] + b"\x09" + b[5:],      # bad version
    lambda b: b + b"\x00",                  # trailing bytes
    lambda b: b[:-1],                       # truncated data
])
def test_malformed_rejected_both_impls(mutate):
    blob = mutate(wire.serialize_tensors(
        [np.arange(6, dtype=np.float32).reshape(2, 3)]))
    with pytest.raises(wire.WireError):
        wire.deserialize_tensors(blob)
    with pytest.raises(wire.WireError):
        native_codec.deserialize_tensors(blob)


# -- trace-context flag (telemetry) -----------------------------------------

def test_trace_context_roundtrip_python():
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3)]
    blob = wire.serialize_tensors_traced(arrays, trace_id=0xDEADBEEF,
                                         parent_span_id=42)
    msg = wire.deserialize_tensors(blob)
    assert msg.flags & wire.FLAG_TRACE_CONTEXT
    tensors, ctx = wire.split_trace_context(msg)
    assert ctx == (0xDEADBEEF, 42)
    assert len(tensors) == 1
    np.testing.assert_array_equal(tensors[0], arrays[0])


def test_trace_context_u64_extremes():
    huge = (1 << 64) - 1
    blob = wire.serialize_tensors_traced([], trace_id=huge,
                                         parent_span_id=huge)
    _, ctx = wire.split_trace_context(wire.deserialize_tensors(blob))
    assert ctx == (huge, huge)


def test_untraced_frames_byte_identical():
    """Frames without the trace bit are EXACTLY today's format — pinned
    against a hand-computed golden blob (checksum field computed here
    independently: CRC-32 of the payload XOR-folded to 16 bits), and
    serialize_tensors_traced with trace_id=None is a byte-level no-op."""
    import zlib
    a = np.arange(3, dtype=np.int32)
    blob = wire.serialize_tensors([a])
    payload = (bytes([int(wire.DType.I32), 1]) + b"\x00\x00"
               + (12).to_bytes(8, "little")              # nbytes
               + (3).to_bytes(8, "little")               # dims
               + a.tobytes())
    crc = zlib.crc32(payload)
    fold = ((crc & 0xFFFF) ^ (crc >> 16)) or 0xFFFF
    golden = (b"DWT1" + bytes([1, 0])                    # ver, flags
              + fold.to_bytes(2, "little")               # checksum
              + (1).to_bytes(4, "little")                # ntensors
              + payload)
    assert blob == golden
    assert wire.serialize_tensors_traced([a], None) == blob
    msg = wire.deserialize_tensors(blob)
    assert not (msg.flags & wire.FLAG_TRACE_CONTEXT)
    tensors, ctx = wire.split_trace_context(msg)
    assert ctx is None and len(tensors) == 1


# -- wire integrity checksum (PR 5) -----------------------------------------

@pytest.mark.parametrize("pos", [6, 12, 13, 25, -1])
def test_checksum_detects_any_flipped_byte(pos):
    blob = wire.serialize_tensors([np.arange(6, dtype=np.float32)])
    bad = bytearray(blob)
    bad[pos] ^= 0x01
    with pytest.raises(wire.WireIntegrityError):
        wire.deserialize_tensors(bytes(bad))
    if native_codec.available():
        with pytest.raises(wire.WireIntegrityError):
            native_codec.deserialize_tensors(bytes(bad))


def test_zero_checksum_legacy_frames_accepted_both_impls():
    """Version compat: a pre-checksum peer's frame (field = 0) decodes
    unchanged — including one whose payload was built by a current
    serializer with checksum=False."""
    a = [np.arange(5, dtype=np.int16)]
    for blob in (wire.serialize_tensors(a, checksum=False),
                 native_codec.serialize_tensors(a, checksum=False)
                 if native_codec.available() else None):
        if blob is None:
            continue
        assert blob[6:8] == b"\x00\x00"
        for decode in (wire.deserialize_tensors,
                       native_codec.deserialize_tensors
                       if native_codec.available() else None):
            if decode is None:
                continue
            np.testing.assert_array_equal(decode(blob).tensors[0], a[0])


def test_checksum_zero_fold_remapped():
    """The empty payload's CRC folds to 0 — the sentinel — so it must be
    remapped (0xFFFF): an empty checksummed message stays verifiable and
    distinguishable from a legacy frame."""
    blob = wire.serialize_tensors([])
    assert blob[6:8] == b"\xff\xff"
    assert wire.deserialize_tensors(blob).tensors == []
    assert wire.payload_checksum(b"") == 0xFFFF


def test_trace_context_native_codec_ignores_flag_gracefully():
    """The C++ decoder (native/codec.cc) predates the trace bit: it must
    decode traced frames without change — flags preserved verbatim, the
    trailer visible as an ordinary u64[2] tensor — so split_trace_context
    works identically on either decoder's output."""
    if not native_codec.available():
        pytest.skip("native codec absent")
    arrays = [np.arange(4, dtype=np.float32)]
    blob = wire.serialize_tensors_traced(arrays, trace_id=7,
                                         parent_span_id=9)
    nat_msg = native_codec.deserialize_tensors(blob)
    assert nat_msg.flags & wire.FLAG_TRACE_CONTEXT
    assert len(nat_msg.tensors) == 2        # payload + trailer, ordinary
    tensors, ctx = wire.split_trace_context(nat_msg)
    assert ctx == (7, 9)
    np.testing.assert_array_equal(tensors[0], arrays[0])


def test_trace_context_native_python_byte_identical():
    """Encoding the payload+trailer+flag through the native serializer
    produces byte-identical wire output (both directions of compat)."""
    if not native_codec.available():
        pytest.skip("native codec absent")
    arrays = [np.arange(4, dtype=np.float32)]
    trailer = np.array([7, 9], dtype="<u8")
    py = wire.serialize_tensors_traced(arrays, 7, 9)
    nat = native_codec.serialize_tensors(
        arrays + [trailer], flags=wire.FLAG_TRACE_CONTEXT)
    assert py == nat


def test_trace_flag_with_malformed_trailer_rejected():
    # flag set but the last tensor is not a u64[2]: hard error, never a
    # silently mis-split payload
    blob = wire.serialize_tensors(
        [np.arange(3, dtype=np.float32)], flags=wire.FLAG_TRACE_CONTEXT)
    with pytest.raises(wire.WireError):
        wire.split_trace_context(wire.deserialize_tensors(blob))
    empty = wire.serialize_tensors([], flags=wire.FLAG_TRACE_CONTEXT)
    with pytest.raises(wire.WireError):
        wire.split_trace_context(wire.deserialize_tensors(empty))


def test_token_roundtrip():
    for t in (0, 1, -1, 2**31 - 1, -(2**31)):
        assert wire.deserialize_token(wire.serialize_token(t)) == t
    assert wire.serialize_token(1) == b"\x01\x00\x00\x00"  # little-endian
    with pytest.raises(wire.WireError):
        wire.deserialize_token(b"\x00" * 8)
