"""Speculative decoding: greedy exactness, all-accept, stochastic sanity.

The load-bearing property is the first one: with ``greedy=True`` the
draft/verify/rollback machinery must be a pure latency optimization —
bit-identical tokens to target-only greedy decode, for any draft model.
"""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                    SpeculativeEngine)

CFG = get_model_config("llama-test")
DRAFT_CFG = dataclasses.replace(CFG, num_layers=2)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def draft_params():
    # different seed AND different depth: a genuinely different proposer
    return init_full_params(jax.random.PRNGKey(1), DRAFT_CFG)


@pytest.mark.quick
def test_greedy_matches_target_only(params, draft_params):
    """Spec decode at greedy must equal plain greedy decode exactly."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=96, sampling=sampling)
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4)
    prompt = np.asarray([[3, 14, 15, 92, 65], [1, 2, 3, 4, 5]])
    want = base.generate(prompt, max_new_tokens=24).tokens
    got, stats = spec.generate(prompt, max_new_tokens=24)
    np.testing.assert_array_equal(want, got.tokens)
    assert stats.emitted == 24
    assert stats.rounds >= 1


@pytest.mark.slow
def test_fp8_kv_greedy_matches_fp8_engine(params, draft_params):
    """Standalone spec decode with fp8 KV caches (both models) matches a
    plain engine running the SAME cache dtype bit-exactly — the same
    insert-rounding + f32-upcast contract the batching engine's fp8 x
    draft mode already satisfies (tests/test_batching.py)."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=96, sampling=sampling,
                           kv_cache_dtype="float8_e4m3fn")
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4,
                             kv_cache_dtype="float8_e4m3fn")
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    want = base.generate(prompt, max_new_tokens=16).tokens
    got, stats = spec.generate(prompt, max_new_tokens=16)
    np.testing.assert_array_equal(want, got.tokens)
    tc, dc = spec.new_caches(1)
    assert str(tc.keys.dtype) == "float8_e4m3fn"
    assert str(dc.keys.dtype) == "float8_e4m3fn"
    # an explicit kernel request must not silently downgrade
    with pytest.raises(ValueError, match="attn_backend"):
        SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                          max_seq=96, sampling=sampling,
                          attn_backend="flash",
                          kv_cache_dtype="float8_e4m3fn")


@pytest.mark.parametrize("plen", [
    pytest.param(5, marks=pytest.mark.slow),
    # tier-1 budget: the draft-model chunked-prefill family rides the
    # slow lane whole; the prompt-lookup twin keeps the quick-lane
    # chunked-prefill-x-speculation rep (tests/test_prompt_lookup.py),
    # and the §22 mixed tests pin spec x chunked admission in tier-1
    pytest.param(8, marks=pytest.mark.slow),
    pytest.param(9, marks=pytest.mark.slow),
    pytest.param(17, marks=pytest.mark.slow),
])
def test_chunked_prefill_matches_whole(params, draft_params, plen):
    """Spec decode with prefill_chunk (C=8, both models chunked) must be
    bit-identical to whole-prompt spec prefill for every remainder
    shape: plen < C, == C, == C+1, spanning 3 chunks."""
    sampling = SamplingParams(greedy=True)
    whole = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                              max_seq=64, sampling=sampling, num_draft=4)
    chunked = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                                max_seq=64, sampling=sampling,
                                num_draft=4, prefill_chunk=8)
    prompt = (np.arange(2 * plen).reshape(2, plen) % 199).astype(np.int32)
    want, _ = whole.generate(prompt, 12)
    got, _ = chunked.generate(prompt, 12)
    np.testing.assert_array_equal(want.tokens, got.tokens)


@pytest.mark.slow
def test_chunked_prefill_padded_past_capacity(params, draft_params):
    """Aligned-last-window regression shape: the chunk-padded prompt
    would spill past max_seq; the left shift must keep spec decode
    bit-identical (both caches).  Slow lane:
    test_chunked_prefill_matches_whole[8] keeps the chunked-prefill
    parity rep quick; this is the capacity-edge twin."""
    sampling = SamplingParams(greedy=True)
    whole = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                              max_seq=24, sampling=sampling, num_draft=3)
    chunked = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                                max_seq=24, sampling=sampling,
                                num_draft=3, prefill_chunk=8)
    plen = 19                       # pads to 24 == max_seq - shift window
    prompt = (np.arange(plen).reshape(1, plen) % 199).astype(np.int32)
    want, _ = whole.generate(prompt, 5)
    got, _ = chunked.generate(prompt, 5)
    np.testing.assert_array_equal(want.tokens, got.tokens)
    with pytest.raises(ValueError, match="prefill_chunk"):
        SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                          max_seq=24, sampling=sampling, prefill_chunk=0)


@pytest.mark.slow
def test_greedy_matches_across_dispatch_sizes(params, draft_params):
    """Rounds-per-dispatch is a pure batching knob: R=1 and R=8 agree."""
    sampling = SamplingParams(greedy=True)
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=3)
    prompt = np.asarray([[7, 8, 9]])
    a, _ = spec.generate(prompt, 17, rounds_per_dispatch=1)
    b, _ = spec.generate(prompt, 17, rounds_per_dispatch=8)
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_self_draft_accepts_everything(params):
    """Draft == target: every draft token must be accepted (greedy), so
    each round emits num_draft+1 tokens."""
    sampling = SamplingParams(greedy=True)
    spec = SpeculativeEngine(CFG, params, CFG, params, max_seq=96,
                             sampling=sampling, num_draft=4)
    prompt = np.asarray([[3, 1, 4]])
    res, stats = spec.generate(prompt, max_new_tokens=21)
    assert res.tokens.shape == (1, 21)
    assert stats.acceptance_rate == 1.0
    assert stats.tokens_per_round > 4.0   # 21 emitted / 4 rounds = 5.25


# slow lane: sampled twin of test_self_draft_accepts_everything (greedy),
# which stays quick; stochastic verify is also hit by sampled_tokens_in_range
@pytest.mark.slow
def test_self_draft_accepts_everything_sampled(params):
    """Draft == target under temperature sampling: p == q so the accept
    rule (u < p/q) accepts every token — exercises the stochastic verify
    path end-to-end."""
    sampling = SamplingParams(temperature=0.9, top_k=0)
    spec = SpeculativeEngine(CFG, params, CFG, params, max_seq=96,
                             sampling=sampling, num_draft=4)
    res, stats = spec.generate(np.asarray([[5, 6]]), max_new_tokens=16)
    assert res.tokens.shape == (1, 16)
    assert stats.acceptance_rate == 1.0


def test_sampled_tokens_in_range(params, draft_params):
    sampling = SamplingParams(temperature=0.8, top_k=7)
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4)
    prompt = np.asarray([[3, 14, 15], [9, 2, 6]])
    res, stats = spec.generate(prompt, max_new_tokens=20, seed=3)
    assert res.tokens.shape == (2, 20)
    assert res.tokens.dtype == np.int32
    assert (res.tokens >= 0).all() and (res.tokens < CFG.vocab_size).all()
    assert 0.0 <= stats.acceptance_rate <= 1.0


@pytest.mark.slow
def test_topk_sampling_respects_support(params, draft_params):
    """Every emitted token must lie in the TARGET's top-k support at its
    position (accepted drafts are filtered by the accept rule; resamples
    come from max(p-q, 0) whose support is within p's; the bonus samples
    from filtered p).  Verified by re-scoring the emitted sequence with
    the target and checking top-k membership position by position."""
    import jax.numpy as jnp
    from distributed_inference_demo_tpu.models.base import KVCache, StageSpec
    from distributed_inference_demo_tpu.models.decoder import stage_forward

    k = 5
    sampling = SamplingParams(temperature=0.7, top_k=k)
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=2)
    prompt = np.asarray([[1, 2, 3]])
    res, _ = spec.generate(prompt, 12, seed=11)

    full = np.concatenate([prompt, res.tokens], axis=1)
    ids = jnp.asarray(full, jnp.int32)
    cache = KVCache.create(CFG, CFG.num_layers, 1, ids.shape[1])
    pos = jnp.broadcast_to(jnp.arange(ids.shape[1]), ids.shape)
    logits, _ = stage_forward(params, CFG, StageSpec(0, 1, 0, CFG.num_layers),
                              ids, cache, pos)
    logits = np.asarray(logits, np.float32)
    plen = prompt.shape[1]
    for t in range(res.tokens.shape[1]):
        # token emitted at step t was sampled from logits after position
        # plen + t - 1 (0-indexed into the scored sequence)
        lg = logits[0, plen + t - 1]
        topk = np.argsort(lg)[-k:]
        assert res.tokens[0, t] in topk, (
            f"step {t}: token {res.tokens[0, t]} outside target top-{k}")

    # and per-seed determinism
    b, _ = spec.generate(prompt, 12, seed=11)
    np.testing.assert_array_equal(res.tokens, b.tokens)


def test_stream_matches_generate(params, draft_params):
    """Streamed tokens (burst-per-round) must equal the blocking path's."""
    sampling = SamplingParams(greedy=True)
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=3)
    prompt = np.asarray([[3, 14, 15], [9, 2, 6]])
    blocking, _ = spec.generate(prompt, 15)
    streamed = np.stack(list(spec.generate_stream(prompt, 15)), axis=1)
    np.testing.assert_array_equal(blocking.tokens, streamed)
    assert streamed.shape == (2, 15)


@pytest.mark.slow
def test_http_backend_surface(params, draft_params):
    """serve --draft-model's backend: /generate, streaming, and /stats
    acceptance diagnostics over the HTTP server."""
    import http.client
    import json

    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    from distributed_inference_demo_tpu.runtime.speculative import (
        SpeculativeBackend)

    sampling = SamplingParams(greedy=True)
    backend = SpeculativeBackend(SpeculativeEngine(
        CFG, params, CFG, params,   # self-draft: 100% acceptance
        max_seq=96, sampling=sampling, num_draft=3))
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=300)
        conn.request("POST", "/generate",
                     json.dumps({"prompt_ids": [[5, 17, 42]],
                                 "max_new_tokens": 9}),
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        assert len(out["tokens"][0]) == 9
        conn.request("GET", "/stats", headers={})
        stats = json.loads(conn.getresponse().read())
        assert stats["speculative"]["acceptance_rate"] == 1.0
        assert stats["speculative"]["num_draft"] == 3
        # streaming also feeds /stats (regression: it used to stay stale)
        conn.request("POST", "/generate",
                     json.dumps({"prompt_ids": [[9, 9]],
                                 "max_new_tokens": 7, "stream": True}),
                     {"Content-Type": "application/json"})
        lines = [l for l in conn.getresponse().read().decode().splitlines()
                 if l.strip()]
        assert len(lines) == 7
        conn.request("GET", "/stats", headers={})
        stats = json.loads(conn.getresponse().read())
        assert stats["speculative"]["rounds"] >= 1
        conn.close()
    finally:
        server.shutdown()


def test_stream_zero_tokens(params, draft_params):
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=SamplingParams(greedy=True))
    assert list(spec.generate_stream(np.asarray([[1, 2]]), 0)) == []


@pytest.mark.slow
def test_tp_mesh_parity(params, draft_params):
    """Draft/verify over a tp=2 mesh (both models sharded): greedy output
    equals the single-device speculative engine's."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    sampling = SamplingParams(greedy=True)
    single = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                               max_seq=96, sampling=sampling, num_draft=3)
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    tp = SpeculativeEngine(
        CFG, shard_engine_params(params, CFG, mesh),
        DRAFT_CFG, shard_engine_params(draft_params, DRAFT_CFG, mesh),
        max_seq=96, sampling=sampling, num_draft=3, mesh=mesh)
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    want, _ = single.generate(prompt, 14)
    got, _ = tp.generate(prompt, 14)
    np.testing.assert_array_equal(want.tokens, got.tokens)


def test_vocab_mismatch_rejected(params):
    other = dataclasses.replace(CFG, vocab_size=128)
    other_params = init_full_params(jax.random.PRNGKey(2), other)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeEngine(CFG, params, other, other_params)


def test_capacity_guard(params, draft_params):
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=32, sampling=SamplingParams(greedy=True))
    with pytest.raises(ValueError, match="exceeds"):
        spec.generate(np.zeros((1, 30), np.int64), 10)


def test_cache_capacity_sublane_aligned(params, draft_params):
    """The draft-window slack (+K+1) lands on a multiple of 8 so the flash
    kernel accepts the buffers (r04 bench regression: max_seq=192, K=4
    allocated 197 and the flash trace raised)."""
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=192, num_draft=4,
                             sampling=SamplingParams(greedy=True))
    tc, dc = spec.new_caches(1)
    assert tc.max_seq % 8 == 0 and tc.max_seq >= 197
    assert dc.max_seq % 8 == 0


@pytest.mark.slow
def test_eos_padding_matches_engine(params, draft_params):
    """With eos_id set, greedy spec decode equals InferenceEngine's
    eos-padded fused scan bit-exactly (rows pad with eos after their
    first eos; unfinished rows are untouched).  Slow lane:
    test_eos_stream_matches_engine_stream stays quick and drives the
    same eos-padding contract through the streamed surface."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=96, sampling=sampling)
    prompt = np.asarray([[3, 14, 15, 92, 65], [1, 2, 3, 4, 5]])
    plain = base.generate(prompt, 24).tokens
    eos = int(plain[0, 4])            # appears mid-run in row 0
    base_eos = InferenceEngine(CFG, params, max_seq=96, sampling=sampling,
                               eos_id=eos)
    want = base_eos.generate(prompt, 24).tokens
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4,
                             eos_id=eos)
    got, _ = spec.generate(prompt, 24)
    np.testing.assert_array_equal(want, got.tokens)


def test_eos_early_stop_skips_rounds(params, draft_params):
    """When every row's FIRST token is eos, the round loop must not
    dispatch at all and the result is full-width eos padding."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=96, sampling=sampling)
    prompt = np.asarray([[3, 1, 4]])
    eos = int(base.generate(prompt, 1).tokens[0, 0])
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4,
                             eos_id=eos)
    got, stats = spec.generate(prompt, 12)
    assert stats.rounds == 0
    np.testing.assert_array_equal(got.tokens,
                                  np.full((1, 12), eos, np.int32))


# slow lane: eos × stream twin; test_eos_early_stop_skips_rounds and
# test_stream_matches_generate keep each seam quick on its own
@pytest.mark.slow
def test_eos_stream_matches_engine_stream(params, draft_params):
    """Streamed spec decode with eos stops at the same step and yields the
    same (eos-padded) tokens as InferenceEngine.generate_stream."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=96, sampling=sampling)
    prompt = np.asarray([[3, 14, 15, 92, 65], [1, 2, 3, 4, 5]])
    plain = base.generate(prompt, 24).tokens
    eos = int(plain[0, 4])
    base_eos = InferenceEngine(CFG, params, max_seq=96, sampling=sampling,
                               eos_id=eos)
    want = list(base_eos.generate_stream(prompt, 24))
    spec = SpeculativeEngine(CFG, params, DRAFT_CFG, draft_params,
                             max_seq=96, sampling=sampling, num_draft=4,
                             eos_id=eos)
    got = list(spec.generate_stream(prompt, 24))
    assert len(want) == len(got)
    np.testing.assert_array_equal(np.stack(want), np.stack(got))
