"""Chaos soak: deterministic fault injection against the elastic pipeline.

The ISSUE-5 acceptance invariants, pinned:

- under a seeded fault plan (drop + delay + duplicate + corrupt + worker
  crash) on a 3-stage loopback elastic pipeline, the greedy token stream
  after recovery is BIT-IDENTICAL to the fault-free run;
- zero leaked KV slots after every crash/reshard;
- a corrupt frame is detected by CRC (never decoded into a wrong token)
  with ``dwt_transport_corrupt_frames_total`` incremented;
- a postmortem bundle is written naming the injected fault;
- same seed + same plan ⇒ byte-identical injected-fault event sequence;
- ``--fault-plan`` is rejected outside ``--chaos`` mode;
- stale-epoch frames (delayed/duplicated pre-reshard traffic) are
  dropped and can never satisfy a newer reshard's ack-wait;
- overload shedding: a full admission queue answers 503 + Retry-After;
  ``--request-timeout`` cancels instead of hanging.

A fast deterministic subset runs in tier-1; the randomized multi-seed
soak is ``@slow``.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm import wire
from distributed_inference_demo_tpu.comm.faults import (
    FaultConfigError, FaultPlan, FaultRule, FaultyTransport, InjectedCrash,
    load_fault_plan, maybe_wrap)
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportTimeout)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import split_layer_ranges
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.elastic import (
    ElasticHeader, ElasticStageRuntime, ElasticWorker)
from distributed_inference_demo_tpu.telemetry import catalog, postmortem
from distributed_inference_demo_tpu.telemetry.flightrecorder import (
    FlightRecorder, set_flight_recorder)
from distributed_inference_demo_tpu.telemetry.postmortem import (
    PostmortemWriter)

GREEDY = SamplingParams(greedy=True)
PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)
MODEL = "llama-test"


@pytest.fixture(autouse=True)
def _isolate_globals():
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)
    yield
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)


def _counter_value(c, **labels) -> float:
    want = tuple(sorted(labels.items()))
    for _name, lab, value in c.samples():
        if tuple(sorted(lab)) == want:
            return value
    return 0.0


_REFERENCE_MEMO = {}


def reference_tokens(prompt, max_new):
    """Memoized per (prompt, max_new): several tests pin against the
    same fault-free stream, and each cold call costs an engine build."""
    prompt = np.asarray(prompt)
    key = (prompt.tobytes(), prompt.shape, max_new)
    if key not in _REFERENCE_MEMO:
        cfg = get_model_config(MODEL)
        params = init_full_params(jax.random.PRNGKey(0), cfg)
        _REFERENCE_MEMO[key] = InferenceEngine(
            cfg, params, max_seq=64, sampling=GREEDY).generate(
            prompt, max_new).tokens
    return _REFERENCE_MEMO[key]


# ---------------------------------------------------------------------------
# fault-plan unit behavior


def test_fault_plan_spec_roundtrip_and_validation():
    spec = {"seed": 99, "name": "soak", "rules": [
        {"kind": "delay", "peer": "s1", "tag_prefix": "h:", "prob": 0.25,
         "delay_ms": 5},
        {"kind": "corrupt", "after": 2, "max_count": 1},
        {"kind": "crash_after", "n_msgs": 10}]}
    plan = FaultPlan.from_spec(spec)
    assert plan.to_spec() == spec
    assert FaultPlan.from_json(json.dumps(spec)).to_spec() == spec
    with pytest.raises(FaultConfigError, match="unknown fault kind"):
        FaultPlan.from_spec({"rules": [{"kind": "nuke"}]})
    with pytest.raises(FaultConfigError, match="n_msgs"):
        FaultPlan.from_spec({"rules": [{"kind": "crash_after"}]})
    with pytest.raises(FaultConfigError, match="unknown fields"):
        FaultPlan.from_spec({"rules": [{"kind": "drop", "probe": 1}]})
    with pytest.raises(FaultConfigError, match="valid JSON"):
        FaultPlan.from_json("{nope")


def _drive(seed: int) -> list:
    """One fixed message sequence through a probabilistic plan."""
    plan = FaultPlan(seed=seed, rules=[
        FaultRule(kind="drop", prob=0.3),
        FaultRule(kind="delay", prob=0.4, delay_ms=1),
        FaultRule(kind="corrupt", prob=0.2)])
    net = LoopbackNetwork()
    t = FaultyTransport(LoopbackTransport("a", net), plan)
    LoopbackTransport("b", net)
    for i in range(64):
        t.send("b", f"h:{i % 7}:{i}", bytes(16 + i))
    return plan.events


def test_injected_faults_are_flight_recorded():
    """Every injected fault lands in the flight ring as a
    ``fault_injected`` event carrying the rule kind as ``fault_kind`` —
    the postmortem analyzer's evidence that a chaos bundle can name its
    own cause."""
    rec = FlightRecorder(max_events=64)
    set_flight_recorder(rec)
    plan = FaultPlan(seed=3, rules=[
        FaultRule(kind="drop", tag_prefix="h:0:0"),
        FaultRule(kind="partition", peer="b", tag_prefix="h:0:1")])
    net = LoopbackNetwork()
    t = FaultyTransport(LoopbackTransport("a", net), plan)
    LoopbackTransport("b", net)
    t.send("b", "h:0:0", b"x")          # dropped
    t.send("b", "h:0:1", b"y")          # partition activates (1st casualty)
    t.send("b", "h:0:2", b"z")          # swallowed by the partition
    got = [e for e in rec.snapshot() if e["kind"] == "fault_injected"]
    kinds = [e["fault_kind"] for e in got]
    assert "drop" in kinds and "partition" in kinds, kinds
    assert "partition_drop" in kinds, kinds
    assert all(e["device"] == "a" for e in got)


def test_same_seed_same_plan_identical_event_sequence():
    """Determinism is itself asserted: same seed + same plan + same
    message sequence ⇒ byte-identical injected-fault event sequence
    (the replay-from-postmortem-by-seed property)."""
    e1, e2 = _drive(1234), _drive(1234)
    assert e1, "plan injected nothing — the drive is too short"
    assert json.dumps(e1) == json.dumps(e2)
    assert json.dumps(_drive(99)) != json.dumps(e1)  # the seed matters


def test_fault_kinds_apply_on_the_wire():
    plan = FaultPlan(seed=0, rules=[
        FaultRule(kind="drop", tag_prefix="d:"),
        FaultRule(kind="duplicate", tag_prefix="u:"),
        FaultRule(kind="corrupt", tag_prefix="c:"),
        FaultRule(kind="reorder", tag_prefix="r:", max_count=1),
        FaultRule(kind="partition", peer="b", tag_prefix="p:")])
    net = LoopbackNetwork()
    fa = FaultyTransport(LoopbackTransport("a", net), plan)
    b = LoopbackTransport("b", net)

    fa.send("b", "d:1", b"dropped")
    fa.send("b", "u:1", b"dup")
    assert b.recv("u:1", timeout=2) == b"dup"
    assert b.recv("u:1", timeout=2) == b"dup"      # the duplicate
    fa.send("b", "c:1", b"payload")
    assert b.recv("c:1", timeout=2) != b"payload"  # corrupted in flight
    fa.send("b", "r:1", b"first")                  # held back
    fa.send("b", "x:1", b"second")                 # overtakes
    tag, _ = b.recv_any(timeout=2)
    assert tag == "x:1"
    assert b.recv("r:1", timeout=2) == b"first"    # released after
    with pytest.raises(TransportTimeout):
        b.recv("d:1", timeout=0.1)
    fa.send("b", "p:1", b"partitioned")            # activates partition
    fa.send("b", "anything", b"also dead")         # peer b is gone now
    with pytest.raises(TransportTimeout):
        b.recv_any(timeout=0.1)
    kinds = [e["kind"] for e in plan.events]
    for k in ("drop", "duplicate", "corrupt", "reorder", "partition",
              "partition_drop"):
        assert k in kinds, kinds


def test_crash_after_counts_sends_and_recvs():
    plan = FaultPlan(seed=0, rules=[
        FaultRule(kind="crash_after", n_msgs=3)])
    net = LoopbackNetwork()
    fa = FaultyTransport(LoopbackTransport("a", net), plan)
    b = LoopbackTransport("b", net)
    fa.send("b", "t", b"1")
    fa.send("b", "t", b"2")
    b.send("a", "t", b"3")
    assert fa.recv("t", timeout=2) == b"3"     # message 3: at the limit
    with pytest.raises(InjectedCrash):
        fa.send("b", "t", b"4")
    with pytest.raises(InjectedCrash):         # dead stays dead
        fa.send("b", "t", b"5")


def test_fault_plan_rejected_without_chaos(monkeypatch):
    spec = '{"seed": 1, "rules": [{"kind": "drop"}]}'
    with pytest.raises(FaultConfigError, match="--chaos"):
        load_fault_plan(spec, chaos=False)
    # the env var alone must be rejected the same way
    monkeypatch.setenv("DWT_FAULT_PLAN", spec)
    with pytest.raises(FaultConfigError, match="--chaos"):
        load_fault_plan("", chaos=False)
    assert load_fault_plan("", chaos=True).seed == 1
    monkeypatch.delenv("DWT_FAULT_PLAN")
    assert load_fault_plan("", chaos=False) is None   # off by default
    t = LoopbackTransport("a", LoopbackNetwork())
    assert maybe_wrap(t, None) is t


def test_serve_cli_rejects_fault_plan_without_chaos(capsys):
    from distributed_inference_demo_tpu import cli
    rc = cli.main(["serve", "--model", MODEL, "--fault-plan",
                   '{"seed": 1, "rules": []}'])
    assert rc == 1
    assert "--chaos" in capsys.readouterr().err
    # --chaos without --chain: the plan has no transport to fault
    rc = cli.main(["serve", "--model", MODEL, "--chaos", "--fault-plan",
                   '{"seed": 1, "rules": []}'])
    assert rc == 1
    assert "--chain" in capsys.readouterr().err


def test_worker_cli_rejects_fault_plan_without_chaos(capsys):
    from distributed_inference_demo_tpu.runtime import worker_main
    rc = worker_main.main([
        "--model", MODEL, "--stage-id", "1", "--num-stages", "2",
        "--layer-start", "0", "--layer-end", "2", "--device-id", "w",
        "--port", "0", "--header", "h@127.0.0.1:1",
        "--fault-plan", '{"seed": 1, "rules": []}'])
    assert rc == 1
    assert "--chaos" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# wire integrity on the ring


def test_corrupt_frame_detected_not_decoded():
    """A flipped byte raises WireIntegrityError out of BOTH codecs; the
    drop bookkeeping increments dwt_transport_corrupt_frames_total."""
    from distributed_inference_demo_tpu.comm import native_codec
    from distributed_inference_demo_tpu.comm.transport import (
        record_corrupt_frame)
    blob = wire.serialize_tensors([np.arange(8, dtype=np.float32)])
    bad = bytearray(blob)
    bad[-3] ^= 0x10
    with pytest.raises(wire.WireIntegrityError):
        wire.deserialize_tensors(bytes(bad))
    if native_codec.available():
        with pytest.raises(wire.WireIntegrityError):
            native_codec.deserialize_tensors(bytes(bad))
    before = _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES)
    try:
        wire.deserialize_tensors(bytes(bad))
    except wire.WireIntegrityError as e:
        record_corrupt_frame("s1", "h:0:0", len(bad), e)
    assert _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES) == before + 1


def test_zero_checksum_frames_from_old_peers_accepted():
    blob = wire.serialize_tensors([np.arange(4, dtype=np.int32)],
                                  checksum=False)
    assert blob[6:8] == b"\x00\x00"
    msg = wire.deserialize_tensors(blob)
    np.testing.assert_array_equal(msg.tensors[0], np.arange(4))


def test_worker_drops_corrupt_frame_without_forwarding():
    """The stage-level contract: a corrupt hidden chunk is counted and
    dropped — no forward, no sample, no cache write."""
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0 = LoopbackTransport("s0", net)
    t1 = LoopbackTransport("s1", net)
    worker = ElasticWorker(
        ElasticStageRuntime(cfg, specs[1], full, 64, GREEDY), t1,
        next_id=None, header_id="s0", step_timeout=5)
    good = wire.serialize_tensors(
        [np.zeros((1, 4, cfg.hidden_size), np.float32)])
    bad = bytearray(good)
    bad[40] ^= 0xFF
    before = _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES)
    assert worker.handle_message("h:0:0", bytes(bad)) is True
    assert worker.rt.caches == {}              # nothing ran
    with pytest.raises(TransportTimeout):      # nothing was forwarded
        t0.recv_any(timeout=0.1)
    assert _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES) == before + 1
    # the same frame uncorrupted runs fine (the worker is not poisoned)
    assert worker.handle_message("h:0:0", good) is True
    assert t0.recv_any(timeout=5)[0].startswith("tok:0:0")


# ---------------------------------------------------------------------------
# the chaos soak itself


class _CrashingWorker(ElasticWorker):
    """Serve loop that dies (thread exit) on InjectedCrash — a real
    worker process would die the same way via the crash handler."""

    def serve_forever(self, idle_timeout=None):
        try:
            super().serve_forever(idle_timeout)
        except InjectedCrash:
            return


def _build_chaos(num_stages, plan, faulty, max_seq=64, step_timeout=30,
                 stall_reshard_timeout=1.0):
    """Elastic loopback pipeline; transports of ids in ``faulty`` are
    wrapped with ``plan``.  Returns (header, workers, threads, ids)."""
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    net = LoopbackNetwork()
    ids = [f"s{i}" for i in range(num_stages)]
    transports = [LoopbackTransport(d, net) for d in ids]
    if plan is not None:
        for i, d in enumerate(ids):
            if d in faulty:
                transports[i] = FaultyTransport(transports[i], plan)
    header = ElasticHeader(
        ElasticStageRuntime(cfg, specs[0], full, max_seq, GREEDY),
        transports[0], chain=ids, step_timeout=step_timeout,
        poll_interval=0.05,
        stall_reshard_timeout=stall_reshard_timeout)
    workers = [
        _CrashingWorker(
            ElasticStageRuntime(cfg, specs[i], full, max_seq, GREEDY),
            transports[i],
            next_id=ids[i + 1] if i + 1 < num_stages else None,
            header_id=ids[0], step_timeout=step_timeout)
        for i in range(1, num_stages)]
    threads = [threading.Thread(target=w.serve_forever, args=(30,),
                                daemon=True) for w in workers]
    for t in threads:
        t.start()
    return header, workers, threads, ids


def _supervise(header, threads, ids):
    """Heartbeat stand-in: signal failure for any worker whose serve
    thread died (the sweeper-driven path is pinned in test_elastic)."""
    stop = threading.Event()

    def watch():
        reported = set()
        while not stop.is_set():
            for wid, t in zip(ids[1:], threads):
                if not t.is_alive() and wid not in reported:
                    reported.add(wid)
                    header.signal_failure(wid)
            stop.wait(0.05)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    return stop


def _assert_no_kv_leaks(header, workers, threads):
    assert header.rt.caches == {}, "header leaked KV slots"
    # the ``end`` frees ride the chain asynchronously: give survivors a
    # bounded moment to process them before calling a slot leaked
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if all(w.rt.caches == {} for w, t in zip(workers, threads)
               if t.is_alive()):
            break
        time.sleep(0.05)
    for w, t in zip(workers, threads):
        if t.is_alive():       # survivors only; the crashed one is gone
            assert w.rt.caches == {}, (
                f"{w.transport.device_id} leaked KV slots")


@pytest.mark.slow
def test_chaos_recovery_bit_identical(tmp_path):
    """THE acceptance scenario: drop + delay + duplicate + corrupt +
    worker crash on a 3-stage loopback elastic pipeline; after recovery
    the greedy stream is bit-identical to the fault-free run, no KV slot
    leaks anywhere, and a postmortem bundle names the injected fault.

    Recovery exercises BOTH reshard paths: the corrupt/dropped frames
    stall the ring and the header reshards IN PLACE (epoch bump +
    drain/resume = retransmit); the crash kills s1's serve thread and
    the failure signal reshards it out of the chain."""
    set_flight_recorder(FlightRecorder(max_events=512))
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))
    want = reference_tokens(PROMPT, 12)

    plan = FaultPlan(seed=1234, rules=[
        # messy-but-self-healing noise on the s1 edge...
        FaultRule(kind="delay", peer="s2", tag_prefix="h:", prob=0.3,
                  delay_ms=5),
        FaultRule(kind="duplicate", peer="s2", tag_prefix="h:", prob=0.3),
        # ...one frame corrupted (CRC drops it), one dropped outright...
        FaultRule(kind="corrupt", peer="s2", tag_prefix="h:", after=2,
                  max_count=1),
        FaultRule(kind="drop", peer="s2", tag_prefix="h:", after=4,
                  max_count=1),
        # ...and then s1 dies for real
        FaultRule(kind="crash_after", n_msgs=26)])
    header, workers, threads, ids = _build_chaos(3, plan, faulty={"s1"})
    stop = _supervise(header, threads, ids)
    try:
        got = header.generate(PROMPT, 12)
    finally:
        stop.set()
    np.testing.assert_array_equal(got, want)      # bit-identical
    assert header.chain == ["s0", "s2"]           # s1 really left the ring
    kinds = {e["kind"] for e in plan.events}
    assert "crash_after" in kinds, "the crash rule never fired"
    assert "corrupt" in kinds and "drop" in kinds, kinds
    _assert_no_kv_leaks(header, workers, threads)

    # the postmortem bundle names the injected fault (analyzer included)
    bundles = postmortem.get_postmortem_writer().bundle_dirs()
    assert bundles, "no postmortem bundle written for the injected crash"
    manifests = [json.load(open(f"{b}/manifest.json")) for b in bundles]
    inj = [m for m in manifests if m["reason"] == "injected_fault_crash"]
    assert inj and inj[0]["detail"]["fault"]["kind"] == "crash_after"
    assert inj[0]["detail"]["plan_seed"] == 1234

    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "postmortem_tool",
        pathlib.Path(__file__).resolve().parents[1] / "tools"
        / "postmortem.py")
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    idx = manifests.index(inj[0])
    summary = tool.summarize_bundle(bundles[idx])
    assert summary["injected_cause"]["kind"] == "crash_after"
    assert summary["fault_plan_seed"] == 1234
    assert "INJECTED FAULT" in tool.format_summary(summary)

    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)


@pytest.mark.slow
def test_chaos_corrupt_frames_counted_during_recovery(tmp_path):
    """The corrupt-frame counter moves during the soak (the acceptance
    bullet: detected by CRC, counted, never a wrong token).  Slow lane
    (redundant-coverage twin): the counter-moves contract is pinned in
    tier-1 by test_corrupt_frame_detected_not_decoded and
    test_worker_drops_corrupt_frame_without_forwarding, and the soak
    recovery path by test_chaos_recovery_bit_identical."""
    set_flight_recorder(FlightRecorder(max_events=512))
    want = reference_tokens(PROMPT, 10)
    before = _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES)
    plan = FaultPlan(seed=7, rules=[
        FaultRule(kind="corrupt", peer="s2", tag_prefix="h:", after=1,
                  max_count=1),
        FaultRule(kind="crash_after", n_msgs=10)])
    header, workers, threads, ids = _build_chaos(3, plan, faulty={"s1"})
    stop = _supervise(header, threads, ids)
    try:
        got = header.generate(PROMPT, 10)
    finally:
        stop.set()
    np.testing.assert_array_equal(got, want)
    assert _counter_value(catalog.TRANSPORT_CORRUPT_FRAMES) >= before + 1
    corrupt = [e for e in plan.events if e["kind"] == "corrupt"]
    assert corrupt, "the corrupt rule never fired"
    _assert_no_kv_leaks(header, workers, threads)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 22, 33, 44, 55])
def test_chaos_soak_multi_seed(seed, tmp_path):
    """Randomized soak: probabilistic noise everywhere + a crash, five
    seeds.  The invariant never changes: bit-identical greedy stream,
    no KV leaks."""
    set_flight_recorder(FlightRecorder(max_events=512))
    want = reference_tokens(PROMPT, 16)
    plan = FaultPlan(seed=seed, rules=[
        FaultRule(kind="delay", prob=0.2, delay_ms=3),
        FaultRule(kind="duplicate", prob=0.2),
        FaultRule(kind="corrupt", tag_prefix="h:", prob=0.1),
        FaultRule(kind="drop", tag_prefix="h:", prob=0.05),
        FaultRule(kind="crash_after", n_msgs=20 + seed % 7)])
    header, workers, threads, ids = _build_chaos(3, plan, faulty={"s1"})
    stop = _supervise(header, threads, ids)
    try:
        got = header.generate(PROMPT, 16)
    finally:
        stop.set()
    np.testing.assert_array_equal(got, want)
    _assert_no_kv_leaks(header, workers, threads)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)


# ---------------------------------------------------------------------------
# elastic epoch hygiene under delay+duplicate


def test_stale_epoch_frames_dropped_property():
    """Property: for any (rid, step), an h-frame tagged with a PRE-reshard
    epoch is dropped by the worker — no compute, no cache write, no
    forward — while the current epoch's frame runs."""
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0 = LoopbackTransport("s0", net)
    t1 = LoopbackTransport("s1", net)
    worker = ElasticWorker(
        ElasticStageRuntime(cfg, specs[1], full, 64, GREEDY), t1,
        next_id=None, header_id="s0", step_timeout=5)
    worker.epoch = 3
    frame = wire.serialize_tensors(
        [np.zeros((1, 2, cfg.hidden_size), np.float32)])
    for rid in (0, 7):
        for stale in (0, 1, 2):
            assert worker.handle_message(f"h:{rid}:0:{stale}", frame)
            assert worker.rt.caches == {}, (
                f"stale epoch {stale} frame ran (rid={rid})")
            with pytest.raises(TransportTimeout):
                t0.recv_any(timeout=0.05)
    assert worker.handle_message("h:0:0:3", frame)   # current epoch runs
    assert t0.recv_any(timeout=5)[0].startswith("tok:0:0")


def test_delayed_duplicated_stale_acks_never_satisfy_reshard():
    """The ack-wait half of epoch hygiene, driven through FaultyTransport
    delay+duplicate rules: stale-epoch ``rack`` frames — even arriving
    multiple times, late, during the newer reshard's window — never
    satisfy its ack-wait; the current epoch's ack does."""
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0 = LoopbackTransport("s0", net)
    plan = FaultPlan(seed=5, rules=[
        FaultRule(kind="duplicate", tag_prefix="rack:"),
        FaultRule(kind="delay", tag_prefix="rack:", delay_ms=30)])
    t1 = FaultyTransport(LoopbackTransport("s1", net), plan)
    header = ElasticHeader(
        ElasticStageRuntime(cfg, specs[0], full, 64, GREEDY),
        t0, chain=["s0", "s1"], step_timeout=1.0, poll_interval=0.1)

    # stale acks (epoch 0 and a future-stale 1-off) injected through the
    # faulty transport: delayed AND duplicated, they land inside the
    # epoch-1 ack window below — and must all be ignored
    t1.send("s0", "rack:s1:0", b"")
    with pytest.raises(TransportTimeout, match="reshard acks"):
        header.reshard(["s0", "s1"])               # -> epoch 1, no valid ack
    assert [e["kind"] for e in plan.events] == ["duplicate", "delay"]

    # the current epoch's ack (epoch 2 after this reshard call bumps it),
    # also delayed+duplicated, satisfies the wait exactly once
    t1.send("s0", "rack:s1:2", b"")
    header.reshard(["s0", "s1"])
    assert header.epoch == 2


# ---------------------------------------------------------------------------
# disaggregated prefill/decode migration under faults (DESIGN.md §15)


def _build_disagg(cfg, full, prefill_plans, max_seq=64, chunk=8,
                  ack_timeout=0.5):
    """Loopback disagg deployment: coordinator + one prefill worker per
    entry of ``prefill_plans`` (its fault plan, or None) + one decode
    worker.  Prefill worker threads die on InjectedCrash like a real
    process would (the crash handler path)."""
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.disagg import (
        DecodeWorker, DisaggCoordinator, PrefillWorker)

    net = LoopbackNetwork()
    tc = LoopbackTransport("coord", net)
    pids = [f"p{i}" for i in range(len(prefill_plans))]
    engine = ContinuousBatchingEngine(
        cfg, full, max_seq=max_seq, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=0)
    pws, threads = [], []
    for pid, plan in zip(pids, prefill_plans):
        t = LoopbackTransport(pid, net)
        if plan is not None:
            t = FaultyTransport(t, plan)
        pw = PrefillWorker(cfg, full, t, max_seq=max_seq,
                           prefill_chunk=chunk, ack_timeout=ack_timeout)
        pws.append(pw)

        def serve(w=pw):
            try:
                w.serve_forever()
            except InjectedCrash:
                return            # the injected death IS the scenario
        th = threading.Thread(target=serve, daemon=True)
        th.start()
        threads.append(th)
    dw = DecodeWorker(engine, LoopbackTransport("d0", net))
    dth = threading.Thread(target=dw.serve_forever, daemon=True)
    dth.start()
    coord = DisaggCoordinator(tc, pids, "d0")
    return coord, pws, dw, engine, threads, dth


def _assert_no_page_leaks(engine, pws):
    """The §15 ownership acceptance: idle ``used == tree.block_count``
    on the decode pool (tree + zero in-flight request pages) AND every
    surviving prefill pool — bounded wait for async completions."""
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        snaps = [engine.kv_cache.snapshot()] + [
            pw.kv_cache.snapshot() for pw in pws]
        if all(s["blocks_used"] == s["tree_blocks"] for s in snaps):
            return
        time.sleep(0.05)
    raise AssertionError(f"page leak: {snaps}")


def test_chaos_migration_faults_bit_identical(tmp_path):
    """The migration-tag fault plan satellite: duplicate + corrupt +
    drop scoped to page-transfer (``pg:``) frames.  The (rid, attempt,
    seq) dedup makes duplicated/retried page frames idempotent, CRC
    drops the corrupt frame before any adopt, and the ack-driven
    go-back-n retransmit refills the holes — greedy output stays
    bit-identical and neither pool leaks a page."""
    set_flight_recorder(FlightRecorder(max_events=512))
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = (np.arange(37) % 50 + 3).astype(np.int32)
    want = reference_tokens(prompt[None], 8)[0]

    plan = FaultPlan(seed=7, rules=[
        FaultRule(kind="duplicate", tag_prefix="pg:", prob=0.5),
        FaultRule(kind="corrupt", tag_prefix="pg:", after=1,
                  max_count=1),
        FaultRule(kind="drop", tag_prefix="pg:", after=3, max_count=1)])
    coord, pws, dw, engine, threads, dth = _build_disagg(cfg, full,
                                                         [plan])
    try:
        got = coord.submit(prompt, 8).wait(timeout=120)
        np.testing.assert_array_equal(got, want)     # bit-identical
        kinds = {e["kind"] for e in plan.events}
        assert {"duplicate", "corrupt", "drop"} & kinds, kinds
        # the faults actually exercised the recovery machinery
        if "corrupt" in kinds or "drop" in kinds:
            assert pws[0].stats["retransmitted_frames"] >= 1
        if "duplicate" in kinds:
            assert dw.stats["dropped_frames"] >= 1
        assert engine.kv_cache.snapshot()["h2d_bytes"] == 0
        _assert_no_page_leaks(engine, pws)
    finally:
        for pw in pws:
            pw.stop()
        dw.stop()
        coord.close()
        engine.close()


def test_chaos_prefill_crash_mid_migration_reschedules(tmp_path):
    """THE §15 chaos acceptance: a prefill worker crashes mid-migration
    (injected ``crash_after`` fires while page frames are in flight);
    the coordinator reschedules the request to the surviving worker
    under a bumped attempt, the decode worker discards the stale
    attempt's staged frames (which held ZERO pool pages), the greedy
    stream is bit-identical, the decode-side radix tree keeps its
    ownership invariant, and the postmortem bundle names the injected
    fault."""
    set_flight_recorder(FlightRecorder(max_events=512))
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = (np.arange(37) % 50 + 3).astype(np.int32)
    want = reference_tokens(prompt[None], 8)[0]

    # msg 1 is the dreq receive, so the crash fires on a page-frame
    # send — genuinely mid-migration
    plan = FaultPlan(seed=1, rules=[
        FaultRule(kind="crash_after", n_msgs=2)])
    coord, pws, dw, engine, threads, dth = _build_disagg(
        cfg, full, [plan, None])
    stop = threading.Event()

    def watch():           # heartbeat stand-in (test_elastic wires the
        while not stop.is_set():     # real sweeper)
            if not threads[0].is_alive():
                coord.signal_failure("p0")
                return
            stop.wait(0.05)
    threading.Thread(target=watch, daemon=True).start()
    try:
        req = coord.submit(prompt, 8)
        got = req.wait(timeout=120)
        stop.set()
        np.testing.assert_array_equal(got, want)     # bit-identical
        assert req.attempt == 1 and req.worker == "p1"
        assert coord.stats["rescheduled"] == 1
        assert "crash_after" in {e["kind"] for e in plan.events}
        # stale attempt fully discarded; no staged residue, no pages
        assert dw._staged == {}
        # ownership invariant on the decode tree: used == tree-owned +
        # in-flight (nothing in flight after completion)
        _assert_no_page_leaks(engine, [pws[1]])

        # the postmortem bundle names the injected fault
        bundles = postmortem.get_postmortem_writer().bundle_dirs()
        assert bundles, "no postmortem bundle for the injected crash"
        manifests = [json.load(open(f"{b}/manifest.json"))
                     for b in bundles]
        inj = [m for m in manifests
               if m["reason"] == "injected_fault_crash"]
        assert inj and inj[0]["detail"]["fault"]["kind"] == "crash_after"
        assert inj[0]["detail"]["plan_seed"] == 1
    finally:
        stop.set()
        for pw in pws:
            pw.stop()
        dw.stop()
        coord.close()
        engine.close()


# ---------------------------------------------------------------------------
# overload shedding + request deadlines (graceful degradation satellites)


def _tiny_batching_engine(max_seq=64, **kw):
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    return ContinuousBatchingEngine(
        cfg, params, max_seq=max_seq, max_batch=1, sampling=GREEDY,
        kv_cache_blocks=0, **kw)


def _wait_for(cond, timeout=60.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_admission_queue_sheds_at_depth():
    from distributed_inference_demo_tpu.runtime.overload import (
        SchedulerOverloaded)
    with _tiny_batching_engine(max_queue_depth=1) as eng:
        prompt = np.arange(8, dtype=np.int32)
        r1 = eng.submit(prompt, 56)        # takes the only slot
        _wait_for(lambda: eng.stats()["active_slots"] == 1,
                  what="r1 to take the slot")
        r2 = eng.submit(prompt, 4)         # queued (depth 1)
        with pytest.raises(SchedulerOverloaded) as exc:
            eng.submit(prompt, 4)          # past the limit: shed
        assert exc.value.retry_after_s >= 1.0
        r1.cancel()
        r2.wait(timeout=60)                # the queued one still serves


def test_multirow_generate_shed_cancels_admitted_rows():
    """All-or-nothing admission: when row 1 of a 2-row generate() is
    shed, the already-admitted row 0 is cancelled — a 503'd request must
    not leave orphan rows burning slots while the server sheds load."""
    from distributed_inference_demo_tpu.runtime.overload import (
        SchedulerOverloaded)
    with _tiny_batching_engine(max_queue_depth=1) as eng:
        prompt = np.arange(8, dtype=np.int32)
        r1 = eng.submit(prompt, 56)        # takes the only slot
        _wait_for(lambda: eng.stats()["active_slots"] == 1,
                  what="r1 to take the slot")
        with pytest.raises(SchedulerOverloaded):
            eng.generate(np.stack([prompt, prompt]), 4)
        r1.cancel()
        _wait_for(lambda: (eng.stats()["queue_depth"] == 0
                           and eng.stats()["active_slots"] == 0),
                  what="the cancelled shed rows to drain, not decode")


def test_http_generate_returns_503_with_retry_after():
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    with _tiny_batching_engine(max_queue_depth=1) as eng:
        srv = InferenceHTTPServer(eng, port=0)
        srv.start()
        try:
            prompt = list(range(8))
            r1 = eng.submit(np.arange(8, dtype=np.int32), 56)
            _wait_for(lambda: eng.stats()["active_slots"] == 1,
                      what="r1 to take the slot")
            r2 = eng.submit(np.arange(8, dtype=np.int32), 4)
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=30)
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [prompt], "max_new_tokens": 4}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 503
            assert int(resp.getheader("Retry-After")) >= 1
            assert "admission queue full" in body["error"]
            conn.close()
            r1.cancel()
            r2.wait(timeout=60)
        finally:
            srv.shutdown()


def test_http_request_timeout_cancels_and_returns_504():
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    class _Tok:          # minimal tokenizer so the stop branch is legal
        def encode(self, text):
            return [1]

        def decode(self, ids, skip_special=True):
            return "".join(f" t{int(i)}" for i in ids)

    with _tiny_batching_engine(max_seq=1100) as eng:
        srv = InferenceHTTPServer(eng, port=0, request_timeout=0.5,
                                  tokenizer=_Tok())
        srv.start()
        try:
            # occupy the single slot for far longer than the deadline
            blocker = eng.submit(np.arange(8, dtype=np.int32), 1000)
            _wait_for(lambda: eng.stats()["active_slots"] == 1,
                      what="blocker to take the slot")
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=60)
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [list(range(8))], "max_new_tokens": 4}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 504
            resp.read()
            # the stop-sequence branch honors the same deadline (it
            # rides generate_stream, a different backend path)
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [list(range(8))], "max_new_tokens": 4,
                 "stop": ["zzzz"]}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 504
            resp.read()
            conn.close()
            blocker.cancel()
            blocker.wait(timeout=60)
            # graceful: the shed request freed its queue spot; a fresh
            # request completes normally
            eng.submit(np.arange(8, dtype=np.int32), 2).wait(timeout=60)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# §18 live-migration chaos (the ISSUE-14 acceptance): seeded faults on
# the pg:/rs: frame stream of a MID-DECODE handoff, and a source that
# crashes partway through the two-phase protocol


MIG_PROMPT = (np.arange(17) % 50 + 3).astype(np.int32)
# a LONG runway: the faulted handoff (rs: drop -> ack-timeout stall,
# corrupt/reorder -> nack rounds) takes ~0.5s, and the row must still
# be decoding when phase 2 freezes it
MIG_MAX_NEW = 480


@pytest.fixture(scope="module")
def mig_pair():
    """Two decode replicas on one loopback fabric, the target's
    migration worker serving; each test wires its own (faulty) source
    transport.  The fault-free reference stream is computed on the
    source engine itself — exact parity by construction."""
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.migration import (
        MigrationWorker)
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)

    def mk():
        return ContinuousBatchingEngine(
            cfg, params, max_seq=512, max_batch=2, sampling=GREEDY,
            kv_cache_blocks=80, kv_block_tokens=8)

    net = LoopbackNetwork()
    src_e, dst_e = mk(), mk()
    dst_w = MigrationWorker(dst_e, LoopbackTransport("dst", net),
                            ack_timeout=10.0)
    th = threading.Thread(target=dst_w.serve_forever, daemon=True)
    th.start()
    ref = [int(t) for t in src_e.submit(MIG_PROMPT,
                                        MIG_MAX_NEW).wait(120)]
    from types import SimpleNamespace
    yield SimpleNamespace(net=net, src_e=src_e, dst_e=dst_e,
                          dst_w=dst_w, ref=ref,
                          MigrationWorker=MigrationWorker)
    dst_w.stop()
    th.join(timeout=2)
    src_e.close()
    dst_e.close()


def _mig_no_pool_leaks(*engines):
    deadline = time.monotonic() + 5.0
    while True:
        snaps = [e.kv_cache.snapshot() for e in engines]
        if all(s["blocks_used"] == s["tree_blocks"] for s in snaps):
            return
        if time.monotonic() > deadline:
            raise AssertionError(
                "page leak: " + ", ".join(
                    f"{s['blocks_used']}/{s['tree_blocks']}"
                    for s in snaps))
        time.sleep(0.05)


def test_chaos_live_migration_faults_bit_identical(mig_pair):
    """Seeded drop + corrupt + duplicate + reorder on the pg:/rs: frame
    stream of a LIVE mid-decode handoff: the go-back-n/nack machinery
    heals every fault, the handoff still completes, and the client
    stream is bit-identical to the never-migrated run with zero pool
    pages leaked on either replica."""
    # the CPU decode can FINISH before a badly-stalled handoff freezes
    # the row — a legal local resolution; retry with a fresh rid
    for i in range(4):
        rid = f"cm{i}"
        plan = FaultPlan(seed=7 + i, rules=[
            FaultRule(kind="duplicate", tag_prefix="pg:", prob=0.5),
            FaultRule(kind="corrupt", tag_prefix="pg:", after=1,
                      max_count=1),
            FaultRule(kind="drop", tag_prefix="pg:", after=2,
                      max_count=1),
            FaultRule(kind="reorder", tag_prefix="pg:", prob=0.4),
            FaultRule(kind="drop", tag_prefix="rs:", max_count=1)])
        src_w = mig_pair.MigrationWorker(
            mig_pair.src_e,
            FaultyTransport(LoopbackTransport(f"cmsrc{i}", mig_pair.net),
                            plan),
            ack_timeout=0.25, retries=10)
        # the source must serve its own transport: after the handoff
        # the client stream is fed by the target's tok:/fin: relay
        th = threading.Thread(target=src_w.serve_forever, daemon=True)
        th.start()
        req = mig_pair.src_e.submit(MIG_PROMPT, MIG_MAX_NEW,
                                    request_id=rid)
        deadline = time.monotonic() + 30
        while len(req.tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.002)
        moved = src_w.migrate_out(rid, "dst")
        got = [int(t) for t in req.wait(60)]
        src_w.stop()
        th.join(timeout=2)
        assert got == mig_pair.ref
        assert req.error is None and req.done.is_set()
        if moved:
            break
    else:
        pytest.fail("handoff never outran the decode in 4 attempts")
    assert plan.events, "no fault fired — the plan never engaged"
    assert src_w.stats["migrated_out"] == 1
    assert mig_pair.dst_w.stats["migrated_in"] >= 1
    # the faulted staging fully drained into the adoption
    deadline = time.monotonic() + 5.0
    while (rid in mig_pair.dst_w.stager._staged
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert rid not in mig_pair.dst_w.stager._staged
    _mig_no_pool_leaks(mig_pair.src_e, mig_pair.dst_e)


@pytest.mark.slow
def test_chaos_mid_speculation_migration_rs_faults_bit_identical():
    """§22 chaos acceptance: a SPECULATING row (prompt-lookup proposer,
    adaptive K live) hands off mid-decode while seeded faults hammer
    the rs: resume-state frame — the frame that now carries the §22
    spec_k/spec_ewma scalars.  Drops stall into ack-timeout retries,
    corrupt frames are detected and retransmitted; the handoff still
    completes (or legally resolves locally), the stream is
    bit-identical to the never-migrated spec run, staging drains to
    zero bytes, and no pool page leaks on either replica."""
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)
    from distributed_inference_demo_tpu.runtime.migration import (
        MigrationWorker)
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)

    def mk():
        return ContinuousBatchingEngine(
            cfg, params, max_seq=512, max_batch=2, sampling=GREEDY,
            kv_cache_blocks=80, kv_block_tokens=8,
            prompt_lookup=True, num_draft=3)

    net = LoopbackNetwork()
    src_e, dst_e = mk(), mk()
    dst_w = MigrationWorker(dst_e, LoopbackTransport("smdst", net),
                            ack_timeout=10.0)
    th = threading.Thread(target=dst_w.serve_forever, daemon=True)
    th.start()
    try:
        ref = [int(t) for t in src_e.submit(MIG_PROMPT,
                                            MIG_MAX_NEW).wait(180)]
        # a spec row emits several tokens per round, so the faulted
        # handoff races a faster decode than the plain chaos test —
        # same retry idiom, fresh rid + seed per attempt
        moved = False
        for i in range(4):
            rid = f"sm{i}"
            plan = FaultPlan(seed=7 + i, rules=[
                FaultRule(kind="drop", tag_prefix="rs:", max_count=1),
                FaultRule(kind="corrupt", tag_prefix="rs:", after=1,
                          max_count=1),
                FaultRule(kind="duplicate", tag_prefix="rs:", prob=0.5),
                FaultRule(kind="duplicate", tag_prefix="pg:", prob=0.3),
                FaultRule(kind="reorder", tag_prefix="pg:", prob=0.3)])
            # tight ack timeout: each fault still costs a real
            # stall-and-retry, but the handoff can beat a spec row
            # that emits K+1 tokens per dispatch
            src_w = MigrationWorker(
                src_e,
                FaultyTransport(LoopbackTransport(f"smsrc{i}", net),
                                plan),
                ack_timeout=0.05, retries=10)
            sth = threading.Thread(target=src_w.serve_forever,
                                   daemon=True)
            sth.start()
            req = src_e.submit(MIG_PROMPT, MIG_MAX_NEW, request_id=rid)
            deadline = time.monotonic() + 30
            while len(req.tokens) < 2 and time.monotonic() < deadline:
                time.sleep(0.002)
            moved = src_w.migrate_out(rid, "smdst")
            got = [int(t) for t in req.wait(120)]
            src_w.stop()
            sth.join(timeout=2)
            assert got == ref
            assert req.error is None and req.done.is_set()
            if moved:
                break
        else:
            pytest.fail("spec handoff never outran the decode in 4 "
                        "attempts")
        assert plan.events, "no fault fired — the plan never engaged"
        assert src_w.stats["migrated_out"] == 1
        assert dst_w.stats["migrated_in"] >= 1
        deadline = time.monotonic() + 5.0
        while (rid in dst_w.stager._staged
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert rid not in dst_w.stager._staged
        assert dst_w.staged_bytes == 0
        _mig_no_pool_leaks(src_e, dst_e)
    finally:
        dst_w.stop()
        th.join(timeout=2)
        src_e.close()
        dst_e.close()


def test_chaos_source_crash_mid_migration_promotes_or_survives(
        mig_pair):
    """crash_after on the source transport mid-protocol.  Wherever the
    crash lands, no token is ever lost: before the phase-1 manifest the
    never-frozen row completes locally; after it the target holds a
    complete staged checkpoint and ``promote_staged`` resumes it — the
    promoted stream (snapshot prefix + re-decoded tail) is bit-identical
    to the reference, and staging held ZERO pool pages throughout."""
    promoted = None
    for i in range(3):
        rid = f"cp{i}"
        plan = FaultPlan(seed=31 + i, rules=[
            FaultRule(kind="crash_after", n_msgs=2 + i)])
        src_w = mig_pair.MigrationWorker(
            mig_pair.src_e,
            FaultyTransport(LoopbackTransport(f"cpsrc{i}", mig_pair.net),
                            plan),
            ack_timeout=0.5, retries=1)
        req = mig_pair.src_e.submit(MIG_PROMPT, MIG_MAX_NEW,
                                    request_id=rid)
        deadline = time.monotonic() + 30
        while len(req.tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.002)
        with pytest.raises(InjectedCrash):
            src_w.migrate_out(rid, "dst")
        # give the already-delivered frames a beat to process, then try
        # to promote the orphaned staging on the target
        deadline = time.monotonic() + 3.0
        while promoted is None and time.monotonic() < deadline:
            promoted = mig_pair.dst_w.promote_staged(rid)
            if promoted is None:
                time.sleep(0.05)
        if promoted is not None:
            break
        # crash landed before the phase-1 manifest: staging is partial
        # (zero pool pages by construction) — the source row, never
        # frozen, just keeps decoding to the bit-identical stream
        assert [int(t) for t in req.wait(60)] == mig_pair.ref
        assert req.error is None
        mig_pair.dst_w.handle_message(f"pgx:{rid}", b"")
        assert rid not in mig_pair.dst_w.stager._staged
        assert mig_pair.dst_w.staged_bytes == 0
    else:
        pytest.fail("no crash point left a promotable checkpoint")
    assert [int(t) for t in promoted.wait(60)] == mig_pair.ref
    assert mig_pair.dst_w.stats["promoted_requests"] >= 1
    _mig_no_pool_leaks(mig_pair.dst_e)
