"""Test harness: emulate an 8-device TPU-like mesh on CPU.

Per SURVEY.md §4, the reference has no multi-node test affordances at all;
here every test runs against a virtual 8-device CPU backend so pipeline /
tensor / sequence parallel paths are exercised without hardware.

Note: the environment preloads jax via sitecustomize with JAX_PLATFORMS=axon
(a remote TPU tunnel), so plain env-var assignment inside this process is too
late — we must force the platform through jax.config before any backend
initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process integration test")
    config.addinivalue_line(
        "markers", "quick: fast-lane smoke set (~2 min): one cheap, "
        "representative test per subsystem, for the edit-verify loop "
        "(`pytest -m quick`); the full suite stays the merge gate")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
