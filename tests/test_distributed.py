"""Distributed pipeline tests: multi-stage parity with the single-process
engine, in-flight request interleaving, and a real multi-process run.

The parity property: an N-stage pipeline over any transport must produce
token-for-token identical greedy output to the single-stage InferenceEngine
(the reference has no such test — or any test; SURVEY.md §4)."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import StageSpec, get_model_config
from distributed_inference_demo_tpu.models.base import slice_stage, \
    split_layer_ranges
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)

GREEDY = SamplingParams(greedy=True)


def reference_tokens(model, prompt, max_new):
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=128, sampling=GREEDY)
    return engine.generate(prompt, max_new).tokens


def reference_classify(model, prompt, label_token_ids):
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=128, sampling=GREEDY)
    return engine.classify(prompt, label_token_ids)


def build_pipeline(model, num_stages, max_seq=128):
    """In-process pipeline over loopback: header + workers on threads."""
    cfg = get_model_config(model)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    net = LoopbackNetwork()
    ids = [f"s{i}" for i in range(num_stages)]
    transports = [LoopbackTransport(d, net) for d in ids]

    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     max_seq, GREEDY),
        transports[0], next_id=ids[1], step_timeout=60)
    workers = []
    for i in range(1, num_stages):
        rt = StageRuntime(cfg, specs[i], slice_stage(full, cfg, specs[i]),
                          max_seq, GREEDY)
        workers.append(PipelineWorker(
            rt, transports[i],
            next_id=ids[i + 1] if i + 1 < num_stages else None,
            header_id=ids[0], step_timeout=60))
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    return header, threads


PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)


@pytest.mark.parametrize("model,num_stages", [
    ("llama-test", 2),          # BASELINE config #1 shape: 2-way split
    # 3-way split twin — slow lane: middle-stage (no-embed/no-head)
    # handling stays quick via the 3-stage chaos/elastic loopbacks
    pytest.param("llama-test", 3, marks=pytest.mark.slow),
    # bloom 2-way twin — slow lane: the split math is model-agnostic
    # (llama 2-way rep stays); bloom family parity stays quick via
    # hf_parity + test_models kv-cache decode
    pytest.param("bloom-test", 2, marks=pytest.mark.slow),
    # MoE across the cut — slow lane: test_expert pins EP-stage parity
    pytest.param("mixtral-test", 2, marks=pytest.mark.slow),
])
def test_pipeline_matches_single_engine(model, num_stages):
    want = reference_tokens(model, PROMPT, 12)
    header, threads = build_pipeline(model, num_stages)
    got = header.generate(PROMPT, 12)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    np.testing.assert_array_equal(got, want)


# tier-1 budget: pipeline_eos_early_stop and the quick
# pipeline_matches_single_engine params are the quick-lane reps
@pytest.mark.slow
def test_pipeline_interleaved_requests_match():
    """pool_size=2: two requests share the pipeline; results must equal the
    sequential single-engine output for each prompt."""
    p0 = PROMPT
    p1 = np.array([[9, 8, 7, 6, 5, 4, 3, 2]], dtype=np.int32)
    want0 = reference_tokens("llama-test", p0, 10)
    want1 = reference_tokens("llama-test", p1, 10)

    header, threads = build_pipeline("llama-test", 2)
    got = header.generate_many([p0, p1], 10, pool_size=2)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)
    np.testing.assert_array_equal(got[0], want0)
    np.testing.assert_array_equal(got[1], want1)


@pytest.mark.quick
def test_pipeline_eos_early_stop():
    """EOS: the header must stop a request early and release the stages."""
    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    want = reference_tokens("llama-test", PROMPT, 12)
    eos = int(want[0, 3])  # pretend this token value is EOS
    stop_at = int(np.argmax(want[0] == eos)) + 1  # first occurrence + 1

    net = LoopbackNetwork()
    specs = split_layer_ranges(cfg.num_layers, 2)
    t0, t1 = LoopbackTransport("s0", net), LoopbackTransport("s1", net)
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     128, GREEDY),
        t0, next_id="s1", eos_id=eos, step_timeout=60)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(full, cfg, specs[1]),
                     128, GREEDY),
        t1, next_id=None, header_id="s0", step_timeout=60)
    th = threading.Thread(target=worker.serve_forever, daemon=True)
    th.start()
    got = header.generate(PROMPT, 12)
    header.shutdown_pipeline()
    th.join(timeout=30)
    assert got.shape[1] == stop_at                # stopped at EOS
    np.testing.assert_array_equal(got[0], want[0, :stop_at])
    assert not worker.rt.caches                   # end:{rid} freed the slot


def test_capacity_checked_before_launch():
    header, threads = build_pipeline("llama-test", 2, max_seq=16)
    with pytest.raises(ValueError, match="exceeds KV capacity"):
        header.generate(PROMPT, 100)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)


@pytest.mark.slow
def test_two_process_pipeline_over_sockets(tmp_path):
    """BASELINE config #1 shape: TinyLlama-arch model split across two OS
    processes on localhost, sockets in between (the reference's 2-device
    bloom560m demo, ``server.py:26-27``, done as a real test)."""
    from distributed_inference_demo_tpu.comm.transport import ZmqTransport

    model = "llama-test"
    cfg = get_model_config(model)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    want = reference_tokens(model, PROMPT, 8)

    header_transport = ZmqTransport("header")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_inference_demo_tpu.runtime.worker_main",
         "--model", model, "--stage-id", "1", "--num-stages", "2",
         "--layer-start", str(specs[1].layer_start),
         "--layer-end", str(specs[1].layer_end),
         "--device-id", "w1", "--port", "0",
         "--header", f"header@{header_transport.address}",
         "--max-seq", "128", "--greedy"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("WORKER_READY w1 "), line
        worker_addr = line.split()[-1]
        header_transport.connect("w1", worker_addr)
        header = PipelineHeader(
            StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                         128, GREEDY),
            header_transport, next_id="w1", step_timeout=120)
        got = header.generate(PROMPT, 8)
        np.testing.assert_array_equal(got, want)
        header.shutdown_pipeline()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        header_transport.close()


@pytest.mark.slow
def test_two_process_pipeline_worker_tp(tmp_path):
    """Pipeline x tensor parallelism: the worker process runs its stage
    tp=2-sharded over virtual devices while the header stays single-
    device — greedy tokens must still match the plain engine (the wire
    carries replicated [b, s, H] either way)."""
    from distributed_inference_demo_tpu.comm.transport import ZmqTransport

    model = "llama-test"
    cfg = get_model_config(model)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    want = reference_tokens(model, PROMPT, 8)

    header_transport = ZmqTransport("header")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_inference_demo_tpu.runtime.worker_main",
         "--model", model, "--stage-id", "1", "--num-stages", "2",
         "--layer-start", str(specs[1].layer_start),
         "--layer-end", str(specs[1].layer_end),
         "--device-id", "w1", "--port", "0",
         "--header", f"header@{header_transport.address}",
         "--max-seq", "128", "--greedy", "--tp", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("WORKER_READY w1 "), line
        header_transport.connect("w1", line.split()[-1])
        header = PipelineHeader(
            StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                         128, GREEDY),
            header_transport, next_id="w1", step_timeout=120)
        got = header.generate(PROMPT, 8)
        np.testing.assert_array_equal(got, want)
        header.shutdown_pipeline()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        header_transport.close()


@pytest.mark.slow
def test_pipeline_fp8_kv_cache_matches_fp8_engine():
    """--chain --kv-cache-dtype: every stage stores its own layers' K/V
    at fp8 with the engine's insert-cast/read-upcast contract, so the
    pipeline must match the single fp8 engine bit-exactly."""
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    oracle = InferenceEngine(cfg, full, max_seq=128, sampling=GREEDY,
                             kv_cache_dtype="float8_e4m3fn")
    want = oracle.generate(PROMPT, 12).tokens

    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    transports = [LoopbackTransport(d, net) for d in ("s0", "s1")]
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     128, GREEDY, kv_cache_dtype="float8_e4m3fn"),
        transports[0], next_id="s1", step_timeout=60)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(full, cfg, specs[1]),
                     128, GREEDY, kv_cache_dtype="float8_e4m3fn"),
        transports[1], next_id=None, header_id="s0", step_timeout=60)
    t = threading.Thread(target=worker.serve_forever, daemon=True)
    t.start()
    got = header.generate(PROMPT, 12)
    header.shutdown_pipeline()
    t.join(timeout=30)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# dynamic batching over the pipeline (serve --chain --pool-size)


@pytest.mark.slow
def test_dynamic_batching_backend_concurrent_parity():
    """Concurrent requests with DIFFERENT lengths group into
    generate_many windows and each comes out bit-exact; stats/classify
    commands execute between windows on the one transport consumer."""
    from distributed_inference_demo_tpu.runtime.dynamic_batch import (
        DynamicBatchingHeaderBackend)

    header, threads = build_pipeline("llama-test", 2)
    backend = DynamicBatchingHeaderBackend(header, max_seq=128,
                                           num_stages=2, pool_size=2)
    try:
        prompts = [np.array([[5, 17, 42, 7]], dtype=np.int32),
                   np.array([[9, 8, 7]], dtype=np.int32),
                   np.array([[1, 2]], dtype=np.int32)]
        ns = [10, 6, 8]
        wants = [reference_tokens("llama-test", p, n)
                 for p, n in zip(prompts, ns)]

        results = {}

        def run(i):
            results[i] = backend.generate(prompts[i], ns[i]).tokens

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        for i, want in enumerate(wants):
            np.testing.assert_array_equal(results[i], want)

        # streaming yields per-step [b] arrays matching the blocking path
        steps = list(backend.generate_stream(prompts[0], 5))
        np.testing.assert_array_equal(np.stack(steps, axis=1), wants[0][:, :5])

        # control ops ride the scheduler thread between windows
        stats = backend.stats()
        assert {s["role"] for s in stats["stages"]} == {"header", "tail"}
        labels = [7, 42, 99]
        want_cls = reference_classify("llama-test", prompts[0], labels)
        assert backend.classify(prompts[0], labels).tolist() == \
            want_cls.tolist()
    finally:
        backend.close()
        header.shutdown_pipeline()
        for t in threads:
            t.join(timeout=30)


def test_dynamic_batching_backend_close_drains_waiters():
    """close() must fail queued waiters with a clear error instead of
    hanging them, and reject post-close submissions."""
    from distributed_inference_demo_tpu.runtime.dynamic_batch import (
        DynamicBatchingHeaderBackend)

    header, threads = build_pipeline("llama-test", 2)
    backend = DynamicBatchingHeaderBackend(header, max_seq=128,
                                           num_stages=2, pool_size=2)
    prompt = np.array([[5, 17, 42]], dtype=np.int32)
    # one request completes normally first (proves the loop was live)
    assert backend.generate(prompt, 4).tokens.shape == (1, 4)
    backend.close()
    with pytest.raises(RuntimeError, match="closed"):
        backend.generate(prompt, 4)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)
