"""The cost observatory (docs/DESIGN.md §20): dispatch signatures are
stable, sampling keeps the off-path free (zero syncs, zero clock reads
on unsampled dispatches), the compile ledger feeds recompile_storm
through the documented decision table, HBM watermarks are monotone and
retire on engine close, and workload sketches are byte-deterministic
artifacts the planner parses as workload input."""

import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

from distributed_inference_demo_tpu.planner import (
    SketchError, load_workload_sketch, plan_from_sketch)
from distributed_inference_demo_tpu.telemetry import profiling
from distributed_inference_demo_tpu.telemetry.anomaly import (
    AnomalyDetector, AnomalyMonitor, Thresholds)
from distributed_inference_demo_tpu.telemetry.profiling import (
    CompileTracker, DispatchProfiler, HbmWatermarks,
    WorkloadSketchRecorder, batch_bucket, dispatch_signature,
    kv_dispatch_bytes, merge_sketches, parse_signature, render_sketch)

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


class FakeClock:
    """Deterministic clock: every call returns the current time, and the
    call COUNT is the syncs-proxy the overhead contract pins."""

    def __init__(self, t: float = 1000.0, step: float = 0.0):
        self.t = t
        self.step = step
        self.calls = 0

    def __call__(self) -> float:
        self.calls += 1
        self.t += self.step
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- dispatch signatures ----------------------------------------------------

def test_signature_stability_and_bucketing():
    """Identical call shapes map to identical signatures; near-identical
    batch sizes share a pow2 bucket (slots vary by ±1 constantly — the
    cost regime doesn't fork per unit of batch)."""
    a = dispatch_signature("mixed_step", batch=5, chunk=4, kv_dtype="int8")
    b = dispatch_signature("mixed_step", batch=5, chunk=4, kv_dtype="int8")
    assert a == b == "mixed_step|b8|c4|int8"
    for n in (5, 6, 7, 8):
        assert batch_bucket(n) == 8
    assert batch_bucket(9) == 16
    assert batch_bucket(0) == 1          # empty active set still keys
    assert dispatch_signature("prefill") == "prefill|b1|c0|bf16"


def test_signature_parse_roundtrip():
    sig = dispatch_signature("paged_multi_step", batch=12, chunk=8,
                             kv_dtype="int4")
    assert parse_signature(sig) == {"program": "paged_multi_step",
                                    "batch_bucket": 16, "chunk": 8,
                                    "kv_dtype": "int4"}
    with pytest.raises(ValueError):
        parse_signature("not-a-signature")


# -- sampled dispatch profiler ----------------------------------------------

def test_sampling_cadence_every_nth_per_signature():
    clock = FakeClock(step=0.001)
    prof = DispatchProfiler(sample_n=4, clock=clock)
    sampled = [prof.begin("p|b1|c0|bf16") is not None for _ in range(12)]
    assert sampled == [False, False, False, True] * 3
    # cadence is PER signature: a second signature has its own counter
    assert prof.begin("q|b1|c0|bf16") is None
    assert prof.dispatch_counts() == {"p|b1|c0|bf16": 12,
                                      "q|b1|c0|bf16": 1}


def test_unsampled_path_is_free_no_clock_no_stats():
    """The §20 overhead contract: an UNSAMPLED begin/end pair touches
    the clock zero times (the clock read is the proxy for the
    block_until_ready sync end() would otherwise pay) and allocates no
    per-signature stats."""
    clock = FakeClock(step=0.001)
    prof = DispatchProfiler(sample_n=64, clock=clock)
    for _ in range(63):
        t0 = prof.begin("p|b1|c0|bf16")
        assert t0 is None
        assert prof.end("p|b1|c0|bf16", t0, out=object(),
                        hbm_bytes=10 ** 9) is None
    assert clock.calls == 0
    assert prof.snapshot() == {}
    # the 64th dispatch is the sampled one: exactly two clock reads
    t0 = prof.begin("p|b1|c0|bf16")
    assert t0 is not None
    assert prof.end("p|b1|c0|bf16", t0) is not None
    assert clock.calls == 2


def test_sample_n_zero_disables_even_counting():
    """DWT_PROFILE_SAMPLE_N=0: begin returns None without touching ANY
    state — the observatory is bit-for-bit absent from the hot path."""
    prof = DispatchProfiler(sample_n=0)
    for _ in range(5):
        assert prof.begin("p|b1|c0|bf16") is None
    assert prof.dispatch_counts() == {}
    assert prof.snapshot() == {}


def test_profiler_snapshot_percentiles_and_attribution(monkeypatch):
    """Sampled durations roll up to deterministic p50/p95/mean, and an
    hbm_bytes attribution yields achieved GB/s reconciled against the
    DWT_ROOFLINE_GBS ceiling override."""
    monkeypatch.setenv("DWT_ROOFLINE_GBS", "100.0")
    clock = FakeClock()
    prof = DispatchProfiler(sample_n=1, clock=clock)
    sig = dispatch_signature("decode_loop", batch=8, chunk=4)
    for ms in (1.0, 2.0, 3.0, 4.0, 5.0):
        t0 = prof.begin(sig)
        clock.advance(ms / 1e3)
        # 15 MB in `ms` — achieved GB/s varies per sample
        prof.end(sig, t0, hbm_bytes=15 * 1000 * 1000)
    snap = prof.snapshot()[sig]
    assert snap["dispatches"] == snap["samples"] == 5
    assert snap["p50_ms"] == 3.0          # nearest-rank over 5 samples
    assert snap["p95_ms"] == 5.0
    assert snap["mean_ms"] == 3.0
    # 75 MB over 15 ms total = 5 GB/s; ceiling 100 GB/s -> 0.05
    assert snap["achieved_gbs"] == 5.0
    assert snap["roofline_frac"] == 0.05


def test_kv_dispatch_bytes_tracks_quant_math():
    """The attribution uses the one-owner byte math in ops/quant.py:
    int8 pages are narrower than bf16 (scale sidecar accounted), K and
    V both counted."""
    bf16 = kv_dispatch_bytes(16, 4, 2, 64, "bf16", "bfloat16")
    int8 = kv_dispatch_bytes(16, 4, 2, 64, "int8", "bfloat16")
    assert bf16 == 16 * 4 * 2 * 2 * (64 * 2)
    assert 0 < int8 < bf16
    assert kv_dispatch_bytes(0, 4, 2, 64, None, "bfloat16") == 0


# -- compile observability --------------------------------------------------

class FakeJit:
    """A jit-shaped callable: _cache_size grows on unseen static args."""

    def __init__(self):
        self.cache = set()

    def _cache_size(self):
        return len(self.cache)

    def __call__(self, static_arg):
        self.cache.add(static_arg)
        return static_arg


def test_compile_tracker_counts_cache_growth():
    tracker = CompileTracker()
    fn = tracker.wrap("mixed_step", FakeJit(), variant_budget=2)
    fn("v1")
    fn("v1")                               # cache hit: not a compile
    fn("v2")
    snap = tracker.snapshot()["mixed_step"]
    assert snap["compiles"] == 2
    assert snap["cache_entries"] == 2
    assert snap["variant_budget"] == 2
    assert snap["compile_seconds"] >= 0.0
    # an unbudgeted program records None (ineligible for recompile_storm)
    tracker.wrap("prefill", FakeJit())("v1")
    assert tracker.snapshot()["prefill"]["variant_budget"] is None


def test_compile_tracker_passthrough_without_cache_size():
    """Wrapping a plain callable (no _cache_size) must pass through
    untouched — no accounting, no crash."""
    tracker = CompileTracker()
    fn = tracker.wrap("plain", lambda x: x + 1)
    assert fn(41) == 42
    assert tracker.snapshot()["plain"]["compiles"] == 0


def _storm_thresholds(slack=0, sustain=1):
    return Thresholds(recompile_slack=slack, sustain=sustain,
                      cooldown_s=300.0)


def test_recompile_storm_decision_table():
    """The detector's full decision table under an injected clock:
    within-budget quiet, budget+slack tolerated, overrun fires (once,
    cooldown eats repeats), slack=-1 disables, unbudgeted ignored."""
    clock = FakeClock()

    def observe(det, compiles, budget, slack_prog="mixed_step"):
        out = det.observe({"compile": {slack_prog: {
            "compiles": compiles, "variant_budget": budget,
            "compile_seconds": 1.5, "cache_entries": compiles}}})
        clock.advance(1.0)
        return out

    # within budget: never fires
    det = AnomalyDetector(_storm_thresholds(), clock=clock)
    for _ in range(3):
        assert observe(det, 2, 2) == []
    # overrun: fires exactly once (cooldown), critical, named detail
    fired = []
    for _ in range(5):
        fired += observe(det, 3, 2)
    assert [a.kind for a in fired] == ["recompile_storm"]
    assert fired[0].severity == "critical"
    assert fired[0].detail == {"program": "mixed_step", "compiles": 3,
                               "variant_budget": 2, "slack": 0,
                               "compile_seconds": 1.5}
    # slack tolerates exactly that many extra compiles
    det = AnomalyDetector(_storm_thresholds(slack=1), clock=clock)
    assert observe(det, 3, 2) == []
    assert [a.kind for a in observe(det, 4, 2)] == ["recompile_storm"]
    # slack=-1 disables the detector outright
    det = AnomalyDetector(_storm_thresholds(slack=-1), clock=clock)
    for _ in range(3):
        assert observe(det, 10, 2) == []
    # unbudgeted programs (variant_budget None) never fire
    det = AnomalyDetector(_storm_thresholds(), clock=clock)
    for _ in range(3):
        assert observe(det, 50, None) == []


def test_recompile_storm_sustain_and_recovery():
    """sustain=3: two breaches + a recovered observation + two more
    breaches must NOT fire (consecutive means consecutive)."""
    clock = FakeClock()
    det = AnomalyDetector(_storm_thresholds(sustain=3), clock=clock)

    def obs(compiles):
        out = det.observe({"compile": {"mixed_step": {
            "compiles": compiles, "variant_budget": 2}}})
        clock.advance(1.0)
        return out

    assert obs(3) == [] and obs(3) == []
    assert obs(2) == []                    # recovery clears the streak
    assert obs(3) == [] and obs(3) == []
    assert [a.kind for a in obs(3)] == ["recompile_storm"]


def test_recompile_storm_end_to_end_with_real_jit(tmp_path):
    """The acceptance scenario: a REAL jitted program wrapped as
    mixed_step with the §19 two-variant budget compiles a third variant
    — the observatory's compile fragment turns it into a critical
    recompile_storm with a postmortem bundle on disk."""
    import jax
    import jax.numpy as jnp

    from distributed_inference_demo_tpu.telemetry import postmortem

    tracker = CompileTracker()
    step = tracker.wrap("mixed_step", jax.jit(lambda x: x * 2),
                        variant_budget=2)
    for n in (2, 4, 8):                   # three shapes = three variants
        np.asarray(step(jnp.ones((n,), jnp.float32)))
    snap = tracker.snapshot()["mixed_step"]
    assert snap["compiles"] == 3
    assert snap["cache_entries"] == 3
    assert snap["compile_seconds"] > 0

    clock = FakeClock()
    writer = postmortem.PostmortemWriter(str(tmp_path), clock=clock)
    postmortem.set_postmortem_writer(writer)
    try:
        mon = AnomalyMonitor(
            AnomalyDetector(_storm_thresholds(), clock=clock),
            min_interval_s=0.0, clock=clock)
        fired = mon.observe({"compile": tracker.snapshot()})
        assert [a.kind for a in fired] == ["recompile_storm"]
        assert fired[0].detail["program"] == "mixed_step"
        assert len(mon.bundles) == 1
        bundle = Path(mon.bundles[0])
        assert bundle.is_dir()
        manifest = json.loads((bundle / "manifest.json").read_text())
        assert manifest["reason"] == "recompile_storm"
    finally:
        postmortem.set_postmortem_writer(None)


# -- HBM watermark ledger ---------------------------------------------------

def test_hbm_watermark_monotone_until_reset():
    hbm = HbmWatermarks()
    hbm.sample("kv_page_pool", 100)
    hbm.sample("kv_page_pool", 400)
    hbm.sample("kv_page_pool", 50)        # pool shrank; watermark holds
    w = hbm.watermarks()["kv_page_pool"]
    assert w == {"bytes": 50, "watermark_bytes": 400}
    hbm.sample("stage_pool", 7)
    hbm.reset("kv_page_pool")             # one owner retires
    assert "kv_page_pool" not in hbm.watermarks()
    assert hbm.watermarks()["stage_pool"]["watermark_bytes"] == 7
    hbm.reset()
    assert hbm.watermarks() == {}


def test_engine_feeds_watermarks_and_sketch_reset_on_close():
    """End to end on the paged scheduler: serving one request feeds the
    kv_page_pool watermark and the workload sketch; close() retires the
    engine's watermark owners (reset-on-close) while the process-wide
    sketch survives."""
    import jax

    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params)
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.batching import (
        ContinuousBatchingEngine)

    profiling.reset_observatory()
    try:
        cfg = get_model_config("llama-test")
        params = init_full_params(jax.random.PRNGKey(0), cfg)
        with ContinuousBatchingEngine(
                cfg, params, max_seq=64, max_batch=2,
                sampling=SamplingParams(greedy=True),
                prompt_buckets=(16,)) as eng:
            eng.submit([3, 14, 15, 92], 6).wait(timeout=300)
            hbm = profiling.get_hbm_watermarks().watermarks()
            assert hbm["kv_page_pool"]["watermark_bytes"] > 0
            # the scheduler's dispatches are being counted (default
            # sampling keeps the exact-count half of the observatory on)
            assert profiling.get_profiler().dispatch_counts()
            # compile ledger saw the paged programs compile, and the
            # budgeted ones carry the documented invariant
            comp = profiling.get_compile_tracker().snapshot()
            assert any(e["compiles"] > 0 for e in comp.values())
        assert "kv_page_pool" not in (
            profiling.get_hbm_watermarks().watermarks())
        sk = profiling.get_sketch()
        assert sk.requests == 1
        assert sk.decode_tokens.count >= 1
    finally:
        profiling.reset_observatory()


# -- workload sketches ------------------------------------------------------

def _record_trace(rec: WorkloadSketchRecorder) -> None:
    t = 100.0
    for i, (plen, tenant) in enumerate([(40, "a"), (600, "b"), (40, "a"),
                                        (3000, "a")]):
        rec.record_request(plen, tenant=tenant, now=t + i * 0.5)
    rec.record_prefix(32, 40)
    rec.record_prefix(0, 600)
    for n in (10, 20, 200):
        rec.record_decode(n)


def test_sketch_byte_determinism():
    """Identical traces fold to byte-identical canonical JSON — the
    contract GET /sketch serves verbatim and tools/sketch.py preserves."""
    a, b = WorkloadSketchRecorder(), WorkloadSketchRecorder()
    _record_trace(a)
    _record_trace(b)
    assert a.to_json() == b.to_json()
    obj = json.loads(a.to_json())
    assert obj["schema_version"] == profiling.SKETCH_SCHEMA_VERSION
    assert obj["requests"] == 4
    assert obj["window_s"] == 1.5
    assert obj["tenants"] == {"a": 3, "b": 1}
    assert obj["prefix_hit"] == {"matched_tokens": 32,
                                 "prompt_tokens": 640,
                                 "share": 0.05}
    # canonical form survives a parse/render round trip byte-for-byte
    assert render_sketch(obj) == a.to_json()


def test_sketch_merge_deterministic_and_schema_gated():
    """The gateway's fleet merge: section order doesn't matter, counts
    sum bin-wise, window is the max, and a schema-mismatched replica is
    dropped (named) instead of poisoning the merge."""
    a, b = WorkloadSketchRecorder(), WorkloadSketchRecorder()
    _record_trace(a)
    b.record_request(64, tenant="c", now=5.0)
    b.record_request(64, tenant="c", now=9.0)
    sa, sb = a.snapshot(), b.snapshot()
    stale = dict(sb, schema_version=999)
    merged = merge_sketches([("r1", sb), ("r0", sa), ("r2", stale)])
    flipped = merge_sketches([("r2", stale), ("r0", sa), ("r1", sb)])
    assert render_sketch(merged) == render_sketch(flipped)
    assert merged["replicas"] == ["r0", "r1"]
    assert merged["dropped_replicas"] == ["r2"]
    assert merged["requests"] == 6
    assert merged["tenants"] == {"a": 3, "b": 1, "c": 2}
    assert merged["window_s"] == 4.0      # max over sections, r2 included
    assert (merged["prompt_tokens"]["count"]
            == sa["prompt_tokens"]["count"] + sb["prompt_tokens"]["count"])


def test_gateway_fleet_sketch_socket_free():
    """The gateway's federated GET /sketch through the injectable
    fetcher: up replicas merge (sorted by rid), an unreachable replica
    is skipped — never a crash, never a poisoned merge."""
    from distributed_inference_demo_tpu.runtime.gateway.server import (
        GatewayHTTPServer)

    class Reg:
        def up_replicas(self):
            return ["h:2", "h:1", "h:3"]

        def endpoint(self, rid):
            host, port = rid.rsplit(":", 1)
            return host, int(port)

    a, b = WorkloadSketchRecorder(), WorkloadSketchRecorder()
    _record_trace(a)
    b.record_request(64, tenant="c", now=1.0)
    payloads = {"h:1": a.snapshot(), "h:2": b.snapshot()}

    def fetch(rid, host, port):
        if rid not in payloads:
            raise ConnectionError("replica down")
        return payloads[rid]

    gw = GatewayHTTPServer(Reg(), None, sketch_fetcher=fetch)
    merged = gw._fleet_sketch()
    assert merged["replicas"] == ["h:1", "h:2"]
    assert merged["requests"] == 5
    assert merged["tenants"] == {"a": 3, "b": 1, "c": 1}
    assert "h:3" not in merged.get("dropped_replicas", [])


def test_sketch_feeds_planner_as_workload_input():
    """The loop closes: a recorder artifact parses into the planner's
    WorkloadSketch and drives plan_from_sketch to a real plan whose ctx
    came from the measured p95s discounted by the prefix share."""
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.planner import DeviceProfile

    rec = WorkloadSketchRecorder()
    _record_trace(rec)
    ws = load_workload_sketch(rec.to_json())
    assert ws.requests == 4
    assert ws.window_s == 1.5
    assert ws.arrival_rate == pytest.approx(4 / 1.5)
    assert ws.prompt_p50 == 64.0          # bucket upper edges
    assert ws.prompt_p95 == 4096.0
    assert ws.decode_p50 == 32.0
    assert ws.prefix_share == 0.05
    assert ws.ctx_tokens == 4096 + 256

    cfg = get_model_config("llama-test")
    devices = [DeviceProfile(device_id=f"d{i}",
                             address=f"10.0.0.{i}:9000",
                             flops_per_sec=1e12, memory_bytes=16 << 30,
                             platform="cpu", chips=1,
                             egress_bandwidth=1e9, egress_latency=1e-3)
               for i in range(2)]
    plan = plan_from_sketch(cfg, "llama-test", devices, rec.to_json())
    assert sum(b - a for a, b in plan.stage_ranges.values()) \
        == cfg.num_layers


def test_sketch_loader_rejects_drift():
    rec = WorkloadSketchRecorder()
    rec.record_request(10)
    obj = rec.snapshot()
    with pytest.raises(SketchError):
        load_workload_sketch(dict(obj, schema_version=999))
    missing = dict(obj)
    del missing["interarrival_s"]
    with pytest.raises(SketchError):
        load_workload_sketch(missing)
    with pytest.raises(SketchError):
        load_workload_sketch([1, 2, 3])


def test_check_sketch_schema_lint_is_clean():
    """The tier-1 half of tools/check_sketch_schema.py: the recorder's
    and the planner's pinned schema copies agree RIGHT NOW."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_sketch_schema
        assert check_sketch_schema.check() == []
    finally:
        sys.path.remove(str(REPO / "tools"))


def test_observatory_state_shape(monkeypatch):
    """/debugz section: every ledger present, sample_n from the env."""
    monkeypatch.setenv("DWT_PROFILE_SAMPLE_N", "16")
    profiling.reset_observatory()
    try:
        state = profiling.observatory_state()
        assert state["sample_n"] == 16
        for key in ("profile", "compile", "hbm"):
            assert state[key] == {}
        assert state["sketch_requests"] == 0
    finally:
        monkeypatch.delenv("DWT_PROFILE_SAMPLE_N", raising=False)
        profiling.reset_observatory()
