"""Gateway mid-stream failover (docs/DESIGN.md §23): zero-loss streams.

Chaos at every seam the ISSUE names, cheapest first:

- STUB replicas speaking the resume protocol pin the gateway-side
  mechanics socket-free of engines: journal contents, torn-line
  handling, the resume payload, routing exclusion of the dead replica,
  step continuity, exhaustion fallback, and the resume_limit=0
  behavior pin;
- a seeded comm/faults ``crash_after`` rule over REAL batching engines
  pins end-to-end bit-identity through the gateway hop (the replica's
  own error line is the death signal on this seam — no socket ever
  breaks);
- a real SIGKILL'd replica subprocess (an OS-level death: FIN/RST with
  no terminating chunk) resumes onto a survivor.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.comm.faults import (FaultPlan,
                                                        FaultRule)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)

from test_gateway import (_CrashyBackend, _engine, _gateway,  # noqa: E402
                          _post_stream)

CFG = get_model_config("llama-test")
TOKENS = list(range(100, 108))          # the stubs' canonical stream


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


class _ResumableStub:
    """A replica double that speaks the RESUME side of the serving
    surface: ``POST /generate`` streams ``TOKENS`` as chunked JSONL,
    honoring ``{"resume": {"delivered_tokens": [...]}}`` by starting
    after the delivered prefix with continuing step numbers.

    ``sever_after=N`` kills the socket after N complete lines of its
    OWN response (no terminating chunk); ``tear_line=True`` addition-
    ally writes the first half of line N before severing — the torn
    trailing fragment the gateway must never forward or journal."""

    def __init__(self, sever_after=None, tear_line=False):
        self.sever_after = sever_after
        self.tear_line = tear_line
        self.requests = 0
        self.resumes = []               # every resume payload received
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"queue_depth": 0,
                                   "kvcache": {"nodes": 1}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                outer.requests += 1
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                resume = req.get("resume")
                start = 0
                if resume is not None:
                    outer.resumes.append(resume)
                    start = len(resume["delivered_tokens"])
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")

                def sever(partial=b""):
                    if partial:
                        # a chunk header promising MORE than the bytes
                        # that follow: the reader sees a complete-
                        # looking chunk stream end mid-line
                        self.wfile.write(
                            f"{len(partial) + 20:x}\r\n".encode())
                        self.wfile.write(partial)
                    self.wfile.flush()
                    self.close_connection = True
                    self.connection.shutdown(socket.SHUT_RDWR)

                for i in range(start, len(TOKENS)):
                    line_no = i - start
                    if (outer.sever_after is not None
                            and line_no >= outer.sever_after):
                        line = json.dumps(
                            {"step": i, "tokens": [TOKENS[i]]}
                        ).encode() + b"\n"
                        sever(line[:len(line) // 2]
                              if outer.tear_line else b"")
                        return
                    chunk(json.dumps({"step": i, "tokens": [TOKENS[i]]}
                                     ).encode() + b"\n")
                chunk(b"")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.host, self.port = self.httpd.server_address
        self.rid = f"{self.host}:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _scrape(gw):
    conn = HTTPConnection(gw.host, gw.port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def _series(text, name):
    for ln in text.splitlines():
        if ln.startswith(name + " ") or ln.startswith(name + "{"):
            return float(ln.rsplit(" ", 1)[1])
    return 0.0


# ---------------------------------------------------------------------------
# seam 1: severed socket (stub fleet — protocol mechanics)
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_sever_resumes_on_survivor_no_loss_no_duplicates():
    victim = _ResumableStub(sever_after=2)
    survivor = _ResumableStub()
    gw = _gateway([(victim.host, victim.port),
                   (survivor.host, survivor.port)], sustain=1)
    try:
        toks = list(range(2, 18))
        gw.router.record(victim.rid, toks)
        st, headers, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=30)
        # the client sees ONE unbroken stream: every token exactly
        # once, steps contiguous, no error line, clean termination
        assert st == 200 and not truncated
        assert [d["tokens"][0] for d in lines] == TOKENS
        assert [d["step"] for d in lines] == list(range(8))
        assert not any("error" in d for d in lines)
        # the survivor got the journal: delivered prefix + offset
        assert survivor.resumes == [
            {"delivered_tokens": TOKENS[:2], "rng_step_offset": 2}]
        # the victim was struck (reason=mid-stream), the survivor
        # learned the prefix for future routing
        assert not gw.registry.is_up(victim.rid)
        reasons = gw.registry.debug_state()["failure_reasons"]
        assert reasons["mid-stream"] == 1
        assert gw.router.match_tokens(survivor.rid, toks) > 0
        text = _scrape(gw)
        assert _series(text, "dwt_gateway_resume_attempts_total") >= 1
        assert _series(text, "dwt_gateway_resume_succeeded_total") >= 1
        assert "dwt_gateway_resume_ttf_seconds" in text
        assert 'dwt_gateway_replica_failures_total{reason="mid-stream"}' \
            in text
    finally:
        gw.shutdown()
        victim.close()
        survivor.close()


@pytest.mark.quick
def test_torn_trailing_line_never_forwarded_journal_ends_complete():
    """ISSUE-20 satellite: the victim tears mid-JSONL-line.  The
    fragment must reach neither the client nor the journal — the
    resume hands the survivor exactly the COMPLETE-line prefix, and
    the client stream holds each token exactly once."""
    victim = _ResumableStub(sever_after=2, tear_line=True)
    survivor = _ResumableStub()
    gw = _gateway([(victim.host, victim.port),
                   (survivor.host, survivor.port)], sustain=1)
    try:
        toks = list(range(2, 18))
        gw.router.record(victim.rid, toks)
        st, _, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=30)
        # _post_stream json-parses every line: a forwarded fragment
        # would have flagged `truncated`
        assert st == 200 and not truncated
        assert [d["tokens"][0] for d in lines] == TOKENS
        assert survivor.resumes == [
            {"delivered_tokens": TOKENS[:2], "rng_step_offset": 2}]
    finally:
        gw.shutdown()
        victim.close()
        survivor.close()


@pytest.mark.quick
def test_resume_exhaustion_falls_back_to_error_line_not_a_hang():
    victim = _ResumableStub(sever_after=2)
    gw = _gateway([(victim.host, victim.port)], sustain=1)
    try:
        before = _scrape(gw)    # counters are process-global
        st, _, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [list(range(2, 18))],
                               "max_new_tokens": 8, "stream": True},
            timeout=30)
        # no survivor: delivered prefix + ONE error line, terminated —
        # exactly the pre-resume contract, and nothing duplicated
        assert st == 200
        assert [d["tokens"][0] for d in lines[:-1]] == TOKENS[:2]
        assert "error" in lines[-1] and victim.rid in lines[-1]["error"]
        assert not gw.registry.is_up(victim.rid)
        text = _scrape(gw)
        for name, delta in (
                ("dwt_gateway_resume_exhausted_requests_total", 1),
                ("dwt_gateway_resume_attempts_total", 1),
                ("dwt_gateway_resume_succeeded_total", 0)):
            assert _series(text, name) - _series(before, name) == delta, \
                name
    finally:
        gw.shutdown()
        victim.close()


@pytest.mark.quick
def test_resume_limit_zero_pins_the_error_line_contract():
    """--resume-limit 0 restores the pre-§23 behavior byte-for-byte:
    the healthy survivor is never consulted even though it could have
    finished the stream."""
    victim = _ResumableStub(sever_after=2)
    survivor = _ResumableStub()
    gw = _gateway([(victim.host, victim.port),
                   (survivor.host, survivor.port)], sustain=1,
                  resume_limit=0)
    try:
        toks = list(range(2, 18))
        gw.router.record(victim.rid, toks)
        st, _, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=30)
        assert st == 200
        assert [d["tokens"][0] for d in lines[:-1]] == TOKENS[:2]
        assert "error" in lines[-1]
        assert survivor.requests == 0
        assert survivor.resumes == []
    finally:
        gw.shutdown()
        victim.close()
        survivor.close()


@pytest.mark.quick
def test_second_sever_within_limit_resumes_again():
    """resume_limit=2 survives TWO mid-stream deaths: the journal keeps
    absorbing delivered lines across attempts, each survivor gets the
    up-to-date prefix, and the client still sees every token once."""
    stubs = [_ResumableStub() for _ in range(3)]
    gw = _gateway([(s.host, s.port) for s in stubs], sustain=1,
                  resume_limit=2, retry_limit=2)
    try:
        toks = list(range(2, 18))
        # assign death order along the ROUTER's own rendezvous order
        # (stable under eviction), so the chain victim -> dying
        # survivor -> final survivor is deterministic
        d = gw.router.route(toks)
        by_rid = {s.rid: s for s in stubs}
        order = [by_rid[r] for r in [d.rid] + d.candidates]
        order[0].sever_after = 2          # the original victim
        order[1].sever_after = 3          # dies AGAIN mid-resume
        st, _, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=30)
        assert st == 200 and not truncated
        assert [d["tokens"][0] for d in lines] == TOKENS
        assert not any("error" in d for d in lines)
        # each resume carried the journal as of ITS moment
        assert [len(r["delivered_tokens"]) for r in order[1].resumes] \
            == [2]
        assert [len(r["delivered_tokens"]) for r in order[2].resumes] \
            == [5]
    finally:
        gw.shutdown()
        for s in stubs:
            s.close()


# ---------------------------------------------------------------------------
# seam 2: FaultPlan crash_after over real engines (error-line seam)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sampling", [
    SamplingParams(greedy=True),
    # tier-1 budget: greedy is the quick rep for the engine-backed
    # chaos leg; the sampled twin (same seam, rng fast-forward already
    # pinned per-cut in test_resume.py) rides the slow lane
    pytest.param(SamplingParams(temperature=0.9, top_k=40),
                 marks=pytest.mark.slow),
], ids=["greedy", "sampled"])
def test_injected_crash_resumes_bit_identical(params, sampling):
    """The seeded chaos plan from the ISSUE acceptance bar: a
    crash_after rule kills replica0 after 3 streamed tokens (its error
    line is the death signal — the socket never breaks on this seam);
    the gateway must intercept it, resume on replica1, and hand the
    client the exact token sequence of an unfailed run."""
    plan = FaultPlan(seed=7, rules=[FaultRule(kind="crash_after",
                                              n_msgs=3, max_count=1)])
    engines = [_engine(params, sampling=sampling, seed=11)
               for _ in range(2)]
    servers = []
    for i, eng in enumerate(engines):
        backend = (_CrashyBackend(eng, plan, "replica0") if i == 0
                   else eng)
        srv = InferenceHTTPServer(backend, port=0)
        srv.start()
        servers.append(srv)
    gw = _gateway([(s.host, s.port) for s in servers], min_prefix=8,
                  block_tokens=8)
    try:
        toks = list(range(2, 18))
        crashy_rid = f"{servers[0].host}:{servers[0].port}"
        gw.router.record(crashy_rid, toks)
        # the unfailed reference: replica1 directly, then drop the
        # blocks so the resumed run re-prefills like a cold survivor
        st, _, ref_lines, _ = _post_stream(
            servers[1].host, servers[1].port,
            {"prompt_ids": [toks], "max_new_tokens": 8, "stream": True},
            timeout=300)
        assert st == 200
        ref = [d["tokens"][0] for d in ref_lines]
        assert len(ref) == 8
        st, _, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=300)
        assert st == 200 and not truncated
        assert [e["kind"] for e in plan.events] == ["crash_after"]
        assert not any("error" in d for d in lines)
        got = [d["tokens"][0] for d in lines]
        assert got == ref                      # bit-identity across kill
        assert [d["step"] for d in lines] == list(range(8))
        # survivor-side evidence: one resumed request, zero divergence,
        # no leaked pages
        st1 = engines[1].stats()
        assert st1["resumed"]["requests"] == 1
        assert st1["resumed"]["diverged"] == 0
        mgr = engines[1].kv_cache
        assert mgr.used_blocks == mgr.tree.block_count
    finally:
        gw.shutdown()
        for srv, eng in zip(servers, engines):
            srv.shutdown()
            eng.close()


# ---------------------------------------------------------------------------
# seam 3: a real SIGKILL'd replica subprocess
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, sys, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

TOKENS = list(range(100, 108))

class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a): pass
    def do_GET(self):
        body = json.dumps({"queue_depth": 0}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        for i, t in enumerate(TOKENS):
            data = json.dumps({"step": i, "tokens": [t]}).encode() + b"\n"
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()
            time.sleep(0.25)      # slow enough to SIGKILL mid-stream
        self.wfile.write(b"0\r\n\r\n")

httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
print(f"PORT {httpd.server_address[1]}", flush=True)
httpd.serve_forever()
"""


def test_sigkilled_replica_subprocess_resumes_on_survivor():
    """An OS-level death: the victim is a separate PROCESS streaming
    real chunked JSONL, SIGKILL'd mid-stream (kernel sends FIN with no
    terminating chunk — nothing in the victim gets to clean up).  The
    gateway resumes on the survivor stub and the client never sees the
    kill."""
    child = subprocess.Popen([sys.executable, "-c", _CHILD],
                             stdout=subprocess.PIPE, text=True)
    survivor = _ResumableStub()
    gw = None
    try:
        port_line = child.stdout.readline().strip()
        assert port_line.startswith("PORT ")
        victim_port = int(port_line.split()[1])
        victim_rid = f"127.0.0.1:{victim_port}"
        gw = _gateway([("127.0.0.1", victim_port),
                       (survivor.host, survivor.port)], sustain=1)
        toks = list(range(2, 18))
        gw.router.record(victim_rid, toks)

        killed = {}

        def kill_soon():
            time.sleep(0.6)       # ~2 lines at 0.25s/line
            os.kill(child.pid, signal.SIGKILL)
            killed["t"] = time.time()

        threading.Thread(target=kill_soon, daemon=True).start()
        st, _, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=60)
        assert killed, "the kill never fired"
        assert st == 200 and not truncated
        assert [d["tokens"][0] for d in lines] == TOKENS
        assert not any("error" in d for d in lines)
        assert [d["step"] for d in lines] == list(range(8))
        # the survivor was handed the mid-kill journal
        assert len(survivor.resumes) == 1
        delivered = survivor.resumes[0]["delivered_tokens"]
        assert 1 <= len(delivered) < 8
        assert delivered == TOKENS[:len(delivered)]
        assert not gw.registry.is_up(victim_rid)
    finally:
        if gw is not None:
            gw.shutdown()
        survivor.close()
        if child.poll() is None:
            child.kill()
        child.wait(timeout=10)
