"""``serve --vision`` — the multimodal HTTP surface.

Image+text requests through MultimodalBackend must match
MultimodalEngine.generate exactly; text-only requests must match the
plain engine; shape/batch mismatches are clean 400s; image against a
non-multimodal backend is an honest 501.
"""

import http.client
import json

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu import cli
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.models.vision import (
    VisionConfig, init_vision_params)
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)
from distributed_inference_demo_tpu.runtime.multimodal import (
    MultimodalBackend, MultimodalEngine)

GREEDY = SamplingParams(greedy=True)
VCFG = VisionConfig(image_size=32, patch_size=16, hidden_size=32,
                    num_layers=2, num_heads=2, intermediate_size=64)


def _req(server, method, path, body=None):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


@pytest.fixture(scope="module")
def vision_server():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    vparams = init_vision_params(jax.random.PRNGKey(1), VCFG,
                                 cfg.hidden_size)
    mm = MultimodalEngine(cfg, params, VCFG, vparams, max_seq=64,
                          sampling=GREEDY)
    server = InferenceHTTPServer(MultimodalBackend(mm), port=0,
                                 model_name="llama-test")
    server.start()
    yield server, mm
    server.shutdown()


def test_image_request_matches_engine(vision_server):
    server, mm = vision_server
    img = np.full((32, 32, 3), 0.25, np.float32)
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "image": img.tolist(),
                         "max_new_tokens": 5})
    assert status == 200
    got = json.loads(data)["tokens"]
    want = mm.generate(img[None], np.asarray(prompt), 5).tokens.tolist()
    assert got == want


def test_text_only_matches_plain_engine(vision_server):
    server, mm = vision_server
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 5})
    assert status == 200
    plain = InferenceEngine(mm.cfg, mm.engine.params, max_seq=64,
                            sampling=GREEDY)
    want = plain.generate(np.asarray(prompt), 5).tokens.tolist()
    assert json.loads(data)["tokens"] == want


def test_bad_image_shapes_are_400(vision_server):
    server, _ = vision_server
    prompt = [[5, 17, 42, 7]]
    bad = np.zeros((16, 16, 3), np.float32).tolist()   # wrong size
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "image": bad,
                         "max_new_tokens": 4})
    assert status == 400
    assert "32" in json.loads(data)["error"]
    # batch mismatch: 2 images for a 1-row prompt
    two = np.zeros((2, 32, 32, 3), np.float32).tolist()
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "image": two,
                         "max_new_tokens": 4})
    assert status == 400
    assert "batch" in json.loads(data)["error"]


def test_image_stream_rejected_501(vision_server):
    server, _ = vision_server
    img = np.zeros((32, 32, 3), np.float32).tolist()
    status, _ = _req(server, "POST", "/generate",
                     {"prompt_ids": [[1, 2]], "image": img,
                      "max_new_tokens": 4, "stream": True})
    assert status == 501


def test_image_against_text_backend_is_501():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, model_name="llama-test")
    server.start()
    try:
        img = np.zeros((32, 32, 3), np.float32).tolist()
        status, data = _req(server, "POST", "/generate",
                            {"prompt_ids": [[1, 2]], "image": img,
                             "max_new_tokens": 4})
        assert status == 501
        assert "image" in json.loads(data)["error"]
    finally:
        server.shutdown()


def test_text_only_full_surface_delegates(vision_server):
    """Streaming, logprobs, and /classify all work text-only against a
    --vision server — the wrapped engine's surface is not narrowed."""
    server, mm = vision_server
    prompt = [[5, 17, 42, 7]]
    # streaming
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request("POST", "/generate",
                 body=json.dumps({"prompt_ids": prompt,
                                  "max_new_tokens": 4, "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    lines = [json.loads(line) for line in resp.read().decode().splitlines()
             if line.strip()]
    conn.close()
    plain = InferenceEngine(mm.cfg, mm.engine.params, max_seq=64,
                            sampling=GREEDY)
    want = plain.generate(np.asarray(prompt), 4).tokens[0].tolist()
    assert [line["tokens"][0] for line in lines] == want
    # logprobs
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 4,
                         "logprobs": True})
    assert status == 200
    assert len(json.loads(data)["logprobs"][0]) == 4
    # logprobs WITH an image is a clean 400 (unsupported, not silent)
    img = np.zeros((32, 32, 3), np.float32).tolist()
    status, _ = _req(server, "POST", "/generate",
                     {"prompt_ids": prompt, "image": img,
                      "max_new_tokens": 4, "logprobs": True})
    assert status == 400
    # classify
    status, data = _req(server, "POST", "/classify",
                        {"prompt_ids": prompt, "label_token_ids": [5, 9]})
    assert status == 200


def test_vision_stats(vision_server):
    server, _ = vision_server
    status, data = _req(server, "GET", "/stats")
    assert status == 200
    body = json.loads(data)
    assert body["mode"] == "multimodal"
    assert body["patches_per_image"] == VCFG.num_patches


def test_vision_serve_mode_pairing_rules(capsys):
    base = ["serve", "--model", "llama-test", "--vision"]
    assert cli.main(base + ["--batch-slots", "2"]) == 1
    assert cli.main(base + ["--draft-model", "llama-test"]) == 1
    assert cli.main(base + ["--sp", "2"]) == 1
    assert cli.main(base + ["--chain", "w@127.0.0.1:1"]) == 1
    assert cli.main(base + ["--tp", "2"]) == 1
    assert cli.main(base + ["--kv-cache-dtype", "float8_e4m3fn"]) == 1
    err = capsys.readouterr().err
    assert "--vision" in err or "--kv-cache-dtype" in err
