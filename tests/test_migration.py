"""Live KV migration between decode replicas (docs/DESIGN.md §18).

The ISSUE-14 invariants, pinned:

- a request that migrates MID-DECODE from one replica to another keeps
  one unbroken client stream, greedy output BIT-IDENTICAL to the
  never-migrated run — at most one boundary step replays (deduped by
  the (rid, step) rule) and no step is ever skipped;
- the checkpoint seam (``export_request``/``import_request``) is exact:
  a detached checkpoint re-imported elsewhere resumes at the freeze
  step with zero prefill dispatch;
- every failure path leaves both pools leak-free: an unreachable
  target fails the migration loudly while the request completes
  locally; a lost phase-2 ack self-heals by local re-import; a staged
  checkpoint whose source died promotes on the target;
- cancel crossing a handoff is forwarded and terminates cleanly on the
  replica that owns the row — no hang, every page released;
- the adopted/aborted gates are attempt-AWARE, so a request can bounce
  A → B → A and each hop stages fresh (higher attempt) instead of
  being dropped as a duplicate;
- the DecodeWorker abort path clears staged bytes exactly and blocks
  restaging by late frames of the aborted attempt (the §15 accounting
  this PR's shared PageStager must preserve);
- :class:`MigrationController` picks hot → light rebalances off the
  gateway registry's load view and drives a draining replica empty.

The chaos-side §18 acceptance (seeded faults on pg:/rs: frames, source
crash mid-migration) lives in tests/test_chaos.py.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm.faults import (
    FaultPlan, FaultRule, FaultyTransport)
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportError)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.runtime.disagg import (
    DecodeWorker, MigrationError, PageStager, _meta_frame, _page_frame)
from distributed_inference_demo_tpu.runtime.migration import (
    CoServingWorker, MigrationController, MigrationWorker, _state_meta,
    _state_tensors)
from distributed_inference_demo_tpu.telemetry.tracing import to_chrome_trace

GREEDY = SamplingParams(greedy=True)
MODEL = "llama-test"
# CPU timing reality: llama-test decodes a token every few ms, so the
# migration tests need enough remaining budget that the two-phase
# handoff lands while the row is still decoding — 17-token prompt, 96
# new tokens, migrate after ~2 (max_seq must cover 17 + 96)
PROMPT = (np.arange(17) % 50 + 3).astype(np.int32)
MAX_NEW = 96


def _mk_engine(cfg, params):
    return ContinuousBatchingEngine(
        cfg, params, max_seq=160, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=32, kv_block_tokens=8)


@pytest.fixture(scope="module")
def cfg_params():
    cfg = get_model_config(MODEL)
    return cfg, init_full_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def reference(cfg_params):
    """Memoized fault-free greedy stream per (prompt, max_new)."""
    cfg, params = cfg_params
    memo = {}

    def run(prompt, max_new):
        prompt = np.asarray(prompt, np.int32)
        key = (prompt.tobytes(), int(max_new))
        if key not in memo:
            eng = _mk_engine(cfg, params)
            try:
                memo[key] = [int(t)
                             for t in eng.submit(prompt, max_new).wait(120)]
            finally:
                eng.close()
        return memo[key]
    return run


@pytest.fixture(scope="module")
def pair(cfg_params):
    """Two decode replicas ("src", "dst") with live-migration workers on
    one loopback fabric, plus spare endpoints the failure-path tests
    address: "ghost" (registered, never served), "deadsrc"/"client0"
    (ack/relay sinks for the manually-staged promote test)."""
    cfg, params = cfg_params
    net = LoopbackNetwork()
    src_e, dst_e = _mk_engine(cfg, params), _mk_engine(cfg, params)
    src_w = MigrationWorker(src_e, LoopbackTransport("src", net),
                            ack_timeout=10.0)
    dst_w = MigrationWorker(dst_e, LoopbackTransport("dst", net),
                            ack_timeout=10.0)
    for extra in ("ghost", "deadsrc", "client0"):
        LoopbackTransport(extra, net)
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in (src_w, dst_w)]
    for t in threads:
        t.start()
    yield SimpleNamespace(net=net, src_e=src_e, dst_e=dst_e,
                          src_w=src_w, dst_w=dst_w)
    src_w.stop()
    dst_w.stop()
    for t in threads:
        t.join(timeout=2)
    src_e.close()
    dst_e.close()


def _wait_tokens(req, n, timeout=30.0):
    deadline = time.monotonic() + timeout
    while len(req.tokens) < n:
        if time.monotonic() > deadline:
            raise AssertionError(
                f"only {len(req.tokens)}/{n} tokens within {timeout}s")
        time.sleep(0.002)
    return len(req.tokens)


def _idle_no_leaks(*engines):
    """§11 ownership invariant on every pool: idle ⇒ every allocated
    page is tree-owned (request pages freed, adopted pages handed over)
    — bounded wait for the async completions."""
    deadline = time.monotonic() + 5.0
    while True:
        snaps = [e.kv_cache.snapshot() for e in engines]
        if all(s["blocks_used"] == s["tree_blocks"] for s in snaps):
            return
        if time.monotonic() > deadline:
            raise AssertionError(
                "page leak: " + ", ".join(
                    f"{s['blocks_used']}/{s['tree_blocks']}"
                    for s in snaps))
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# unit: staging gates + abort accounting (no engine)


def _blk(cfg):
    return np.zeros((1, cfg.num_layers, cfg.num_kv_heads, 16,
                     cfg.head_dim), np.float32)


def test_decode_abort_clears_bytes_and_blocks_restaging(cfg_params):
    """The satellite-2 pin: DecodeWorker._on_abort pops the staged
    record AND its byte accounting (``staged_bytes`` back to 0 exactly),
    and a late frame of the aborted attempt drops instead of silently
    restaging the leak the abort just cleaned up."""
    cfg, _ = cfg_params

    class _FakeEngine:
        def submit_premigrated(self, *a, **k):
            raise AssertionError("no join expected in this test")

    net = LoopbackNetwork()
    dw = DecodeWorker(_FakeEngine(), LoopbackTransport("dz", net))
    LoopbackTransport("pz", net)
    blk = _blk(cfg)
    dw.handle_message("pg:ra:1:0", _page_frame(blk, blk, 0))
    dw.handle_message("pg:ra:1:1", _page_frame(blk, blk, 1))
    assert dw._staged["ra"]["expected"] == 2
    before = dw.stager.staged_bytes
    assert before > 0
    assert dw.handle_message("pgx:ra", b"")
    assert dw._staged == {}
    assert dw.stager.staged_bytes == 0
    assert dw.stats["aborted_migrations"] == 1
    # late retransmit of the ABORTED attempt: dropped, never restaged
    dw.handle_message("pg:ra:1:2", _page_frame(blk, blk, 2))
    assert dw._staged == {} and dw.stager.staged_bytes == 0
    # a second abort for the same rid is a no-op, not a double count
    dw.handle_message("pgx:ra", b"")
    assert dw.stats["aborted_migrations"] == 1
    # a NEWER attempt is a fresh migration: stages normally
    dw.handle_message("pg:ra:2:0", _page_frame(blk, blk, 0))
    assert dw._staged["ra"]["attempt"] == 2
    assert dw.stager.staged_bytes == before // 2


def test_migration_worker_gates_are_attempt_aware(cfg_params):
    """The adopted/aborted gates compare ATTEMPTS, not rids: a request
    that migrated away and bounces back under a higher attempt stages
    fresh, while retransmits at or below the resolved attempt drop."""
    cfg, _ = cfg_params
    net = LoopbackNetwork()
    mw = MigrationWorker(object(), LoopbackTransport("mw", net))
    LoopbackTransport("peer", net)
    blk = _blk(cfg)
    mw.handle_message("pg:rb:1:0", _page_frame(blk, blk, 0))
    assert mw.stager._staged["rb"]["attempt"] == 1
    mw.handle_message("pgx:rb", b"")
    assert mw.stager._staged == {} and mw.staged_bytes == 0
    assert mw.stats["aborted_migrations"] == 1
    # late frame of the aborted attempt: dropped
    mw.handle_message("pg:rb:1:1", _page_frame(blk, blk, 1))
    assert mw.stager._staged == {}
    # attempt 2 adopted here: its frames re-ack/drop, attempt 3 stages
    mw._mark_adopted("rb", 2)
    mw.handle_message("pg:rb:2:0", _page_frame(blk, blk, 0))
    assert mw.stager._staged == {}
    mw.handle_message("pg:rb:3:0", _page_frame(blk, blk, 0))
    assert mw.stager._staged["rb"]["attempt"] == 3
    assert not mw._is_adopted("rb", 3)


def test_coserving_worker_requires_one_shared_stager():
    """pg:/pgx: tags are shared by the §15 join and the §18 handoff —
    two stagers on one transport would split the dedup/abort state, so
    the co-serving seam refuses to build that way."""
    net = LoopbackNetwork()
    t = LoopbackTransport("cs", net)
    dec = SimpleNamespace(stager=PageStager("cs"), transport=t,
                          device_id="cs")
    with pytest.raises(ValueError, match="share one PageStager"):
        CoServingWorker(dec, SimpleNamespace(stager=PageStager("cs")))
    co = CoServingWorker(dec, SimpleNamespace(stager=dec.stager))
    assert co.device_id == "cs" and co.transport is t


# ---------------------------------------------------------------------------
# unit: controller policy (fake registry, no engine)


class _FakeRegistry:
    def __init__(self, loads, draining=(), down=()):
        self.loads = dict(loads)
        self.draining = set(draining)
        self.down = set(down)

    def replica_ids(self):
        return sorted(self.loads)

    def is_up(self, rid):
        return rid not in self.down

    def is_draining(self, rid):
        return rid in self.draining

    def routable_replicas(self):
        return [r for r in sorted(self.loads)
                if r not in self.down and r not in self.draining]

    def set_draining(self, rid, flag=True):
        (self.draining.add if flag else self.draining.discard)(rid)

    def get(self, rid):
        if rid not in self.loads:
            return None
        return SimpleNamespace(
            last_stats={"active_slots": self.loads[rid],
                        "queue_depth": 0})


def test_controller_pick_rebalance_policy():
    mover = lambda s, d, n: n                                  # noqa: E731
    # hot → light when the gap clears load_gap; n = half the gap
    c = MigrationController(_FakeRegistry({"a": 5, "b": 1}), mover,
                            load_gap=2, max_moves_per_round=4)
    assert c.pick_rebalance() == ("a", "b", 2)
    # max_moves caps the pick
    c = MigrationController(_FakeRegistry({"a": 9, "b": 1}), mover,
                            load_gap=2, max_moves_per_round=1)
    assert c.pick_rebalance() == ("a", "b", 1)
    # balanced fleet: no move
    c = MigrationController(_FakeRegistry({"a": 2, "b": 1}), mover,
                            load_gap=2)
    assert c.pick_rebalance() is None
    # a DRAINING source moves even below the gap — its whole load goes
    c = MigrationController(
        _FakeRegistry({"a": 2, "b": 1}, draining={"a"}), mover,
        load_gap=5, max_moves_per_round=8)
    assert c.pick_rebalance() == ("a", "b", 2)
    # nowhere routable to put the load: no move
    c = MigrationController(
        _FakeRegistry({"a": 3, "b": 1}, draining={"a", "b"}), mover)
    assert c.pick_rebalance() is None
    # a single replica can never be its own target
    c = MigrationController(_FakeRegistry({"a": 7}), mover, load_gap=1)
    assert c.pick_rebalance() is None


def test_controller_rebalance_once_counts_moved():
    calls = []

    def mover(src, dst, n):
        calls.append((src, dst, n))
        return 1

    reg = _FakeRegistry({"a": 6, "b": 0})
    c = MigrationController(reg, mover, load_gap=2, max_moves_per_round=2)
    assert c.rebalance_once() == 1
    assert calls == [("a", "b", 2)]
    assert c.stats["rebalances"] == 1
    assert c.stats["moved_requests"] == 1
    # a mover that moved nothing records nothing
    c2 = MigrationController(reg, lambda s, d, n: 0, load_gap=2)
    assert c2.rebalance_once() == 0
    assert c2.stats["rebalances"] == 0


def test_controller_drain_drives_replica_empty():
    reg = _FakeRegistry({"a": 3, "b": 0})

    def mover(src, dst, n):
        moved = min(n, reg.loads[src])
        reg.loads[src] -= moved
        reg.loads[dst] += moved
        return moved

    c = MigrationController(reg, mover, max_moves_per_round=1)
    moved = c.drain("a", deadline_s=5.0, poll_s=0.01)
    assert moved == 3
    assert reg.loads == {"a": 0, "b": 3}
    assert "a" in reg.draining           # stays draining until undrained
    assert c.stats["drained_requests"] == 3


# ---------------------------------------------------------------------------
# the checkpoint seam


def test_export_import_roundtrip_bit_identical(pair, reference):
    """Detach on one replica, import on another, with NO wire in
    between: the checkpoint alone carries everything a resume needs,
    and the combined stream is bit-identical to the un-migrated run."""
    ref = reference(PROMPT, MAX_NEW)
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="seam")
    _wait_tokens(req, 2)
    ckpt = pair.src_e.export_request("seam", detach=True)
    # the §18 checkpoint schema (docs/DESIGN.md table) — a missing key
    # here breaks cross-version migration silently
    assert {"rid", "prompt", "max_new", "tokens", "lps", "length",
            "last_tok", "kv_dtype", "block_tokens", "k", "v",
            "rng"} <= set(ckpt)
    assert ckpt["tokens"] == ref[:len(ckpt["tokens"])]
    # the freeze point: the source never steps this row again
    assert pair.src_e.get_request("seam") is None
    resumed = pair.dst_e.import_request(ckpt)
    assert [int(t) for t in resumed.wait(60)] == ref
    with pytest.raises(KeyError):
        pair.src_e.export_request("no-such-rid")
    _idle_no_leaks(pair.src_e, pair.dst_e)


# ---------------------------------------------------------------------------
# §22 mid-speculation migration: the verify-boundary freeze


@pytest.fixture(scope="module")
def draft_cfg_params(cfg_params):
    """A cheap 2-layer draft of the same family (the speculative-engine
    test idiom) for the draft-model proposer variants."""
    import dataclasses
    cfg, _ = cfg_params
    dcfg = dataclasses.replace(cfg, num_layers=2)
    return dcfg, init_full_params(jax.random.PRNGKey(1), dcfg)


def _mk_spec_engine(cfg, params, proposer, draft, max_seq=160,
                    kv_blocks=32):
    kw = (dict(prompt_lookup=True, num_draft=3) if proposer == "pld"
          else dict(draft_cfg=draft[0], draft_params=draft[1],
                    num_draft=3))
    return ContinuousBatchingEngine(
        cfg, params, max_seq=max_seq, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=kv_blocks, kv_block_tokens=8, **kw)


def _draft_pool_idle(*engines):
    """§22 zero-leak extension: the draft scratch pool holds NO pages
    while an engine is idle (scratch is per-active-row only; drafts
    are never shipped, so an import must not strand importer-side
    scratch either)."""
    for e in engines:
        if e._dmgr is not None:
            assert e._dmgr.used_blocks == 0, (
                f"draft scratch leak: {e._dmgr.used_blocks} pages")


@pytest.mark.parametrize("proposer", [
    "pld",
    # tier-1 budget: the pld seam is the quick-lane rep; the draft
    # variant (an extra pair of two-model engine builds) rides the
    # slow lane with the live-migration test
    pytest.param("draft", marks=pytest.mark.slow),
])
def test_mid_speculation_seam_bit_identical_zero_leak(
        cfg_params, draft_cfg_params, proposer):
    """§22 freeze rule: exports land between dispatches — a verify
    boundary — so the checkpoint carries the adaptive controller's
    scalars (``spec_k``/``spec_ewma``) and NO in-flight drafts; the
    importer rebuilds proposer state (draft scratch prefill / lookup
    history) from prompt + emitted tokens, the stitched greedy stream
    is bit-identical to the unmigrated spec run, and both engines end
    with zero leaks in the target pool AND the draft scratch pool."""
    cfg, params = cfg_params
    src = _mk_spec_engine(cfg, params, proposer, draft_cfg_params)
    dst = _mk_spec_engine(cfg, params, proposer, draft_cfg_params)
    try:
        ref = [int(t) for t in src.submit(PROMPT, 40).wait(120)]
        req = src.submit(PROMPT, 40, request_id="sp1")
        _wait_tokens(req, 8)
        ckpt = src.export_request("sp1", detach=True)
        # §22 checkpoint schema additions ride the §18 schema
        assert {"rid", "prompt", "tokens", "length", "last_tok", "k",
                "v", "rng", "spec_k", "spec_ewma"} <= set(ckpt)
        assert 1 <= ckpt["spec_k"] <= 3
        assert 0.0 <= ckpt["spec_ewma"] <= 1.0
        # drafts are dropped at the freeze, never serialized
        assert "drafts" not in ckpt and "dk" not in ckpt
        assert ckpt["tokens"] == ref[:len(ckpt["tokens"])]
        assert src.get_request("sp1") is None
        resumed = dst.import_request(ckpt)
        assert [int(t) for t in resumed.wait(120)] == ref
        _idle_no_leaks(src, dst)
        _draft_pool_idle(src, dst)
    finally:
        src.close()
        dst.close()


@pytest.mark.slow
def test_mid_speculation_live_migration_drains_staging(
        cfg_params, draft_cfg_params):
    """A speculating row handed off LIVE over the pg:/rs: wire (draft
    proposer): the scratch drafts never ship, target staging drains to
    zero bytes, the client stream stays bit-identical, and target pool
    + draft scratch pool end clean on both replicas."""
    cfg, params = cfg_params
    net = LoopbackNetwork()
    src = _mk_spec_engine(cfg, params, "draft", draft_cfg_params,
                          max_seq=512, kv_blocks=80)
    dst = _mk_spec_engine(cfg, params, "draft", draft_cfg_params,
                          max_seq=512, kv_blocks=80)
    src_w = MigrationWorker(src, LoopbackTransport("spsrc", net),
                            ack_timeout=10.0)
    dst_w = MigrationWorker(dst, LoopbackTransport("spdst", net),
                            ack_timeout=10.0)
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in (src_w, dst_w)]
    for t in threads:
        t.start()
    try:
        max_new = 480
        ref = [int(t) for t in src.submit(PROMPT, max_new).wait(180)]
        # a speculating row emits multiple tokens per round, so the
        # handoff can lose the race to completion — legal locally;
        # retry with a fresh rid (the chaos-test idiom)
        for i in range(4):
            rid = f"spl{i}"
            req = src.submit(PROMPT, max_new, request_id=rid)
            _wait_tokens(req, 2)
            moved = src_w.migrate_out(rid, "spdst")
            got = [int(t) for t in req.wait(180)]
            assert got == ref
            assert req.error is None and req.done.is_set()
            if moved:
                break
        else:
            pytest.fail("handoff never outran the spec decode in 4 "
                        "attempts")
        assert src_w.stats["migrated_out"] >= 1
        assert dst_w.stats["migrated_in"] >= 1
        # staging fully drained: zero held bytes, nothing parked
        assert dst_w.stager._staged == {}
        assert dst_w.staged_bytes == 0
        _idle_no_leaks(src, dst)
        _draft_pool_idle(src, dst)
    finally:
        src_w.stop()
        dst_w.stop()
        for t in threads:
            t.join(timeout=2)
        src.close()
        dst.close()


# ---------------------------------------------------------------------------
# the loopback e2e (the -m quick live-migration rep)


@pytest.mark.quick
def test_live_migration_loopback_bit_identical_and_leak_free(
        pair, reference):
    """THE tentpole scenario at test scale: a request decoding on src
    migrates mid-flight to dst; the client stream never breaks, the
    greedy output is bit-identical to the never-migrated run, at most
    one boundary step replays, both pools end leak-free, and one trace
    id spans the source's export/freeze/handoff and the target's
    adopt."""
    ref = reference(PROMPT, MAX_NEW)
    pair.src_w.tracer.drain()
    pair.dst_w.tracer.drain()
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="m1")
    _wait_tokens(req, 2)
    assert "m1" in pair.src_w.pick_migratable(4)
    replay_before = pair.src_w.stats["replayed_steps"]
    assert pair.src_w.migrate_out("m1", "dst") is True
    got = [int(t) for t in req.wait(60)]
    assert got == ref
    assert req.error is None and req.done.is_set()
    # the handoff moved the row: dst decoded the tail, src freed it
    assert pair.src_w.stats["migrated_out"] >= 1
    assert pair.dst_w.stats["migrated_in"] >= 1
    assert pair.src_w.stats["moved_pages"] > 0
    assert pair.src_w.stats["moved_bytes"] > 0
    # at most the one in-flight boundary step replayed, none skipped
    assert pair.src_w.stats["replayed_steps"] - replay_before <= 1
    # target staging fully drained into the pool adoption
    assert pair.dst_w.stager._staged == {}
    assert pair.dst_w.staged_bytes == 0
    # a late pgx for the adopted attempt is a no-op, not an abort
    aborted = pair.dst_w.stats["aborted_migrations"]
    pair.dst_w.handle_message("pgx:m1", b"")
    assert pair.dst_w.stats["aborted_migrations"] == aborted
    # ONE trace id stitches source and target spans (Perfetto export)
    spans = pair.src_w.tracer.drain() + pair.dst_w.tracer.drain()
    names = {s["name"] for s in spans}
    assert {"migration_export", "migration_freeze", "migration_handoff",
            "migration_adopt"} <= names
    tids = {s["trace_id"] for s in spans}
    assert len(tids) == 1
    chrome = to_chrome_trace(spans)
    procs = {e["args"]["name"] for e in chrome["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"migration:src", "migration:dst"} <= procs
    wire_tids = {e["args"]["trace_id"] for e in chrome["traceEvents"]
                 if e["ph"] == "X"}
    assert len(wire_tids) == 1
    # debug surfaces on both sides
    assert pair.src_w.debug_state()["migration"]["migrated_out"] >= 1
    assert pair.dst_w.debug_state()["migration"]["migrated_in"] >= 1
    _idle_no_leaks(pair.src_e, pair.dst_e)


def test_cancel_after_handoff_forwards_and_frees_both_pools(
        pair, reference):
    """The satellite-3 race: the client cancels AFTER the row handed
    off.  The source forwards the cancel (mcx:), the target's sweep
    frees its slot/pages, fin reports the clean termination — a clean
    terminal stream (tokens so far, no error), never a hang, and both
    replicas release every page."""
    ref = reference(PROMPT, MAX_NEW)
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="m2")
    _wait_tokens(req, 2)
    assert pair.src_w.migrate_out("m2", "dst") is True
    req.cancel()
    got = [int(t) for t in req.wait(30)]
    assert req.done.is_set() and req.error is None
    # every emitted token is a real step: a prefix of the reference
    assert got == ref[:len(got)]
    # relay + adoption bookkeeping cleaned up on both sides
    deadline = time.monotonic() + 5.0
    while (("m2" in pair.src_w._relays or "m2" in pair.dst_w._imported)
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert "m2" not in pair.src_w._relays
    assert "m2" not in pair.dst_w._imported
    _idle_no_leaks(pair.src_e, pair.dst_e)


def test_phase1_unreachable_target_fails_loudly_request_survives(
        pair, reference):
    """A target that never acks phase 1 fails the migration with a
    MigrationError — and the request, never frozen, just keeps decoding
    locally to the bit-identical stream."""
    ref = reference(PROMPT, MAX_NEW)
    src2 = MigrationWorker(pair.src_e, LoopbackTransport("src2", pair.net),
                           ack_timeout=0.15, retries=1)
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="mf")
    _wait_tokens(req, 2)
    with pytest.raises(MigrationError, match="phase-1"):
        src2.migrate_out("mf", "ghost")
    assert src2.stats["failed_migrations"] == 1
    assert src2.stats["migrated_out"] == 0
    assert [int(t) for t in req.wait(60)] == ref
    assert req.error is None
    _idle_no_leaks(pair.src_e)


def test_phase2_ack_loss_self_heals_locally(pair, reference):
    """Every rsd: frame dropped: the freeze already happened, so the
    source re-imports its own detached checkpoint — the client stream
    survives on the ORIGINAL Request object, the target's staging is
    aborted (pgx:), and the caller still sees the loud MigrationError."""
    ref = reference(PROMPT, MAX_NEW)
    plan = FaultPlan(seed=11, rules=[
        FaultRule(kind="drop", tag_prefix="rsd:")])
    srcf = MigrationWorker(
        pair.src_e,
        FaultyTransport(LoopbackTransport("srcf", pair.net), plan),
        ack_timeout=0.2, retries=1)
    aborted_before = pair.dst_w.stats["aborted_migrations"]
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="mh")
    _wait_tokens(req, 2)
    with pytest.raises(MigrationError, match="re-imported locally"):
        srcf.migrate_out("mh", "dst")
    assert srcf.stats["healed_requests"] == 1
    assert srcf.stats["failed_migrations"] == 1
    assert [int(t) for t in req.wait(60)] == ref
    assert req.error is None and req.done.is_set()
    # the target aborted its (complete) phase-1 staging
    deadline = time.monotonic() + 5.0
    while ("mh" in pair.dst_w.stager._staged
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert "mh" not in pair.dst_w.stager._staged
    assert pair.dst_w.stats["aborted_migrations"] == aborted_before + 1
    assert len(plan.events) > 0          # the faults really fired
    _idle_no_leaks(pair.src_e, pair.dst_e)


class _DiesAfterPhase1Ack:
    """Delegating transport whose peer hard-dies the moment the phase-1
    ack lands: every later send raises TransportError outright — the
    worst failure point, AFTER the freeze decision, BEFORE the handoff,
    with no ack-timeout path to soften it."""

    def __init__(self, inner):
        self._inner = inner
        self.device_id = inner.device_id
        self.dead = False

    def send(self, peer, tag, body):
        if self.dead:
            raise TransportError(f"{peer} is gone")
        return self._inner.send(peer, tag, body)

    def recv(self, tag, timeout=None):
        out = self._inner.recv(tag, timeout=timeout)
        if tag.startswith("pga:"):
            self.dead = True
        return out

    def recv_any(self, timeout=None):
        return self._inner.recv_any(timeout=timeout)


def test_target_dies_after_phase1_ack_heals_not_orphans(pair, reference):
    """A raw TransportError on the post-detach sends (dead peer, not a
    quiet ack timeout) must run the SAME self-heal as a lost ack: the
    detached checkpoint re-imports locally and the caller sees the loud
    MigrationError — never an orphaned request whose pages are released
    and whose stream nobody owns."""
    ref = reference(PROMPT, MAX_NEW)
    t = _DiesAfterPhase1Ack(LoopbackTransport("srcd", pair.net))
    srcd = MigrationWorker(pair.src_e, t, ack_timeout=0.2, retries=1)
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="md")
    _wait_tokens(req, 2)
    with pytest.raises(MigrationError, match="re-imported locally"):
        srcd.migrate_out("md", "dst")
    assert t.dead                        # the failure mode really fired
    assert srcd.stats["healed_requests"] == 1
    assert srcd.stats["failed_migrations"] == 1
    assert "md" not in srcd._relays
    assert [int(tok) for tok in req.wait(60)] == ref
    assert req.error is None and req.done.is_set()
    # the pgx: abort never reached the dead wire — clean the target's
    # phase-1 staging up by hand so later tests see empty staging
    pair.dst_w.handle_message("pgx:md", b"")
    assert "md" not in pair.dst_w.stager._staged
    _idle_no_leaks(pair.src_e, pair.dst_e)


class _SendLog:
    """Delegating transport that records every sent tag."""

    def __init__(self, inner):
        self._inner = inner
        self.device_id = inner.device_id
        self.sent = []

    def send(self, peer, tag, body):
        self.sent.append(tag)
        return self._inner.send(peer, tag, body)

    def recv(self, tag, timeout=None):
        return self._inner.recv(tag, timeout=timeout)

    def recv_any(self, timeout=None):
        return self._inner.recv_any(timeout=timeout)


def test_phase2_ack_lost_after_adopt_cancels_duplicate(pair, reference):
    """The adopted-ack-lost corner: the target ADOPTS the handoff but
    every rsa: ack back is dropped.  The source cannot distinguish this
    from a dead target, so it heals locally (correct) — and because
    pgx: deliberately ignores adopted rids, an mcx: must ride along so
    the target cancels its duplicate row instead of burning a slot
    decoding it to completion."""
    ref = reference(PROMPT, MAX_NEW)
    plan = FaultPlan(seed=13, rules=[
        FaultRule(kind="drop", tag_prefix="rsa:")])
    dstr_w = MigrationWorker(
        pair.dst_e,
        FaultyTransport(LoopbackTransport("dstr", pair.net), plan),
        ack_timeout=10.0)
    th = threading.Thread(target=dstr_w.serve_forever, daemon=True)
    th.start()
    try:
        t = _SendLog(LoopbackTransport("srcr", pair.net))
        srcr = MigrationWorker(pair.src_e, t, ack_timeout=0.25, retries=1)
        req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="mr")
        _wait_tokens(req, 2)
        with pytest.raises(MigrationError, match="re-imported locally"):
            srcr.migrate_out("mr", "dstr")
        assert plan.events                     # the acks really dropped
        assert srcr.stats["healed_requests"] == 1
        # the target DID adopt — only the ack back was lost
        assert dstr_w.stats["migrated_in"] == 1
        # the heal sent the duplicate-reaper alongside the abort
        assert "mcx:mr" in t.sent and "pgx:mr" in t.sent
        # the client stream survives on the healed local copy
        assert [int(tok) for tok in req.wait(60)] == ref
        assert req.error is None and req.done.is_set()
        # the duplicate terminates on the target and its slot/pages free
        deadline = time.monotonic() + 10.0
        while "mr" in dstr_w._imported and time.monotonic() < deadline:
            time.sleep(0.02)
        assert "mr" not in dstr_w._imported
        _idle_no_leaks(pair.src_e, pair.dst_e)
    finally:
        dstr_w.stop()
        th.join(timeout=2)


def test_export_timeout_abandons_mailbox_never_detaches(
        cfg_params, reference):
    """A scheduler stalled past export_request's timeout must not
    execute the export later with no caller left to own delivery — a
    late detach would orphan the request (pages released, stream never
    fed).  The timed-out box is ABANDONED: its service is a no-op and
    the row keeps decoding locally to the bit-identical stream."""
    from distributed_inference_demo_tpu.runtime import batching as B
    cfg, params = cfg_params
    eng = _mk_engine(cfg, params)
    try:
        ref = reference(PROMPT, MAX_NEW)
        req = eng.submit(PROMPT, MAX_NEW, request_id="ab")
        _wait_tokens(req, 1)
        entered = threading.Event()
        release = threading.Event()

        class _WedgedDone:
            @staticmethod
            def is_set():
                entered.set()
                release.wait(20)
                return True       # -> ValueError("already finished")

        wedge = {"req": SimpleNamespace(rid="wedge", cancelled=False,
                                        done=_WedgedDone()),
                 "detach": False, "ckpt": None, "err": None,
                 "claimed": False, "abandoned": False,
                 "event": threading.Event()}
        with eng._submit_lock:
            eng._export_q.append(wedge)
            eng._queue.put(B._WAKE)
        assert entered.wait(20)   # scheduler is now wedged mid-export
        detached_before = eng.migration_stats["detached_requests"]
        with pytest.raises(TimeoutError, match="abandoned"):
            eng.export_request("ab", detach=True, timeout=0.2)
        release.set()
        # the late service of the abandoned box must NOT detach the row
        assert [int(tok) for tok in req.wait(60)] == ref
        assert req.error is None
        assert eng.migration_stats["detached_requests"] == detached_before
        assert eng.get_request("ab") is None   # finished, not orphaned
        assert wedge["event"].is_set()
        assert isinstance(wedge["err"], ValueError)
    finally:
        release.set()
        eng.close()


def test_promote_staged_resumes_after_source_death(pair, reference):
    """Phase 1 completed, then the source died before the handoff: the
    target promotes the staged bulk checkpoint and resumes at step T —
    replayed steps dedup downstream, none skip, and the promoted stream
    completes bit-identically from the snapshot."""
    ref = reference(PROMPT, MAX_NEW)
    # build the "dead source"'s phase-1 traffic by hand from a real
    # detached checkpoint (detach == the source never steps again)
    req = pair.src_e.submit(PROMPT, MAX_NEW, request_id="mp")
    _wait_tokens(req, 2)
    ckpt = pair.src_e.export_request("mp", detach=True)
    bt = pair.src_e.kv_cache.block_tokens
    n_blocks = -(-ckpt["length"] // bt)
    frames = []
    for first in range(0, n_blocks, 4):
        sl = slice(first, min(first + 4, n_blocks))
        kb = jax.tree.map(lambda a: a[sl], ckpt["k"])
        vb = jax.tree.map(lambda a: a[sl], ckpt["v"])
        frames.append((f"pg:mp:1:{len(frames)}",
                       _page_frame(kb, vb, first)))
    meta = _state_meta(ckpt, rid="mp", attempt=1, n_frames=len(frames),
                       n_blocks=n_blocks, source_id="deadsrc",
                       reply_to="client0")
    for tag, body in frames:
        pair.dst_w.handle_message(tag, body)
    pair.dst_w.handle_message(
        "rs:mp:1", _meta_frame(meta, _state_tensors(ckpt)))
    assert pair.dst_w.stager._staged["mp"]["state_meta"] is not None
    # nothing promotable under an unknown rid
    assert pair.dst_w.promote_staged("nope") is None
    promoted = pair.dst_w.promote_staged("mp")
    assert promoted is not None
    assert [int(t) for t in promoted.wait(60)] == ref
    assert pair.dst_w.stats["promoted_requests"] == 1
    # staging fully consumed; a second promote finds nothing
    assert "mp" not in pair.dst_w.stager._staged
    assert pair.dst_w.promote_staged("mp") is None
    _idle_no_leaks(pair.src_e, pair.dst_e)


def test_bounce_migration_src_to_dst_and_back(pair, reference):
    """A → B → A: the second hop runs under a HIGHER attempt, so A —
    which still remembers shipping the request away — stages it fresh
    instead of dropping its own request as a duplicate.  The chained
    relay (A's adopt streams to B, B forwards to the original Request)
    still delivers one unbroken bit-identical stream."""
    ref = reference(PROMPT, MAX_NEW)
    # the CPU decode can FINISH the row before a hop's freeze lands — a
    # legal local resolution, not a bounce.  THROTTLE both engines'
    # decode dispatch (a sleep around the same program: bit-identity
    # untouched) so each freeze has a wide window, and retry with a
    # fresh rid as the backstop (each attempt still pins bit-identical
    # output, bounced or not).
    throttled = []
    for e in (pair.src_e, pair.dst_e):
        orig = e._paged_multi_step

        def slow(*a, _orig=orig, **k):
            time.sleep(0.005)
            return _orig(*a, **k)

        throttled.append((e, orig))
        e._paged_multi_step = slow
    try:
        for i in range(12):
            rid = f"mb{i}"
            req = pair.src_e.submit(PROMPT, MAX_NEW, request_id=rid)
            _wait_tokens(req, 2)
            if not pair.src_w.migrate_out(rid, "dst"):
                continue                 # finished before hop 1's freeze
            assert pair.src_w._attempts[rid] == 1
            try:
                bounced = pair.dst_w.migrate_out(rid, "src")
            except KeyError:
                bounced = False          # finished on dst pre-freeze
            got = [int(t) for t in req.wait(60)]
            assert got == ref
            assert req.error is None
            if bounced:
                break
        else:
            pytest.fail("bounce never landed in 12 attempts")
    finally:
        for e, orig in throttled:
            e._paged_multi_step = orig
    # the attempt counter chained through the adoption: hop 2 used 2,
    # and src — the original source — staged its own request fresh
    assert pair.dst_w._attempts[rid] == 2
    assert pair.src_w._attempts[rid] == 2
    assert pair.src_w.stats["migrated_in"] >= 1
    assert pair.dst_w.stats["migrated_out"] >= 1
    _idle_no_leaks(pair.src_e, pair.dst_e)


# ---------------------------------------------------------------------------
# slow soak: concurrent migrations under load


@pytest.mark.slow
def test_concurrent_migrations_under_load(cfg_params, reference):
    """Three requests decoding concurrently; two migrate mid-flight
    (picked by pick_migratable, the controller's mechanism) while the
    third stays — every stream bit-identical, both pools leak-free."""
    cfg, params = cfg_params
    net = LoopbackNetwork()
    src_e, dst_e = _mk_engine(cfg, params), _mk_engine(cfg, params)
    src_w = MigrationWorker(src_e, LoopbackTransport("s", net),
                            ack_timeout=10.0)
    dst_w = MigrationWorker(dst_e, LoopbackTransport("d", net),
                            ack_timeout=10.0)
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in (src_w, dst_w)]
    for t in threads:
        t.start()
    try:
        prompts = [PROMPT, (np.arange(23) % 47 + 2).astype(np.int32),
                   (np.arange(11) % 31 + 5).astype(np.int32)]
        refs = [reference(p, MAX_NEW) for p in prompts]
        reqs = [src_e.submit(p, MAX_NEW, request_id=f"c{i}")
                for i, p in enumerate(prompts)]
        for r in reqs:
            _wait_tokens(r, 2)
        moved = 0
        for rid in src_w.pick_migratable(2):
            if src_w.migrate_out(rid, "d"):
                moved += 1
        assert moved >= 1
        for r, want in zip(reqs, refs):
            assert [int(t) for t in r.wait(120)] == want
        assert src_w.stats["migrated_out"] == moved
        assert dst_w.stats["migrated_in"] == moved
        _idle_no_leaks(src_e, dst_e)
    finally:
        src_w.stop()
        dst_w.stop()
        for t in threads:
            t.join(timeout=2)
        src_e.close()
        dst_e.close()
