"""The measurement tooling is round evidence infrastructure — pin its
merge/guard semantics so a regression can't silently destroy measured
results (bench.py `_load_prior`/`headline_summary`, tools/measure_session
merge/retry logic).  Pure-JSON logic, no device needed."""

import importlib.util
import json
import sys

import pytest
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def _ms():
    spec = importlib.util.spec_from_file_location(
        "measure_session", REPO / "tools" / "measure_session.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


PARAMS = {"model": "m", "batch": 8, "prompt_len": 64, "new_tokens": 128,
          "flagship": "f"}


@pytest.mark.quick
def test_merge_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    art = ms.merge(art, "sweep", {"points": [1]}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "late boom"}, PARAMS)
    assert art["extras"]["sweep"] == {"points": [1]}
    assert "error" in art["extras"]["sweep_rerun"]


def test_merge_retry_attempts_and_exhaustion():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for n in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "sweep")
        art = ms.merge(art, "sweep", {"error": "boom"}, PARAMS)
    assert ms.leg_exhausted(art, "sweep")
    # a success resets the ledger
    art = ms.merge(art, "sweep", {"points": [2]}, PARAMS)
    assert ms.leg_done(art, "sweep") and not ms.leg_exhausted(art, "sweep")


def test_merge_headline_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "metric": "m0", "value": 1.0, "headline": {"x": 1},
           "extras": {}}
    art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    # the measured top-level value/metric/headline survive the failure
    assert art["value"] == 1.0 and art["metric"] == "m0"
    assert art["headline"] == {"x": 1}
    assert "error" in art["extras"]["headline_rerun"]
    # a measured leg is done: it never re-enters the todo list, so
    # exhaustion bookkeeping is moot for it
    assert ms.leg_done(art, "headline")


def test_merge_unmeasured_headline_errors_exhaust():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for _ in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "headline")
        art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    assert art["headline"] == {}           # still unmeasured, never faked
    assert ms.leg_exhausted(art, "headline")


def test_load_prior_skips_errors_and_stamps_provenance(tmp_path,
                                                       monkeypatch):
    art = {"note": "n", "metric": "m", "value": 2.0, "vs_baseline": 3.0,
           "headline": {"decode_tokens_per_sec": 2.0},
           "extras": {"good": {"v": 1}, "bad": {"error": "x"},
                      "bad_rerun": {"error": "y"},
                      "baseline": {"tokens_per_sec": 1}}}
    p = tmp_path / "prior.json"
    p.write_text(json.dumps(art))
    monkeypatch.setattr(bench, "REPO", tmp_path)
    monkeypatch.setenv("BENCH_PRIOR_ARTIFACT", "prior.json")
    prior = bench._load_prior()
    assert set(prior["legs"]) == {"headline", "good"}
    assert "prior.json" in prior["source"] and "written" in prior["source"]
    assert prior["value"] == 2.0


def test_load_prior_missing_artifact(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "REPO", tmp_path)
    assert bench._load_prior() == {}


def test_merge_forced_rerun_failures_accumulate_attempts():
    # an errored --force re-run of a MEASURED leg lands in the rerun
    # slot with a running attempts counter (without it, repeatedly
    # failing forced re-runs never registered in the retry ledger)
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    art = ms.merge(art, "sweep", {"points": [1]}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "a"}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "b"}, PARAMS)
    assert art["extras"]["sweep"] == {"points": [1]}   # still measured
    assert art["extras"]["sweep_rerun"]["attempts"] == 2


def _ledger_at(tmp_path, monkeypatch):
    """Point bench's roofline ledger at a scratch file (tests must never
    write the committed repo-root ledger)."""
    path = tmp_path / "ROOFLINE_LEDGER.json"
    monkeypatch.setattr(bench, "ROOFLINE_LEDGER_PATH", path)
    return path


def test_session_ceiling_and_ledger_forbid_frac_above_one(tmp_path,
                                                          monkeypatch):
    ledger = _ledger_at(tmp_path, monkeypatch)
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {
        "roofline_probe": {"hbm_read_gbs": 300.0},
        "probe_history": [{"hbm_gbs": 450.0}, {"hbm_gbs": 120.0}]}}
    assert ms.session_ceiling(art) == 450.0
    # a decode leg beating every probe IS the better bandwidth
    # measurement: the ledger is raised to it, the leg reports frac 1.0
    # with the raise stamped — never a >1.0 "fraction" (the r05
    # artifact shipped 1.691 that way)
    art = ms.merge(art, "headline_int8",
                   {"achieved_gbs": 500.0, "device": "TPU v5 lite"},
                   PARAMS)
    r = art["extras"]["headline_int8"]
    assert r["hbm_roofline_frac_measured"] == 1.0
    assert "ledger_raised" in r
    assert bench.load_roofline_ledger("TPU v5 lite")["hbm_gbs"] == 500.0
    assert ledger.exists()
    # the next merge is judged against the DECLARED ceiling
    # max(session probes, ledger) = 500: fraction < 1, stamp clears
    art = ms.merge(art, "pipeline", {"tok_s": 1}, PARAMS)
    r = art["extras"]["headline_int8"]
    assert r["hbm_roofline_frac_measured"] == 1.0  # 500/500
    assert art["extras"]["measured_ceiling_gbs"] == 500.0
    assert art["extras"]["roofline_ledger"]["ledger_gbs"] == 500.0
    # a DEGRADED later session (probes far below the chip) inherits the
    # committed ceiling instead of minting a lower one
    art2 = {"note": "", "headline": {}, "extras": {
        "probe_history": [{"hbm_gbs": 120.0}],
        "sweep": {"points": [{"achieved_gbs": 480.0,
                              "device": "TPU v5 lite"}]}}}
    art2 = ms.merge(art2, "pipeline", {"tok_s": 1}, PARAMS)
    assert art2["extras"]["measured_ceiling_gbs"] == 500.0
    pt = art2["extras"]["sweep"]["points"][0]
    assert pt["hbm_roofline_frac_measured"] == 0.96


def test_roofline_ledger_is_monotone_max(tmp_path, monkeypatch):
    _ledger_at(tmp_path, monkeypatch)
    assert bench.update_roofline_ledger("dev", 400.0, source="a")
    assert not bench.update_roofline_ledger("dev", 300.0, source="b")
    assert bench.load_roofline_ledger("dev")["hbm_gbs"] == 400.0
    assert bench.load_roofline_ledger("dev")["source"] == "a"
    assert bench.update_roofline_ledger("dev", 500.0, source="c")
    assert bench.load_roofline_ledger("dev")["hbm_gbs"] == 500.0
    # no device / no number: never writes
    assert not bench.update_roofline_ledger(None, 600.0, source="x")
    assert not bench.update_roofline_ledger("dev", None, source="x")


def test_apply_measured_frac_never_emits_above_one(tmp_path, monkeypatch):
    """The acceptance-criteria invariant, by sweep: whatever the
    achieved/ceiling combination, the emitted fraction is <= 1.0."""
    _ledger_at(tmp_path, monkeypatch)
    for achieved in (1.0, 99.9, 500.0, 819.0, 2000.0):
        for ceiling in (None, 100.0, 500.0, 819.0):
            leg = {"achieved_gbs": achieved, "device": "d"}
            bench.apply_measured_frac(leg, ceiling, "d")
            frac = leg.get("hbm_roofline_frac_measured")
            assert frac is None or frac <= 1.0, (achieved, ceiling, frac)


def test_micro_prepass_banks_all_legs_and_commits_first(tmp_path,
                                                        monkeypatch):
    ms = _ms()
    monkeypatch.setattr(ms, "tunnel_healthy", lambda: (True, 100.0))
    ran, committed = [], []
    monkeypatch.setattr(
        ms.bench, "_spawn_leg",
        lambda leg, params, timeout, micro=False: (
            ran.append((leg, micro)) or {"micro": True, "ok_leg": leg}))
    monkeypatch.setattr(ms, "commit",
                        lambda path, msg: committed.append(msg) or True)
    art = {"note": "", "headline": {}, "extras": {}}
    path = tmp_path / "a.json"
    legs = ["headline", "planner_pipeline", "sweep"]
    assert ms.micro_prepass(art, path, legs, PARAMS) == 0
    # every leg ran in micro mode — including planner_pipeline — and
    # the banked results were committed in ONE prepass commit
    assert ran == [(l, True) for l in legs]
    assert all(ms.micro_done(art, l) for l in legs)
    assert len(committed) == 1 and "micro prepass" in committed[0]
    assert json.loads(path.read_text())["extras"]["micro"]["sweep"][
        "ok_leg"] == "sweep"
    # second invocation: nothing to do, no re-runs, no commit
    ran.clear(), committed.clear()
    assert ms.micro_prepass(art, path, legs, PARAMS) == 0
    assert ran == [] and committed == []


def test_micro_prepass_timeout_stops_and_commits_partial(tmp_path,
                                                         monkeypatch):
    ms = _ms()
    monkeypatch.setattr(ms, "tunnel_healthy", lambda: (True, None))
    results = {"headline": {"micro": True},
               "sweep": {"error": "leg timed out after 300s"}}
    monkeypatch.setattr(
        ms.bench, "_spawn_leg",
        lambda leg, params, timeout, micro=False: dict(results[leg]))
    committed = []
    monkeypatch.setattr(ms, "commit",
                        lambda path, msg: committed.append(msg) or True)
    art = {"note": "", "headline": {}, "extras": {}}
    path = tmp_path / "a.json"
    # a wedge mid-prepass returns 3 (watcher retries) with the banked
    # micros already committed
    assert ms.micro_prepass(art, path, ["headline", "sweep", "pipeline"],
                            PARAMS) == 3
    assert ms.micro_done(art, "headline")
    assert not ms.micro_done(art, "sweep")
    assert "pipeline" not in art["extras"]["micro"]   # never attempted
    assert len(committed) == 1


@pytest.mark.quick
def test_timed_out_leg_retries_once_at_reduced_budget(monkeypatch):
    """A timed-out leg re-runs ONCE with halved new_tokens before its
    failure is recorded; the retried result is stamped
    ``retried_reduced`` so consumers can see the reduced shape."""
    ms = _ms()
    calls = []

    def fake_spawn(leg, params, timeout, micro=False):
        calls.append((leg, dict(params), timeout))
        if len(calls) == 1:
            return {"error": f"leg timed out after {timeout}s"}
        return {"tok_s": 10.0}

    monkeypatch.setattr(ms.bench, "_spawn_leg", fake_spawn)
    result = ms.run_leg_with_retry("spec_mixed", dict(PARAMS), 2400)
    assert len(calls) == 2
    # the retry runs the SAME leg at the SAME time budget but half the
    # measured work per round
    assert calls[1][0] == "spec_mixed" and calls[1][2] == 2400
    assert calls[1][1]["new_tokens"] == PARAMS["new_tokens"] // 2
    assert result["retried_reduced"] is True and result["tok_s"] == 10.0
    # the original params dict is not mutated by the reduced retry
    assert PARAMS["new_tokens"] == 128


def test_timed_out_retry_failure_records_error_no_third_attempt(
        monkeypatch):
    ms = _ms()
    calls = []
    monkeypatch.setattr(
        ms.bench, "_spawn_leg",
        lambda leg, params, timeout, micro=False: (
            calls.append(leg) or {"error": f"leg timed out after {timeout}s"}))
    result = ms.run_leg_with_retry("sweep", dict(PARAMS), 1200)
    # exactly one retry — the reduced re-run must not recurse
    assert calls == ["sweep", "sweep"]
    assert "timed out" in result["error"]
    assert result["retried_reduced"] is True


def test_non_timeout_failure_does_not_retry(monkeypatch):
    ms = _ms()
    calls = []
    monkeypatch.setattr(
        ms.bench, "_spawn_leg",
        lambda leg, params, timeout, micro=False: (
            calls.append(leg) or {"error": "leg exited rc=1"}))
    result = ms.run_leg_with_retry("sweep", dict(PARAMS), 1200)
    assert calls == ["sweep"]
    assert "retried_reduced" not in result


def test_multichip_render_matches_driver_bytes():
    """The driver rewrites MULTICHIP artifacts from parsed JSON in its
    own format; tools/record_multichip.render_artifact must reproduce a
    driver-written file BYTE-IDENTICALLY (no git_head field, no trailing
    newline) or every re-run shows the artifact dirty (VERDICT r2-r5)."""
    spec = importlib.util.spec_from_file_location(
        "record_multichip", REPO / "tools" / "record_multichip.py")
    rm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rm)
    raw = (REPO / "MULTICHIP_r05.json").read_text()
    parsed = json.loads(raw)
    rendered = rm.render_artifact(parsed["n_devices"], parsed["rc"],
                                  parsed["tail"],
                                  skipped=parsed["skipped"])
    assert rendered == raw
    assert not rendered.endswith("\n")
    assert "git_head" not in rendered


def test_load_prior_chains_artifacts_with_per_leg_provenance(
        tmp_path, monkeypatch):
    new = {"note": "r5", "metric": "m5", "value": 5.0, "vs_baseline": 1.5,
           "headline": {"decode_tokens_per_sec": 5.0},
           "extras": {"probe_history": [{"hbm_gbs": 1}]}}
    old = {"note": "r4", "metric": "m4", "value": 4.0, "vs_baseline": 1.4,
           "headline": {"decode_tokens_per_sec": 4.0},
           "extras": {"sweep": {"points": [1]}}}
    (tmp_path / "new.json").write_text(json.dumps(new))
    (tmp_path / "old.json").write_text(json.dumps(old))
    monkeypatch.setattr(bench, "REPO", tmp_path)
    monkeypatch.setenv("BENCH_PRIOR_ARTIFACT", "new.json")
    monkeypatch.setattr(bench, "PRIOR_ARTIFACT_FALLBACKS", ["old.json"])
    prior = bench._load_prior()
    # headline from the newest artifact, sweep borrowed from the older
    # one — each stamped with the artifact it came from
    assert prior["value"] == 5.0
    assert "new.json" in prior["legs"]["headline"]["prior_source"]
    assert "old.json" in prior["legs"]["sweep"]["prior_source"]
    # probe_history is session bookkeeping, never surfaced as a leg
    assert "probe_history" not in prior["legs"]


def test_headline_summary_null_when_not_comparable():
    # a different batch than the stored CPU baseline must report null,
    # never a mislabeled multiplier
    s = bench.headline_summary(
        {"decode_tokens_per_sec": 100.0, "dtype": "bf16"},
        dict(PARAMS, model="tinyllama-1.1b", batch=999), "dev")
    assert s["value"] == 100.0 and s["vs_baseline"] is None
